"""Minimal training loop: LeNet on synthetic MNIST-shaped data.

Run: python examples/mnist_lenet.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # delete on a real TPU host

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


def synthetic_mnist(n=512, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n)
    imgs = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, y in enumerate(labels):          # class-dependent blob
        imgs[i, 0, y * 2:y * 2 + 4, y * 2:y * 2 + 4] += 1.0
    return imgs, labels[:, None]


def main():
    paddle.seed(0)
    xs, ys = synthetic_mnist()
    ds = paddle.io.TensorDataset([xs, ys])
    loader = paddle.io.DataLoader(ds, batch_size=64, shuffle=True)

    net = paddle.models.LeNet()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net),
        loss=nn.CrossEntropyLoss(),
        metrics=[paddle.metric.Accuracy()])
    model.fit(loader, epochs=3, verbose=1)
    eval_logs = model.evaluate(loader, verbose=0)
    print("final:", {k: float(v) for k, v in eval_logs.items()})

    model.save("/tmp/lenet_example")        # params + optimizer state
    print("saved to /tmp/lenet_example*")


if __name__ == "__main__":
    main()
