"""Variable-length text without unbounded recompiles: length bucketing
pads every batch to one of a FIXED set of shapes, so XLA compiles once
per bucket instead of once per distinct length.

Run: python examples/variable_length_text.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # delete on a real TPU host

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import io, nn


class RaggedText(io.Dataset):
    def __init__(self, n=256, vocab=500, seed=0):
        rng = np.random.RandomState(seed)
        self.seqs = [rng.randint(1, vocab, rng.randint(4, 120))
                     for _ in range(n)]
        self.labels = [int(s.sum() % 2) for s in self.seqs]

    def __len__(self):
        return len(self.seqs)

    def __getitem__(self, i):
        return self.seqs[i], self.labels[i]


def main():
    paddle.seed(0)
    ds = RaggedText()
    sampler = io.LengthBucketBatchSampler(
        ds, lengths=lambda item: len(item[0]), batch_size=16,
        boundaries=[16, 32, 128], shuffle=True, drop_last=True)
    loader = io.DataLoader(ds, batch_sampler=sampler,
                           collate_fn=io.bucket_collate(sampler))

    class Clf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(500, 32)
            self.fc = nn.Linear(32, 2)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    model = paddle.Model(Clf())
    model.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=3e-3, parameters=model.network),
        loss=nn.CrossEntropyLoss())
    for epoch in range(3):
        for ids, label in loader:
            logs = model.train_batch([ids],
                                     [np.asarray(label)[:, None]])
        print(f"epoch {epoch}  loss {float(logs['loss']):.4f}  "
              f"distinct compiled shapes: "
              f"{model.compiled_shape_count}")  # <= 3 buckets


if __name__ == "__main__":
    main()
