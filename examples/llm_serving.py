"""Continuous-batching LLM serving demo.

Builds a small GPT, serves it through ``inference.LLMEngine`` (paged
KV cache, token-granularity admission, on-device sampling) behind the
HTTP front, and fires concurrent clients at it — the decode-era analog
of `serve_native.py`'s static-artifact serving.

Run: python examples/llm_serving.py  (CPU or TPU; first compile is
the slow part on TPU — subsequent requests share the jitted step)
"""

import json
import threading
import time
from urllib.request import Request, urlopen

import numpy as np

import paddle_tpu as pt
from paddle_tpu.inference import LLMEngine, serve_llm
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.observability import server as debug
from paddle_tpu.observability import tracing


def main():
    pt.seed(0)
    # request-scoped tracing + the live debug surface: scrape
    # /metrics, inspect /statusz occupancy, read /tracez span trees
    tracing.enable()
    cfg = gpt_config("gpt2-small", num_layers=4, hidden_size=256,
                     num_heads=4, vocab_size=1000,
                     max_position_embeddings=256,
                     hidden_dropout=0.0, attention_dropout=0.0)
    net = GPTForCausalLM(cfg)

    # decode_ticks_per_dispatch=8: the device-resident decode loop —
    # 8 decode ticks per XLA dispatch (sampling/EOS/page writes on
    # device), ~2x decode tokens/sec at small batch on CPU (PERF.md
    # "serving dispatch overhead"); watch llm_host_dispatches_total
    # vs llm_decode_ticks on /metrics to see the fusion.
    # mixed_tick=True: prefill chunks ride INSIDE the slab as one
    # ragged batch with the decode rows (llm_mixed_slabs_total).
    # kv_dtype="int8": quantized KV pages + per-token scales — ~2x
    # page capacity at fixed HBM (the /memz kv_pool rows show the
    # int8-page / scale_table split; PERF.md "Ragged mixed tick +
    # int8 KV" documents the greedy-parity tolerance).
    with LLMEngine(net, max_seqs=8, page_size=16, num_pages=256,
                   prefill_buckets=(32, 128),
                   decode_ticks_per_dispatch=8, mixed_tick=True,
                   kv_dtype="int8") as engine:
        srv = serve_llm(engine)
        host, port = srv.server_address
        print(f"serving on http://{host}:{port}/generate")
        dbg = debug.start_debug_server()
        print(f"debug surface on {dbg.address}"
              f" (/metrics /healthz /statusz /tracez)")

        rng = np.random.RandomState(0)
        # prompts generated BEFORE the threads start: RandomState is
        # not thread-safe, and the seeded demo should be reproducible
        prompts = [rng.randint(0, 1000, 8 + i * 3).tolist()
                   for i in range(12)]
        results = {}

        def client(i):
            body = {"prompt_ids": prompts[i],
                    "max_new_tokens": 24,
                    "temperature": 0.7 if i % 2 else 0.0}
            req = Request(f"http://{host}:{port}/generate",
                          data=json.dumps(body).encode(),
                          headers={"Content-Type": "application/json"})
            with urlopen(req, timeout=600) as r:
                results[i] = json.loads(r.read())

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0

        tokens = sum(len(r["output_ids"]) for r in results.values())
        print(f"{len(results)} clients, {tokens} tokens in {dt:.2f}s "
              f"({tokens / dt:.0f} tok/s aggregate)")
        for i in sorted(results)[:3]:
            r = results[i]
            print(f"  client {i}: ttft {r['ttft_s']:.3f}s "
                  f"latency {r['latency_s']:.3f}s "
                  f"out {r['output_ids'][:8]}...")
        srv.shutdown()
        print(f"engine: {engine.n_steps} decode steps, "
              f"{engine.n_tokens} tokens")
        phases = tracing.rollup(prefix="llm.", exclude=("llm.request",))
        print("phase shares: " + ", ".join(
            f"{k.split('.', 1)[1]}={v['share']:.1%}"
            for k, v in phases.items()))
        debug.stop_debug_server()


if __name__ == "__main__":
    main()
