"""Long-context training with sequence parallelism (context parallel).

A LLaMA-style model training on 16k-token sequences that no single
device's attention could hold densely: ``GPTConfig.sequence_parallel``
routes attention through ring attention over the mesh's ``sp`` axis
(K/V chunks rotate the ICI ring; exact numerics), and
``ring_chunk_size`` streams each block's K/V in tiles so per-device
attention memory is O(s * chunk / sp) rather than O((s/sp)^2).
``scan_layers`` keeps the compile O(1) in depth with structural remat.

Run (CPU demo: 8 virtual devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_ring.py
On a real TPU slice, drop the env var — the mesh picks up the chips.
"""

import os
import sys

if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# pass --tpu to run on an attached TPU slice; the default pins the CPU
# demo WITHOUT probing the backend (initializing a wedged/busy TPU
# tunnel hangs before the demo even starts)
ON_TPU = "--tpu" in sys.argv
if not ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import parallel
from paddle_tpu.models.gpt import (GPTForCausalLM,
                                   GPTPretrainingCriterion, llama_config)


def main():
    # 16k tokens on a real slice; the CPU demo default stays small
    # enough to compile+run in minutes on a laptop core
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    seq = int(args[0]) if args else (16384 if ON_TPU else 4096)
    sp, dp = 4, 2

    cfg = llama_config(hidden_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, vocab_size=512,
                       max_position_embeddings=seq, use_flash=False,
                       scan_layers=True, remat=True,
                       sequence_parallel=True, ring_chunk_size=min(512, seq // sp))
    mesh = parallel.init_mesh(sp=sp, dp=dp)

    paddle.seed(0)
    net = GPTForCausalLM(cfg)
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=net, weight_decay=0.1),
        loss=GPTPretrainingCriterion())
    parallel.distributed_model(model, mesh=mesh)

    rng = np.random.RandomState(0)
    for step in range(3):
        ids = rng.randint(0, cfg.vocab_size, (2 * dp, seq))
        logs = model.train_batch([ids], [ids])
        print(f"step {step}: loss {logs['loss']:.4f} "
              f"({2 * dp} x {seq} tokens over sp={sp} ring)")


if __name__ == "__main__":
    main()
