"""GPT training with the full hybrid: pipeline x tensor x data
parallelism, checkpointing, and preemption-safe looping — BASELINE
config 4's structure at toy scale.

Run: python examples/gpt_hybrid_parallel.py
On a real pod, drop the two config lines and size the mesh axes to the
slice (e.g. pp=4, tp=8, dp=2 on 64 chips).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")      # delete on a real TPU host
jax.config.update("jax_num_cpu_devices", 8)    # virtual 8-chip mesh

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import parallel
from paddle_tpu.distributed import elastic
from paddle_tpu.io.checkpoint import AutoCheckpoint
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLMPipe,
                                   GPTPretrainingCriterion)


def main():
    # pp=2 stages x tp=2 model shards x dp=2 data replicas = 8 devices
    mesh = parallel.init_mesh(pp=2, tp=2, dp=2)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLMPipe(cfg, num_microbatches=4, mesh=mesh)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.AdamW(learning_rate=2e-3,
                                         parameters=net,
                                         weight_decay=0.01),
        loss=GPTPretrainingCriterion())
    parallel.distributed_model(model, mesh=mesh)

    guard = elastic.PreemptionGuard()           # SIGTERM-safe
    acp = AutoCheckpoint.for_model("/tmp/gpt_hybrid_ckpt", model)

    # one fixed batch: the loop demonstrably memorizes it (loss drops)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 64))
    for step in acp.epochs(30):                 # resumes after restart
        logs = model.train_batch([ids], [ids])
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(logs['loss']):.4f}")
            acp.commit(step)
        guard.check(save=lambda: acp.commit(step))
    acp.commit(29)
    print("done; checkpoints in /tmp/gpt_hybrid_ckpt")


if __name__ == "__main__":
    main()
