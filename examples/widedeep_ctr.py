"""Wide&Deep CTR with a beyond-HBM sparse table: the host-offloaded
embedding keeps the (arbitrarily large) table in host RAM; the jitted
step's device memory is O(batch) regardless of vocabulary size.

Run: python examples/widedeep_ctr.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # delete on a real TPU host

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


class WideDeepCTR(nn.Layer):
    def __init__(self, vocab=100_000_000, dim=16):
        super().__init__()
        # 100M-row table: never materialized — rows live in host RAM,
        # touched rows stream to the device per batch
        self.sparse = nn.HostOffloadedEmbedding(
            vocab, dim, optimizer="adagrad", learning_rate=0.05,
            hash_ids=True)
        self.deep = nn.Sequential(nn.Linear(13, 64), nn.ReLU(),
                                  nn.Linear(64, 16), nn.ReLU(),
                                  nn.Linear(16, 1))
        self.wide_proj = nn.Linear(16, 1)

    def forward(self, slot_ids, dense_feats):
        return self.deep(dense_feats) + self.wide_proj(
            self.sparse(slot_ids))


def main():
    paddle.seed(0)
    net = WideDeepCTR()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net),
        loss=nn.BCEWithLogitsLoss())

    rng = np.random.RandomState(0)
    for step in range(50):
        ids = rng.randint(1, 100_000_000, (256, 26))   # 26 slots
        dense = rng.randn(256, 13).astype(np.float32)
        y = (rng.rand(256, 1) < 0.3).astype(np.float32)
        logs = model.train_batch([ids, dense], [y])
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(logs['loss']):.4f}  "
                  f"touched rows {net.sparse.touched_rows}")
    jax.effects_barrier()
    net.sparse.snapshot("/tmp/ctr_table.npz")          # PS-style snapshot
    print("table snapshot: /tmp/ctr_table.npz "
          f"({net.sparse.touched_rows} touched rows)")


if __name__ == "__main__":
    main()
