"""Export → native serving: save a model as a StableHLO artifact and
serve it from the C++ PJRT predictor (no Python jax in the serving
process).

Run: python examples/serve_native.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # delete on a real TPU host

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference, jit, nn


def main():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 4))
    net.eval()
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    ref = np.asarray(net(x))

    path = "/tmp/served_model"
    jit.save(net, path, input_spec=[jit.InputSpec((2, 8), "float32")])
    print("exported StableHLO artifact:", path)

    cfg = inference.Config(path)
    try:
        predictor = inference.create_predictor(cfg)  # C++ PJRT, ctypes
    except (TimeoutError, RuntimeError) as e:  # wedged / no plugin .so
        print(f"device unavailable ({e}); set PT_PJRT_PLUGIN to a "
              f"reachable PJRT plugin .so to serve — artifact is ready")
        return
    out = predictor.run([x])[0]
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
    print("native predictor output matches python forward; serving ok")


if __name__ == "__main__":
    main()
