"""Serving fleet tests: circuit breaker, prefix-affinity routing,
failover, quotas/SLO, TCPStore membership, and token-identical
cross-replica retry (ISSUE 6 tentpole).

Stub replicas cover the router's control plane without compiles; one
real two-engine fleet at the end pins the exactness property the whole
failover story rests on (same weights + seed + nonce → same stream)."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.inference.llm import AdmissionShed, RequestCancelled
from paddle_tpu.serving import (CircuitBreaker, LocalReplica,
                                ReplicaUnavailable, Router, SLOClass,
                                TenantQuota)
from paddle_tpu.serving.router import affinity_key, rendezvous_pick


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_trips_after_threshold_and_recovers():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=3, open_for=5.0,
                       half_open_probes=1, clock=lambda: t[0])
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"          # under threshold
    b.record_failure()
    assert b.state == "open" and not b.allow()
    t[0] = 4.9
    assert not b.allow()                # cooldown not over
    t[0] = 5.0
    assert b.state == "half_open"
    assert b.allow()                    # the single probe
    assert not b.allow()                # probe budget spent
    b.record_success()
    assert b.state == "closed" and b.allow()
    assert b.n_opens == 1


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=1, open_for=2.0,
                       clock=lambda: t[0])
    b.record_failure()
    t[0] = 2.5
    assert b.allow()
    b.record_failure()                  # probe failed
    assert b.state == "open"
    t[0] = 4.0                          # 1.5s into the NEW cooldown
    assert not b.allow()
    t[0] = 4.6
    assert b.allow()
    b.record_success()
    assert b.state == "closed"
    assert b.n_opens == 2


def test_breaker_success_resets_failure_streak():
    b = CircuitBreaker(fail_threshold=2)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == "closed"          # streak broken, never tripped


def test_breaker_reset_forces_closed():
    b = CircuitBreaker(fail_threshold=1, open_for=1e9)
    b.record_failure()
    assert b.state == "open"
    b.reset()
    assert b.state == "closed" and b.allow()


# ---------------------------------------------------------------------------
# affinity key + rendezvous hashing
# ---------------------------------------------------------------------------


def test_affinity_key_commits_to_prefix_not_tail():
    prefix = list(range(32))            # 2 full pages at page_size 16
    k1 = affinity_key(prefix + [1, 2, 3], 16, 2)
    k2 = affinity_key(prefix + list(range(40, 90)), 16, 2)
    assert k1 == k2                     # same first-2-pages family
    k3 = affinity_key([7] + prefix[1:] + [1, 2, 3], 16, 2)
    assert k3 != k1                     # different history → new family


def test_affinity_key_short_prompt_hashes_tokens():
    assert affinity_key([1, 2, 3], 16, 2) == \
        affinity_key([1, 2, 3], 16, 2)
    assert affinity_key([1, 2, 3], 16, 2) != \
        affinity_key([1, 2, 4], 16, 2)


def test_rendezvous_stability_under_membership_churn():
    names = ["r0", "r1", "r2", "r3"]
    rng = np.random.RandomState(0)
    keys = [bytes(rng.bytes(16)) for _ in range(64)]
    before = {k: rendezvous_pick(k, names) for k in keys}
    gone = "r2"
    after = {k: rendezvous_pick(k, [n for n in names if n != gone])
             for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # ONLY keys that preferred the removed name remap
    assert all(before[k] == gone for k in moved)
    assert any(before[k] == gone for k in keys)


def test_rendezvous_spreads_keys():
    names = ["r0", "r1", "r2"]
    rng = np.random.RandomState(1)
    picks = {rendezvous_pick(bytes(rng.bytes(16)), names)
             for _ in range(64)}
    assert picks == set(names)


# ---------------------------------------------------------------------------
# router over stub replicas (no compiles)
# ---------------------------------------------------------------------------


class StubReplica:
    """Scriptable replica: fail the first ``fail_n`` submits, shed
    while ``drain`` is set, else echo. Records every submit kwargs."""

    def __init__(self, fail_n=0, drain=False, block=None,
                 healthy=True):
        self.fail_n = fail_n
        self.drain = drain
        self.block = block              # threading.Event to wait on
        self.healthy = healthy
        self.calls = []
        self._mu = threading.Lock()

    def submit(self, prompt_ids, **kw):
        with self._mu:
            self.calls.append(dict(kw, prompt_ids=list(prompt_ids)))
            if self.fail_n > 0:
                self.fail_n -= 1
                raise ReplicaUnavailable("injected crash")
        if self.drain:
            raise AdmissionShed("draining", reason="draining")
        if self.block is not None:
            assert self.block.wait(timeout=30)
        return {"output_ids": [1] * kw.get("max_new_tokens", 1),
                "prompt_ids": list(prompt_ids)}

    def health(self):
        if not self.healthy:
            return None
        return "draining" if self.drain else "healthy"

    def cancel(self, request_id):
        return False

    def close(self):
        pass


def mk_router(replicas, **kw):
    kw.setdefault("health_poll_interval", 0.05)
    kw.setdefault("breaker_open_for", 0.2)
    return Router(replicas, **kw)


def prompt_for(target, names, length=6, seed=0):
    """A prompt whose affinity preference is ``target`` (rejection-
    sampled, deterministic) — stub tests that script one replica's
    behavior need traffic that actually prefers it."""
    rng = np.random.RandomState(seed)
    while True:
        p = rng.randint(0, 97, length).tolist()
        if rendezvous_pick(affinity_key(p, 16, 2), names) == target:
            return p


def test_router_routes_and_pins_unique_nonces():
    stubs = {f"r{i}": StubReplica() for i in range(3)}
    with mk_router(stubs) as r:
        outs = [r.submit([i, 50 + i, 90 - i], max_new_tokens=3)
                .result(timeout=30) for i in range(9)]
    assert all(o["output_ids"] == [1, 1, 1] for o in outs)
    assert all(o["replica"] in stubs and o["failovers"] == 0
               for o in outs)
    nonces = [kw["nonce"] for s in stubs.values() for kw in s.calls]
    assert len(nonces) == 9 and len(set(nonces)) == 9


def test_router_same_prefix_colocates():
    stubs = {f"r{i}": StubReplica() for i in range(3)}
    prefix = list(range(40))
    with mk_router(stubs) as r:
        outs = [r.submit(prefix + [100 + i], max_new_tokens=1)
                .result(timeout=30) for i in range(6)]
    assert len({o["replica"] for o in outs}) == 1


def test_router_failover_within_budget_same_nonce():
    flaky = StubReplica(fail_n=1)
    backup = StubReplica()
    with mk_router({"a": flaky, "b": backup},
                   failover_budget=2) as r:
        out = r.submit(prompt_for("a", ("a", "b")),
                       max_new_tokens=2).result(timeout=30)
        assert out["output_ids"] == [1, 1]
        assert out["failovers"] == 1
        assert r.n_failovers == 1
    # the re-submission carried the SAME nonce — token identity's
    # control-plane half
    failed = flaky.calls[0]["nonce"]
    assert any(kw["nonce"] == failed for kw in backup.calls)


def test_router_failover_budget_exhaustion_is_typed():
    stubs = {f"r{i}": StubReplica(fail_n=99) for i in range(3)}
    with mk_router(stubs, failover_budget=1) as r:
        fut = r.submit([1, 2, 3])
        with pytest.raises(ReplicaUnavailable):
            fut.result(timeout=30)


def test_router_draining_rebalance_and_no_new_admissions():
    draining = StubReplica(drain=True)
    ok = StubReplica()
    with mk_router({"a": draining, "b": ok}) as r:
        outs = [r.submit([i, i, i]).result(timeout=30)
                for i in range(4)]
        assert all(o["replica"] == "b" for o in outs)
        # draining never consumed failover budget
        assert r.n_failovers == 0 and r.n_rebalanced >= 1
        first_wave = len(draining.calls)
        time.sleep(0.15)                # > one poll interval
        for i in range(4):
            r.submit([9, i, 9]).result(timeout=30)
        assert len(draining.calls) == first_wave, (
            "a draining replica received new admissions")


def test_router_all_unroutable_sheds_typed():
    with mk_router({"a": StubReplica(drain=True),
                    "b": StubReplica(drain=True)}) as r:
        fut = r.submit([1, 2])
        with pytest.raises(AdmissionShed) as ei:
            fut.result(timeout=30)
    assert ei.value.reason in ("draining", "queue_full")


def test_router_tenant_quota_and_slo_mapping():
    gate = threading.Event()
    stub = StubReplica(block=gate)
    slos = {"interactive": SLOClass("interactive", deadline_s=30.0,
                                    priority=5)}
    tenants = {"acme": TenantQuota(max_inflight=1, slo="interactive")}
    with mk_router({"a": stub}, slo_classes=slos,
                   tenants=tenants) as r:
        f1 = r.submit([1, 2, 3], tenant="acme")
        # wait until the first request is ON the replica
        deadline = time.time() + 10
        while not stub.calls and time.time() < deadline:
            time.sleep(0.01)
        f2 = r.submit([4, 5, 6], tenant="acme")   # over quota
        with pytest.raises(AdmissionShed):
            f2.result(timeout=30)
        gate.set()
        assert f1.result(timeout=30)["output_ids"]
        # SLO class mapped onto the engine's machinery
        kw = stub.calls[0]
        assert kw["priority"] == 5
        assert kw["deadline_s"] is not None and kw["deadline_s"] <= 30.0
        # quota slot released → next request admitted
        assert r.submit([7, 8], tenant="acme").result(timeout=30)


def test_router_cancel_between_attempts():
    gate = threading.Event()
    stub = StubReplica(block=gate)
    with mk_router({"a": stub}, max_workers=1) as r:
        f1 = r.submit([1, 2, 3])        # occupies the only worker
        deadline = time.time() + 10
        while not stub.calls and time.time() < deadline:
            time.sleep(0.01)
        f2 = r.submit([4, 5, 6])        # queued behind f1
        assert r.cancel(f2.request_id)
        gate.set()
        assert f1.result(timeout=30)["output_ids"]
        with pytest.raises(RequestCancelled):
            f2.result(timeout=30)
        assert not r.cancel(f2.request_id)   # already resolved


def test_router_breaker_opens_then_health_probe_recloses():
    stub = StubReplica(fail_n=99, healthy=False)
    backup = StubReplica()
    with mk_router({"a": stub, "b": backup}, failover_budget=2,
                   breaker_fail_threshold=2,
                   breaker_open_for=0.15) as r:
        for i in range(3):
            r.submit(prompt_for("a", ("a", "b"), seed=i)
                     ).result(timeout=30)
        st = r._status()["replicas"]["a"]
        assert st["breaker"] == "open", st
        # replica "recovers": health polls become the half-open probes
        stub.healthy = True
        stub.fail_n = 0
        deadline = time.time() + 10
        while time.time() < deadline:
            if r._status()["replicas"]["a"]["breaker"] == "closed":
                break
            time.sleep(0.02)
        assert r._status()["replicas"]["a"]["breaker"] == "closed"
        assert r._aggregate_health() == "healthy"


def test_router_half_open_probe_settles_on_shed_verdict():
    """A half-open probe that draws a REFUSAL (shed) must settle the
    breaker — a refusal proves the replica is reachable. Regression:
    the probe slot leaked, wedging the breaker half-open forever (no
    traffic, and polls skipped by the spent probe budget)."""
    stub = StubReplica(fail_n=2, healthy=False)
    backup = StubReplica()
    # poll interval long enough that TRAFFIC, not the poller, consumes
    # the half-open probe
    with mk_router({"a": stub, "b": backup}, failover_budget=2,
                   breaker_fail_threshold=2, breaker_open_for=0.1,
                   health_poll_interval=30.0) as r:
        for i in range(2):
            r.submit(prompt_for("a", ("a", "b"), seed=i)
                     ).result(timeout=30)
        assert r._status()["replicas"]["a"]["breaker"] == "open"
        time.sleep(0.15)                # cooldown → half-open
        stub.fail_n = 0
        stub.drain = True               # reachable, but refusing
        r.submit(prompt_for("a", ("a", "b"), seed=7)
                 ).result(timeout=30)   # rebalances to b
        assert r._status()["replicas"]["a"]["breaker"] == "closed", (
            "shed probe wedged the breaker: "
            f"{r._status()['replicas']['a']}")


def test_router_engine_closed_rebalances_budget_free():
    """A replica whose engine is shutting down answers EngineClosed;
    the router must treat it like draining (rebalance, no failover
    budget, no client error)."""
    from paddle_tpu.inference.llm import EngineClosed

    class ClosingStub(StubReplica):
        def submit(self, prompt_ids, **kw):
            raise EngineClosed("engine closed")

    with mk_router({"a": ClosingStub(), "b": StubReplica()},
                   failover_budget=0) as r:
        out = r.submit(prompt_for("a", ("a", "b"))).result(timeout=30)
        assert out["replica"] == "b" and out["failovers"] == 0
        assert r.n_rebalanced >= 1
        assert r._status()["replicas"]["a"]["health"] == "draining"


def test_router_reset_breakers_via_http():
    import json
    from urllib.request import Request, urlopen
    from paddle_tpu.observability.server import DebugServer
    stub = StubReplica(fail_n=99, healthy=False)
    with mk_router({"a": stub}, breaker_fail_threshold=1,
                   breaker_open_for=1e9) as r:
        with pytest.raises(Exception):
            r.submit([1]).result(timeout=30)
        assert r._status()["replicas"]["a"]["breaker"] == "open"
        # the replica "recovers" BEFORE the operator reset, so the
        # health poller can't immediately re-trip the breaker
        stub.healthy = True
        stub.fail_n = 0
        with DebugServer(port=0) as srv:
            req = Request(f"http://127.0.0.1:{srv.port}/reset_health",
                          data=b"{}")
            with urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
        assert any(n.startswith("router") for n in body["reset"])
        assert r._status()["replicas"]["a"]["breaker"] == "closed"


def test_reset_health_404_when_nothing_registered(monkeypatch):
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen
    from paddle_tpu.observability import server as dbgsrv
    monkeypatch.setattr(dbgsrv, "_reset_handlers", {})
    with dbgsrv.DebugServer(port=0) as srv:
        req = Request(f"http://127.0.0.1:{srv.port}/reset_health",
                      data=b"{}")
        with pytest.raises(HTTPError) as ei:
            urlopen(req, timeout=10)
        assert ei.value.code == 404


# ---------------------------------------------------------------------------
# TCPStore membership
# ---------------------------------------------------------------------------


def test_membership_roster_and_staleness():
    from paddle_tpu.distributed.tcp_store import (TCPMembership,
                                                  TCPStoreClient,
                                                  TCPStoreServer)
    srv = TCPStoreServer("127.0.0.1", 0)
    try:
        endpoint = f"127.0.0.1:{srv.port}"
        client = TCPStoreClient(endpoint)
        m1 = TCPMembership(endpoint, "r0", {"generate": "u0"},
                           beat_interval=0.05)
        m2 = TCPMembership(endpoint, "r1", {"generate": "u1"},
                           beat_interval=0.05)
        roster = TCPMembership.list_members(client, stale_after=1.0)
        assert set(roster) == {"r0", "r1"}
        assert roster["r0"]["generate"] == "u0"
        m2.stop()                       # stops heartbeating
        time.sleep(0.4)
        roster = TCPMembership.list_members(client, stale_after=0.2)
        assert set(roster) == {"r0"}, roster
        # re-registration under the same name replaces the info
        m2b = TCPMembership(endpoint, "r1", {"generate": "u1-new"},
                            beat_interval=0.05)
        roster = TCPMembership.list_members(client, stale_after=1.0)
        assert roster["r1"]["generate"] == "u1-new"
        m1.stop()
        m2b.stop()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# real engines: the exactness property failover rests on
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_pair():
    from paddle_tpu.serving.replica import make_engine_from_spec
    spec = {"vocab": 97, "layers": 2, "hidden": 64}
    engines = [make_engine_from_spec(spec) for _ in range(2)]
    yield engines
    for e in engines:
        e.close()


class FlakyOnce:
    """LocalReplica that dies on its first submit — the in-process
    stand-in for a replica crash mid-request."""

    def __init__(self, inner):
        self.inner = inner
        self.tripped = False

    def submit(self, *a, **kw):
        if not self.tripped:
            self.tripped = True
            raise ReplicaUnavailable("simulated crash")
        return self.inner.submit(*a, **kw)

    def health(self):
        return self.inner.health()

    def cancel(self, rid):
        return self.inner.cancel(rid)

    def close(self):
        pass


def test_failover_is_token_identical_across_real_replicas(fleet_pair):
    engA, engB = fleet_pair
    flaky = FlakyOnce(LocalReplica(engA))
    prompt = prompt_for("a", ("a", "b"), length=15)
    with mk_router({"a": flaky, "b": LocalReplica(engB)},
                   failover_budget=2) as r:
        # desynchronize replica B's internal nonce counter: identity
        # must come from the PINNED nonce, not from matching counters
        engB.submit([3, 1, 4], max_new_tokens=2,
                    temperature=0.5).result(timeout=120)
        out = r.submit(prompt, max_new_tokens=8,
                       temperature=0.9).result(timeout=120)
    assert out["failovers"] == 1
    # the reference: what a healthy replica produces for this
    # (prompt, nonce) — the failover'd stream must be identical
    ref = engA.submit(prompt, max_new_tokens=8, temperature=0.9,
                      nonce=out["request_id"]).result(timeout=120)
    assert ref["output_ids"] == out["output_ids"]


def test_engine_nonce_pinning_is_schedule_independent(fleet_pair):
    engA, engB = fleet_pair
    prompt = list(range(30, 42))
    a = engA.submit(prompt, max_new_tokens=6, temperature=0.8,
                    nonce=12345).result(timeout=120)
    for i in range(3):                  # different scheduler history
        engB.submit([i, i + 1], max_new_tokens=2,
                    temperature=0.3).result(timeout=120)
    b = engB.submit(prompt, max_new_tokens=6, temperature=0.8,
                    nonce=12345).result(timeout=120)
    assert a["output_ids"] == b["output_ids"]


def test_engine_rejects_out_of_range_nonce(fleet_pair):
    engA, _ = fleet_pair
    with pytest.raises(ValueError):
        engA.submit([1, 2], nonce=2 ** 31)
    with pytest.raises(ValueError):
        engA.submit([1, 2], nonce=-1)
