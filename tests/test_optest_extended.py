"""OpSpec numeric sweep, part 2 (VERDICT r3 item 6): the conv / pool /
pad / vision-functional / norm / indexing / linalg families — the r3
coverage fills and alias targets that were previously "resolved" but
not NumPy-reference-checked, now in the same declarative table as
tests/test_optest.py, multi-shape for the headline ops.

References are written from the op DEFINITIONS (reference unittests:
test_conv2d_op.py, test_pool2d_op.py, test_pad3d_op.py,
test_grid_sampler_op.py, test_pixel_shuffle.py, test_norm_all.py ...),
as loops/np formulas — independent of the implementation under test."""

import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as pt
import paddle_tpu.tensor as T
from paddle_tpu import linalg
from paddle_tpu.nn import functional as F
from paddle_tpu.vision import ops as vops
from paddle_tpu.testing import OpSpec, arr, run_spec

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


# ---------------------------------------------------------------------------
# NumPy references (dimension-generic loops; shapes are tiny)
# ---------------------------------------------------------------------------

def _tup(v, nd):
    return (v,) * nd if np.isscalar(v) else tuple(v)


def _np_conv(x, w, stride=1, pad=0, groups=1):
    """x (N,Cin,*S), w (Cout,Cin/g,*K) → (N,Cout,*O)."""
    nd = x.ndim - 2
    stride, pad = _tup(stride, nd), _tup(pad, nd)
    xp = np.pad(x, [(0, 0), (0, 0)] + [(p, p) for p in pad])
    K = w.shape[2:]
    O = [(xp.shape[2 + i] - K[i]) // stride[i] + 1 for i in range(nd)]
    N, Cout = x.shape[0], w.shape[0]
    cing = x.shape[1] // groups
    coutg = Cout // groups
    out = np.zeros((N, Cout, *O), np.float64)
    for n in range(N):
        for co in range(Cout):
            g = co // coutg
            for pos in np.ndindex(*O):
                sl = tuple(slice(pos[i] * stride[i],
                                 pos[i] * stride[i] + K[i])
                           for i in range(nd))
                patch = xp[(n, slice(g * cing, (g + 1) * cing)) + sl]
                out[(n, co) + pos] = (patch * w[co]).sum()
    return out.astype(np.float32)


def _np_conv_transpose(x, w, stride=1, pad=0, output_padding=0):
    """x (N,Cin,*S), w (Cin,Cout,*K) → scatter-add transpose conv."""
    nd = x.ndim - 2
    stride, pad = _tup(stride, nd), _tup(pad, nd)
    op = _tup(output_padding, nd)
    K = w.shape[2:]
    O = [(x.shape[2 + i] - 1) * stride[i] - 2 * pad[i] + K[i] + op[i]
         for i in range(nd)]
    N, Cin, Cout = x.shape[0], x.shape[1], w.shape[1]
    out = np.zeros((N, Cout, *O), np.float64)
    for n in range(N):
        for ci in range(Cin):
            for pos in np.ndindex(*x.shape[2:]):
                for kpos in np.ndindex(*K):
                    o = tuple(pos[i] * stride[i] + kpos[i] - pad[i]
                              for i in range(nd))
                    if all(0 <= o[i] < O[i] for i in range(nd)):
                        out[(n, slice(None)) + o] += \
                            x[(n, ci) + pos] * w[(ci, slice(None)) + kpos]
    return out.astype(np.float32)


def _np_pool(x, k, stride=None, pad=0, mode="avg",
             count_include_pad=True):
    nd = x.ndim - 2
    k = _tup(k, nd)
    stride = _tup(stride if stride is not None else k, nd)
    pad = _tup(pad, nd)
    if mode == "avg":
        fill = 0.0
    else:
        fill = -np.inf
    xp = np.pad(x, [(0, 0), (0, 0)] + [(p, p) for p in pad],
                constant_values=fill)
    O = [(xp.shape[2 + i] - k[i]) // stride[i] + 1 for i in range(nd)]
    out = np.zeros((*x.shape[:2], *O), np.float64)
    for n in range(x.shape[0]):
        for c in range(x.shape[1]):
            for pos in np.ndindex(*O):
                sl = tuple(slice(pos[i] * stride[i],
                                 pos[i] * stride[i] + k[i])
                           for i in range(nd))
                win = xp[(n, c) + sl]
                if mode == "max":
                    out[(n, c) + pos] = win.max()
                elif count_include_pad:
                    out[(n, c) + pos] = win.mean()
                else:
                    finite = win[np.isfinite(win)]
                    # zeros-padded avg windows excluding pad counts
                    lo = tuple(pos[i] * stride[i] for i in range(nd))
                    cnt = 1
                    for i in range(nd):
                        a = max(lo[i], pad[i])
                        b = min(lo[i] + k[i], pad[i] + x.shape[2 + i])
                        cnt *= max(0, b - a)
                    out[(n, c) + pos] = win.sum() / cnt
                    del finite
    return out.astype(np.float32)


def _np_adaptive_pool(x, out_size, mode="avg"):
    nd = x.ndim - 2
    out_size = _tup(out_size, nd)
    out = np.zeros((*x.shape[:2], *out_size), np.float64)
    for n in range(x.shape[0]):
        for c in range(x.shape[1]):
            for pos in np.ndindex(*out_size):
                sl = []
                for i in range(nd):
                    L = x.shape[2 + i]
                    a = (pos[i] * L) // out_size[i]
                    b = -(-((pos[i] + 1) * L) // out_size[i])
                    sl.append(slice(a, b))
                win = x[(n, c) + tuple(sl)]
                out[(n, c) + pos] = win.max() if mode == "max" \
                    else win.mean()
    return out.astype(np.float32)


def _np_maxout(x, groups, axis=1):
    # paddle semantics: C → C/groups, out[...,c,...] = max over the
    # `groups` consecutive channels of block c
    sh = list(x.shape)
    co = sh[axis] // groups
    resh = sh[:axis] + [co, groups] + sh[axis + 1:]
    return x.reshape(resh).max(axis=axis + 1)


def _np_grid_sample(x, grid, mode="bilinear", align_corners=True):
    """zeros padding; grid (N,Ho,Wo,2) with (gx, gy) in [-1,1]."""
    N, C, H, W = x.shape
    _, Ho, Wo, _ = grid.shape
    out = np.zeros((N, C, Ho, Wo), np.float64)

    def unnorm(g, L):
        if align_corners:
            return (g + 1) / 2 * (L - 1)
        return ((g + 1) * L - 1) / 2

    def at(n, c, iy, ix):
        if 0 <= iy < H and 0 <= ix < W:
            return x[n, c, iy, ix]
        return 0.0

    for n in range(N):
        for ho in range(Ho):
            for wo in range(Wo):
                gx, gy = grid[n, ho, wo]
                fx, fy = unnorm(gx, W), unnorm(gy, H)
                if mode == "nearest":
                    ix, iy = int(np.round(fx)), int(np.round(fy))
                    for c in range(C):
                        out[n, c, ho, wo] = at(n, c, iy, ix)
                    continue
                x0, y0 = int(np.floor(fx)), int(np.floor(fy))
                tx, ty = fx - x0, fy - y0
                for c in range(C):
                    out[n, c, ho, wo] = (
                        at(n, c, y0, x0) * (1 - tx) * (1 - ty) +
                        at(n, c, y0, x0 + 1) * tx * (1 - ty) +
                        at(n, c, y0 + 1, x0) * (1 - tx) * ty +
                        at(n, c, y0 + 1, x0 + 1) * tx * ty)
    return out.astype(np.float32)


def _np_affine_grid(theta, out_shape, align_corners=True):
    N, _, H, W = out_shape
    if align_corners:
        xs = np.linspace(-1, 1, W)
        ys = np.linspace(-1, 1, H)
    else:
        xs = (np.arange(W) * 2 + 1) / W - 1
        ys = (np.arange(H) * 2 + 1) / H - 1
    base = np.stack(
        [np.tile(xs, (H, 1)),
         np.tile(ys[:, None], (1, W)),
         np.ones((H, W))], -1)          # (H,W,3)
    out = np.einsum("hwk,nik->nhwi", base, theta)
    return out.astype(np.float32)


def _np_pixel_shuffle(x, r):
    N, C, H, W = x.shape
    c = C // (r * r)
    y = x.reshape(N, c, r, r, H, W)
    return y.transpose(0, 1, 4, 2, 5, 3).reshape(N, c, H * r, W * r)


def _np_pixel_unshuffle(x, r):
    N, C, H, W = x.shape
    h, w = H // r, W // r
    y = x.reshape(N, C, h, r, w, r)
    return y.transpose(0, 1, 3, 5, 2, 4).reshape(N, C * r * r, h, w)


def _np_channel_shuffle(x, g):
    N, C, H, W = x.shape
    return x.reshape(N, g, C // g, H, W).transpose(0, 2, 1, 3, 4) \
        .reshape(N, C, H, W)


def _np_interp_nearest(x, size):
    N, C, H, W = x.shape
    Ho, Wo = size
    iy = (np.arange(Ho) * H // Ho)
    ix = (np.arange(Wo) * W // Wo)
    return x[:, :, iy][:, :, :, ix]


def _np_interp_bilinear_ac(x, size):
    """align_corners=True separable linear interpolation."""
    N, C, H, W = x.shape
    Ho, Wo = size
    fy = np.linspace(0, H - 1, Ho)
    fx = np.linspace(0, W - 1, Wo)
    y0 = np.floor(fy).astype(int)
    y1 = np.minimum(y0 + 1, H - 1)
    ty = fy - y0
    x0 = np.floor(fx).astype(int)
    x1 = np.minimum(x0 + 1, W - 1)
    tx = fx - x0
    a = x[:, :, y0] * (1 - ty)[None, None, :, None] + \
        x[:, :, y1] * ty[None, None, :, None]
    return (a[:, :, :, x0] * (1 - tx) + a[:, :, :, x1] * tx) \
        .astype(np.float32)


def _np_temporal_shift(x, seg_num, shift_ratio=0.25):
    NT, C, H, W = x.shape
    N = NT // seg_num
    v = x.reshape(N, seg_num, C, H, W)
    fold = int(C * shift_ratio)
    out = np.zeros_like(v)
    # paddle kernel: first fold shifts from t-1 (zero at t=0), second
    # fold from t+1 (zero at t=T-1), rest pass through
    out[:, 1:, :fold] = v[:, :-1, :fold]
    out[:, :-1, fold:2 * fold] = v[:, 1:, fold:2 * fold]
    out[:, :, 2 * fold:] = v[:, :, 2 * fold:]
    return out.reshape(NT, C, H, W)


def _np_lrn(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    N, C, H, W = x.shape
    sq = x ** 2
    out = np.zeros_like(x)
    half = size // 2
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c + half + size % 2)
        s = sq[:, lo:hi].sum(1)
        out[:, c] = x[:, c] / (k + alpha / size * s) ** beta
    return out


def _np_group_norm(x, groups, eps=1e-5):
    N, C = x.shape[:2]
    v = x.reshape(N, groups, -1)
    m = v.mean(-1, keepdims=True)
    var = v.var(-1, keepdims=True)
    return ((v - m) / np.sqrt(var + eps)).reshape(x.shape)


def _np_unfold(x, k, stride=1, pad=0):
    """im2col: (N,C,H,W) → (N, C*kh*kw, L) column order matching the
    reference's im2col (C-major, then kh, kw)."""
    N, C, H, W = x.shape
    kh, kw = _tup(k, 2)
    sh, sw = _tup(stride, 2)
    ph, pw = _tup(pad, 2)
    xp = np.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    cols = np.zeros((N, C * kh * kw, Ho * Wo), x.dtype)
    for n in range(N):
        idx = 0
        for c in range(C):
            for i in range(kh):
                for j in range(kw):
                    patch = xp[n, c, i:i + Ho * sh:sh, j:j + Wo * sw:sw]
                    cols[n, idx] = patch.reshape(-1)
                    idx += 1
    return cols


def _np_renorm(x, p, axis, max_norm):
    out = x.copy()
    x_m = np.moveaxis(x, axis, 0)
    o_m = np.moveaxis(out, axis, 0)
    for i in range(x_m.shape[0]):
        n = (np.abs(x_m[i]) ** p).sum() ** (1.0 / p)
        if n > max_norm:
            o_m[i] = x_m[i] * (max_norm / n)
    return out


def _np_ctc_loss(log_probs, labels, blank=0):
    """Forward-algorithm CTC negative log likelihood for ONE sequence.
    log_probs (t=T, C) log-softmaxed; labels (L,)."""
    Tn, _ = log_probs.shape
    ext = [blank]
    for l in labels:
        ext += [int(l), blank]
    S = len(ext)
    alpha = np.full((Tn, S), -np.inf)
    alpha[0, 0] = log_probs[0, blank]
    if S > 1:
        alpha[0, 1] = log_probs[0, ext[1]]
    for t in range(1, Tn):
        for s in range(S):
            cands = [alpha[t - 1, s]]
            if s >= 1:
                cands.append(alpha[t - 1, s - 1])
            if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                cands.append(alpha[t - 1, s - 2])
            alpha[t, s] = sps.logsumexp(cands) + log_probs[t, ext[s]]
    return -sps.logsumexp([alpha[-1, -1], alpha[-1, -2]])


# ---------------------------------------------------------------------------
# inputs
# ---------------------------------------------------------------------------

_X1 = arr((2, 3, 8), seed=40)                 # N,C,L
_W1 = arr((4, 3, 3), seed=41, low=-0.5, high=0.5)
_X2 = arr((2, 3, 6, 7), seed=42)              # N,C,H,W
_W2 = arr((4, 3, 3, 3), seed=43, low=-0.5, high=0.5)
_X3 = arr((1, 2, 4, 5, 4), seed=44)           # N,C,D,H,W
_W3 = arr((3, 2, 2, 3, 2), seed=45, low=-0.5, high=0.5)
_WT1 = arr((3, 4, 3), seed=46, low=-0.5, high=0.5)   # Cin,Cout,K
_WT2 = arr((3, 4, 3, 3), seed=47, low=-0.5, high=0.5)
_WT3 = arr((2, 3, 2, 2, 2), seed=48, low=-0.5, high=0.5)
_G1 = arr((2, 5, 6, 2), seed=49, low=-0.95, high=0.95)   # grid
_G2 = arr((1, 3, 3, 2), seed=50, low=-0.95, high=0.95)
_TH = arr((2, 2, 3), seed=51)                # affine theta
_SQ = np.eye(4, dtype=np.float32) * 2 + 0.3 * arr((4, 4), seed=52)
_SPD = (_SQ @ _SQ.T + np.eye(4, dtype=np.float32)).astype(np.float32)
_M64 = arr((4, 6), seed=53)

SPECS = [
    # -- conv family (test_conv{1,2,3}d_op.py) --------------------------
    OpSpec("conv1d", F.conv1d, _np_conv, (_X1, _W1), grad_wrt=(0, 1)),
    OpSpec("conv1d.s2p1", F.conv1d,
           lambda x, w: _np_conv(x, w, stride=2, pad=1), (_X1, _W1),
           kwargs=dict(stride=2, padding=1), grad_wrt=(0, 1)),
    OpSpec("conv2d", F.conv2d, _np_conv, (_X2, _W2), grad_wrt=(0, 1)),
    OpSpec("conv2d.s2p1", F.conv2d,
           lambda x, w: _np_conv(x, w, stride=2, pad=1), (_X2, _W2),
           kwargs=dict(stride=2, padding=1), grad_wrt=(0, 1)),
    OpSpec("conv2d.groups", F.conv2d,
           lambda x, w: _np_conv(x, w, groups=2),
           (arr((2, 4, 5, 5), seed=54),
            arr((6, 2, 3, 3), seed=55, low=-0.5, high=0.5)),
           kwargs=dict(groups=2), grad_wrt=(0, 1)),
    OpSpec("conv3d", F.conv3d, _np_conv, (_X3, _W3), grad_wrt=(0, 1)),
    OpSpec("conv3d.s2p1", F.conv3d,
           lambda x, w: _np_conv(x, w, stride=2, pad=1), (_X3, _W3),
           kwargs=dict(stride=2, padding=1), grad_wrt=(0, 1)),

    # -- transpose convs (test_conv{2,3}d_transpose_op.py) --------------
    OpSpec("conv1d_transpose", F.conv1d_transpose, _np_conv_transpose,
           (_X1, _WT1), grad_wrt=(0, 1)),
    OpSpec("conv2d_transpose", F.conv2d_transpose, _np_conv_transpose,
           (_X2, _WT2), grad_wrt=(0, 1)),
    OpSpec("conv2d_transpose.s2", F.conv2d_transpose,
           lambda x, w: _np_conv_transpose(x, w, stride=2, pad=1),
           (_X2, _WT2), kwargs=dict(stride=2, padding=1),
           grad_wrt=(0, 1)),
    OpSpec("conv3d_transpose", F.conv3d_transpose, _np_conv_transpose,
           (_X3, _WT3), grad_wrt=(0, 1)),
    OpSpec("conv3d_transpose.s2", F.conv3d_transpose,
           lambda x, w: _np_conv_transpose(x, w, stride=2),
           (arr((1, 2, 3, 3, 3), seed=56), _WT3),
           kwargs=dict(stride=2), grad_wrt=(0, 1)),

    # -- pooling (test_pool{1,2,3}d_op.py, adaptive, maxout) ------------
    OpSpec("avg_pool1d", lambda x: F.avg_pool1d(x, 2),
           lambda x: _np_pool(x, 2), (_X1,)),
    OpSpec("avg_pool2d", lambda x: F.avg_pool2d(x, 2),
           lambda x: _np_pool(x, 2), (_X2,)),
    OpSpec("avg_pool2d.s1p1", lambda x: F.avg_pool2d(
        x, 3, stride=1, padding=1),
        lambda x: _np_pool(x, 3, 1, 1), (_X2,)),
    OpSpec("avg_pool2d.nopad", lambda x: F.avg_pool2d(
        x, 3, stride=1, padding=1, count_include_pad=False),
        lambda x: _np_pool(x, 3, 1, 1, count_include_pad=False),
        (_X2,)),
    OpSpec("avg_pool3d", lambda x: F.avg_pool3d(x, 2),
           lambda x: _np_pool(x, 2), (_X3,)),
    OpSpec("max_pool1d", lambda x: F.max_pool1d(x, 2),
           lambda x: _np_pool(x, 2, mode="max"), (_X1,)),
    OpSpec("max_pool2d", lambda x: F.max_pool2d(x, 2),
           lambda x: _np_pool(x, 2, mode="max"), (_X2,)),
    OpSpec("max_pool2d.s1", lambda x: F.max_pool2d(x, 3, stride=1),
           lambda x: _np_pool(x, 3, 1, mode="max"), (_X2,)),
    OpSpec("max_pool3d", lambda x: F.max_pool3d(x, 2),
           lambda x: _np_pool(x, 2, mode="max"), (_X3,)),
    # adaptive pools: output_size must divide the input length (the
    # recorded static-shape TPU constraint, nn/functional.py)
    OpSpec("adaptive_avg_pool1d", lambda x: F.adaptive_avg_pool1d(x, 4),
           lambda x: _np_adaptive_pool(x, 4), (_X1,)),
    OpSpec("adaptive_avg_pool2d", lambda x: F.adaptive_avg_pool2d(
        x, (3, 7)), lambda x: _np_adaptive_pool(x, (3, 7)), (_X2,)),
    OpSpec("adaptive_avg_pool3d", lambda x: F.adaptive_avg_pool3d(
        x, (2, 5, 2)), lambda x: _np_adaptive_pool(x, (2, 5, 2)),
        (_X3,)),
    OpSpec("adaptive_max_pool2d", lambda x: F.adaptive_max_pool2d(
        x, (3, 7)), lambda x: _np_adaptive_pool(x, (3, 7), "max"),
        (_X2,)),
    OpSpec("maxout", lambda x: F.maxout(x, 2),
           lambda x: _np_maxout(x, 2), (arr((2, 6, 3, 3), seed=57),)),

    # -- pad family (test_pad3d_op.py; constant/reflect/replicate/
    #    circular over 3D/4D/5D inputs) ---------------------------------
    OpSpec("pad.1d_const", lambda x: F.pad(x, [1, 2], value=0.5,
                                           data_format="NCL"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (1, 2)],
                            constant_values=0.5), (_X1,)),
    OpSpec("pad.2d_reflect", lambda x: F.pad(x, [1, 2, 2, 1],
                                             mode="reflect"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (2, 1), (1, 2)],
                            mode="reflect"), (_X2,)),
    OpSpec("pad.2d_replicate", lambda x: F.pad(x, [1, 2, 2, 1],
                                               mode="replicate"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (2, 1), (1, 2)],
                            mode="edge"), (_X2,)),
    OpSpec("pad.2d_circular", lambda x: F.pad(x, [1, 2, 2, 1],
                                              mode="circular"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (2, 1), (1, 2)],
                            mode="wrap"), (_X2,)),
    OpSpec("pad.3d_const", lambda x: F.pad(x, [1, 1, 2, 0, 0, 2],
                                           value=1.0,
                                           data_format="NCDHW"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (0, 2), (2, 0), (1, 1)],
                            constant_values=1.0), (_X3,)),
    OpSpec("pad.3d_reflect", lambda x: F.pad(x, [1, 1, 2, 1, 1, 2],
                                             mode="reflect",
                                             data_format="NCDHW"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (1, 2), (2, 1), (1, 1)],
                            mode="reflect"), (_X3,)),
    OpSpec("pad.3d_replicate", lambda x: F.pad(x, [1, 1, 2, 1, 1, 2],
                                               mode="replicate",
                                               data_format="NCDHW"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (1, 2), (2, 1), (1, 1)],
                            mode="edge"), (_X3,)),
    OpSpec("pad.3d_circular", lambda x: F.pad(x, [1, 1, 2, 1, 1, 2],
                                              mode="circular",
                                              data_format="NCDHW"),
           lambda x: np.pad(x, [(0, 0), (0, 0), (1, 2), (2, 1), (1, 1)],
                            mode="wrap"), (_X3,)),

    # -- vision functional (test_grid_sampler_op.py, pixel_shuffle,
    #    temporal_shift, interpolate) -----------------------------------
    OpSpec("grid_sample", F.grid_sample, _np_grid_sample,
           (arr((2, 3, 4, 5), seed=58), _G1), grad_wrt=(0, 1),
           grad_rtol=0.1),
    OpSpec("grid_sample.shape2", F.grid_sample, _np_grid_sample,
           (arr((1, 2, 6, 6), seed=59), _G2), grad_wrt=(0, 1),
           grad_rtol=0.1),
    OpSpec("grid_sample.nearest",
           lambda x, g: F.grid_sample(x, g, mode="nearest"),
           lambda x, g: _np_grid_sample(x, g, mode="nearest"),
           (arr((1, 2, 6, 6), seed=60), _G2), grad=False),
    OpSpec("affine_grid", lambda t: F.affine_grid(t, (2, 3, 4, 5)),
           lambda t: _np_affine_grid(t, (2, 3, 4, 5)), (_TH,)),
    OpSpec("affine_grid.nac",
           lambda t: F.affine_grid(t, (2, 3, 3, 6),
                                   align_corners=False),
           lambda t: _np_affine_grid(t, (2, 3, 3, 6),
                                     align_corners=False), (_TH,)),
    OpSpec("pixel_shuffle", lambda x: F.pixel_shuffle(x, 2),
           lambda x: _np_pixel_shuffle(x, 2),
           (arr((2, 8, 3, 4), seed=61),)),
    OpSpec("pixel_unshuffle", lambda x: F.pixel_unshuffle(x, 2),
           lambda x: _np_pixel_unshuffle(x, 2),
           (arr((2, 2, 6, 4), seed=62),)),
    OpSpec("channel_shuffle", lambda x: F.channel_shuffle(x, 3),
           lambda x: _np_channel_shuffle(x, 3),
           (arr((2, 6, 3, 3), seed=63),)),
    OpSpec("interpolate.nearest",
           lambda x: F.interpolate(x, size=(12, 14)),
           lambda x: _np_interp_nearest(x, (12, 14)), (_X2,)),
    OpSpec("interpolate.bilinear",
           lambda x: F.interpolate(x, size=(12, 14), mode="bilinear",
                                   align_corners=True),
           lambda x: _np_interp_bilinear_ac(x, (12, 14)), (_X2,)),
    OpSpec("temporal_shift", lambda x: vops.temporal_shift(x, 2),
           lambda x: _np_temporal_shift(x, 2),
           (arr((4, 4, 3, 3), seed=64),)),
    OpSpec("sequence_mask",
           lambda: F.sequence_mask(np.array([1, 3, 2]), maxlen=4),
           lambda: np.arange(4)[None, :] < np.array([1, 3, 2])[:, None],
           (), grad=False),
    OpSpec("embedding",
           lambda w: F.embedding(np.array([[0, 2], [1, 1]]), w),
           lambda w: w[np.array([[0, 2], [1, 1]])],
           (arr((5, 4), seed=65),)),
    OpSpec("unfold", lambda x: F.unfold(x, 2),
           lambda x: _np_unfold(x, 2), (_X2,)),
    OpSpec("unfold.s2p1", lambda x: F.unfold(x, 3, strides=2,
                                             paddings=1),
           lambda x: _np_unfold(x, 3, 2, 1), (_X2,)),

    # -- norm layers (test_batch_norm_op.py, group_norm, lrn) -----------
    OpSpec("batch_norm.eval",
           lambda x, m, v: F.batch_norm(x, m, v)[0],
           lambda x, m, v: (x - m[None, :, None, None]) /
           np.sqrt(v[None, :, None, None] + 1e-5),
           (_X2, arr((3,), seed=66), arr((3,), seed=67, **dict(
               low=0.5, high=1.5)))),
    OpSpec("instance_norm", F.instance_norm,
           lambda x: (x - x.mean((2, 3), keepdims=True)) /
           np.sqrt(x.var((2, 3), keepdims=True) + 1e-5), (_X2,)),
    OpSpec("group_norm", lambda x: F.group_norm(x, 3),
           lambda x: _np_group_norm(x, 3),
           (arr((2, 6, 3, 4), seed=68),)),
    OpSpec("local_response_norm", F.local_response_norm, _np_lrn,
           (arr((2, 8, 3, 3), seed=69),)),

    # -- indexing / selection -------------------------------------------
    OpSpec("topk", lambda x: T.topk(x, 3, axis=1),
           lambda x: (np.sort(x, 1)[:, ::-1][:, :3],
                      np.argsort(-x, 1, kind="stable")[:, :3]),
           (arr((3, 6), seed=70),), grad=False),
    OpSpec("scatter",
           lambda x: T.scatter(x, np.array([2, 0]),
                               np.zeros((2, 4), np.float32)),
           lambda x: np.stack([np.zeros(4, np.float32), x[1],
                               np.zeros(4, np.float32)]),
           (arr((3, 4), seed=71),)),
    OpSpec("gather_nd",
           lambda x: T.gather_nd(x, np.array([[0, 1], [2, 3]])),
           lambda x: x[[0, 2], [1, 3]], (arr((3, 4), seed=72),)),
    OpSpec("repeat_interleave",
           lambda x: T.repeat_interleave(x, 2, axis=1),
           lambda x: np.repeat(x, 2, axis=1), (arr((2, 3), seed=73),)),
    OpSpec("unbind", lambda x: T.unbind(x, axis=1),
           lambda x: [x[:, i] for i in range(x.shape[1])],
           (arr((2, 3), seed=74),)),
    OpSpec("put_along_axis",
           lambda x: T.put_along_axis(x, np.array([[0, 2]]),
                                      np.array([[9.0, 8.0]],
                                               np.float32), 1),
           lambda x: np.stack([[9.0, x[0, 1], 8.0]]).astype(np.float32),
           (arr((1, 3), seed=75),)),
    OpSpec("index_sample",
           lambda x: T.index_sample(x, np.array([[2, 0], [1, 3]])),
           lambda x: np.take_along_axis(
               x, np.array([[2, 0], [1, 3]]), 1),
           (arr((2, 4), seed=76),)),
    OpSpec("isclose", T.isclose, np.isclose,
           (np.array([1.0, 2.0, np.nan], np.float32),
            np.array([1.0, 2.1, np.nan], np.float32)), grad=False),
    OpSpec("equal_all", T.equal_all,
           lambda x, y: np.asarray(True), (_M64, _M64 * 1.0),
           grad=False),
    OpSpec("nanmean", T.nanmean, np.nanmean,
           (np.array([[1.0, np.nan], [2.0, 4.0]], np.float32),),
           grad=False),
    OpSpec("nansum", T.nansum, np.nansum,
           (np.array([[1.0, np.nan], [2.0, 4.0]], np.float32),),
           grad=False),
    OpSpec("nanmedian", T.nanmedian, np.nanmedian,
           (np.array([1.0, np.nan, 3.0, 2.0], np.float32),),
           grad=False),
    OpSpec("heaviside", T.heaviside, np.heaviside,
           (np.array([-1.0, 0.0, 2.0], np.float32),
            np.array([0.5, 0.5, 0.5], np.float32)), grad=False),
    OpSpec("frac", T.frac, lambda x: x - np.trunc(x),
           (arr((3, 4), seed=77, low=-3, high=3),), grad=False),
    OpSpec("renorm", lambda x: T.renorm(x, 2.0, 0, 1.0),
           lambda x: _np_renorm(x, 2.0, 0, 1.0),
           (arr((3, 4), seed=78, low=-2, high=2),)),

    # -- linalg (test_linalg_*, test_cholesky_op.py ...) ----------------
    OpSpec("cholesky", lambda: pt.linalg.cholesky(_SPD),
           lambda: np.linalg.cholesky(_SPD), (), grad=False),
    OpSpec("det", pt.linalg.det, np.linalg.det, (_SQ,)),
    OpSpec("slogdet", pt.linalg.slogdet,
           lambda x: tuple(np.linalg.slogdet(x)), (_SQ,), grad=False),
    OpSpec("matrix_power", lambda x: pt.linalg.matrix_power(x, 3),
           lambda x: np.linalg.matrix_power(x, 3), (_SQ,)),
    OpSpec("pinv", pt.linalg.pinv, np.linalg.pinv, (_M64,),
           rtol=1e-4, atol=1e-4),
    OpSpec("solve", pt.linalg.solve, np.linalg.solve,
           (_SPD, arr((4,), seed=79))),
    OpSpec("triangular_solve",
           lambda a, b: pt.linalg.triangular_solve(a, b),
           lambda a, b: np.linalg.solve(np.triu(a), b),
           (_SPD + 3 * np.eye(4, dtype=np.float32),
            arr((4, 1), seed=80))),
    OpSpec("matrix_rank", pt.linalg.matrix_rank,
           lambda x: np.asarray(np.linalg.matrix_rank(x)), (_SPD,),
           grad=False),
    OpSpec("cov", pt.linalg.cov, np.cov, (_M64,)),
    OpSpec("corrcoef", pt.linalg.corrcoef, np.corrcoef, (_M64,),
           rtol=1e-4, atol=1e-4),
    # decomposition grads: JAX implements no VJP for wide-matrix QR;
    # reconstruction identity is the forward check
    OpSpec("qr.reconstruct",
           lambda x: (lambda q, r: q @ r)(*pt.linalg.qr(x)),
           lambda x: x, (_M64,), rtol=1e-4, atol=1e-4, grad=False),
    OpSpec("svd.reconstruct",
           lambda x: (lambda u, s, vh: (u * s) @ vh)(
               *pt.linalg.svd(x, full_matrices=False)),
           lambda x: x, (_M64,), rtol=1e-4, atol=1e-4, grad=False),
    OpSpec("eigh.reconstruct",
           lambda x: (lambda w, v: (v * w) @ v.T)(*pt.linalg.eigh(x)),
           lambda x: x, (_SPD,), rtol=1e-4, atol=1e-4, grad=False),
    OpSpec("multi_dot",
           lambda a, b: pt.linalg.multi_dot([a, b]),
           np.matmul, (arr((3, 5), seed=81), arr((5, 4), seed=82)),
           grad_wrt=(0, 1)),

    # -- activation/selection stragglers from the resolved-only list ----
    OpSpec("celu", F.celu,
           lambda x: np.maximum(0, x) + np.minimum(
               0, np.expm1(np.minimum(x, 0))), (_M64,)),
    OpSpec("prelu",
           lambda x, w: F.prelu(x, w),
           lambda x, w: np.where(x >= 0, x, w.reshape(1, -1, 1, 1) * x),
           (arr((2, 3, 4, 4), seed=88),
            arr((3,), seed=89, low=0.1, high=0.5)), grad_wrt=(0, 1)),
    OpSpec("thresholded_relu", F.thresholded_relu,
           lambda x: np.where(x > 1.0, x, 0.0),
           (arr((3, 4), seed=90, low=-2, high=2),)),
    OpSpec("dropout.eval",
           lambda x: F.dropout(x, 0.5, training=False),
           lambda x: x, (_M64,)),
    OpSpec("allclose", T.allclose, np.allclose,
           (np.array([1.0, 2.0], np.float32),
            np.array([1.0, 2.0 + 5e-9], np.float32)), grad=False),
    OpSpec("scatter_nd_add",
           lambda x: T.scatter_nd_add(
               x, np.array([[1], [1], [0]]),
               np.ones((3, 4), np.float32)),
           lambda x: x + np.array([[1.0], [2.0], [0.0]]) *
           np.ones((1, 4), np.float32),
           (arr((3, 4), seed=91),)),
    OpSpec("cholesky_solve",
           lambda b: pt.linalg.cholesky_solve(
               b, np.linalg.cholesky(_SPD)),
           lambda b: np.linalg.solve(_SPD, b),
           (arr((4, 2), seed=92),), rtol=1e-4, atol=1e-4),

    # -- losses ----------------------------------------------------------
    OpSpec("margin_ranking_loss",
           lambda a, b: F.margin_ranking_loss(
               a, b, np.ones((4,), np.float32), margin=0.1),
           lambda a, b: np.maximum(0, -(a - b) + 0.1).mean(),
           (arr((4,), seed=83), arr((4,), seed=84)), grad_wrt=(0, 1)),
    OpSpec("dice_loss",
           lambda p: F.dice_loss(p, np.array([[0], [1], [1]])),
           lambda p: 1 - (2 * p[np.arange(3), [0, 1, 1]].sum()) /
           (p.sum() + 3),
           (np.asarray(sps.softmax(arr((3, 2), seed=85), -1)),),
           rtol=1e-4, atol=1e-4),
    OpSpec("softmax_with_cross_entropy",
           lambda lg: F.softmax_with_cross_entropy(
               lg, np.array([0, 2, 1]), reduction="none"),
           lambda lg: -(lg - sps.logsumexp(lg, -1, keepdims=True))[
               np.arange(3), [0, 2, 1]],
           (arr((3, 4), seed=86),)),
]


def _np_log_softmax(x):
    return x - sps.logsumexp(x, axis=-1, keepdims=True)


_CTC_LOGITS = arr((5, 1, 4), seed=87)   # T,N,C

SPECS.append(OpSpec(
    "ctc_loss",
    lambda lg: F.ctc_loss(lg, np.array([[1, 2]]), np.array([5]),
                          np.array([2]), reduction="none"),
    lambda lg: np.asarray(
        [_np_ctc_loss(_np_log_softmax(lg[:, 0, :]), [1, 2])],
        np.float32),
    (_CTC_LOGITS,), rtol=1e-4, atol=1e-4))


_IDS = []
for s in SPECS:
    n = s.name
    while n in _IDS:
        n += "'"
    _IDS.append(n)


# smoke-tier representative slice for the conv/pool/vision families
# this file owns (see test_optest.py's slice for the core families)
_SMOKE_NAMES = ("conv2d", "max_pool2d", "grid_sample")
_SMOKE_SPECS = [s for s in SPECS if s.name in _SMOKE_NAMES]
assert len(_SMOKE_SPECS) >= 3, "smoke slice silently lost an op"


@pytest.mark.smoke
@pytest.mark.parametrize("spec", _SMOKE_SPECS,
                         ids=[s.name for s in _SMOKE_SPECS])
def test_op_extended_smoke(spec):
    run_spec(spec)


@pytest.mark.parametrize("spec", SPECS, ids=_IDS)
def test_op_extended(spec):
    run_spec(spec)


# bf16 forward sweep over the float-smooth subset (same dimension as
# tests/test_optest.py's)
_BF16_SKIP = {
    "pinv", "qr.reconstruct", "svd.reconstruct", "eigh.reconstruct",
    "cholesky", "det", "slogdet", "matrix_power", "solve",
    "triangular_solve", "cov", "corrcoef", "renorm",  # decompositions /
    # ill-conditioned at bf16 resolution
    "ctc_loss", "dice_loss", "cholesky_solve",
}
_BF16_SPECS = [s for s in SPECS
               if s.grad and s.ref is not None and s.jit
               and s.name not in _BF16_SKIP]
_BF16_IDS = []
for s in _BF16_SPECS:
    n = s.name + "-bf16"
    while n in _BF16_IDS:
        n += "'"
    _BF16_IDS.append(n)


@pytest.mark.parametrize("spec", _BF16_SPECS, ids=_BF16_IDS)
def test_op_extended_bf16(spec):
    from paddle_tpu.testing import check_forward_bf16
    check_forward_bf16(spec)
