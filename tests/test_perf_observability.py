"""Continuous perf observability (ISSUE 11): program cost registry,
live roofline gauges, /perfz surfaces, served-FLOPs attribution, and
fleet MFU federation.

Covers the acceptance criteria:
- /perfz returns live MFU + a step-time breakdown for BOTH a
  ``Model.fit`` run (steps_per_loop>1) and an ``LLMEngine``
  decode-slab run (decode_ticks_per_dispatch>1);
- cost lookups never re-lower (signature-keyed bounded cache in
  cost_model), and a backend with no cost analysis increments
  ``perf_cost_analysis_failures_total`` instead of raising;
- the analytic FLOPs path (``pt.flops`` / the planner formulas) and
  the XLA-counted FLOPs from the cost registry agree within a
  documented tolerance for a transformer block;
- ``fleet_mfu`` reads a down replica as a HOLE (not a zero), and the
  per-tenant served-FLOPs counter survives a nonce-pinned failover
  without double counting.
"""

import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import flags
from paddle_tpu.observability import default_registry
from paddle_tpu.observability import perf
from paddle_tpu.observability import server as debug_server


@pytest.fixture(autouse=True)
def _fresh_perf():
    """Each test gets its own PerfRegistry (the metric registry stays
    process-wide, like every other observability test)."""
    perf.reset()
    perf.enable()
    yield
    perf.reset()
    perf.enable()


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# peak table + overrides
# ---------------------------------------------------------------------------

def test_peak_table_known_kinds():
    assert perf.peak_flops_for("TPU v5 lite") == 197e12
    assert perf.peak_flops_for("TPU v4") == 275e12
    assert perf.peak_flops_for("TPU v6e") == 918e12
    assert perf.peak_flops_for("cpu") is None
    assert perf.peak_flops_for("") is None


def test_detect_peaks_cpu_fallback_and_override():
    spec = perf.detect_peaks("cpu")
    assert spec.source == "cpu-fallback"
    assert spec.flops > 0 and spec.hbm_bytes_per_s > 0
    spec = perf.detect_peaks("TPU v5 lite")
    assert spec.source == "table" and spec.flops == 197e12 \
        and spec.hbm_bytes_per_s == 819e9
    # the override knob for TPU generations the table doesn't know
    flags.set_flags({"perf_peak_flops": 1.23e15,
                     "perf_peak_hbm_gbps": 2000.0})
    try:
        spec = perf.detect_peaks("TPU v9 hypothetical")
        assert spec.source == "override"
        assert spec.flops == 1.23e15
        assert spec.hbm_bytes_per_s == 2000.0 * 1e9
    finally:
        flags.set_flags({"perf_peak_flops": 0.0,
                         "perf_peak_hbm_gbps": 0.0})


def test_bench_peak_delegates_to_one_table():
    import bench
    # CPU backend: bench MFU must read null, not the perf fallback
    assert bench.chip_peak_flops() is None
    flags.set_flags({"perf_peak_flops": 5e13})
    try:
        assert bench.chip_peak_flops() == 5e13
    finally:
        flags.set_flags({"perf_peak_flops": 0.0})


# ---------------------------------------------------------------------------
# cost cache: never re-lowers, failures are counted not raised
# ---------------------------------------------------------------------------

def test_cost_cache_lowers_once_and_caches_failure():
    from paddle_tpu.cost_model import ProgramCostCache
    import jax

    cache = ProgramCostCache()
    calls = {"n": 0}

    def lower():
        calls["n"] += 1
        return jax.jit(lambda x: x @ x).lower(
            jax.ShapeDtypeStruct((16, 16), np.float32))

    a1 = cache.get_or_compute(("k",), lower)
    a2 = cache.get_or_compute(("k",), lower)
    assert calls["n"] == 1, "second lookup re-lowered"
    assert a1 is a2 and a1["flops"] > 0

    boom = {"n": 0}

    def bad():
        boom["n"] += 1
        raise RuntimeError("no analysis on this backend")

    assert cache.get_or_compute(("bad",), bad) is None
    assert cache.get_or_compute(("bad",), bad) is None
    assert boom["n"] == 1, "failure was not cached"


def test_cost_cache_bounded():
    from paddle_tpu.cost_model import ProgramCostCache
    cache = ProgramCostCache(cap=4)
    for i in range(10):
        cache.get_or_compute(("k", i), lambda: (_ for _ in ()).throw(
            RuntimeError("x")))
    assert len(cache) == 4


def test_registry_failure_counter_not_raise():
    reg = perf.instance()
    h = reg.register_program(
        "train", "step", sig=("boom",),
        lower=lambda: (_ for _ in ()).throw(RuntimeError("no backend")))
    h.record(0.01)             # registration already resolved (failed)
    h.record(0.01)
    assert h.cost_failed and not h.cost_resolved
    fam = default_registry().get("perf_cost_analysis_failures_total")
    assert fam is not None and fam.value >= 1
    # payload still renders, the program rides with flops=None
    payload = reg.payload()
    assert payload["cost_failures"] >= 1


def test_program_cap_discipline():
    reg = perf.instance()
    for i in range(perf.PROGRAM_CAP + 10):
        h = reg.register_program("train", "step", sig=(i,))
        if i < perf.PROGRAM_CAP:
            assert h is not None
    assert reg.register_program("train", "step", sig=("over",)) is None
    # existing signatures still resolve to their handle
    assert reg.register_program("train", "step", sig=(0,)) is not None


def test_program_scope_disambiguates_owners():
    """Two engines/models with the same (kind, sig) but different
    networks are different programs: the scope token keeps one
    owner's FLOPs from being read off a sibling's cache entry."""
    reg = perf.instance()
    h1 = reg.register_program("llm", "decode_step", scope="a")
    h2 = reg.register_program("llm", "decode_step", scope="b")
    assert h1 is not h2
    assert reg.register_program("llm", "decode_step", scope="a") is h1
    assert reg.get_program("llm", "decode_step", scope="b") is h2


def test_perfz_payload_never_relowers():
    """Repeated /perfz pulls must not trace again: the lowering thunk
    runs at most once per program (acceptance: lookups never
    re-lower)."""
    import jax
    calls = {"n": 0}

    def lower():
        calls["n"] += 1
        return jax.jit(lambda x: x * 2).lower(
            jax.ShapeDtypeStruct((8,), np.float32))

    reg = perf.instance()
    # a kind no hot path uses: the cost cache is process-wide and
    # survives perf.reset(), so this test must own its key outright
    h = reg.register_program("llm", "relower_probe", lower=lower,
                             scope="test")
    h.record(0.001)
    for _ in range(3):
        reg.payload()
    assert calls["n"] == 1


def _probe_lower(shape=(16, 16)):
    import jax
    return lambda: jax.jit(lambda x: x @ x).lower(
        jax.ShapeDtypeStruct(shape, np.float32))


def test_rates_hold_last_value_while_idle():
    """Documented semantics: an idle process HOLDS its last windowed
    rates instead of decaying to zero — a replica going quiet must
    not drag fleet_mfu down as if its roofline vanished."""
    reg = perf.instance()
    h = reg.register_program("llm", "idle_probe", lower=_probe_lower(),
                             scope="t")
    h.record(0.01)             # cost resolved at registration
    r1 = reg.rates()
    assert r1["mfu"] > 0
    with reg._mu:          # simulate the 60 s window expiring
        reg._buckets.clear()
    assert reg.rates() == r1


def test_failed_cost_busy_time_excluded_from_mfu():
    """A program whose backend reported no cost analysis must not
    enter the MFU denominator as zero-FLOP busy time (documented:
    excluded, visibly — not folded in)."""
    reg = perf.instance()
    good = reg.register_program("llm", "good", lower=_probe_lower(),
                                scope="t")
    good.record(1.0)
    mfu_before = reg.rates()["mfu"]
    assert mfu_before > 0
    bad = reg.register_program(
        "llm", "bad", scope="t",
        lower=lambda: (_ for _ in ()).throw(RuntimeError("none")))
    bad.record(10.0)       # 10x the busy time, zero counted FLOPs
    assert bad.cost_failed
    assert reg.rates()["mfu"] == pytest.approx(mfu_before), \
        "uncosted busy seconds deflated MFU"


def test_compile_attribution_survives_recompile_guard_optout():
    """FLAGS.recompile_warn_threshold=0 opts out of the recompile
    WARNING — perf must still split each signature's first (compiling)
    dispatch out of its MFU accounting via its own freshness
    tracking."""
    flags.set_flags({"recompile_warn_threshold": 0})
    try:
        model = _tiny_model()
        x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 2, (8, 1))
        model.train_batch([x], [y])      # compile
        model.train_batch([x], [y])      # dispatch
        model.train_batch([x], [y])      # dispatch
        progs = [p.to_dict() for p in perf.instance().programs()
                 if p.kind == "step"]
        assert progs and progs[0]["dispatches"] == 2, progs
        ph = perf.instance().breakdown()["train"]["phases"]
        assert ph.get("compile", 0) > 0
    finally:
        flags.set_flags({"recompile_warn_threshold": 8})


def test_discarded_model_releases_registry_entries():
    """A sweep process building a Model per config must not fill
    PROGRAM_CAP with dead entries: GC of an unreferenced Model
    releases its scope (weakref.finalize backstop)."""
    import gc
    model = _tiny_model()
    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (8, 1))
    model.train_batch([x], [y])
    scope = model._perf_scope
    reg = perf.instance()
    assert any(h.scope == scope for h in reg.programs())
    del model
    gc.collect()
    assert not any(h.scope == scope for h in reg.programs()), \
        "collected Model left perf-registry entries behind"


def test_prepare_resets_perf_programs():
    """Re-prepare rebuilds the compiled step (different optimizer →
    different FLOPs): the new program must not accumulate under the
    old program's cached cost entry."""
    model = _tiny_model()
    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (8, 1))
    model.train_batch([x], [y])
    scope1 = model._perf_scope
    assert model._perf_programs
    model.prepare(optimizer=pt.optimizer.SGD(
        learning_rate=0.01, parameters=model.network),
        loss=nn.CrossEntropyLoss())
    assert model._perf_programs == {}
    assert model._perf_scope != scope1


# ---------------------------------------------------------------------------
# Model.fit — live MFU + breakdown over HTTP
# ---------------------------------------------------------------------------

def _tiny_model():
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.CrossEntropyLoss())
    return model


def test_model_fit_perfz_live_mfu_and_breakdown():
    from paddle_tpu.io import TensorDataset
    model = _tiny_model()
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))
    # the metric registry is process-wide (other tests' fit runs share
    # the histogram); the breakdown comparison uses this test's delta
    hist0 = default_registry().get("train_loop_dispatch_seconds")
    hist0_sum = hist0.sum if hist0 is not None else 0.0
    model.fit(TensorDataset([x, y]), batch_size=16, epochs=2,
              verbose=0, steps_per_loop=2)

    srv = debug_server.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        pz = _get_json(base, "/perfz")
        assert pz["enabled"]
        assert pz["mfu"] > 0
        assert pz["flops_per_second"] > 0
        assert pz["peaks"]["flops"] > 0 and pz["peaks"]["source"]
        loops = [p for p in pz["programs"]
                 if p["component"] == "train" and p["kind"] == "loop"]
        assert loops, pz["programs"]
        assert loops[0]["steps_per_dispatch"] == 2
        assert loops[0]["cost_resolved"] and loops[0]["flops"] > 0
        assert loops[0]["dispatches"] > 0
        # breakdown phases reproduce the dispatch histogram (same dt
        # values, compile split out) — "phases sum ≈ step time"
        ph = pz["breakdown"]["train"]["phases"]
        assert ph.get("dispatch", 0) > 0
        hist = default_registry().get("train_loop_dispatch_seconds")
        hist_delta = hist.sum - hist0_sum
        total = ph.get("dispatch", 0.0) + ph.get("compile", 0.0)
        assert hist_delta > 0 and \
            abs(total - hist_delta) / hist_delta < 0.05
        # /statusz carries the summary row; /metrics the gauges
        st = _get_json(base, "/statusz")
        assert st["perf"]["enabled"] and st["perf"]["programs"] >= 1
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        assert "perf_mfu" in text and "perf_flops_per_second" in text
    finally:
        srv.stop()


def test_perf_disabled_records_nothing():
    model = _tiny_model()
    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (8, 1))
    perf.disable()
    try:
        model.train_batch([x], [y])
        model.train_batch([x], [y])
        assert perf.instance().programs() == []
        assert perf.instance().breakdown() == {}
        assert model._perf_programs == {}
    finally:
        perf.enable()


# ---------------------------------------------------------------------------
# LLMEngine decode slab — live MFU, breakdown, served FLOPs
# ---------------------------------------------------------------------------

def _tiny_engine(decode_ticks=4, **kw):
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=128,
                     hidden_dropout=0.0, attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    return LLMEngine(net, max_seqs=4, page_size=8, num_pages=32,
                     max_len=64, prefill_buckets=(8,),
                     decode_ticks_per_dispatch=decode_ticks, **kw)


def test_engine_slab_perfz_and_served_flops():
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, 8).tolist() for _ in range(3)]
    with _tiny_engine(decode_ticks=4) as eng:
        fpt = eng.flops_per_token
        assert fpt > 0
        futs = [eng.submit(p, max_new_tokens=16, tenant="gold")
                for p in prompts]
        outs = [f.result(timeout=240) for f in futs]
        # /perfz while live: close() removes the engine's program
        # entries from the registry (PROGRAM_CAP hygiene)
        pz = perf.perfz_payload()
    assert perf.instance().get_program(
        "llm", "decode_loop", (4,), scope=eng._perf_scope) is None, \
        "closed engine left program entries in the registry"
    # per-request attribution: analytic marginal cost of the computed
    # tokens, returned on the result and counted per tenant
    for o in outs:
        assert o["served_flops"] == fpt * (
            len(o["prompt_ids"]) + len(o["output_ids"]))
    fam = default_registry().get("llm_served_flops_total")
    got = fam.labels("gold").value
    assert got == sum(o["served_flops"] for o in outs)
    assert pz["mfu"] > 0
    slabs = [p for p in pz["programs"]
             if p["component"] == "llm" and p["kind"] == "decode_loop"]
    assert slabs and slabs[0]["sig"] == [4]
    assert slabs[0]["steps_per_dispatch"] == 4
    assert any(p["cost_resolved"] and p["flops"] > 0 for p in slabs)
    ph = pz["breakdown"]["llm"]["phases"]
    assert ph.get("decode", 0) > 0


def test_engine_perf_disabled_one_flag_check():
    rng = np.random.RandomState(0)
    perf.disable()
    try:
        with _tiny_engine(decode_ticks=4) as eng:
            eng.generate([rng.randint(0, 97, 8).tolist()],
                         max_new_tokens=8)
            assert eng._perf_programs == {}
        assert perf.instance().programs() == []
    finally:
        perf.enable()


def test_warming_process_exports_no_perf_gauges():
    """A registry that has never completed costed work must not SET
    the perf gauges: a warming replica's /metrics prescrape would
    otherwise export perf_mfu=0.0 and drag the fleet_mfu mean down —
    it must stay a hole (absent family) until real work lands."""
    reg = default_registry()
    reg.gauge("perf_mfu", "").set(0.7)   # value from earlier real work
    r = perf.instance().update_gauges()  # fresh registry, no work yet
    assert r["mfu"] == 0.0
    assert reg.get("perf_mfu").value == 0.7, \
        "never-worked registry stomped the gauge with 0.0"


def test_perf_attribute_idle_gap_consumes_chunk_count():
    """A 'p' record drained across an idle gap (unmeasurable interval)
    must still CONSUME the pending chunk-dispatch count and the
    compile-skip marker — carrying either into a later record would
    credit FLOPs to an interval that never covered them."""
    import time as _time
    with _tiny_engine(decode_ticks=1) as eng:
        eng._perf_chunks_unattributed = 3
        eng._last_fetch_t = None
        eng._perf_attribute("p", 0, 1)
        assert eng._perf_chunks_unattributed == 0
        assert ("prefill_chunk",) in eng._perf_skipped
        h = perf.instance().register_program(
            "llm", "prefill_chunk", lower=_probe_lower(),
            scope=eng._perf_scope)
        eng._perf_programs[("prefill_chunk",)] = h
        eng._perf_chunks_unattributed = 2
        eng._last_fetch_t = _time.monotonic() - 0.01
        eng._perf_attribute("p", 0, 1)
        assert h.dispatches == 2, \
            "measured interval must scale by ITS chunk count only"


def test_served_flops_excludes_cached_prefix_tokens():
    """The cost denominator charges COMPUTED tokens: a prefix-cache
    hit serves pages without recomputing them, and the second
    request's served_flops must be lower by exactly the reused
    tokens."""
    rng = np.random.RandomState(0)
    prefix = rng.randint(0, 97, 16).tolist()
    p1 = prefix + rng.randint(0, 97, 8).tolist()
    p2 = prefix + rng.randint(0, 97, 8).tolist()
    with _tiny_engine(decode_ticks=1, prefix_cache=True) as eng:
        fpt = eng.flops_per_token
        o1 = eng.submit(p1, max_new_tokens=4).result(timeout=240)
        o2 = eng.submit(p2, max_new_tokens=4).result(timeout=240)
        cached = eng.n_cached_tokens
    assert cached > 0, "shared prefix produced no cache hits"
    assert o1["served_flops"] == fpt * (len(p1) + len(o1["output_ids"]))
    assert o2["served_flops"] == fpt * (
        len(p2) - cached + len(o2["output_ids"]))


# ---------------------------------------------------------------------------
# analytic FLOPs vs XLA-counted FLOPs (parity pin, satellite 3)
# ---------------------------------------------------------------------------

def test_flops_parity_transformer_block():
    """Pin the analytic FLOPs path (``pt.flops``: per-layer formulas,
    the same multiply-add convention as the planner/test_summary_flops)
    against XLA's counted FLOPs for ONE transformer encoder block,
    read through the perf cost registry.

    Documented tolerance: the analytic count covers the Linear
    projections + norms only; XLA additionally counts the attention
    score/value matmuls (≈ s/(3·d_model) of the projection FLOPs at
    seq s), softmax/GELU elementwise work, and fuses some of it away.
    At s=32, d_model=128 that bounds the gap well inside ±25%, which
    is the pin — a broken analytic formula (dropped 2x, missing
    layer) lands far outside it."""
    import jax

    pt.seed(0)
    s, d = 32, 128
    net = nn.TransformerEncoderLayer(d_model=d, nhead=4,
                                     dim_feedforward=4 * d,
                                     dropout=0.0)
    net.eval()
    analytic = pt.flops(net, (1, s, d))
    assert analytic > 0

    from paddle_tpu.nn.layer import functional_call, split_state
    params, buffers = split_state(net)

    def fwd(p, b, x):
        out, _ = functional_call(net, p, b, x, training=False)
        return out

    x = np.zeros((1, s, d), np.float32)
    jitted = jax.jit(fwd)
    h = perf.register_program(
        "train", "block_fwd",
        lower=perf.make_lower(jitted, (params, buffers, x)))
    h.record(0.001)
    assert h.cost_resolved, "XLA cost analysis unavailable on CPU?"
    xla = h.flops
    ratio = analytic / xla
    assert 0.75 <= ratio <= 1.25, (
        f"analytic {analytic:.3g} vs XLA {xla:.3g} "
        f"(ratio {ratio:.3f}) — outside the documented ±25% band")


# ---------------------------------------------------------------------------
# fleet federation: down replica is a hole; failover attribution
# ---------------------------------------------------------------------------

def _prom(mfu=None, completed=1.0, fps=None):
    lines = ["# TYPE llm_requests_completed counter",
             f"llm_requests_completed {completed}"]
    if mfu is not None:
        lines += ["# TYPE perf_mfu gauge", f"perf_mfu {mfu}"]
    if fps is not None:
        lines += ["# TYPE perf_flops_per_second gauge",
                  f"perf_flops_per_second {fps}"]
    return "\n".join(lines) + "\n"


def test_fleet_mfu_down_replica_is_hole():
    from paddle_tpu.observability.metrics import MetricRegistry
    from paddle_tpu.serving.fleet import FleetScraper

    reg = MetricRegistry()
    sc = FleetScraper(registry=reg)
    sc.record("r0", _prom(mfu=0.4, fps=100.0))
    sc.record("r1", _prom(mfu=0.2, fps=50.0))
    agg = sc.aggregates()
    assert agg["mfu"] == pytest.approx(0.3)
    assert agg["mfu_replicas"] == 2
    assert agg["flops_per_second"] == pytest.approx(150.0)

    # r1 dies: its 0.2 must leave the mean entirely (a hole), not be
    # averaged in as 0.0 (which would read as "idle capacity")
    sc.record("r1", None)
    agg = sc.aggregates()
    assert agg["mfu"] == pytest.approx(0.4), \
        "down replica folded into fleet_mfu as a zero"
    assert agg["mfu_replicas"] == 1
    assert reg.get("fleet_mfu").value == pytest.approx(0.4)
    assert reg.get("fleet_replica_up").labels("r1").value == 0

    # a replica that exports no perf series at all is also a hole
    sc.record("r2", _prom(mfu=None))
    agg = sc.aggregates()
    assert agg["mfu"] == pytest.approx(0.4)
    assert agg["mfu_replicas"] == 1

    # nobody reports: mfu is None (unknown), not a fake zero
    sc.record("r0", None)
    agg = sc.aggregates()
    assert agg["mfu"] is None and agg["mfu_replicas"] == 0


def test_fleet_federates_perf_series():
    from paddle_tpu.observability.metrics import MetricRegistry
    from paddle_tpu.serving.fleet import FleetScraper

    sc = FleetScraper(registry=MetricRegistry())
    sc.record("r0", _prom(mfu=0.31))
    text = sc.render_prometheus()
    assert 'fleet_perf_mfu{replica="r0"} 0.31' in text


class _CrashOnceReplica:
    """First dispatch dies like a SIGKILLed sibling (ReplicaUnavailable
    before the engine sees the request — a real crash takes its
    process, and its counters, with it); later dispatches pass
    through. The router's nonce-pinned failover then re-runs the
    request on the healthy replica."""

    def __init__(self, inner):
        self.inner = inner
        self.crashed = False

    def submit(self, prompt_ids, **kw):
        from paddle_tpu.serving.replica import ReplicaUnavailable
        if not self.crashed:
            self.crashed = True
            raise ReplicaUnavailable("replica crashed mid-dispatch")
        return self.inner.submit(prompt_ids, **kw)

    def health(self):
        return self.inner.health()

    def cancel(self, request_id, **kw):
        return self.inner.cancel(request_id)

    def close(self):
        pass


def test_served_flops_failover_no_double_count():
    from paddle_tpu.serving import LocalReplica, Router

    fam = default_registry().get("llm_served_flops_total")
    base = fam.labels("gold").value if fam is not None else 0.0
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, 8).tolist() for _ in range(2)]
    with _tiny_engine(decode_ticks=1) as eng:
        flaky = _CrashOnceReplica(LocalReplica(eng))
        healthy = LocalReplica(eng)
        router = Router({"r0": flaky, "r1": healthy},
                        policy="round_robin",
                        health_poll_interval=5.0, failover_budget=2)
        try:
            # two submissions: round-robin touches both seats, so the
            # flaky replica's crash-and-failover path runs regardless
            # of which seat goes first
            outs = [router.submit(p, max_new_tokens=8,
                                  tenant="gold").result(timeout=240)
                    for p in prompts]
        finally:
            router.close()
    assert flaky.crashed, "the crash path never ran"
    assert all(o["output_ids"] and o.get("served_flops", 0) > 0
               for o in outs)
    got = default_registry().get(
        "llm_served_flops_total").labels("gold").value - base
    # exactly the finished requests' worth: the crashed dispatch never
    # reached a finish, so each failover re-run is the only
    # attribution for its request
    assert got == pytest.approx(sum(o["served_flops"] for o in outs)), \
        f"failover double-counted served FLOPs: {got}"
