"""Flash attention Pallas kernel vs reference math.

Mirrors the reference's OpTest method (SURVEY.md §4: NumPy/reference-impl
forward comparison + gradient comparison) — here the 'reference' is the
plain XLA softmax-attention, and grads are compared analytically
(custom-VJP kernel vs jax.grad of the reference), which is stronger than
finite differences.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.flash_attention import flash_attention

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _reference(q, k, v, causal=False):
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        ql, kl = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((ql, kl), dtype=bool), kl - ql)
        logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape), dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q = _rand((2, 256, 4, 64), 0)
    k = _rand((2, 256, 4, 64), 1)
    v = _rand((2, 256, 4, 64), 2)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_gqa():
    q = _rand((1, 128, 8, 64), 0)
    k = _rand((1, 128, 2, 64), 1)
    v = _rand((1, 128, 2, 64), 2)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_cross_attention_lengths(causal):
    """sq != sk; causal must be bottom-right aligned like the fallback
    (query i attends keys <= i + (sk - sq)) — chunked-prefill shape."""
    q = _rand((1, 128, 2, 64), 0)
    k = _rand((1, 256, 2, 64), 1)
    v = _rand((1, 256, 2, 64), 2)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_cross_attention_lengths_grads():
    q = _rand((1, 128, 2, 64), 0)
    k = _rand((1, 256, 2, 64), 1)
    v = _rand((1, 256, 2, 64), 2)
    g_flash = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, causal=True,
                                           interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q = _rand((1, 128, 2, 64), 0)
    k = _rand((1, 128, 2, 64), 1)
    v = _rand((1, 128, 2, 64), 2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))  # non-trivial cotangent

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_grads_gqa():
    q = _rand((1, 128, 4, 64), 0)
    k = _rand((1, 128, 2, 64), 1)
    v = _rand((1, 128, 2, 64), 2)
    g_flash = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, causal=True,
                                           interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_causal_longer_query_rejected():
    """causal sq > sk: leading rows see no keys (NaN in reference math) —
    the kernel refuses and the dispatcher keeps it on the XLA path."""
    from paddle_tpu.ops.flash_attention import flash_attention_available
    q = _rand((1, 256, 2, 64), 0)
    k = _rand((1, 128, 2, 64), 1)
    with pytest.raises(ValueError, match="s_q <= s_k"):
        flash_attention(q, k, k, causal=True, interpret=True)
    assert not flash_attention_available(q.shape, k.shape, None, 0.0,
                                         False, is_causal=True)
    assert flash_attention_available(q.shape, k.shape, None, 0.0,
                                     False, is_causal=False)


def test_bf16_runs():
    q = _rand((1, 128, 2, 64), 0, jnp.bfloat16)
    k = _rand((1, 128, 2, 64), 1, jnp.bfloat16)
    v = _rand((1, 128, 2, 64), 2, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=3e-2, rtol=3e-2)


def test_sdpa_dispatch_uses_flash(monkeypatch):
    """F.scaled_dot_product_attention routes big shapes to the kernel."""
    import importlib

    import paddle_tpu.nn.functional as F
    from paddle_tpu.core import flags
    fa_mod = importlib.import_module("paddle_tpu.ops.flash_attention")

    calls = []
    real = fa_mod.flash_attention

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(fa_mod, "flash_attention", spy)

    q = _rand((1, 256, 2, 64), 0)
    k = _rand((1, 256, 2, 64), 1)
    v = _rand((1, 256, 2, 64), 2)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert calls, "flash kernel was not dispatched"
    ref = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # flag off → same numbers via the XLA path, no kernel call
    calls.clear()
    flags.set_flags({"flash_attention": False})
    try:
        out2 = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    finally:
        flags.set_flags({"flash_attention": True})
    assert not calls, "flag off must not dispatch to the kernel"
    np.testing.assert_allclose(out2, ref, atol=2e-5, rtol=2e-5)
    # odd lengths must take the fallback, not die in Mosaic tiling
    calls.clear()
    q5 = _rand((1, 255, 2, 64), 3)
    out3 = F.scaled_dot_product_attention(q5, q5, q5, is_causal=True)
    assert not calls and out3.shape == q5.shape
