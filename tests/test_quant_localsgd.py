"""Quantization (PTQ int8, QAT fake-quant) and LocalSGD.

Analogs of the reference's slim quantization tests
(slim/tests/test_imperative_qat.py, test_post_training_quantization_*)
and the LocalSGD meta-optimizer tests (test_fleet_localsgd_meta_
optimizer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, parallel, quant
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer import functional_call, split_state

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(
        ("fc1", nn.Linear(16, 32)),
        ("act", nn.ReLU()),
        ("fc2", nn.Linear(32, 8)),
    )


def _x(n=4, d=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(n, d),
                       jnp.float32)


# -- primitives -------------------------------------------------------------

def test_quantize_dequantize_roundtrip_error_small():
    w = _x(64, 32, seed=1)
    q, s = quant.quantize_weight(w, axis=0)
    assert q.dtype == jnp.int8 and s.shape == (1, 32)
    back = quant.dequantize_weight(q, s)
    # absmax int8: error bounded by scale/2 per element
    assert float(jnp.max(jnp.abs(back - w))) <= float(jnp.max(s)) * 0.5 + 1e-6


def test_fake_quant_straight_through_gradient():
    x = jnp.asarray([0.3, -0.7, 2.0])
    scale = jnp.asarray(0.01)
    g = jax.grad(lambda v: quant.fake_quant(v, scale).sum())(x)
    # inside the representable range (|x| <= 127.5*scale=1.275): grad 1;
    # outside (2.0): clipped, grad 0
    np.testing.assert_allclose(np.asarray(g), [1.0, 1.0, 0.0])


# -- PTQ --------------------------------------------------------------------

def test_ptq_weight_only_close_to_fp32():
    net = _mlp()
    x = _x()
    ref = np.asarray(net(x))
    n = quant.quantize_post_training(net)
    assert n == 2
    out = np.asarray(net(x))
    assert out.shape == ref.shape
    # int8 weight-only on a small MLP: sub-percent relative error
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.02, rel


def test_ptq_int8_activations_with_calibration():
    net = _mlp()
    x = _x()
    ref = np.asarray(net(x))
    n = quant.quantize_post_training(
        net, calibration_batches=[x], quant_act=True)
    assert n == 2
    for l in net.sublayers():
        if isinstance(l, quant.QuantizedLinear):
            assert l.act_scale is not None
    out = np.asarray(net(x))
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.1, rel


def test_ptq_artifact_serves_and_shrinks(tmp_path):
    """jit.save of a quantized net carries int8 params — the artifact
    shrinks ~4x and stays a valid StableHLO program."""
    from paddle_tpu import jit
    import os
    net = _mlp()
    x = np.asarray(_x())
    spec = [jit.InputSpec([4, 16], "float32")]
    p32 = str(tmp_path / "fp32")
    jit.save(net, p32, input_spec=spec)
    quant.quantize_post_training(net)
    ref = np.asarray(net(x))
    p8 = str(tmp_path / "int8")
    jit.save(net, p8, input_spec=spec)
    sz32 = os.path.getsize(os.path.join(p32, "params.pbin"))
    sz8 = os.path.getsize(os.path.join(p8, "params.pbin"))
    assert sz8 < 0.5 * sz32, (sz8, sz32)
    loaded = jit.load(p8)
    np.testing.assert_allclose(np.asarray(loaded(x)), ref,
                               rtol=1e-5, atol=1e-5)


def test_ptq_gpt_logits_close():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=16,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = np.random.RandomState(0).randint(0, 64, (2, 16))
    ref = np.asarray(net(ids))
    n = quant.quantize_post_training(net)
    assert n > 0
    out = np.asarray(net(ids))
    # top-1 prediction agreement is the metric that matters
    agree = (out.argmax(-1) == ref.argmax(-1)).mean()
    assert agree > 0.95, agree


# -- QAT --------------------------------------------------------------------

def test_qat_trains_and_converts():
    pt.seed(0)
    net = _mlp()
    n = quant.prepare_qat(net)
    assert n == 2
    x = _x(32, 16, seed=3)
    y = jnp.asarray(
        np.random.RandomState(4).randn(32, 8), jnp.float32)
    params, buffers = split_state(net)

    def loss_fn(p, b):
        out, nb = functional_call(net, p, b, x)
        return ((out - y) ** 2).mean(), nb

    losses = []
    for _ in range(60):
        (l, buffers), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params, buffers)
        params = jax.tree_util.tree_map(
            lambda p_, g_: p_ - 0.02 * g_, params, g)
        losses.append(float(l))
    # random-label regression has a high floor; require clear descent
    assert losses[-1] < 0.75 * losses[0], losses[:3] + losses[-3:]

    # write trained state back, convert to int8, outputs stay close
    for k, v in params.items():
        net._assign_by_path(k, v)
    for k, v in buffers.items():
        net._assign_by_path(k, v)
    qat_out = np.asarray(net(x))
    n = quant.convert(net)
    assert n == 2
    for l in net.sublayers():
        assert not isinstance(l, quant.QATLinear)
    int8_out = np.asarray(net(x))
    # the QAT forward already simulated int8: conversion is faithful
    rel = np.abs(int8_out - qat_out).max() / \
        (np.abs(qat_out).max() + 1e-9)
    assert rel < 0.15, rel


def test_qat_observer_tracks_activation_range():
    net = _mlp()
    quant.prepare_qat(net)
    big = 10.0 * _x(8, 16, seed=5)
    net.train()
    net(big)
    for l in net.sublayers():
        if isinstance(l, quant.QATLinear):
            assert float(l.act_absmax) > 0


def test_qat_observer_frozen_in_eval():
    """eval() must not pollute the calibrated range (ref:
    moving_average_abs_max_scale freezes in is_test mode)."""
    net = _mlp()
    quant.prepare_qat(net)
    net.train()
    net(_x(8, 16, seed=6))
    before = [float(l.act_absmax) for l in net.sublayers()
              if isinstance(l, quant.QATLinear)]
    net.eval()
    net(100.0 * _x(8, 16, seed=7))  # outlier eval batch
    after = [float(l.act_absmax) for l in net.sublayers()
             if isinstance(l, quant.QATLinear)]
    assert before == after


# -- LocalSGD ---------------------------------------------------------------

def _grad_and_update(lr=0.1):
    def grad_fn(params, batch):
        x, y = batch

        def loss(p):
            h = jnp.maximum(x @ p["w1"], 0.0)
            return ((h @ p["w2"] - y) ** 2).mean()

        l, g = jax.value_and_grad(loss)(params)
        return l, g

    def update_fn(params, grads):
        return jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)

    return grad_fn, update_fn


def _toy_params(seed=0):
    r = np.random.RandomState(seed)
    return {"w1": jnp.asarray(r.randn(8, 16) * 0.3, jnp.float32),
            "w2": jnp.asarray(r.randn(16, 4) * 0.3, jnp.float32)}


def test_local_sgd_sync_every_1_equals_dp():
    """k=1 degenerates to synchronous data parallelism."""
    mesh = parallel.init_mesh(dp=8)
    try:
        params = _toy_params()
        grad_fn, update_fn = _grad_and_update()
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(16, 8), jnp.float32)
        y = jnp.asarray(r.randn(16, 4), jnp.float32)

        # reference: plain full-batch DP sgd
        ref = dict(params)
        for i in range(3):
            _, g = grad_fn(ref, (x, y))
            ref = update_fn(ref, g)

        stacked = parallel.replicate_params(params, mesh)
        step = parallel.build_local_sgd_step(grad_fn, update_fn,
                                             sync_every=1, mesh=mesh)
        for i in range(3):
            stacked, loss = step(stacked, (x, y), jnp.asarray(i))
        got = parallel.unreplicate_params(stacked)
        for k in ref:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(ref[k]),
                                       rtol=2e-4, atol=2e-5)
    finally:
        parallel.set_mesh(None)


def test_local_sgd_sync_every_k_averages_local_runs():
    """k=4: each replica trains alone on its shard for 4 steps, then
    params equal the average of the 8 independent local runs."""
    mesh = parallel.init_mesh(dp=8)
    try:
        params = _toy_params()
        grad_fn, update_fn = _grad_and_update()
        r = np.random.RandomState(2)
        x = jnp.asarray(r.randn(16, 8), jnp.float32)
        y = jnp.asarray(r.randn(16, 4), jnp.float32)
        k = 4

        # reference: 8 independent local runs on each shard, averaged
        locals_ = []
        for s in range(8):
            p = dict(params)
            xs, ys = x[2 * s:2 * s + 2], y[2 * s:2 * s + 2]
            for _ in range(k):
                _, g = grad_fn(p, (xs, ys))
                p = update_fn(p, g)
            locals_.append(p)
        avg = {key: np.mean([np.asarray(p[key]) for p in locals_], 0)
               for key in params}

        stacked = parallel.replicate_params(params, mesh)
        step = parallel.build_local_sgd_step(grad_fn, update_fn,
                                             sync_every=k, mesh=mesh)
        for i in range(k):
            stacked, _ = step(stacked, (x, y), jnp.asarray(i))
        got = parallel.unreplicate_params(stacked)
        for key in avg:
            np.testing.assert_allclose(np.asarray(got[key]), avg[key],
                                       rtol=2e-4, atol=2e-5)
    finally:
        parallel.set_mesh(None)
