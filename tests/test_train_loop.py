"""Fused multi-step train loop (ISSUE 3): `Model.fit(steps_per_loop=K)`
scans K optimizer steps inside ONE XLA dispatch, fed by double-buffered
[K, ...] superbatches. The pinned contract: the loss stream is
BIT-IDENTICAL to the K=1 path (per-step keys derived from the step
index inside the scan, exactly `rng.split_for_step`), metric coercion
defers to log/display boundaries, and the recompile guard counts one
signature per superbatch shape."""

import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.io import DataLoader, TensorDataset, stack_batches
from paddle_tpu.metric import Accuracy
from paddle_tpu.optimizer import Adam


def _make_model(metrics=(), dropout=0.0, seed=7, lr=1e-3):
    pt.seed(seed)
    layers = [nn.Flatten(), nn.Linear(12, 32), nn.ReLU()]
    if dropout:
        layers.append(nn.Dropout(dropout))
    layers.append(nn.Linear(32, 4))
    net = nn.Sequential(*layers)
    model = pt.Model(net)
    model.prepare(optimizer=Adam(learning_rate=lr, parameters=net),
                  loss=nn.CrossEntropyLoss(), metrics=list(metrics))
    return model


def _data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 12).astype(np.float32)
    y = rs.randint(0, 4, n).astype(np.int64)
    return x, y


class _RecordLoss(Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


# ---------------------------------------------------------------------------
# bit-identical parity (the acceptance-pinned invariant)
# ---------------------------------------------------------------------------

def test_train_loop_batch_bit_identical_to_train_batch():
    x, y = _data(64)
    xs = x.reshape(8, 8, 12)
    ys = y.reshape(8, 8)

    m1 = _make_model()
    ref = [float(np.asarray(m1.train_batch([xs[i]], [ys[i]])["loss"]))
           for i in range(8)]

    m2 = _make_model()
    logs = m2.train_loop_batch([xs[:4]], [ys[:4]])
    logs += m2.train_loop_batch([xs[4:]], [ys[4:]])
    fused = [float(lg["loss"]) for lg in logs]

    assert ref == fused  # bitwise, not allclose
    # final state identical too (same donated-carry math)
    m1.sync_weights()
    m2.sync_weights()
    for (n1, v1), (n2, v2) in zip(
            sorted(m1.network.state_dict().items()),
            sorted(m2.network.state_dict().items())):
        assert n1 == n2
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    assert m1._step_count == m2._step_count == 8


def test_train_loop_rng_parity_with_dropout():
    """Per-step keys inside the scan must match rng.split_for_step —
    dropout makes a key mismatch show up in the loss stream."""
    x, y = _data(64)
    xs, ys = x.reshape(8, 8, 12), y.reshape(8, 8)
    m1 = _make_model(dropout=0.5)
    ref = [float(np.asarray(m1.train_batch([xs[i]], [ys[i]])["loss"]))
           for i in range(8)]
    m2 = _make_model(dropout=0.5)
    fused = [float(lg["loss"])
             for lg in m2.train_loop_batch([xs], [ys])]
    assert ref == fused


def test_fit_steps_per_loop_parity_and_ragged_tail():
    # 72 samples / batch 8 = 9 steps → K=4 slabs of 4 + 4 + 1 (tail
    # runs the per-step path)
    x, y = _data(72)
    ds = TensorDataset([x, y])

    rec1, rec4 = _RecordLoss(), _RecordLoss()
    m1 = _make_model()
    m1.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False,
           callbacks=[rec1], steps_per_loop=1)
    m4 = _make_model()
    m4.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False,
           callbacks=[rec4], steps_per_loop=4)

    assert len(rec1.losses) == len(rec4.losses) == 18
    assert rec1.losses == rec4.losses
    assert m1._step_count == m4._step_count == 18


def test_fit_steps_per_loop_flag_default():
    from paddle_tpu.core import flags
    x, y = _data(32)
    ds = TensorDataset([x, y])
    rec1, recf = _RecordLoss(), _RecordLoss()
    m1 = _make_model()
    m1.fit(ds, batch_size=8, epochs=1, verbose=0, shuffle=False,
           callbacks=[rec1])
    flags.set_flags({"steps_per_loop": 4})
    try:
        mf = _make_model()
        mf.fit(ds, batch_size=8, epochs=1, verbose=0, shuffle=False,
               callbacks=[recf])  # no explicit arg: flag drives K
    finally:
        flags.set_flags({"steps_per_loop": 1})
    assert rec1.losses == recf.losses
    # the flag-driven run dispatched slabs: its only signature is the
    # [4, ...] loop program
    assert mf.compiled_shape_count == 1
    assert m1.compiled_shape_count == 1


def test_fit_steps_per_loop_learns():
    """The fused path trains for real: LeNet-free tiny problem must
    still converge through slab dispatches."""
    rs = np.random.RandomState(3)
    y = rs.randint(0, 4, 256)
    x = (np.eye(4, 12, dtype=np.float32)[y] * 3.0
         + rs.randn(256, 12).astype(np.float32) * 0.1)
    ds = TensorDataset([x, y.astype(np.int64)])
    m = _make_model(metrics=[Accuracy()], lr=1e-2)
    m.fit(ds, batch_size=32, epochs=8, verbose=0, shuffle=True,
          steps_per_loop=4)
    res = m.evaluate(ds, batch_size=32, verbose=0)
    assert res["acc"] > 0.9, res


# ---------------------------------------------------------------------------
# recompile guard accounting (satellite)
# ---------------------------------------------------------------------------

def test_guard_one_signature_per_superbatch_shape():
    x, y = _data(64)
    xs, ys = x.reshape(8, 8, 12), y.reshape(8, 8)
    m = _make_model()
    for _ in range(3):
        m.train_loop_batch([xs[:4]], [ys[:4]])
    assert m.compiled_shape_count == 1  # same slab shape = one program
    m.train_loop_batch([xs[:2]], [ys[:2]])
    assert m.compiled_shape_count == 2  # new K = new signature
    m.train_batch([xs[0]], [ys[0]])
    # K=1 step program counted consistently, as its own signature
    assert m.compiled_shape_count == 3


def test_guard_cap_holds_for_loop_signatures():
    m = _make_model()
    x, y = _data(16)
    xs, ys = x.reshape(2, 8, 12), y.reshape(2, 8)
    m._shape_signatures = {("pad", i) for i in range(4096)}
    m.train_loop_batch([xs], [ys])
    assert m.compiled_shape_count == 4096  # bounded at the cap
    m.train_batch([x[:8]], [y[:8]])
    assert m.compiled_shape_count == 4096


# ---------------------------------------------------------------------------
# superbatch iterator (io)
# ---------------------------------------------------------------------------

def test_superbatches_stacks_and_flushes_ragged_tail():
    x = np.arange(72, dtype=np.float32).reshape(72, 1)
    y = np.arange(72, dtype=np.int64)
    dl = DataLoader(TensorDataset([x, y]), batch_size=8, shuffle=False,
                    to_device=False)
    slabs = list(dl.superbatches(4))
    assert [s[0].shape for s in slabs] == [(4, 8, 1), (4, 8, 1), (1, 8, 1)]
    np.testing.assert_array_equal(slabs[0][1][1],
                                  np.arange(8, 16))  # order preserved
    np.testing.assert_array_equal(slabs[2][1][0], np.arange(64, 72))


def test_superbatches_flushes_on_shape_change():
    # 20 samples / batch 8, drop_last=False → 8, 8, 4: the short final
    # batch cannot stack with the full ones and must flush the slab
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    dl = DataLoader(TensorDataset([x]), batch_size=8, shuffle=False,
                    to_device=False)
    slabs = list(dl.superbatches(4))
    assert [s[0].shape for s in slabs] == [(2, 8, 1), (1, 4, 1)]


def test_superbatches_device_prefetch():
    import jax
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    dl = DataLoader(TensorDataset([x]), batch_size=8, shuffle=False)
    slabs = list(dl.superbatches(2))
    assert all(isinstance(s[0], jax.Array) for s in slabs)


def test_stack_batches_structure():
    a = (np.ones((2, 3)), np.zeros(2))
    b = (np.full((2, 3), 2.0), np.ones(2))
    out = stack_batches([a, b])
    assert out[0].shape == (2, 2, 3)
    np.testing.assert_array_equal(out[1], [[0, 0], [1, 1]])


# ---------------------------------------------------------------------------
# deferred metric coercion (satellite)
# ---------------------------------------------------------------------------

def test_metric_update_deferred_until_display():
    x, y = _data(32)
    acc = Accuracy()
    m = _make_model(metrics=[acc])
    logs = m.train_batch([x[:8]], [y[:8]])
    logs2 = m.train_batch([x[8:16]], [y[8:16]])
    # no host coercion yet: the accumulator has seen nothing
    assert acc.count == 0
    v = float(logs2["acc"])  # display boundary → drain
    assert acc.count == 16  # both buffered steps folded in
    assert 0.0 <= v <= 1.0
    # draining is idempotent
    assert float(logs["acc"]) == v


def test_metric_values_match_eager_reference():
    x, y = _data(64)
    xs, ys = x.reshape(8, 8, 12), y.reshape(8, 8)

    # eager reference: update per step, read after 8 steps
    ref_acc = Accuracy()
    m1 = _make_model(metrics=[ref_acc])
    for i in range(8):
        logs = m1.train_batch([xs[i]], [ys[i]])
    ref = float(logs["acc"])

    fused_acc = Accuracy()
    m2 = _make_model(metrics=[fused_acc])
    logs = m2.train_loop_batch([xs], [ys])
    got = float(logs[-1]["acc"])
    assert got == ref
    assert fused_acc.count == ref_acc.count == 64


def test_lazy_log_values_behave_like_floats():
    """Old contract: logs carried plain floats — callbacks doing
    comparisons/arithmetic on metric entries must keep working."""
    x, y = _data(32)
    m = _make_model(metrics=[Accuracy()])
    logs = m.train_loop_batch([x.reshape(4, 8, 12)], [y.reshape(4, 8)])[-1]
    acc, loss = logs["acc"], logs["loss"]
    assert (acc > -1.0) and (acc <= 1.0)
    assert acc * 2 == 2 * float(acc)
    assert 1.0 - acc == pytest.approx(1.0 - float(acc))
    assert loss > 0.0
    assert f"{acc:.4f}" == f"{float(acc):.4f}"
    assert round(acc, 4) == round(float(acc), 4)
    assert int(loss) == int(float(loss))


def test_drain_metrics_public_api_and_boundary_semantics():
    """Manual eval_batch loops read accumulate() after drain_metrics();
    evaluate()/fit() fold still-buffered outputs BEFORE resetting, so
    Metric state at every boundary matches immediate-update semantics;
    a log value coerced at its display boundary memoizes and survives a
    later reset."""
    x, y = _data(32)
    acc = Accuracy()
    m = _make_model(metrics=[acc])
    for i in range(2):
        m.eval_batch([x[i * 16:(i + 1) * 16]], [y[i * 16:(i + 1) * 16]])
    assert acc.count == 0  # deferred
    m.drain_metrics()
    assert acc.count == 32  # public drain folds everything

    logs = m.train_batch([x[:16]], [y[:16]])
    train_acc = float(logs["acc"])  # display boundary → memoized
    m.evaluate(TensorDataset([x, y]), batch_size=16, verbose=0)
    assert float(logs["acc"]) == train_acc  # reset doesn't corrupt it


def test_pending_metric_buffer_is_bounded():
    """Nothing displaying (verbose=0 loops) must not pile up unbounded
    device buffers: the pending list auto-drains at the cap."""
    x, y = _data(16)
    acc = Accuracy()
    m = _make_model(metrics=[acc])
    for _ in range(m._PENDING_DRAIN_CAP + 10):
        m.train_batch([x], [y])
    assert len(m._metric_pending) <= m._PENDING_DRAIN_CAP
    assert acc.count > 0  # the backstop drain actually folded updates


def test_eval_metrics_drained_by_evaluate():
    x, y = _data(64)
    acc = Accuracy()
    m = _make_model(metrics=[acc])
    res = m.evaluate(TensorDataset([x, y]), batch_size=16, verbose=0)
    assert acc.count == 64
    assert res["acc"] == pytest.approx(acc.accumulate())


def test_update_stacked_matches_per_step_updates():
    rs = np.random.RandomState(0)
    correct = rs.rand(4, 8, 1) > 0.5  # [K, batch, topk] compute output
    a1, a2 = Accuracy(), Accuracy()
    for i in range(4):
        a1.update(correct[i])
    a2.update_stacked((correct,), nsteps=4)
    assert a1.count == a2.count
    assert a1.accumulate() == a2.accumulate()


# ---------------------------------------------------------------------------
# distributed composition (shard_superbatch)
# ---------------------------------------------------------------------------

def test_train_loop_parity_under_data_parallel_mesh():
    """The fused loop composes with DistributedModel: superbatches are
    sharded on dim 1 (batch) over the dp axis while dim 0 (steps) stays
    replicated for the scan — losses must still match the sharded K=1
    path bitwise."""
    from paddle_tpu import parallel
    from paddle_tpu.distributed import fleet

    x, y = _data(128)
    ds = TensorDataset([x, y])
    streams = []
    for k in (1, 4):
        fleet.init(is_collective=True)
        try:
            m = _make_model()
            fleet.distributed_model(m)
            assert m._shard_superbatch is not None
            rec = _RecordLoss()
            m.fit(ds, batch_size=16, epochs=1, verbose=0, shuffle=False,
                  callbacks=[rec], steps_per_loop=k)
            streams.append(rec.losses)
        finally:
            parallel.set_mesh(None)
    assert len(streams[0]) == len(streams[1]) == 8
    assert streams[0] == streams[1]


# ---------------------------------------------------------------------------
# observability + compilation cache (satellites)
# ---------------------------------------------------------------------------

def test_train_loop_metrics_registered():
    from paddle_tpu import observability as obs
    x, y = _data(32)
    ds = TensorDataset([x, y])
    m = _make_model(metrics=[Accuracy()])
    m.fit(ds, batch_size=8, epochs=1, verbose=0, shuffle=False,
          steps_per_loop=4)
    snap = obs.default_registry().snapshot()
    assert snap.get("train_loop_dispatch_seconds_count", 0) >= 1
    assert snap.get("train_loop_slab_size_count", 0) >= 1
    assert snap.get("train_loop_slabs", 0) >= 1
    # the fit() epoch-end freeze coerces → at least one drain observed
    assert snap.get("train_loop_drain_seconds_count", 0) >= 1
    # prefetch wait histogram exists (observed by the slab iterator)
    assert "train_loop_prefetch_wait_seconds_count" in snap


def test_compilation_cache_flag(tmp_path):
    from paddle_tpu.core import flags
    cache = str(tmp_path / "xla-cache")
    flags.set_flags({"compilation_cache_dir": cache})
    try:
        x, y = _data(16)
        m = _make_model()
        m.train_batch([x], [y])
        import jax
        assert jax.config.jax_compilation_cache_dir == cache
        assert os.path.isdir(cache)
        assert os.listdir(cache), "no persistent cache entries written"
    finally:
        flags.set_flags({"compilation_cache_dir": ""})
