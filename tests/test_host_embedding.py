"""Beyond-HBM embedding path (VERDICT r2 item 4): host-RAM table with
streamed pull/push (paddle_tpu/nn/layers/host_embedding.py) — the
MemorySparseTable / communicator / sparse_sgd_rule redesign.

Key claims tested mechanically:
- device memory of the compiled train step is INDEPENDENT of table size
  (the whole point of beyond-HBM),
- per-row accessor rules match hand math, duplicates merge before the
  rule step,
- lazy init is deterministic regardless of touch order,
- snapshot/restore resumes training losslessly,
- WideDeep-style training with a table far larger than any batch works
  end to end under jit."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn.layers.host_embedding import HostOffloadedEmbedding

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_lookup_matches_host_rows_and_lazy_init_deterministic():
    pt.seed(0)
    a = HostOffloadedEmbedding(1000, 8, seed=7)
    b = HostOffloadedEmbedding(1000, 8, seed=7)
    ids1 = np.array([[5, 9], [3, 5]])
    ids2 = np.array([[3, 9], [5, 3]])
    out_a = np.asarray(a(ids1))           # a touches 5,9,3 in this order
    _ = np.asarray(b(ids2))               # b touches 3,9,5 first
    out_b2 = np.asarray(b(ids1))
    np.testing.assert_allclose(out_a, out_b2, rtol=1e-6)
    assert a.touched_rows == 3


def test_pull_under_jit_and_grad_updates_host_table():
    """Differentiating the model params (the real training shape) fires
    the push: each touched row steps by -lr * dL/drow."""
    from paddle_tpu.nn.layer import functional_call, split_state

    pt.seed(0)
    e = HostOffloadedEmbedding(100, 4, optimizer="sgd", learning_rate=1.0,
                               padding_idx=None, combiner="sum")
    params, _ = split_state(e)
    ids = jnp.asarray([[1, 2]])
    before = e._pull(np.array([1, 2])).copy()

    @jax.jit
    def loss(p, ids):
        out, _ = functional_call(e, p, {}, ids)
        return out.sum()

    g = jax.grad(loss)(params, ids)
    jax.effects_barrier()
    # anchor's own grad is exactly zero (it never moves)
    np.testing.assert_allclose(np.asarray(g["push_anchor"]), 0.0)
    # d(sum of pooled)/d(row) = 1 per touched id; lr=1 → row -= 1
    after = e._pull(np.array([1, 2]))
    np.testing.assert_allclose(after, before - 1.0, rtol=1e-6)


def test_adagrad_rule_and_duplicate_merge():
    e = HostOffloadedEmbedding(100, 2, optimizer="adagrad",
                               learning_rate=0.5, initial_accumulator=0.1,
                               padding_idx=None)
    row = e._pull(np.array([4]))[0].copy()
    # duplicate id in one batch: grads merge BEFORE the rule step
    e._push(np.array([4, 4]), np.array([[1.0, 0.0], [1.0, 0.0]]))
    acc = 0.1 + 2.0 ** 2
    expect = row - 0.5 * np.array([2.0, 0.0]) / np.sqrt([acc, 1e30])
    got = e._pull(np.array([4]))[0]
    np.testing.assert_allclose(got[0], expect[0], rtol=1e-6)
    np.testing.assert_allclose(got[1], row[1], rtol=1e-6)  # zero grad dim
    assert pytest.raises(ValueError, HostOffloadedEmbedding, 10, 2,
                         optimizer="ftrl")


def test_device_memory_independent_of_table_size():
    """The compiled step's device buffers must not scale with
    num_embeddings — the table never lands in HBM."""
    def step_bytes(n_table):
        e = HostOffloadedEmbedding(n_table, 16)
        fc = nn.Linear(16, 1)
        from paddle_tpu.nn.layer import functional_call, split_state
        params, _ = split_state(fc)

        def loss(p, ids):
            pooled = e(ids)
            out, _ = functional_call(fc, p, {}, pooled)
            return out.sum()

        ids = jnp.asarray(np.random.RandomState(0).randint(
            1, n_table, (8, 4)))
        compiled = jax.jit(jax.grad(loss)).lower(params, ids).compile()
        mem = compiled.memory_analysis()
        return (mem.temp_size_in_bytes + mem.argument_size_in_bytes +
                mem.output_size_in_bytes)

    small = step_bytes(10_000)
    huge = step_bytes(50_000_000)   # 50M x 16 f32 = 3.2 GB if dense
    assert huge == small, (small, huge)


def test_widedeep_style_training_with_large_table(tmp_path):
    """End-to-end: wide (host-offloaded sparse) + deep tower trains under
    Model.train_batch, loss decreases, snapshot/restore is lossless."""
    pt.seed(0)
    table = HostOffloadedEmbedding(1_000_000, 8, optimizer="adagrad",
                                   learning_rate=0.1, hash_ids=True)

    class WideDeep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.sparse = table
            self.deep = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                      nn.Linear(16, 1))

        def forward(self, ids, dense):
            return self.deep(dense) + self.sparse(ids) @ jnp.ones((8, 1))

    rng = np.random.RandomState(0)
    ids = rng.randint(1, 1_000_000, (64, 4))
    dense = rng.randn(64, 8).astype(np.float32)
    y = ((ids.sum(1, keepdims=True) % 7) > 3).astype(np.float32)

    model = pt.Model(WideDeep())
    model.prepare(optimizer=pt.optimizer.Adam(
        learning_rate=5e-3, parameters=model.network),
        loss=nn.BCEWithLogitsLoss())
    # probe the host table via folded ids (hash_ids maps raw -> range);
    # an eager forward would read the donated anchor buffer post-train
    folded = np.asarray(table._fold_ids(jnp.asarray(ids[:1])))
    rows_before = table._pull(folded).copy()
    losses = [float(model.train_batch([ids, dense], [y])["loss"])
              for _ in range(30)]
    jax.effects_barrier()
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < 0.8 * np.mean(losses[:5]), losses[:3]
    assert table.touched_rows > 0
    # the HOST table itself trained (push fired), not just the deep tower
    assert not np.allclose(table._pull(folded), rows_before)

    # snapshot → clear → restore → identical lookup
    snap = str(tmp_path / "table.npz")
    table.snapshot(snap)
    probe_ids = np.asarray(table._fold_ids(jnp.asarray(ids[:2])))
    probe = table._pull(probe_ids).copy()
    fresh = HostOffloadedEmbedding(1_000_000, 8, optimizer="adagrad",
                                   learning_rate=0.1, hash_ids=True)
    fresh.restore(snap)
    np.testing.assert_allclose(fresh._pull(probe_ids), probe, rtol=1e-6)
    bad = HostOffloadedEmbedding(999, 8)
    with pytest.raises(ValueError, match="snapshot shape"):
        bad.restore(snap)


def test_pool_index_survives_merges_and_growth():
    """The sorted-index + tail map (r4 vectorization) returns the same
    rows across index merges, pool growth, and duplicate-heavy batches
    as a fresh table touching the same ids."""
    a = HostOffloadedEmbedding(10_000_000, 8, seed=3, padding_idx=None,
                               optimizer="sgd", learning_rate=1.0)
    rng = np.random.RandomState(7)
    seen = []
    for _ in range(12):                    # crosses the 1024 merge gate
        ids = rng.randint(1, 10_000_000, (64, 8))
        seen.append(ids)
        a._pull(ids)
    b = HostOffloadedEmbedding(10_000_000, 8, seed=3, padding_idx=None,
                               optimizer="sgd", learning_rate=1.0)
    probe = np.concatenate([s.reshape(-1) for s in seen])[::17]
    np.testing.assert_allclose(a._pull(probe), b._pull(probe))
    # duplicate-heavy push merges before the rule step (vectorized path)
    dup_ids = np.full((32,), int(probe[0]), np.int64)
    before = a._pull(probe[:1])[0].copy()
    a._push(dup_ids, np.ones((32, 8), np.float32))
    np.testing.assert_allclose(a._pull(probe[:1])[0], before - 32.0,
                               rtol=1e-6)
    # sgd table never allocates the accumulator pool; snapshot is clean
    assert a._pool_acc is None and len(a._accum) == 0


def test_async_push_and_prefetch_bounded_staleness():
    """The async-communicator mode (VERDICT r3 ask #9): queued pushes
    apply after flush(); a prefetched pull reads rows as-of prefetch
    time (stale across an interleaved push — the bounded trade), and a
    fresh pull after flush sees the update."""
    e = HostOffloadedEmbedding(1000, 4, optimizer="sgd",
                               learning_rate=1.0, padding_idx=None,
                               async_push=True)
    ids = np.array([[1, 2]])
    before = e._pull(ids).copy()
    e.prefetch(ids)                       # snapshot-in-flight
    for slot in e._prefetched.values():   # deterministic ordering:
        slot["ev"].wait()                 # gather completes pre-push
    e._push(ids, np.ones((2, 4), np.float32))
    e.flush()
    stale = e._pull(ids)                  # consumes the prefetched block
    np.testing.assert_allclose(stale, before, rtol=1e-6)
    fresh = e._pull(ids)                  # no prefetch left: live rows
    np.testing.assert_allclose(fresh, before - 1.0, rtol=1e-6)
    # snapshot flushes pending pushes before writing
    e._push(ids, np.ones((2, 4), np.float32))
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        e.snapshot(td + "/t.npz")
        z = np.load(td + "/t.npz")
        got = dict(zip(z["ids"].tolist(), z["values"]))
        np.testing.assert_allclose(got[1], before[0, 0] - 2.0, rtol=1e-6)


def test_geo_merge_averages_held_rows(tmp_path):
    """Geo-SGD periodic merge: rows average over the replicas that hold
    them; rows unique to one replica pass through unchanged."""
    a = HostOffloadedEmbedding(100, 2, seed=1)
    b = HostOffloadedEmbedding(100, 2, seed=2)
    a._rows = {1: np.array([1.0, 1.0], np.float32),
               2: np.array([2.0, 2.0], np.float32)}
    b._rows = {1: np.array([3.0, 3.0], np.float32),
               5: np.array([5.0, 5.0], np.float32)}
    b._accum = {1: np.array([0.5, 0.5], np.float32)}
    snap = str(tmp_path / "b.npz")
    b.snapshot(snap)
    a.geo_merge(snap)
    np.testing.assert_allclose(a._rows[1], [2.0, 2.0])   # mean(1, 3)
    np.testing.assert_allclose(a._rows[2], [2.0, 2.0])   # only in a
    np.testing.assert_allclose(a._rows[5], [5.0, 5.0])   # adopted from b
    np.testing.assert_allclose(a._accum[1], [0.5, 0.5])  # max-merge
    with pytest.raises(ValueError, match="mismatch"):
        HostOffloadedEmbedding(99, 2).geo_merge(snap)


def test_spill_dir_parity_and_files(tmp_path):
    """Disk-spill tier (ref: ssd_sparse_table.h): with spill_dir the
    value/accumulator pools are memmap files — identical numerics to
    the RAM pool (init is deterministic in (seed, id)), capacity bound
    by disk, files regenerated across pool growth."""
    import os
    from paddle_tpu.nn import HostOffloadedEmbedding

    ids = np.asarray([[3, 9, 500_001, 0], [77, 77, 12, 0]])
    grads = np.random.RandomState(0).randn(2, 4, 8).astype(np.float32)

    def run(spill):
        emb = HostOffloadedEmbedding(
            1_000_000, 8, optimizer="adagrad", learning_rate=0.1,
            seed=7, spill_dir=str(tmp_path / "spill") if spill else None)
        outs = []
        for _ in range(3):
            out = np.asarray(emb._pull(ids.reshape(-1)))
            emb._apply_push(ids.reshape(-1),
                            grads.reshape(-1, 8))
            outs.append(out)
        return emb, np.stack(outs)

    emb_ram, ram = run(False)
    emb_spill, spill = run(True)
    np.testing.assert_allclose(ram, spill, atol=0, rtol=0)
    assert isinstance(emb_spill._pool_vals, np.memmap)
    assert isinstance(emb_spill._pool_acc, np.memmap)
    assert not isinstance(emb_ram._pool_vals, np.memmap)
    files = os.listdir(tmp_path / "spill")
    assert any("pool_vals" in f for f in files), files
    # growth rewrote generations; stale files unlinked (one live file
    # per pool array)
    assert sum("pool_vals" in f for f in files) == 1, files
    assert sum("pool_acc" in f for f in files) == 1, files


def test_spill_dir_shared_by_two_tables(tmp_path):
    """Two tables over one spill_dir must not truncate or unlink each
    other's pools (per-instance file tags)."""
    from paddle_tpu.nn import HostOffloadedEmbedding

    d = str(tmp_path / "shared")
    a = HostOffloadedEmbedding(10_000, 4, seed=1, spill_dir=d)
    b = HostOffloadedEmbedding(10_000, 4, seed=2, spill_dir=d)
    ids = np.arange(1, 300)  # forces pool growth in both
    va1 = np.asarray(a._pull(ids))
    vb1 = np.asarray(b._pull(ids))
    vb2 = np.asarray(b._pull(ids))   # b again after a allocated
    va2 = np.asarray(a._pull(ids))
    np.testing.assert_array_equal(va1, va2)
    np.testing.assert_array_equal(vb1, vb2)
    assert not np.allclose(va1, vb1)  # different seeds, distinct pools


def test_spill_reaps_dead_process_files(tmp_path):
    """Files left by a crashed (dead-pid) run are reaped on init;
    live-pid files survive."""
    import os
    from paddle_tpu.nn import HostOffloadedEmbedding

    d = tmp_path / "reap"
    d.mkdir()
    dead = d / "pool_vals.p999999.i1.gen3.f32"   # no such pid
    live = d / f"pool_vals.p{os.getpid()}.i0.gen1.f32"
    other = d / "unrelated.bin"
    for f in (dead, live, other):
        f.write_bytes(b"x" * 16)
    HostOffloadedEmbedding(100, 4, spill_dir=str(d))
    assert not dead.exists()
    assert live.exists() and other.exists()


def test_spill_snapshot_restore_roundtrip(tmp_path):
    from paddle_tpu.nn import HostOffloadedEmbedding

    emb = HostOffloadedEmbedding(10_000, 4, optimizer="sgd",
                                 learning_rate=0.1, seed=3,
                                 spill_dir=str(tmp_path / "s"))
    ids = np.asarray([5, 17, 999, 5])
    emb._apply_push(ids, np.ones((4, 4), np.float32))
    before = np.asarray(emb._pull(ids))
    emb.snapshot(str(tmp_path / "snap.npz"))

    emb2 = HostOffloadedEmbedding(10_000, 4, optimizer="sgd",
                                  learning_rate=0.1, seed=3,
                                  spill_dir=str(tmp_path / "s2"))
    emb2.restore(str(tmp_path / "snap.npz"))
    np.testing.assert_allclose(np.asarray(emb2._pull(ids)), before)
    assert isinstance(emb2._pool_vals, np.memmap)


def test_native_accessor_parity_and_fallback(monkeypatch):
    """The fused C++ push (native/sparse_accessor.cc, the
    sparse_sgd_rule.cc twin) produces the same table as the numpy
    path for adagrad AND sgd, skipping padding and never-pulled rows;
    PT_NATIVE_ACCESSOR=0 falls back cleanly."""
    import paddle_tpu.nn.layers.native_accessor as na

    def run(optimizer, native):
        if native:
            monkeypatch.delenv("PT_NATIVE_ACCESSOR", raising=False)
            na._FAILED = False
            # the test is vacuous if the C++ path silently fell back
            assert na.available(), "native accessor failed to build"
        else:
            monkeypatch.setenv("PT_NATIVE_ACCESSOR", "0")
        na._FAILED = False
        e = HostOffloadedEmbedding(100_000, 8, optimizer=optimizer,
                                   learning_rate=0.1, hash_ids=True,
                                   seed=11)
        rng = np.random.RandomState(2)
        ids = rng.randint(1, 100_000, (32, 4)).astype(np.int64)
        folded = np.asarray(e._fold_ids(jnp.asarray(ids)))
        e._pull(folded)
        g = rng.randn(32 * 4, 8).astype(np.float32)
        for _ in range(4):
            e._push(folded, g)
        # also push ids NEVER pulled (slot -1): must be skipped
        fresh = np.full((4, 1), 77777, np.int64)
        e._push(fresh, np.ones((4, 8), np.float32))
        return e._pull(folded)

    for opt in ("adagrad", "sgd"):
        got = run(opt, native=True)
        ref = run(opt, native=False)
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7,
                                   err_msg=opt)
