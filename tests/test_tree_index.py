"""TreeIndex / layerwise sampler (the TDM retrieval index; ref:
python/paddle/distributed/fleet/dataset/index_dataset.py TreeIndex,
distributed/index_dataset/index_wrapper.h:33, index_sampler.h
LayerWiseSampler) — closes the last 'absent' inventory row."""

import numpy as np
import pytest

from paddle_tpu.distributed.index_dataset import TreeIndex

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_tree_structure_and_codes():
    items = list(range(100, 110))          # 10 items
    t = TreeIndex.from_items("t", items, branch=2)
    assert t.branch() == 2
    assert t.height() == 5                 # 16 leaf slots at level 4
    leafs = t.get_all_leafs()
    assert [n.id() for n in leafs] == items
    assert all(n.is_leaf() for n in leafs)
    # travel codes: leaf -> root, parent relation holds
    path = t.get_travel_codes(items[3])
    assert len(path) == 5 and path[-1] == 0
    for child, parent in zip(path, path[1:]):
        assert (child - 1) // 2 == parent
    # ancestor at level 1 consistent with travel
    anc = t.get_ancestor_codes([items[3]], 1)[0]
    assert anc == path[-2]
    assert t.get_pi_relation([items[3]], 1) == {items[3]: anc}
    # children of root at level 2 are exactly the level-2 codes
    assert sorted(t.get_children_codes(0, 2)) == \
        t.get_layer_codes(2).tolist()
    # travel path child->ancestor excludes the ancestor
    tp = t.get_travel_path(path[0], path[2])
    assert tp == [path[0], path[1]]
    # node ids: leaves keep item ids; ancestors get fresh ids
    assert t.emb_size() > max(items)
    assert t.total_node_nums() == sum(
        len(t.get_layer_codes(lv)) for lv in range(t.height()))


def test_save_load_roundtrip(tmp_path):
    t = TreeIndex.from_items("t", [5, 7, 9, 11], branch=2)
    p = str(tmp_path / "tree.npz")
    t.save(p)
    t2 = TreeIndex("t2", p)
    assert t2.height() == t.height()
    assert [n.id() for n in t2.get_all_leafs()] == [5, 7, 9, 11]
    assert t2.get_travel_codes(9) == t.get_travel_codes(9)


def test_embedding_tree_clusters_similar_items():
    """Items with similar embeddings share deeper subtrees: two tight
    clusters end up split at the root."""
    rng = np.random.RandomState(0)
    a = rng.randn(8, 4) * 0.1 + 5.0
    b = rng.randn(8, 4) * 0.1 - 5.0
    embs = np.concatenate([a, b])
    ids = list(range(16))
    t = TreeIndex.from_embeddings("e", ids, embs, branch=2)
    side = {i: t.get_ancestor_codes([i], 1)[0] for i in ids}
    left = {side[i] for i in range(8)}
    right = {side[i] for i in range(8, 16)}
    assert len(left) == 1 and len(right) == 1 and left != right


def test_layerwise_sampler_reference_format():
    items = list(range(200, 216))
    t = TreeIndex.from_items("t", items, branch=2)
    counts = [1, 2, 2, 3]                   # height 5, start layer 1
    t.init_layerwise_sampler(counts, start_sample_layer=1, seed=0)
    users = [[1, 2], [3, 4]]
    rows = t.layerwise_sample(users, [items[0], items[5]])
    # per pair: one positive + counts[j] negatives per layer
    per_pair = sum(1 + c for c in counts)
    assert len(rows) == 2 * per_pair
    for row in rows:
        assert len(row) == 4                # 2 user feats + node + label
        assert row[-1] in (0, 1)
    pos = [r for r in rows if r[-1] == 1]
    assert len(pos) == 2 * len(counts)
    # positives on the first pair's path are its ancestors' ids
    path_ids = {t._id_by_code[c]
                for c in t.get_travel_codes(items[0], 1)}
    assert {r[2] for r in pos[:len(counts)]} <= path_ids


def test_layerwise_sampler_fixed_shape_arrays():
    items = list(range(32))
    t = TreeIndex.from_items("t", items, branch=2)
    counts = [2, 4, 8, 8, 8][:t.height() - 1]
    t.init_layerwise_sampler(counts, seed=1)
    ids, labels, mask = t._layerwise_sampler.sample_arrays(
        np.asarray([0, 17, 31]))
    B, L, W = ids.shape
    assert (B, L, W) == (3, len(counts), 1 + max(counts))
    assert (labels[:, :, 0] == 1).all() and (labels[:, :, 1:] == 0).all()
    assert mask[:, :, 0].all()
    # negatives are distinct from the positive within a layer
    for b in range(B):
        for j in range(L):
            negs = ids[b, j, 1:][mask[b, j, 1:]]
            assert ids[b, j, 0] not in negs


def test_sampler_count_validation():
    t = TreeIndex.from_items("t", list(range(8)), branch=2)
    with pytest.raises(ValueError, match="needs"):
        t.init_layerwise_sampler([1, 2])    # wrong layer count
