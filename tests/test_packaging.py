"""Wheel/packaging smoke tests (ref: the reference ships a
paddlepaddle wheel built by python/setup.py.in; BASELINE.json's north
star names a paddlepaddle-tpu wheel). The full `pip wheel .` build is
exercised out-of-band (slow); here we check the metadata is coherent."""

import os
import tomllib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project():
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)["project"]


def test_pyproject_version_matches_package():
    import paddle_tpu
    assert _project()["version"] == paddle_tpu.__version__


def test_launcher_entry_point_resolves():
    ep = _project()["scripts"]["paddle-tpu-launch"]
    mod, fn = ep.split(":")
    import importlib
    m = importlib.import_module(mod)
    assert callable(getattr(m, fn))


def test_native_sources_are_package_data():
    # the wheel carries datafeed.cc for on-demand compilation
    assert os.path.exists(
        os.path.join(REPO, "paddle_tpu", "native", "datafeed.cc"))
