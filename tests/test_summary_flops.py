"""summary/flops table + autotune facade (ref: hapi/model_summary.py,
hapi/dynamic_flops.py, incubate/autotune.py tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.incubate import autotune


def _cnn():
    pt.seed(0)
    return nn.Sequential(
        ("conv", nn.Conv2D(3, 8, 3, padding=1)),
        ("bn", nn.BatchNorm2D(8)),
        ("act", nn.ReLU()),
        ("pool", nn.AdaptiveAvgPool2D(1)),
        ("flat", nn.Flatten()),
        ("fc", nn.Linear(8, 10)),
    )


def test_summary_counts_and_table(capsys):
    net = _cnn()
    info = pt.summary(net, (2, 3, 16, 16))
    out = capsys.readouterr().out
    expected = 3 * 8 * 9 + 8 + 2 * 8 + 8 * 10 + 10
    assert info["total_params"] == expected
    assert info["trainable_params"] == expected
    assert "Conv2D" in out and "Linear" in out
    assert "(2, 8, 16, 16)" in out  # conv output shape from eval_shape


def test_model_summary_delegates():
    net = _cnn()
    model = pt.Model(net)
    info = model.summary((1, 3, 8, 8))
    assert info["total_params"] > 0


def test_flops_analytic_counts():
    net = _cnn()
    total = pt.flops(net, (1, 3, 16, 16))
    conv = 2 * 1 * 16 * 16 * 8 * 3 * 9
    fc = 2 * 8 * 10
    bn = 2 * 8 * 16 * 16
    assert abs(total - (conv + fc + bn)) <= 1e-6 * (conv + fc + bn), \
        (total, conv + fc + bn)


def test_flops_scales_with_batch():
    net = _cnn()
    f1 = pt.flops(net, (1, 3, 16, 16))
    f4 = pt.flops(net, (4, 3, 16, 16))
    assert f4 > 3 * f1


def test_summary_leaves_training_mode_intact():
    net = _cnn()
    net.train()
    pt.summary(net, (1, 3, 8, 8), dtypes=None)
    assert net.training


def test_autotune_config_roundtrip():
    autotune.set_config({"dataloader": {"enable": True}})
    assert autotune.get_config()["dataloader"]["enable"]
    assert autotune.suggested_num_workers() >= 1
    autotune.set_config({"dataloader": {"enable": False}})
    assert autotune.suggested_num_workers() == 0
    with pytest.raises(ValueError, match="unknown autotune"):
        autotune.set_config({"bogus": {}})


def test_flops_counts_conv1d_and_bn1d():
    pt.seed(0)
    net = nn.Sequential(("c", nn.Conv1D(2, 4, 3, padding=1)),
                        ("b", nn.BatchNorm1D(4)))
    total = pt.flops(net, (1, 2, 16))
    conv = 2 * 1 * 16 * 4 * 2 * 3
    bn = 2 * 4 * 16
    assert abs(total - (conv + bn)) <= 1, (total, conv + bn)


def test_summary_failure_restores_train_mode():
    net = _cnn()
    net.train()
    with pytest.raises(Exception):
        pt.summary(net, (1, 7))  # wrong shape -> trace error
    assert net.training


def test_dataloader_num_workers_auto():
    from paddle_tpu.io import DataLoader

    class DS:
        def __getitem__(self, i):
            return np.zeros(2, np.float32)

        def __len__(self):
            return 4

    from paddle_tpu.io import Dataset

    class D(Dataset):
        def __getitem__(self, i):
            return np.zeros(2, np.float32), np.int64(0)

        def __len__(self):
            return 4

    autotune.set_config({"dataloader": {"enable": False}})
    assert DataLoader(D(), num_workers="auto").num_workers == 0
    autotune.set_config({"dataloader": {"enable": True}})
    try:
        assert DataLoader(D(), num_workers="auto").num_workers >= 1
    finally:
        autotune.set_config({"dataloader": {"enable": False}})
