"""Fleet observability (ISSUE 7 tentpoles 2+3 and satellites):
metrics federation (FleetScraper, /fleetz, replica-labeled re-export),
SLO burn-rate monitoring (SLOTracker, /sloz, breach latch on
/healthz), the /tracez query filters, and the trace_merge tool.

Stub replicas throughout — this is the control/observability plane,
no compiles needed.
"""

import json
import threading
import time
import urllib.request
from urllib.error import HTTPError
from urllib.request import Request, urlopen

import pytest

from paddle_tpu.observability import tracing
from paddle_tpu.observability.metrics import MetricRegistry
from paddle_tpu.observability.server import DebugServer
from paddle_tpu.observability.slo import SLOTracker
from paddle_tpu.serving import Router, SLOClass
from paddle_tpu.serving.fleet import FleetScraper, parse_prometheus_text

REPLICA_TEXT = """# HELP llm_tokens_generated tokens emitted
# TYPE llm_tokens_generated counter
llm_tokens_generated {tokens}
# TYPE llm_prompt_tokens counter
llm_prompt_tokens {prompt}
# TYPE llm_prefix_cache_hit_tokens counter
llm_prefix_cache_hit_tokens {hits}
# TYPE llm_requests_completed counter
llm_requests_completed {done}
# TYPE llm_kv_page_utilization gauge
llm_kv_page_utilization {kv}
# TYPE llm_batch_occupancy histogram
llm_batch_occupancy_bucket{{le="0.5"}} 1
llm_batch_occupancy_bucket{{le="+Inf"}} 2
llm_batch_occupancy_sum {occ_sum}
llm_batch_occupancy_count 2
"""


def replica_text(tokens=10, prompt=100, hits=40, done=3, kv=0.5,
                 occ_sum=1.0):
    return REPLICA_TEXT.format(tokens=tokens, prompt=prompt, hits=hits,
                               done=done, kv=kv, occ_sum=occ_sum)


# ---------------------------------------------------------------------------
# prometheus parsing
# ---------------------------------------------------------------------------

def test_parse_prometheus_text_families_and_labels():
    fams = parse_prometheus_text(replica_text())
    assert fams["llm_tokens_generated"]["type"] == "counter"
    assert fams["llm_tokens_generated"]["samples"] == [
        ("llm_tokens_generated", {}, 10.0)]
    occ = fams["llm_batch_occupancy"]
    assert occ["type"] == "histogram"
    names = [s[0] for s in occ["samples"]]
    assert "llm_batch_occupancy_sum" in names
    buckets = [s for s in occ["samples"]
               if s[0] == "llm_batch_occupancy_bucket"]
    assert buckets[0][1] == {"le": "0.5"}
    assert buckets[1][2] == 2.0    # +Inf parses


def test_parse_skips_garbage_lines():
    fams = parse_prometheus_text(
        "not a metric line at all\nx{y=unquoted} 1\nok_metric 3\n")
    assert fams["ok_metric"]["samples"] == [("ok_metric", {}, 3.0)]
    assert "x" not in fams


def test_parse_label_value_with_comma():
    fams = parse_prometheus_text('m{a="x,y",b="z"} 1\n')
    assert fams["m"]["samples"] == [("m", {"a": "x,y", "b": "z"}, 1.0)]


# ---------------------------------------------------------------------------
# FleetScraper
# ---------------------------------------------------------------------------

class ScrapableStub:
    def __init__(self, text):
        self.text = text

    def metrics_text(self):
        return self.text


def test_scraper_federates_with_replica_label():
    s = FleetScraper(registry=MetricRegistry())
    s.record("r0", replica_text(tokens=10))
    s.record("r1", replica_text(tokens=20))
    out = s.render_prometheus()
    assert 'fleet_llm_tokens_generated{replica="r0"} 10.0' in out
    assert 'fleet_llm_tokens_generated{replica="r1"} 20.0' in out
    # histogram labels merge after the replica label
    assert 'fleet_llm_batch_occupancy_bucket{replica="r0",le="0.5"} ' \
        in out
    assert "# TYPE fleet_llm_tokens_generated counter" in out


def test_scraper_aggregates_hit_rate_is_fleet_wide():
    reg = MetricRegistry()
    s = FleetScraper(registry=reg)
    s.record("r0", replica_text(prompt=100, hits=40))
    s.record("r1", replica_text(prompt=300, hits=20))
    agg = s.aggregates()
    # sum(hits)/sum(prompts), NOT the mean of per-replica rates
    assert agg["prefix_cache_hit_rate"] == pytest.approx(60 / 400)
    assert agg["replicas_scraped"] == 2
    assert agg["tokens_generated"] == 20.0
    assert agg["occupancy"] == pytest.approx(0.5)
    assert reg.get("fleet_prefix_cache_hit_rate").value == \
        pytest.approx(0.15)


def test_scraper_down_replica_drops_out_of_aggregates():
    reg = MetricRegistry()
    s = FleetScraper(registry=reg)
    s.record("r0", replica_text(tokens=10))
    s.record("r1", replica_text(tokens=20))
    s.record("r1", None)               # scrape failed
    agg = s.aggregates()
    assert agg["replicas_scraped"] == 1
    assert agg["tokens_generated"] == 10.0
    assert 'replica="r1"' not in s.render_prometheus()
    rep = s.replica_report()
    assert rep["r1"]["up"] is False    # marked down, not hidden
    assert rep["r0"]["up"] is True
    assert reg.get("fleet_replica_up").labels("r1").value == 0


def test_scraper_scrape_uses_client_surface_and_tolerates_absence():
    s = FleetScraper(registry=MetricRegistry())
    assert s.scrape("r0", ScrapableStub(replica_text())) is True
    # non-exporters (no surface / deliberate opt-out) stay ABSENT —
    # a healthy LocalReplica must not read as a down replica
    assert s.scrape("r1", object()) is False
    class OptOut:
        metrics_opt_out = True
        def metrics_text(self):
            return None
    assert s.scrape("r2", OptOut()) is False
    rep = s.replica_report()
    assert rep["r0"]["up"]
    assert "r1" not in rep and "r2" not in rep
    # an EXPORTER whose scrape fails IS down
    class Broken:
        def metrics_text(self):
            return None
    assert s.scrape("r3", Broken()) is False
    assert s.replica_report()["r3"]["up"] is False
    # mark_unreachable follows the same split
    s.mark_unreachable("r0", ScrapableStub(""))
    assert s.replica_report()["r0"]["up"] is False
    s.mark_unreachable("r2", OptOut())
    assert "r2" not in s.replica_report()


def test_scraper_forget_zeroes_liveness_of_past_exporter():
    reg = MetricRegistry()
    s = FleetScraper(registry=reg)
    s.record("r0", replica_text())
    assert reg.get("fleet_replica_up").labels("r0").value == 1
    s.forget("r0")
    assert reg.get("fleet_replica_up").labels("r0").value == 0
    assert s.aggregates()["replicas_scraped"] == 0


def test_slo_gauges_decay_via_refresh_and_report():
    t, clock = mk_tracker(targets={"gold": 0.9})
    for _ in range(5):
        t.record("gold", None, 0.01, "error")
    g = t.registry.get("slo_burn_rate")
    assert g.labels("gold", "short").value == pytest.approx(10.0)
    clock["t"] += 500.0                # everything ages out
    # no new traffic: refresh (the router poll) must decay the gauge
    t.refresh()
    assert g.labels("gold", "short").value == 0.0
    assert g.labels("gold", "long").value == 0.0
    # and reading /sloz republishes too (they can never disagree)
    for _ in range(2):
        t.record("gold", None, 0.01, "error")
    assert g.labels("gold", "short").value > 0
    clock["t"] += 500.0
    rep = t.report()
    assert rep["classes"]["gold"]["windows"]["short"]["burn_rate"] == 0
    assert g.labels("gold", "short").value == 0.0


def test_slo_latency_percentiles_merge_across_tenants():
    t, clock = mk_tracker(targets={"gold": 0.9})
    # one fast tenant, one slow tenant, plus untenanted traffic —
    # the class percentiles must see ALL of it
    for _ in range(10):
        t.record("gold", "fast-co", 0.01, "ok")
    for _ in range(10):
        t.record("gold", "slow-co", 4.0, "ok")
    t.record("gold", None, 0.01, "ok")
    lat = t.report()["classes"]["gold"]["latency_s"]
    assert lat["p99"] > 1.0, lat       # the slow tenant is visible
    assert lat["p50"] < 1.0, lat


# ---------------------------------------------------------------------------
# SLOTracker
# ---------------------------------------------------------------------------

def mk_tracker(**kw):
    clock = {"t": 1000.0}
    kw.setdefault("registry", MetricRegistry())
    kw.setdefault("windows", (10.0, 100.0))
    kw.setdefault("breach_threshold", 5.0)
    kw.setdefault("min_samples", 4)
    t = SLOTracker(clock=lambda: clock["t"], **kw)
    return t, clock


def test_burn_rate_math():
    t, clock = mk_tracker(targets={"gold": 0.9})   # 10% budget
    for i in range(8):
        t.record("gold", None, 0.01, "ok")
    for i in range(2):
        t.record("gold", None, 0.01, "deadline")
    # 2 errors / 10 requests = 20% error rate; budget 10% → burn 2.0
    assert t.burn_rates("gold") == {"short": pytest.approx(2.0),
                                    "long": pytest.approx(2.0)}
    assert t.breached() == []          # burn 2.0 < threshold 5.0


def test_short_window_forgets_old_errors():
    t, clock = mk_tracker(targets={"gold": 0.9})
    for _ in range(5):
        t.record("gold", None, 0.01, "error")
    assert t.burn_rates("gold")["short"] == pytest.approx(10.0)
    clock["t"] += 20.0                 # past the 10s short window
    for _ in range(5):
        t.record("gold", None, 0.01, "ok")
    rates = t.burn_rates("gold")
    assert rates["short"] == 0.0       # errors aged out
    assert rates["long"] == pytest.approx(5.0)   # still in the 100s


def test_breach_latches_only_on_both_windows_and_is_sticky():
    t, clock = mk_tracker(targets={"gold": 0.99})
    for _ in range(6):
        t.record("gold", None, 0.01, "deadline")
    assert t.breached() == ["gold"]
    assert t.health() == "degraded"
    g = t.registry.get("slo_breach_latched")
    assert g.labels("gold").value == 1
    # traffic recovers; the latch stays until acknowledged
    clock["t"] += 200.0
    for _ in range(10):
        t.record("gold", None, 0.01, "ok")
    assert t.burn_rates("gold") == {"short": 0.0, "long": 0.0}
    assert t.breached() == ["gold"]
    t.reset_breach()
    assert t.breached() == [] and t.health() == "healthy"
    assert g.labels("gold").value == 0


def test_min_samples_gates_the_latch():
    t, clock = mk_tracker(targets={"gold": 0.99}, min_samples=10)
    for _ in range(5):                  # burning hard, but thin data
        t.record("gold", None, 0.01, "error")
    assert t.burn_rates("gold")["short"] > 5.0
    assert t.breached() == []


def test_cancelled_burns_no_budget():
    t, clock = mk_tracker(targets={"gold": 0.5})
    for _ in range(6):
        t.record("gold", None, 0.01, "cancelled")
    assert t.burn_rates("gold") == {"short": 0.0, "long": 0.0}
    rep = t.report()
    assert rep["classes"]["gold"]["windows"]["short"]["requests"] == 0


def test_deadline_hit_ratio_counts_only_deadline_carriers():
    t, clock = mk_tracker()
    t.record("x", None, 0.01, "ok", had_deadline=True)
    t.record("x", None, 0.01, "ok", had_deadline=True)
    t.record("x", None, 0.01, "deadline", had_deadline=True)
    t.record("x", None, 0.01, "ok", had_deadline=False)   # neutral
    rep = t.report()["classes"]["x"]
    assert rep["deadline_hits"] == 2 and rep["deadline_misses"] == 1
    assert rep["deadline_hit_ratio"] == pytest.approx(2 / 3)
    assert t.registry.get("slo_deadline_hit_ratio") \
        .labels("x").value == pytest.approx(2 / 3)


def test_report_shape_and_latency_percentiles():
    t, clock = mk_tracker(targets={"gold": 0.95})
    for ms in (10, 20, 30):
        t.record("gold", None, ms / 1000.0, "ok")
    rep = t.report()
    gold = rep["classes"]["gold"]
    assert gold["target"] == 0.95
    assert gold["error_budget"] == pytest.approx(0.05)
    assert gold["windows"]["short"]["requests"] == 3
    assert gold["windows"]["short"]["window_s"] == 10.0
    assert "p99" in gold["latency_s"]
    assert rep["breached"] == []


def test_tenant_label_lands_on_latency_histogram():
    t, clock = mk_tracker()
    t.record("gold", "acme", 0.05, "ok")
    fam = t.registry.get("slo_request_seconds")
    assert fam.labels("gold", "acme").count == 1


# ---------------------------------------------------------------------------
# router integration: /fleetz, /sloz, /healthz latch, reset
# ---------------------------------------------------------------------------

class ObsStub:
    """Stub replica with a metrics surface."""

    def __init__(self, tokens=10):
        self.tokens = tokens
        self.n = 0
        self._mu = threading.Lock()

    def submit(self, prompt_ids, **kw):
        with self._mu:
            self.n += 1
        return {"output_ids": [1] * kw.get("max_new_tokens", 1),
                "prompt_ids": list(prompt_ids)}

    def health(self):
        return "healthy"

    def metrics_text(self):
        return replica_text(tokens=self.tokens, done=self.n)

    def cancel(self, request_id):
        return False

    def close(self):
        pass


def _get_json(url, timeout=30):
    with urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


@pytest.fixture()
def obs_router():
    stubs = {"r0": ObsStub(tokens=10), "r1": ObsStub(tokens=30)}
    router = Router(
        stubs, health_poll_interval=0.05, page_size=16,
        slo_classes={"gold": SLOClass("gold", deadline_s=30.0,
                                      target=0.9)},
        slo_windows=(5.0, 50.0), slo_min_samples=4,
        slo_breach_threshold=5.0)
    srv = DebugServer(port=0).start()
    yield stubs, router, f"http://127.0.0.1:{srv.port}"
    router.close()
    srv.stop()


def _wait(fn, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_fleetz_over_http_aggregates_and_labels(obs_router):
    stubs, router, base = obs_router
    for i in range(4):
        router.submit([i, i + 1, i + 2], max_new_tokens=2) \
            .result(timeout=30)

    def both_scraped():
        _code, fz = _get_json(base + "/fleetz")
        fleet = next(iter(fz["fleets"].values()))
        reps = fleet["replicas"]
        ok = all((reps[n].get("metrics") or {}).get("up")
                 for n in ("r0", "r1"))
        return fleet if ok else None

    fleet = _wait(both_scraped, what="/fleetz scraping both stubs")
    assert fleet["aggregates"]["replicas_scraped"] == 2
    assert fleet["aggregates"]["tokens_generated"] == 40.0
    assert fleet["replicas"]["r0"]["breaker"] == "closed"
    assert fleet["replicas"]["r0"]["health"] == "healthy"
    # the federated block rides the router process's own /metrics
    with urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'fleet_llm_tokens_generated{replica="r0"} 10.0' in text
    assert 'fleet_llm_tokens_generated{replica="r1"} 30.0' in text
    assert "fleet_replicas_scraped 2.0" in text
    # exposition still parses line-by-line after the append
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        float(line.rsplit(" ", 1)[1].replace("+Inf", "inf"))


def test_sloz_burn_rate_moves_and_latch_shows_on_healthz(obs_router):
    stubs, router, base = obs_router
    from paddle_tpu.reliability.retry import DeadlineExceeded
    code, sz = _get_json(base + "/sloz")
    assert code == 200
    # deadline-miss storm on the gold class (hopeless by construction
    # — a tiny-but-positive deadline races the dispatch thread on a
    # fast host and the request can legitimately SUCCEED)
    futs = [router.submit([1, 2, 3], max_new_tokens=2, slo="gold",
                          deadline=-1.0) for _ in range(6)]
    for f in futs:
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)
    _code, sz = _get_json(base + "/sloz")
    rep = next(iter(sz["slo"].values()))
    gold = rep["classes"]["gold"]
    assert gold["windows"]["short"]["burn_rate"] > 5.0
    assert gold["windows"]["long"]["burn_rate"] > 5.0
    assert rep["breached"] == ["gold"]
    # the latch is a degraded /healthz component
    _code, hz = _get_json(base + "/healthz")
    slo_components = {k: v for k, v in hz["components"].items()
                      if k.endswith("_slo")}
    assert list(slo_components.values()) == ["degraded"]
    assert hz["status"] == "degraded"
    # operator acknowledgment over HTTP clears it
    with urlopen(Request(base + "/reset_health", data=b"{}"),
                 timeout=30) as r:
        assert r.status == 200
    _code, sz = _get_json(base + "/sloz")
    assert next(iter(sz["slo"].values()))["breached"] == []


def test_sloz_and_fleetz_404_when_no_router(monkeypatch):
    from paddle_tpu.observability import server as dbg
    monkeypatch.setattr(dbg, "_fleet_providers", {})
    monkeypatch.setattr(dbg, "_slo_providers", {})
    srv = DebugServer(port=0).start()
    try:
        for path in ("/fleetz", "/sloz"):
            with pytest.raises(HTTPError) as ei:
                urlopen(f"http://127.0.0.1:{srv.port}{path}",
                        timeout=30)
            assert ei.value.code == 404
    finally:
        srv.stop()


def test_router_close_unregisters_fleet_surfaces(obs_router):
    stubs, router, base = obs_router
    router.close()
    with pytest.raises(HTTPError) as ei:
        urlopen(base + "/fleetz", timeout=30)
    assert ei.value.code == 404


# ---------------------------------------------------------------------------
# /tracez query filters + ts_wall
# ---------------------------------------------------------------------------

@pytest.fixture()
def traced_server():
    tracing.clear()
    tracing.enable()
    srv = DebugServer(port=0).start()
    yield f"http://127.0.0.1:{srv.port}"
    srv.stop()
    tracing.disable()
    tracing.clear()


def test_tracez_trace_id_and_limit_filters(traced_server):
    base = traced_server
    roots = []
    for i in range(3):
        root = tracing.start_span(f"req{i}", parent=None)
        tracing.start_span("child", parent=root).end()
        roots.append(root)
    roots[0].end()
    roots[1].end()          # roots[2] stays live
    target = roots[0].trace_id
    _code, tz = _get_json(base + f"/tracez?trace_id={target}")
    assert tz["finished_matched"] == 2
    assert {s["trace_id"] for s in tz["finished"]} == {target}
    assert {s["name"] for s in tz["finished"]} == {"req0", "child"}
    assert tz["live"] == []
    assert tz["finished_total"] == 5    # the unfiltered ring size
    # live spans filter too
    live_tid = roots[2].trace_id
    _code, tz = _get_json(base + f"/tracez?trace_id={live_tid}")
    assert [s["name"] for s in tz["live"]] == ["req2"]
    # limit applies after the filter; 0 = uncapped
    _code, tz = _get_json(base + f"/tracez?trace_id={target}&limit=1")
    assert len(tz["finished"]) == 1 and tz["finished_matched"] == 2
    _code, tz = _get_json(base + "/tracez?limit=0")
    assert len(tz["finished"]) == 5
    # every span carries ts_wall for cross-process alignment
    assert all(isinstance(s["ts_wall"], float)
               for s in tz["finished"])
    roots[2].end()


# ---------------------------------------------------------------------------
# trace_merge
# ---------------------------------------------------------------------------

def test_trace_merge_aligns_processes_on_wall_time(tmp_path):
    from tools.trace_merge import load_source, merge_chrome_trace
    tid = "a" * 32
    router_spans = [{
        "name": "router.dispatch", "trace_id": tid, "span_id": "r1",
        "parent_id": None, "ts": 5.0, "dur": 0.1, "tid": 1,
        "tname": "disp", "status": "ok", "attrs": {}, "events": [],
        "ts_wall": 100.0, "live": False,
        "links": [{"trace_id": tid, "span_id": "r0"}],
    }]
    # the replica's perf clock is wildly different; ts_wall aligns
    replica_spans = [{
        "name": "llm.request", "trace_id": tid, "span_id": "s1",
        "parent_id": "r1", "ts": 9000.0, "dur": 0.05, "tid": 7,
        "tname": "loop", "status": "ok", "attrs": {}, "ts_wall": 100.02,
        "events": [{"ts": 9000.01, "name": "chunk"}], "live": False,
    }, {
        "name": "other.trace", "trace_id": "b" * 32, "span_id": "s2",
        "parent_id": None, "ts": 9000.0, "dur": 0.01, "tid": 7,
        "tname": "loop", "status": "ok", "attrs": {}, "ts_wall": 100.5,
        "events": [], "live": False,
    }]
    # a flight-dump source as the third process
    flight = tmp_path / "flight_1_exception.jsonl"
    flight.write_text(
        json.dumps({"kind": "header", "reason": "exception"}) + "\n"
        + json.dumps({"kind": "span", "live": True,
                      "name": "llm.decode", "trace_id": tid,
                      "span_id": "s3", "parent_id": "s1", "ts": 1.0,
                      "dur": None, "tid": 2, "status": "ok",
                      "attrs": {}, "events": [],
                      "ts_wall": 100.04}) + "\n")
    out = tmp_path / "merged.json"
    summary = merge_chrome_trace(
        {"router": router_spans, "r0": replica_spans,
         "r0-flight": load_source(str(flight))},
        str(out), trace_id=tid)
    assert summary["spans"] == 3       # other.trace filtered out
    assert summary["trace_ids"] == 1
    assert summary["links"] == 1
    chrome = json.loads(out.read_text())
    evs = chrome["traceEvents"]
    pnames = {e["args"]["name"] for e in evs
              if e["name"] == "process_name"}
    assert pnames == {"router", "r0", "r0-flight"}
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"router.dispatch", "llm.request",
                          "llm.decode"}
    # wall alignment: t0 = earliest ts_wall (100.0) → dispatch at 0,
    # llm.request at 20ms, llm.decode at 40ms — perf clocks ignored
    assert spans["router.dispatch"]["ts"] == pytest.approx(0.0)
    assert spans["llm.request"]["ts"] == pytest.approx(20_000, rel=1e-3)
    assert spans["llm.decode"]["ts"] == pytest.approx(40_000, rel=1e-3)
    assert spans["llm.request"]["pid"] != spans["router.dispatch"]["pid"]
    assert spans["llm.decode"]["args"]["live"] is True
    assert spans["router.dispatch"]["args"]["links"] == [
        {"trace_id": tid, "span_id": "r0"}]
    # the span event converted through its span's wall offset
    inst = [e for e in evs if e["ph"] == "i"]
    assert inst and inst[0]["name"] == "llm.request:chunk"
    assert inst[0]["ts"] == pytest.approx(30_000, rel=1e-3)


def test_trace_merge_loads_tracez_url(traced_server):
    from tools.trace_merge import load_source, merge_chrome_trace
    base = traced_server
    root = tracing.start_span("req", parent=None)
    tracing.start_span("child", parent=root).end()
    root.end()
    spans = load_source(base + "/tracez")
    assert {s["name"] for s in spans} == {"req", "child"}
    assert all("ts_wall" in s for s in spans)
    out = "/tmp/pt_trace_merge_url_test.json"
    summary = merge_chrome_trace({"p": spans}, out,
                                 trace_id=root.trace_id)
    assert summary["spans"] == 2
