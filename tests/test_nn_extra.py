"""Long-tail nn layers (ref: test_activation_op.py, test_pixel_shuffle.py,
test_fold_op.py, test_bilinear_api.py, test_pool3d_op.py families)."""

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def _x(*s, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*s), jnp.float32)


def test_pixel_shuffle_roundtrip():
    x = _x(2, 8, 4, 4)
    up = nn.PixelShuffle(2)(x)
    assert up.shape == (2, 2, 8, 8)
    back = nn.PixelUnshuffle(2)(up)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))


def test_pixel_shuffle_matches_torch():
    import torch
    x = np.random.RandomState(1).randn(1, 4, 3, 3).astype(np.float32)
    ours = np.asarray(nn.PixelShuffle(2)(jnp.asarray(x)))
    ref = torch.nn.functional.pixel_shuffle(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(ours, ref)


def test_fold_inverts_unfold_nonoverlapping():
    x = _x(2, 3, 8, 8)
    cols = F.unfold(x, 2, strides=2)
    back = nn.Fold((8, 8), 2, strides=2)(cols)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               rtol=1e-6, atol=1e-6)


def test_fold_sums_overlaps_like_torch():
    import torch
    x = np.random.RandomState(2).randn(1, 2 * 9, 9).astype(np.float32)
    ours = np.asarray(nn.Fold((5, 5), 3, strides=1)(jnp.asarray(x)))
    ref = torch.nn.functional.fold(torch.from_numpy(x), (5, 5), 3).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_bilinear_layer():
    pt.seed(0)
    b = nn.Bilinear(4, 5, 3)
    x1, x2 = _x(6, 4, seed=3), _x(6, 5, seed=4)
    out = b(x1, x2)
    assert out.shape == (6, 3)
    w = np.asarray(b.weight)
    ref = np.einsum("bi,oij,bj->bo", np.asarray(x1), w,
                    np.asarray(x2)) + np.asarray(b.bias)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-5)


def test_distance_layers():
    x, y = _x(4, 8, seed=5), _x(4, 8, seed=6)
    cs = nn.CosineSimilarity(axis=1)(x, y)
    ref = (np.asarray(x) * np.asarray(y)).sum(1) / (
        np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(np.asarray(cs), ref, rtol=1e-5,
                               atol=1e-6)
    pd = nn.PairwiseDistance()(x, y)
    np.testing.assert_allclose(
        np.asarray(pd), np.linalg.norm(np.asarray(x) - np.asarray(y)
                                       + 1e-6, axis=-1), rtol=1e-5)


def test_maxout_and_celu():
    x = _x(2, 6, 4, 4, seed=7)
    out = nn.Maxout(3)(x)
    assert out.shape == (2, 2, 4, 4)
    import torch
    tx = torch.from_numpy(np.asarray(x))
    np.testing.assert_allclose(
        np.asarray(nn.CELU()(x)),
        torch.nn.functional.celu(tx).numpy(), rtol=1e-5, atol=1e-6)


def test_rrelu_modes():
    pt.seed(0)
    l = nn.RReLU(0.1, 0.3)
    x = -jnp.ones((64,))
    l.eval()
    np.testing.assert_allclose(np.asarray(l(x)), -0.2, rtol=1e-6)
    l.train()
    out = np.asarray(l(x))
    assert (out <= -0.1 + 1e-6).all() and (out >= -0.3 - 1e-6).all()
    assert np.unique(out).size > 1


def test_pads_and_upsample():
    x = _x(1, 2, 4, 4, seed=8)
    padded = nn.ZeroPad2D([1, 1, 2, 2])(x)
    assert padded.shape == (1, 2, 8, 6)
    up = nn.UpsamplingBilinear2D(scale_factor=2)(x)
    assert up.shape == (1, 2, 8, 8)
    near = nn.Upsample(scale_factor=2)(x)
    assert near.shape == (1, 2, 8, 8)


def test_local_response_norm_matches_torch():
    import torch
    x = np.abs(np.random.RandomState(9).randn(2, 8, 4, 4)
               ).astype(np.float32)
    ours = np.asarray(nn.LocalResponseNorm(size=5)(jnp.asarray(x)))
    ref = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), 5).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


def test_pool3d_and_adaptive():
    x = _x(1, 2, 4, 8, 8, seed=10)
    out = nn.MaxPool3D(2)(x)
    assert out.shape == (1, 2, 2, 4, 4)
    out = nn.AvgPool3D(2)(x)
    assert out.shape == (1, 2, 2, 4, 4)
    out = nn.AdaptiveAvgPool3D(2)(x)
    assert out.shape == (1, 2, 2, 2, 2)
    x1 = _x(2, 3, 12, seed=11)
    assert nn.AdaptiveAvgPool1D(4)(x1).shape == (2, 3, 4)
    assert nn.AdaptiveMaxPool1D(3)(x1).shape == (2, 3, 3)
    # adaptive mean == reshape-mean reference
    np.testing.assert_allclose(
        np.asarray(nn.AdaptiveAvgPool1D(4)(x1)),
        np.asarray(x1).reshape(2, 3, 4, 3).mean(-1), rtol=1e-6)


def test_alpha_dropout_preserves_moments():
    pt.seed(0)
    l = nn.AlphaDropout(0.3)
    l.train()
    x = jnp.asarray(np.random.RandomState(12).randn(20000),
                    jnp.float32)
    out = np.asarray(l(x))
    assert abs(out.mean()) < 0.05
    assert abs(out.std() - 1.0) < 0.1
    l.eval()
    np.testing.assert_allclose(np.asarray(l(x)), np.asarray(x))


def test_fold_with_dilation_matches_torch():
    import torch
    x = np.random.RandomState(13).randn(1, 2 * 4, 9).astype(np.float32)
    ours = np.asarray(nn.Fold((7, 7), 2, strides=2,
                              dilations=2)(jnp.asarray(x)))
    ref = torch.nn.functional.fold(torch.from_numpy(x), (7, 7), 2,
                                   dilation=2, stride=2).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_pad_channels_last():
    x = jnp.ones((1, 4, 4, 2))
    out = F.pad(x, [1, 1, 2, 2], data_format="NHWC")
    assert out.shape == (1, 8, 6, 2)  # H+4, W+2, C untouched
    out = nn.Pad2D([1, 1, 2, 2], data_format="NHWC")(x)
    assert out.shape == (1, 8, 6, 2)


def test_activation_positional_args():
    assert float(nn.CELU(0.2)(jnp.asarray(-1.0))) == pytest.approx(
        0.2 * np.expm1(-1.0 / 0.2), rel=1e-5)
    assert float(nn.Hardtanh(-2.0, 2.0)(jnp.asarray(3.0))) == 2.0


def test_maxout_axis_minus_one():
    from paddle_tpu.nn.layers.extra import maxout
    x = _x(2, 4, 4, 6, seed=14)
    out = maxout(x, 3, axis=-1)
    assert out.shape == (2, 4, 4, 2)
    ref = np.asarray(x).reshape(2, 4, 4, 2, 3).max(-1)
    np.testing.assert_allclose(np.asarray(out), ref)
