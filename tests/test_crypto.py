"""Model-artifact encryption (ref: paddle/fluid/framework/io/crypto/
AESCipher + fluid io use_cipher — here an authenticated stdlib XOF
stream cipher, scheme documented in paddle_tpu/io/crypto.py)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit, nn
from paddle_tpu.io import crypto

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_roundtrip_and_tamper_detection():
    key = b"0123456789abcdef"
    data = bytes(range(256)) * 41 + b"tail"
    blob = crypto.encrypt_bytes(data, key)
    assert blob[:8] != data[:8] and len(blob) == len(data) + 56
    assert crypto.decrypt_bytes(blob, key) == data
    # different nonce every time -> different ciphertext, same plain
    blob2 = crypto.encrypt_bytes(data, key)
    assert blob2 != blob
    assert crypto.decrypt_bytes(blob2, key) == data
    # wrong key and bit-flips are rejected BEFORE emitting plaintext
    with pytest.raises(ValueError, match="authentication failed"):
        crypto.decrypt_bytes(blob, b"another-key-16bb")
    flipped = bytearray(blob)
    flipped[70] ^= 1
    with pytest.raises(ValueError, match="authentication failed"):
        crypto.decrypt_bytes(bytes(flipped), key)
    with pytest.raises(ValueError, match="length >= 16"):
        crypto.encrypt_bytes(data, b"short")


def test_jit_save_load_encrypted(tmp_path):
    """The deploy story: encrypted artifact serves only with the key;
    on-disk program/params are opaque; outputs match the plaintext
    artifact exactly."""
    key = b"secret-key-0123456789"
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    spec = [jit.InputSpec([4, 8], "float32")]

    plain_dir = str(tmp_path / "plain")
    jit.save(net, plain_dir, input_spec=spec)
    ref = np.asarray(jit.load(plain_dir)(x))

    enc_dir = str(tmp_path / "enc")
    jit.save(net, enc_dir, input_spec=spec, encrypt_key=key)
    import os
    for fname in ("program.stablehlo", "params.pkl"):
        full = os.path.join(enc_dir, fname)
        if os.path.exists(full):
            assert crypto.is_encrypted(full), fname
    with pytest.raises(ValueError, match="pass decrypt_key"):
        jit.load(enc_dir)
    with pytest.raises(ValueError, match="authentication failed"):
        jit.load(enc_dir, decrypt_key=b"wrong-key-0123456789")
    # stripping the encryption must NOT downgrade an authenticated
    # load to a plaintext pickle (r5 review finding)
    with pytest.raises(ValueError, match="NOT encrypted"):
        jit.load(plain_dir, decrypt_key=key)
    out = np.asarray(jit.load(enc_dir, decrypt_key=key)(x))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
    # no native twins for encrypted artifacts (documented, no warning)
    assert not os.path.exists(os.path.join(enc_dir, "params.pbin"))
