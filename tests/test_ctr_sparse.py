"""Sparse embedding + Wide&Deep/DeepFM CTR path (BASELINE config 5;
replaces the reference's PS tests — SURVEY.md §3.5 Wide&Deep config,
test strategy: convergence on synthetic click data + sharded-table
equivalence on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.metric import Auc
from paddle_tpu.models.widedeep import (DeepFM, WideDeep,
                                        synthetic_criteo)
from paddle_tpu.nn.layer import functional_call, split_state
from paddle_tpu.nn.layers.sparse_embedding import (MultiSlotEmbedding,
                                                   SparseEmbedding)


def test_sparse_embedding_pooling_and_padding():
    emb = SparseEmbedding(100, 8, combiner="sum", padding_idx=0)
    ids = jnp.asarray([[1, 2, 0, 0], [3, 0, 0, 0]])
    out = emb(ids)
    w = emb.weight
    np.testing.assert_allclose(out[0], np.asarray(w[1] + w[2]), atol=1e-6)
    np.testing.assert_allclose(out[1], np.asarray(w[3]), atol=1e-6)
    # mean combiner divides by the number of non-pad ids
    emb2 = SparseEmbedding(100, 8, combiner="mean", padding_idx=0)
    emb2.weight = emb.weight
    out2 = emb2(ids)
    np.testing.assert_allclose(out2[0], np.asarray(w[1] + w[2]) / 2,
                               atol=1e-6)


def test_hash_ids_folds_out_of_range():
    emb = SparseEmbedding(10, 4, hash_ids=True)
    huge = jnp.asarray([[2000000001, 0]])  # out of range + padding
    out = emb(huge)
    # multiply-shift (Fibonacci) whitening before the modulo — a bare
    # id % N clusters structured CTR key spaces onto hot rows
    h = (2000000001 * 0x9E3779B9) & 0xFFFFFFFF  # uint32 wraparound
    h ^= h >> 16
    expected_row = 1 + h % 9
    np.testing.assert_allclose(out[0],
                               np.asarray(emb.weight[expected_row]),
                               atol=1e-6)
    # padding id maps to itself: a row of ONLY padding pools to zero
    only_pad = emb(jnp.asarray([[0, 0]]))
    np.testing.assert_allclose(np.asarray(only_pad), 0.0, atol=1e-7)
    # without hashing, gather clamps (documented XLA semantics)
    emb2 = SparseEmbedding(10, 4, hash_ids=False)
    out2 = emb2(huge)
    np.testing.assert_allclose(out2[0], np.asarray(emb2.weight[9]),
                               atol=1e-6)


def test_multislot_layout():
    ms = MultiSlotEmbedding(50, 4)
    ids = jnp.asarray([[1, 2, 3]])  # 3 slots, single id each
    out = ms(ids)
    assert out.shape == (1, 12)
    w = ms.table.weight
    np.testing.assert_allclose(out[0, :4], np.asarray(w[1]), atol=1e-6)
    np.testing.assert_allclose(out[0, 8:], np.asarray(w[3]), atol=1e-6)


def test_sparse_grads_hit_only_looked_up_rows():
    emb = SparseEmbedding(100, 4)
    params, buffers = split_state(emb)
    ids = jnp.asarray([[5, 7, 0, 0]])

    def loss(p):
        out, _ = functional_call(emb, p, buffers, ids)
        return (out ** 2).sum()

    g = jax.grad(loss)(params)["weight"]
    touched = set(np.nonzero(np.abs(np.asarray(g)).sum(-1))[0])
    assert touched == {5, 7}  # pad row 0 masked out, others untouched


@pytest.mark.parametrize("model_cls", [WideDeep, DeepFM])
def test_ctr_model_learns_and_auc_improves(model_cls):
    dense, sparse, labels = synthetic_criteo(n=2048, vocab_size=2000)
    net = model_cls(vocab_size=2000, embedding_dim=8, hidden=(32, 16))
    params, buffers = split_state(net)
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=net)
    state = opt.init_state(params)

    d = jnp.asarray(dense)
    s = jnp.asarray(sparse)
    y = jnp.asarray(labels)

    @jax.jit
    def step(params, state, i):
        def loss_fn(p):
            logits, _ = functional_call(net, p, buffers, d, s)
            return nn.functional.binary_cross_entropy_with_logits(
                logits, y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply_gradients(params, grads, state, i)
        return params, state, loss

    losses = []
    for i in range(60):
        params, state, loss = step(params, state, i)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[::20]

    # AUC well above chance on the training distribution
    logits, _ = functional_call(net, params, buffers, d, s)
    probs = 1 / (1 + np.exp(-np.asarray(logits)))
    auc = Auc()
    auc.update(probs, np.asarray(y))
    assert auc.accumulate() > 0.7


def test_sparse_table_sharded_over_mesh_matches_dense():
    """Vocab rows sharded over fsdp: same lookups as unsharded — the
    PS-shard equivalence test, minus the PS."""
    emb = SparseEmbedding(64, 8)
    ids = jnp.asarray([[1, 63, 17, 0], [2, 2, 5, 9]])
    ref = np.asarray(emb(ids))
    mesh = parallel.init_mesh(fsdp=8)
    try:
        params, buffers = split_state(emb)
        meta = emb.param_meta()
        sharded = parallel.shard_params(params, meta, mesh)
        # rows really are distributed
        assert "fsdp" in str(sharded["weight"].sharding)

        @jax.jit
        def fwd(p, ids):
            out, _ = functional_call(emb, p, buffers, ids)
            return out

        out = np.asarray(fwd(sharded, ids))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_incubate_namespace():
    from paddle_tpu import incubate
    assert incubate.SparseEmbedding is SparseEmbedding
    assert hasattr(incubate, "FusedMultiHeadAttention")
    assert hasattr(incubate, "MoELayer")
