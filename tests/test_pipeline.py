"""Pipeline parallelism on the 8-device CPU mesh.

Validates the SPMD 1F1B-equivalent scan (parallel/pipeline.py) against
dense execution — the analog of the reference's pipeline tests
(unittests/hybrid_parallel_pp_* — compare pipelined loss to serial)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer import functional_call, split_state
from paddle_tpu.parallel.pipeline import (LayerDesc, PipelineLayer,
                                          PipelineParallel, pipeline_spmd)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.ln = nn.LayerNorm(d)

    def forward(self, x):
        return self.ln(x + self.fc2(F.gelu(self.fc1(x))))


def _x(b=8, d=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(b, d),
                       jnp.float32)


def test_pipeline_layer_groups_stages():
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(8)],
                         num_stages=4)
    assert pipe.num_stages == 4 and pipe.layers_per_stage == 2
    with pytest.raises(ValueError, match="evenly"):
        PipelineLayer([LayerDesc(Block, 16) for _ in range(6)],
                      num_stages=4)


@pytest.mark.parametrize("pp,m", [(2, 4), (4, 8)])
def test_pipeline_forward_matches_dense(pp, m):
    pt.seed(0)
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(8, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, dp=8 // pp)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh)
        out = np.asarray(jax.jit(pp_layer.forward)(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_dense():
    pt.seed(0)
    pp, m = 4, 4
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(8, 16)
    params, buffers = split_state(pipe)

    def loss_dense(p):
        out, _ = functional_call(pipe, p, buffers, x)
        return (out ** 2).mean()

    g_dense = jax.grad(loss_dense)(params)

    mesh = parallel.init_mesh(pp=pp, dp=2)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh)
        # the wrapper exposes the same params nested under .pipe
        wp, wb = split_state(pp_layer)

        def loss_pp(p):
            out, _ = functional_call(pp_layer, p, wb, x)
            return (out ** 2).mean()

        g_pp = jax.jit(jax.grad(loss_pp))(wp)
    finally:
        parallel.set_mesh(None)
    for k, v in g_dense.items():
        np.testing.assert_allclose(
            g_pp[f"pipe.{k}"], v, atol=1e-5, rtol=1e-4, err_msg=k)


def test_pipeline_with_dp_axis():
    """pp x dp hybrid: microbatches keep their dp sharding."""
    pt.seed(0)
    pp, m = 2, 2
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(8, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh,
                                    mb_spec=P("dp"))
        out = np.asarray(jax.jit(pp_layer.forward)(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


def test_pipeline_falls_back_dense_without_pp():
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(2)],
                         num_stages=2)
    x = _x(4, 16)
    out = PipelineParallel(pipe, num_microbatches=2)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(pipe(x)),
                               atol=1e-6)


def test_pipeline_heterogeneous_stages_rejected():
    class Other(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            return self.fc(x)

    pipe = PipelineLayer([LayerDesc(Block, 16), LayerDesc(Other, 16)],
                         num_stages=2)
    mesh = parallel.init_mesh(pp=2, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=2, mesh=mesh)
        with pytest.raises(ValueError, match="structurally identical"):
            pp_layer(_x(4, 16))
    finally:
        parallel.set_mesh(None)
