"""Pipeline parallelism on the 8-device CPU mesh.

Validates the circular SPMD pipeline scan (parallel/pipeline.py) against
dense execution — the analog of the reference's pipeline tests
(unittests/hybrid_parallel_pp_* — compare pipelined loss to serial;
interleaving ref: hybrid_parallel_pp_transformer with virtual stages)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer import functional_call, split_state
from paddle_tpu.parallel.pipeline import (LayerDesc, PipelineLayer,
                                          PipelineParallel, pipeline_spmd)

import pytest
pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.ln = nn.LayerNorm(d)

    def forward(self, x):
        return self.ln(x + self.fc2(F.gelu(self.fc1(x))))


def _x(b=8, d=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(b, d),
                       jnp.float32)


@pytest.mark.smoke  # smoke-tier representative (file is all-slow)
def test_pipeline_layer_groups_stages():
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(8)],
                         num_stages=4)
    assert pipe.num_stages == 4 and pipe.layers_per_stage == 2
    with pytest.raises(ValueError, match="evenly"):
        PipelineLayer([LayerDesc(Block, 16) for _ in range(6)],
                      num_stages=4)


@pytest.mark.smoke  # smoke-tier representative (file is all-slow)
@pytest.mark.parametrize("pp,m", [(2, 4), (4, 8), (4, 6)])
def test_pipeline_forward_matches_dense(pp, m):
    pt.seed(0)
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(24, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, dp=8 // pp)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh)
        out = np.asarray(jax.jit(pp_layer.forward)(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("pp,v,m", [(2, 2, 4), (2, 3, 2), (4, 2, 8)])
def test_pipeline_interleaved_matches_dense(pp, v, m):
    """Circular schedule (virtual_pp_degree > 1) == dense execution."""
    pt.seed(0)
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp * v)],
                         num_stages=pp * v)
    x = _x(8, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, dp=8 // pp)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m,
                                    virtual_pp_degree=v, mesh=mesh)
        out = np.asarray(jax.jit(pp_layer.forward)(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("v", [1, 2])
def test_pipeline_grads_match_dense(v):
    pt.seed(0)
    pp, m = 2, 4
    n_chunks = pp * v
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(n_chunks)],
                         num_stages=n_chunks)
    x = _x(8, 16)
    params, buffers = split_state(pipe)

    def loss_dense(p):
        out, _ = functional_call(pipe, p, buffers, x)
        return (out ** 2).mean()

    g_dense = jax.grad(loss_dense)(params)

    mesh = parallel.init_mesh(pp=pp, dp=8 // pp)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m,
                                    virtual_pp_degree=v, mesh=mesh)
        wp, wb = split_state(pp_layer)

        def loss_pp(p):
            out, _ = functional_call(pp_layer, p, wb, x)
            return (out ** 2).mean()

        g_pp = jax.jit(jax.grad(loss_pp))(wp)
    finally:
        parallel.set_mesh(None)
    # stacked grads: chunk k sits at stacked position (k%pp)*v + k//pp
    for k in range(n_chunks):
        pos = (k % pp) * v + (k // pp)
        for inner in ("0.fc1.weight", "0.fc1.bias", "0.fc2.weight",
                      "0.ln.weight", "0.ln.bias"):
            dense_g = g_dense[f"stages.{k}.{inner}"]
            stacked_g = g_pp[inner.replace(".", "__")][pos]
            np.testing.assert_allclose(
                stacked_g, dense_g, atol=1e-5, rtol=1e-4,
                err_msg=f"chunk {k} {inner}")


def test_pipeline_gpt_blocks_grads_match_dense():
    """VERDICT r1 item 4: pipelined grads == dense grads for GPT decoder
    blocks (the flagship trunk), pp=2 x dp, interleaved v=2."""
    from paddle_tpu.models.gpt import GPTConfig, GPTDecoderLayer

    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                    num_heads=2, max_position_embeddings=16,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    pp, v, m = 2, 2, 2
    pipe = PipelineLayer(
        [GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)],
        num_stages=pp * v)
    x = jnp.asarray(
        np.random.RandomState(0).randn(8, 8, 16) * 0.1, jnp.float32)
    params, buffers = split_state(pipe)

    def loss_dense(p):
        out, _ = functional_call(pipe, p, buffers, x)
        return (out ** 2).mean()

    l_dense, g_dense = jax.value_and_grad(loss_dense)(params)

    mesh = parallel.init_mesh(pp=pp, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m,
                                    virtual_pp_degree=v, mesh=mesh,
                                    mb_spec=P("dp"))
        wp, wb = split_state(pp_layer)

        def loss_pp(p):
            out, _ = functional_call(pp_layer, p, wb, x)
            return (out ** 2).mean()

        l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(wp)
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(float(l_pp), float(l_dense), rtol=1e-5)
    for k in range(cfg.num_layers):
        pos = (k % pp) * v + (k // pp)
        dense_g = g_dense[f"stages.{k}.0.attn.qkv_proj.weight"]
        np.testing.assert_allclose(
            g_pp["0__attn__qkv_proj__weight"][pos], dense_g,
            atol=1e-5, rtol=1e-4, err_msg=f"chunk {k}")


def test_pipeline_with_dp_axis():
    """pp x dp hybrid: microbatches keep their dp sharding."""
    pt.seed(0)
    pp, m = 2, 2
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(8, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh,
                                    mb_spec=P("dp"))
        out = np.asarray(jax.jit(pp_layer.forward)(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


def test_pipeline_params_sharded_over_pp():
    """The stacked stage params carry a leading pp_stage axis that
    shard_params places over the pp mesh axis — each rank holds only its
    own chunks (the pp memory partition)."""
    pt.seed(0)
    pp = 4
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(pp)],
                         num_stages=pp)
    mesh = parallel.init_mesh(pp=pp, dp=2)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=2, mesh=mesh)
        params, _ = split_state(pp_layer)
        placed = parallel.shard_params(params, pp_layer.param_meta(), mesh)
        w = placed["0__fc1__weight"]
        spec = w.sharding.spec
        assert spec and spec[0] == "pp", spec
    finally:
        parallel.set_mesh(None)


class TPBlock(nn.Layer):
    """Megatron-style tp block: column-parallel fc1, row-parallel fc2 —
    declared via logical axes only; the partial-manual pipeline leaves tp
    in GSPMD auto mode so the compiler partitions the stage body."""

    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d, axes=(None, "mlp"),
                             bias_axes=("mlp",))
        self.fc2 = nn.Linear(2 * d, d, axes=("mlp", None))
        self.ln = nn.LayerNorm(d)

    def forward(self, x):
        return self.ln(x + self.fc2(F.gelu(self.fc1(x))))


@pytest.mark.parametrize("v,m", [(1, 4), (2, 2)])
def test_pipeline_with_tp_inside(v, m):
    """TP composed INSIDE the pipeline (the reference's mp x pp hybrid,
    fleet/meta_optimizers/sharding_optimizer.py:123-135): params stay
    tp-sharded on device, forward matches dense."""
    pt.seed(0)
    pp, tp = 2, 2
    pipe = PipelineLayer([LayerDesc(TPBlock, 16) for _ in range(pp * v)],
                         num_stages=pp * v)
    x = _x(8, 16)
    dense = np.asarray(pipe(x))
    mesh = parallel.init_mesh(pp=pp, tp=tp, dp=8 // (pp * tp))
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m,
                                    virtual_pp_degree=v, mesh=mesh,
                                    mb_spec=P("dp"))
        wp, wb = split_state(pp_layer)
        placed = parallel.shard_params(wp, pp_layer.param_meta(), mesh)
        # each device holds 1/(pp*tp) of fc1: [S/pp, d, 2d/tp] locally
        w = placed["0__fc1__weight"]
        S = pp * v
        local = w.addressable_shards[0].data.shape
        assert local == (S // pp, 16, 32 // tp), local
        out = np.asarray(jax.jit(
            lambda p, x: functional_call(pp_layer, p, wb, x)[0]
        )(placed, x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, dense, atol=1e-5, rtol=1e-5)


def test_pipeline_tp_grads_match_dense():
    """pp x tp x dp: pipelined+tp grads == dense grads (BASELINE config 4
    structure at toy scale)."""
    pt.seed(0)
    pp, tp, v, m = 2, 2, 2, 2
    n_chunks = pp * v
    pipe = PipelineLayer([LayerDesc(TPBlock, 16) for _ in range(n_chunks)],
                         num_stages=n_chunks)
    x = _x(8, 16)
    params, buffers = split_state(pipe)

    def loss_dense(p):
        out, _ = functional_call(pipe, p, buffers, x)
        return (out ** 2).mean()

    g_dense = jax.grad(loss_dense)(params)

    mesh = parallel.init_mesh(pp=pp, tp=tp, dp=8 // (pp * tp))
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m,
                                    virtual_pp_degree=v, mesh=mesh,
                                    mb_spec=P("dp"))
        wp, wb = split_state(pp_layer)
        placed = parallel.shard_params(wp, pp_layer.param_meta(), mesh)

        def loss_pp(p):
            out, _ = functional_call(pp_layer, p, wb, x)
            return (out ** 2).mean()

        g_pp = jax.jit(jax.grad(loss_pp))(placed)
        # grads inherit the tp sharding (no silent all-gather of opt state)
        gspec = g_pp["0__fc1__weight"].sharding.spec
        assert "tp" in jax.tree_util.tree_leaves(tuple(gspec)), gspec
    finally:
        parallel.set_mesh(None)
    for k in range(n_chunks):
        pos = (k % pp) * v + (k // pp)
        for inner in ("fc1.weight", "fc2.weight", "ln.weight"):
            np.testing.assert_allclose(
                np.asarray(g_pp["0__" + inner.replace(".", "__")])[pos],
                g_dense[f"stages.{k}.0.{inner}"],
                atol=1e-5, rtol=1e-4, err_msg=f"chunk {k} {inner}")


class DropBlock(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)
        self.drop = nn.Dropout(0.5)

    def forward(self, x):
        return x + self.drop(self.fc(x))


def test_pipeline_eval_mode_reaches_trunk():
    """eval() must disable dropout inside the pipelined stage body (the
    prototype is not a registered sublayer, so the mode is propagated per
    call)."""
    pt.seed(0)
    pp = 2
    pipe = PipelineLayer([LayerDesc(DropBlock, 16) for _ in range(pp)],
                         num_stages=pp)
    x = _x(8, 16)
    mesh = parallel.init_mesh(pp=pp, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=2, mesh=mesh)
        pp_layer.eval()
        fwd = jax.jit(pp_layer.forward)
        a = np.asarray(fwd(x))
        b = np.asarray(fwd(x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_array_equal(a, b)
    # and eval == dense eval (dropout off on both paths)
    pipe.eval()
    np.testing.assert_allclose(a, np.asarray(pipe(x)), atol=1e-5,
                               rtol=1e-5)


def test_pipeline_dropout_masks_differ_per_microbatch():
    """Training-mode dropout draws a distinct mask per tick — identical
    microbatch contents must produce different outputs (a single frozen
    trace-time key would repeat the mask across ticks/chunks)."""
    from paddle_tpu.core import rng as core_rng

    pt.seed(0)
    pp, m = 2, 2
    pipe = PipelineLayer([LayerDesc(DropBlock, 16) for _ in range(pp)],
                         num_stages=pp)
    row = np.random.RandomState(0).randn(1, 16)
    x = jnp.asarray(np.repeat(row, 8, axis=0), jnp.float32)  # mb0 == mb1
    mesh = parallel.init_mesh(pp=pp, dp=4)
    try:
        pp_layer = PipelineParallel(pipe, num_microbatches=m, mesh=mesh)
        pp_layer.train()

        def fwd(key, x):
            with core_rng.key_guard(key):
                return pp_layer(x)

        out = np.asarray(jax.jit(fwd)(jax.random.key(7), x))
    finally:
        parallel.set_mesh(None)
    mb0, mb1 = out[:4], out[4:]
    assert not np.allclose(mb0, mb1), \
        "dropout mask is frozen across microbatches/ticks"


def test_pipeline_falls_back_dense_without_pp():
    pipe = PipelineLayer([LayerDesc(Block, 16) for _ in range(2)],
                         num_stages=2)
    x = _x(4, 16)
    out = PipelineParallel(pipe, num_microbatches=2)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(pipe(x)),
                               atol=1e-6)


def test_pipeline_heterogeneous_stages_rejected():
    class Other(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            return self.fc(x)

    pipe = PipelineLayer([LayerDesc(Block, 16), LayerDesc(Other, 16)],
                         num_stages=2)
    with pytest.raises(ValueError, match="structurally identical"):
        PipelineParallel(pipe, num_microbatches=2)


def test_pipeline_buffered_stages_rejected():
    class BNBlock(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.bn = nn.BatchNorm1D(d)

        def forward(self, x):
            return self.bn(x)

    pipe = PipelineLayer([LayerDesc(BNBlock, 16) for _ in range(2)],
                         num_stages=2)
    with pytest.raises(ValueError, match="buffer-free"):
        PipelineParallel(pipe, num_microbatches=2)


def test_load_flat_state_dict_maps_old_layout():
    """Checkpoints from the pre-stacking revision (flat {j}__{suffix}
    keys, [S, ...] each) load into the homogeneous stacked layout and
    reproduce the same forward (r4 advisor finding)."""
    import paddle_tpu as pt

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x):
            return jax.nn.relu(self.fc(x))

    def build(seed):
        pt.seed(seed)
        return PipelineParallel(
            PipelineLayer([LayerDesc(Block) for _ in range(8)],
                          num_stages=4), num_microbatches=2)

    pp = build(0)
    sd = pp.state_dict()
    assert sorted(sd.keys()) == ["fc__bias", "fc__weight"]  # stacked
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8)
                    .astype(np.float32))
    y_ref = np.asarray(pp(x))

    flat = {f"{j}__{k}": np.asarray(v[:, j])
            for k, v in sd.items() for j in range(v.shape[1])}
    pp2 = build(1)
    assert not np.allclose(np.asarray(pp2(x)), y_ref)
    pp2.load_flat_state_dict(flat)
    np.testing.assert_allclose(np.asarray(pp2(x)), y_ref, rtol=1e-6)


def test_wave_accumulation_bounds_boundary_memory():
    """Long-seq decision record (pipeline.py docstring): running the
    pipeline in waves of pp microbatches with in-step grad
    accumulation bounds the backward boundary set like 1F1B —
    compiled per-device temps drop to ~half of the single-scan
    schedule at the same total batch, and gradients stay EXACT."""
    import os
    import sys
    tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "tools")
    sys.path.insert(0, tools)
    try:
        from pp_longseq_memory import PP, H, SeqBlock, temp_bytes
    finally:
        sys.path.remove(tools)

    full = temp_bytes(2048, 16, wave=16)
    waved = temp_bytes(2048, 16, wave=PP)
    assert waved < 0.60 * full, (waved, full)

    # exactness: wave-accumulated grads == single-scan grads
    pt.seed(0)
    mesh = parallel.init_mesh(pp=PP, dp=8 // PP)
    try:
        pipe = PipelineLayer([LayerDesc(SeqBlock) for _ in range(PP)],
                             num_stages=PP)
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(8, 64, H).astype(np.float32))

        def grads(wave):
            pl = PipelineParallel(pipe, num_microbatches=wave,
                                  mesh=mesh)
            p, b = split_state(pl)

            def wave_loss(pp_, xw, key):
                from paddle_tpu.core import rng as core_rng
                with core_rng.key_guard(key):   # keys stay trace-local
                    out, _ = functional_call(pl, pp_, b, xw)
                return (out ** 2).mean()

            @jax.jit
            def step(p_, key):
                def body(i, acc):
                    xw = jax.lax.dynamic_slice_in_dim(
                        x, i * wave, wave, 0)
                    g = jax.grad(wave_loss)(
                        p_, xw, jax.random.fold_in(key, i))
                    return jax.tree_util.tree_map(jnp.add, acc, g)
                zero = jax.tree_util.tree_map(jnp.zeros_like, p_)
                g = jax.lax.fori_loop(0, 8 // wave, body, zero)
                return jax.tree_util.tree_map(
                    lambda gg: gg / (8 // wave), g)
            return step(p, jax.random.PRNGKey(0))

        g_full = grads(8)
        g_wave = grads(PP)
        for k in g_full:
            np.testing.assert_allclose(np.asarray(g_wave[k]),
                                       np.asarray(g_full[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)
    finally:
        parallel.set_mesh(None)
