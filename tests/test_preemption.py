"""Preemption grace (VERDICT r2 item 8): SIGTERM → final checkpoint →
exit(RESTART_EXIT_CODE) → budget-free restart → lossless mid-range
resume. The kill-during-training test the reference expresses through
its etcd scale-down events (fleet/elastic/manager.py:131, :248-252)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np

import pytest

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

WORKER = os.path.join(os.path.dirname(__file__), "preemption_worker.py")
TOTAL = 30


def _read_losses(path):
    out = {}
    if os.path.exists(path):
        for line in open(path):
            s, v = line.split()
            out[int(s)] = float(v)
    return out


def _run(workdir, wait=True):
    p = subprocess.Popen([sys.executable, WORKER, workdir, str(TOTAL)],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT)
    if wait:
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out.decode()
    return p


def test_sigterm_checkpoints_and_resumes_losslessly(tmp_path):
    base = tmp_path / "baseline"
    base.mkdir()
    _run(str(base))
    baseline = _read_losses(base / "losses.txt")
    assert len(baseline) == TOTAL

    # interrupted run: SIGTERM mid-training
    work = tmp_path / "preempted"
    work.mkdir()
    p = _run(str(work), wait=False)
    loss_file = work / "losses.txt"
    deadline = time.time() + 240
    while time.time() < deadline:
        if len(_read_losses(loss_file)) >= 8:
            break
        time.sleep(0.2)
    else:
        p.kill()
        raise AssertionError("worker never reached step 8")
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=120)
    from paddle_tpu.distributed.elastic import RESTART_EXIT_CODE
    assert p.returncode == RESTART_EXIT_CODE, (p.returncode, out.decode())
    interrupted = _read_losses(loss_file)
    assert 0 < len(interrupted) < TOTAL

    # relaunch: resumes after the last committed step, finishes the range
    _run(str(work))
    final = _read_losses(loss_file)
    assert sorted(final) == list(range(TOTAL))
    # lossless: every step's loss — before AND after the kill — matches
    # the uninterrupted baseline bit-for-bit-ish
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], baseline[s], rtol=1e-6,
                                   err_msg=f"step {s} diverged")


def test_elastic_manager_preemption_is_budget_free(tmp_path):
    """exit(RESTART_EXIT_CODE) restarts even with max_restarts=0."""
    script = tmp_path / "onceworker.py"
    script.write_text(
        "import os, sys\n"
        "m = os.path.join(os.path.dirname(__file__), 'ran_once')\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    sys.exit(67)\n"   # graceful-preemption code
        "print('second incarnation ok')\n")
    from paddle_tpu.distributed.elastic import ElasticManager
    mgr = ElasticManager(nproc=1, training_script=str(script),
                         script_args=[], max_restarts=0)
    assert mgr.run() == 0
    assert mgr.restarts == 0  # failure budget untouched


def test_sigterm_during_first_compile_resumes_losslessly(tmp_path):
    """SIGTERM racing the FIRST compile (VERDICT r3 weak #5): the
    signal lands before any step completes — while train_batch is
    still tracing/compiling. The handler only sets a flag, so the
    compile finishes, step 0 commits, the worker exits 67 with a
    valid checkpoint, and the relaunch completes the range losslessly."""
    base = tmp_path / "baseline"
    base.mkdir()
    _run(str(base))
    baseline = _read_losses(base / "losses.txt")

    work = tmp_path / "compile_raced"
    work.mkdir()
    p = _run(str(work), wait=False)
    loss_file = work / "losses.txt"
    # fire as soon as the guard is installed but before any step lands
    # — i.e. during the trace/compile of the first train step
    sentinel = work / "guard_installed"
    deadline = time.time() + 240
    while time.time() < deadline and not sentinel.exists():
        time.sleep(0.05)
    assert sentinel.exists(), "worker never installed the guard"
    if len(_read_losses(loss_file)) > 0:
        # fast machine: step 0 beat us past the sentinel — the compile
        # race can't be staged here; product behavior is unaffected
        p.send_signal(signal.SIGTERM)
        p.communicate(timeout=240)
        pytest.skip("worker finished step 0 before the signal landed")
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=240)
    from paddle_tpu.distributed.elastic import RESTART_EXIT_CODE
    assert p.returncode == RESTART_EXIT_CODE, (p.returncode, out.decode())
    interrupted = _read_losses(loss_file)
    # the in-flight step still completed and committed before exit
    assert len(interrupted) >= 1

    _run(str(work))
    final = _read_losses(loss_file)
    assert sorted(final) == list(range(TOTAL))
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], baseline[s], rtol=1e-6,
                                   err_msg=f"step {s} diverged")


def test_sigterm_before_guard_is_budget_free(tmp_path):
    """A SIGTERM that kills the rank before PreemptionGuard installs
    (interpreter start / jax import) exits -15, not 67. The manager
    must read the platform's own signal as a preemption — budget-free
    — not as a crash that burns max_restarts."""
    script = tmp_path / "earlykill.py"
    script.write_text(
        "import os, sys, signal, time\n"
        "m = os.path.join(os.path.dirname(__file__), 'killed_once')\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    signal.signal(signal.SIGTERM, signal.SIG_DFL)\n"
        "    os.kill(os.getpid(), signal.SIGTERM)  # die pre-guard\n"
        "    time.sleep(60)\n"
        "print('second incarnation ok')\n")
    from paddle_tpu.distributed.elastic import ElasticManager
    mgr = ElasticManager(nproc=1, training_script=str(script),
                         script_args=[], max_restarts=0)
    assert mgr.run() == 0
    assert mgr.restarts == 0  # failure budget untouched
    assert mgr.generation == 1  # one budget-free respawn happened
