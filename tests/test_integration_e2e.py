"""Capstone integration: the framework's round-3 pieces composed in one
scenario — GPT with pp x tp x dp hybrid parallelism, step-granular
AutoCheckpoint, a (programmatic) preemption mid-run, and a lossless
resume on a fresh model instance. The in-process analog of running
examples/gpt_hybrid_parallel.py, killing it, and re-running it."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import parallel
from paddle_tpu.distributed import elastic
from paddle_tpu.io.checkpoint import AutoCheckpoint
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLMPipe,
                                   GPTPretrainingCriterion)

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

TOTAL = 12
PREEMPT_AT = 5


def _build(mesh):
    pt.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLMPipe(cfg, num_microbatches=2, mesh=mesh)
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.AdamW(learning_rate=1e-3, parameters=net,
                                     weight_decay=0.01),
        loss=GPTPretrainingCriterion())
    parallel.distributed_model(model, mesh=mesh)
    return cfg, model


def _batch(step, cfg):
    rng = np.random.RandomState(100 + step)
    return rng.randint(0, cfg.vocab_size, (4, 32))


def _run(ckpt_dir, preempt_at=None):
    """Train through acp.epochs; optionally 'preempt' (trigger + drain)
    at a step. Returns {step: loss}."""
    mesh = parallel.init_mesh(pp=2, tp=2, dp=2)
    losses = {}
    try:
        cfg, model = _build(mesh)
        guard = elastic.PreemptionGuard(install=False)  # programmatic
        acp = AutoCheckpoint.for_model(str(ckpt_dir), model)
        for step in acp.epochs(TOTAL):
            ids = _batch(step, cfg)
            logs = model.train_batch([ids], [ids])
            losses[step] = float(logs["loss"])
            acp.commit(step)
            if step == preempt_at:
                guard.trigger()            # the SIGTERM analog
            if guard.check(exit=False):    # checkpoint already committed
                return losses
    finally:
        parallel.set_mesh(None)
    return losses


def test_hybrid_parallel_preempt_resume_lossless(tmp_path):
    base = _run(tmp_path / "baseline")
    assert sorted(base) == list(range(TOTAL))

    first = _run(tmp_path / "resumed", preempt_at=PREEMPT_AT)
    assert sorted(first) == list(range(PREEMPT_AT + 1))

    second = _run(tmp_path / "resumed")     # fresh model, resumes
    assert sorted(second) == list(range(PREEMPT_AT + 1, TOTAL))

    merged = {**first, **second}
    for step in range(TOTAL):
        np.testing.assert_allclose(
            merged[step], base[step], rtol=1e-5,
            err_msg=f"step {step} diverged across preempt/resume")
