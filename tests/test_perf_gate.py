"""Compile-time performance regression gate (VERDICT r3 ask #1b).

The TPU tunnel can be unavailable for whole rounds, so the perf story
must be provable without a chip. XLA's compiled ``memory_analysis`` and
``cost_analysis`` are backend-independent properties of the optimized
HLO; these tests pin the program-level invariants each perf lever
bought, so a regression (lost donation, accidental remat, unfused grad
sync, a rematerialized logits buffer) fails the suite at compile time
rather than silently costing MFU on the next hardware run.

Reference context: the reference delegates model perf tracking to an
external benchmark repo (tools/ci_model_benchmark.sh:50) and carries a
frozen per-op latency DB (cost_model/static_op_benchmark.json); here
the compiler's own analysis is the database, read fresh per build
(paddle_tpu/cost_model.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.cost_model import collective_elements, memory_profile

pytestmark = pytest.mark.slow  # compile-heavy; smoke tier skips


# ---------------------------------------------------------------------------
# 1. fused linear-cross-entropy: the [T, V] logits buffer must not exist
# ---------------------------------------------------------------------------

def test_fused_xent_removes_logits_buffer():
    """ops/fused_xent streams the head matmul + loss over vocab chunks;
    the win is that no [T, V] buffer is ever resident. Gate: the fused
    fwd+bwd program's temps undercut the dense path by at least one
    full f32 logits buffer, and stay below half the dense footprint."""
    from paddle_tpu.nn import functional as F
    from paddle_tpu.ops.fused_xent import fused_linear_cross_entropy

    t, h, v = 2048, 256, 32000
    r = np.random.RandomState(0)
    hid = jnp.asarray(r.randn(t, h) * 0.1, jnp.float32)
    w = jnp.asarray(r.randn(v, h) * 0.1, jnp.float32)
    lb = jnp.asarray(r.randint(0, v, (t,)))

    def dense(a, b):
        return F.cross_entropy(a @ b.T, lb)

    def fused(a, b):
        return fused_linear_cross_entropy(a, b, lb, -100, 4096)

    md = memory_profile(jax.grad(dense, argnums=(0, 1)), (hid, w))
    mf = memory_profile(jax.grad(fused, argnums=(0, 1)), (hid, w))
    logits_bytes = t * v * 4
    assert md.temp_bytes - mf.temp_bytes >= logits_bytes, \
        (md.temp_bytes, mf.temp_bytes, logits_bytes)
    assert mf.temp_bytes < 0.5 * md.temp_bytes


# ---------------------------------------------------------------------------
# 2. flash attention: temps scale O(s); the dense path is the O(s²) foil
# ---------------------------------------------------------------------------

def _attn_temp(s: int, flash: bool) -> int:
    from paddle_tpu.ops.flash_attention import flash_attention

    b, h, d = 2, 4, 64
    q = jnp.asarray(np.random.RandomState(0).randn(b, h, s, d),
                    jnp.float32)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True).sum()

    def f_dense(q, k, v):
        sc = (q @ jnp.swapaxes(k, -1, -2)) / np.sqrt(d)
        sc = jnp.where(jnp.tril(jnp.ones((s, s), bool)), sc, -1e30)
        return (jax.nn.softmax(sc, axis=-1) @ v).sum()

    fn = f_flash if flash else f_dense
    return memory_profile(jax.grad(fn, argnums=(0, 1, 2)),
                          (q, q, q)).temp_bytes


def test_flash_attention_temps_linear_in_seq():
    """Doubling seq doubles flash temps (<=2.5x: the [s, s] score
    matrix never lands in memory) while the reference path quadruples
    (>=3.5x). This is the compile-time form of the O(s) HBM claim."""
    f1, f2 = _attn_temp(512, True), _attn_temp(1024, True)
    d1, d2 = _attn_temp(512, False), _attn_temp(1024, False)
    assert f2 / f1 <= 2.5, (f1, f2)
    assert d2 / d1 >= 3.5, (d1, d2)
    # and at seq 1024 flash is already well under the dense footprint
    assert f2 < 0.5 * d2, (f2, d2)


# ---------------------------------------------------------------------------
# 3. DP grad sync: ONE fused all-reduce of exactly the parameter count
# ---------------------------------------------------------------------------

def test_dp_grad_sync_is_one_fused_allreduce():
    """The dp=8 train step's communication budget: gradient sync must
    be a single coalesced all-reduce whose element count equals the
    trainable parameter count (+ the loss scalar and the step counter),
    the coalesce-grad-tensor guarantee (ref:
    framework/ir/coalesce_grad_tensor_pass.cc; fused_all_reduce_op_
    handle.cc) that XLA provides via sharding. Per-layer unfused syncs
    or a duplicated sync trip this gate."""
    from paddle_tpu import parallel
    from paddle_tpu.core import rng as rng_mod

    mesh = parallel.init_mesh(dp=8)
    try:
        pt.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.GELU(),
                            nn.Linear(64, 8))
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.AdamW(
            learning_rate=1e-3, parameters=net),
            loss=nn.CrossEntropyLoss())
        parallel.distributed_model(model, mesh=mesh)
        model._sync_state_in()
        model._train_step_fn = model._build_train_step()
        xs = np.random.RandomState(0).randn(16, 32).astype(np.float32)
        ys = np.random.RandomState(0).randint(0, 8, (16, 1))
        inputs = model._shard_batch((xs,))
        labels = model._shard_batch((ys,))
        key = rng_mod.split_for_step(0)
        comp = model._train_step_fn.lower(
            model._params, model._frozen, model._opt_state,
            model._buffers, 0, key, inputs, labels).compile()
        counts = collective_elements(comp)
        nparams = sum(int(np.prod(p.shape))
                      for p in jax.tree.leaves(model._params))
        ar = counts["all-reduce"]
        # params + loss scalar + sample-count scalar; nothing else
        assert nparams <= ar.elements <= nparams + 16, (ar, nparams)
        # FUSED: grads ride one tuple all-reduce (plus the s32 counter)
        # — per-layer unfusing raises the instruction count
        assert ar.instructions <= 2, ar
        # no other collective families in a pure-DP step
        assert set(counts) <= {"all-reduce"}, counts
    finally:
        parallel.set_mesh(None)


# ---------------------------------------------------------------------------
# 4. GPT train step: FLOPs within the analytic band, memory under budget
# ---------------------------------------------------------------------------

def _gpt_step_compiled(fused_loss: bool):
    from paddle_tpu.core import rng as rng_mod
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTFusedPretrainingCriterion,
                                       GPTPretrainingCriterion)

    pt.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_position_embeddings=256,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False, fused_loss=fused_loss)
    net = GPTForCausalLM(cfg)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(
        learning_rate=1e-4, parameters=net),
        loss=(GPTFusedPretrainingCriterion() if fused_loss
              else GPTPretrainingCriterion()))
    model._sync_state_in()
    model._train_step_fn = model._build_train_step()
    ids = np.random.RandomState(0).randint(0, 512, (8, 256))
    key = rng_mod.split_for_step(0)
    comp = model._train_step_fn.lower(
        model._params, model._frozen, model._opt_state, model._buffers,
        0, key, (ids,), (ids,)).compile()
    nparams = sum(int(np.prod(p.shape))
                  for p in jax.tree.leaves(model._params))
    return comp, nparams, cfg, ids


def test_gpt_train_step_flops_and_memory_budget():
    """Budgets for the flagship train step at a fixed probe config
    (h=128, L=4, s=256, b=8, vocab=512; measured r4: flops ratio 1.15,
    temp 175 MiB):

    - compiled FLOPs / analytic (6·N·T + 6·L·s·h·T) in [1.0, 1.30] —
      an accidental full-graph remat (+~33%) or an extra forward pass
      trips the top; a silently shrunken model trips the floor;
    - temp+output memory ≤ 230 MiB (1.25× measured) — losing buffer
      donation or activation blowup trips it.
    """
    comp, nparams, cfg, ids = _gpt_step_compiled(fused_loss=False)
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    tokens = ids.size
    analytic = (6 * nparams * tokens
                + 6 * cfg.num_layers * cfg.max_position_embeddings
                * cfg.hidden_size * tokens)
    ratio = float(ca["flops"]) / analytic
    assert 1.0 <= ratio <= 1.30, ratio

    from paddle_tpu.cost_model import memory_profile_compiled
    m = memory_profile_compiled(comp)
    mib = (m.temp_bytes + m.output_bytes) / 2**20
    assert mib <= 230, mib


# ---------------------------------------------------------------------------
# 7. ring attention: per-device temps scale with the LOCAL sequence
# ---------------------------------------------------------------------------

def test_ring_attention_partitions_sequence_memory():
    """The long-context claim in compiled form: sp=8 cuts per-device
    attention temps by ~the partition factor (each device holds s/sp
    queries; K/V blocks stream around the ring; the block scores are
    [s/sp, s/sp], never [s, s]). Measured: 7.7x at s=2048, 8.8x at
    s=4096 — the per-device footprint a device would need for 8x the
    context it could hold alone. (Not O(s) per device — each block is
    still quadratic in s/sp; flash-in-block would be the next lever.)"""
    from paddle_tpu import parallel
    from paddle_tpu.ops.ring_attention import ring_attention

    def temps(s, sp):
        mesh = parallel.init_mesh(devices=jax.devices()[:sp], sp=sp)
        try:
            b, h, d = 2, 4, 32
            q = jnp.asarray(np.random.RandomState(0).randn(b, s, h, d),
                            jnp.float32)

            def f(q, k, v):
                return ring_attention(q, k, v, causal=True,
                                      mesh=mesh).sum()

            return memory_profile(jax.grad(f, argnums=(0, 1, 2)),
                                  (q, q, q)).temp_bytes
        finally:
            parallel.set_mesh(None)

    for s in (2048, 4096):
        dense = temps(s, 1)   # one device holds the whole sequence
        ring8 = temps(s, 8)
        assert dense / ring8 >= 6.0, (s, dense, ring8)
