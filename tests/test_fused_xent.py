"""Fused linear cross-entropy: numeric parity with the dense vocab path
(forward + grads), ignore_index, and the GPT wiring."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.fused_xent import fused_linear_cross_entropy

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _data(t=12, h=16, v=40, seed=0):
    r = np.random.RandomState(seed)
    hid = jnp.asarray(r.randn(t, h) * 0.5, jnp.float32)
    w = jnp.asarray(r.randn(v, h) * 0.5, jnp.float32)
    lb = jnp.asarray(r.randint(0, v, (t,)))
    return hid, w, lb


def _dense(hid, w, lb, ignore=-100):
    return F.cross_entropy(hid @ w.T, lb, ignore_index=ignore)


@pytest.mark.parametrize("chunk", [None, 8, 40])
def test_forward_matches_dense(chunk):
    hid, w, lb = _data()
    got = fused_linear_cross_entropy(hid, w, lb, -100, chunk)
    ref = _dense(hid, w, lb)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


def test_grads_match_dense():
    hid, w, lb = _data(seed=1)

    g_f = jax.grad(lambda a, b: fused_linear_cross_entropy(
        a, b, lb, -100, 8), argnums=(0, 1))(hid, w)
    g_d = jax.grad(lambda a, b: _dense(a, b, lb),
                   argnums=(0, 1))(hid, w)
    np.testing.assert_allclose(np.asarray(g_f[0]), np.asarray(g_d[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_f[1]), np.asarray(g_d[1]),
                               rtol=1e-4, atol=1e-6)


def test_ignore_index_masked():
    hid, w, lb = _data(seed=2)
    lb = lb.at[0].set(-100).at[5].set(-100)
    got = fused_linear_cross_entropy(hid, w, lb, -100, 8)
    ref = _dense(hid, w, lb)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    # grads of ignored rows are zero
    gh = jax.grad(lambda a: fused_linear_cross_entropy(
        a, w, lb, -100, 8))(hid)
    assert np.allclose(np.asarray(gh)[0], 0.0)
    assert not np.allclose(np.asarray(gh)[1], 0.0)


def test_bf16_inputs_fp32_math():
    hid, w, lb = _data(seed=3)
    got = fused_linear_cross_entropy(hid.astype(jnp.bfloat16),
                                     w.astype(jnp.bfloat16), lb)
    ref = _dense(hid.astype(jnp.bfloat16).astype(jnp.float32),
                 w.astype(jnp.bfloat16).astype(jnp.float32), lb)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-3)
    g = jax.grad(lambda a: fused_linear_cross_entropy(
        a, w.astype(jnp.bfloat16), lb))(hid.astype(jnp.bfloat16))
    assert g.dtype == jnp.bfloat16


def test_gpt_fused_loss_trains_and_matches_dense():
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTFusedPretrainingCriterion,
                                       GPTPretrainingCriterion)
    pt.seed(0)
    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
              max_position_embeddings=16, hidden_dropout=0.0,
              attention_dropout=0.0, use_flash=False)
    ids = np.random.RandomState(0).randint(0, 64, (2, 16))

    pt.seed(0)
    dense_net = GPTForCausalLM(GPTConfig(**kw))
    dense_model = pt.Model(dense_net)
    dense_model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.0,
                                   parameters=dense_net),
        loss=GPTPretrainingCriterion())
    dense_loss = float(dense_model.train_batch([ids], [ids])["loss"])

    pt.seed(0)
    net = GPTForCausalLM(GPTConfig(fused_loss=True, **kw))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.0, parameters=net),
        loss=GPTFusedPretrainingCriterion())
    fused_loss = float(model.train_batch([ids], [ids])["loss"])
    np.testing.assert_allclose(fused_loss, dense_loss, rtol=1e-4)

    # and it actually trains
    model._sync_state_out()  # reclaim donated params before rebinding
    model2 = pt.Model(net)
    model2.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=3e-3, parameters=net),
        loss=GPTFusedPretrainingCriterion())
    losses = [float(model2.train_batch([ids], [ids])["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0]

    # eval/generate path still produces logits
    model2._sync_state_out()
    net.eval()
    out = net(ids)
    assert out.shape == (2, 16, 64)


def test_untied_head_layout():
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTFusedPretrainingCriterion)
    pt.seed(0)
    cfg = GPTConfig(vocab_size=48, hidden_size=32, num_layers=1,
                    num_heads=2, max_position_embeddings=8,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False, tie_word_embeddings=False,
                    fused_loss=True)
    net = GPTForCausalLM(cfg)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.0,
                                             parameters=net),
                  loss=GPTFusedPretrainingCriterion())
    ids = np.random.RandomState(0).randint(0, 48, (2, 8))
    fused = float(model.train_batch([ids], [ids])["loss"])
    model._sync_state_out()
    net.eval()
    from paddle_tpu.models.gpt import GPTPretrainingCriterion
    dense = float(GPTPretrainingCriterion()(net(ids), jnp.asarray(ids)))
    np.testing.assert_allclose(fused, dense, rtol=1e-4)


def test_eval_batch_works_on_fused_model():
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTFusedPretrainingCriterion)
    pt.seed(0)
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=8,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False, fused_loss=True)
    net = GPTForCausalLM(cfg)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.0,
                                             parameters=net),
                  loss=GPTFusedPretrainingCriterion())
    ids = np.random.RandomState(0).randint(0, 32, (2, 8))
    tr = model.train_batch([ids], [ids])
    ev = model.eval_batch([ids], [ids])
    np.testing.assert_allclose(float(ev["loss"]), float(tr["loss"]),
                               rtol=1e-4)


def test_non_divisor_vocab_chunks():
    # prime-ish vocab: padding keeps chunks full-width
    hid, w, lb = _data(t=6, h=8, v=37, seed=4)
    got = fused_linear_cross_entropy(hid, w, lb, -100, 16)
    ref = _dense(hid, w, lb)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    g_f = jax.grad(lambda a, b: fused_linear_cross_entropy(
        a, b, lb, -100, 16), argnums=(0, 1))(hid, w)
    g_d = jax.grad(lambda a, b: _dense(a, b, lb),
                   argnums=(0, 1))(hid, w)
    np.testing.assert_allclose(np.asarray(g_f[1]), np.asarray(g_d[1]),
                               rtol=1e-4, atol=1e-6)


def test_mixed_dtype_operands():
    hid, w, lb = _data(seed=5)
    got = fused_linear_cross_entropy(hid.astype(jnp.bfloat16), w, lb)
    assert np.isfinite(float(got))
    gw = jax.grad(lambda b: fused_linear_cross_entropy(
        hid.astype(jnp.bfloat16), b, lb), argnums=0)(w)
    assert gw.dtype == w.dtype


def test_fused_gpt_trains_on_sharded_mesh():
    """fused_loss composes with dp x tp GSPMD sharding."""
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTFusedPretrainingCriterion)
    mesh = parallel.init_mesh(dp=4, tp=2)
    try:
        pt.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=16,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        use_flash=False, fused_loss=True)
        net = GPTForCausalLM(cfg)
        model = pt.Model(net)
        model.prepare(
            optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=net),
            loss=GPTFusedPretrainingCriterion())
        parallel.distributed_model(model, mesh=mesh)
        ids = np.random.RandomState(0).randint(0, 128, (8, 16))
        losses = [float(model.train_batch([ids], [ids])["loss"])
                  for _ in range(4)]
        assert losses[-1] < losses[0]
    finally:
        parallel.set_mesh(None)


def test_greedy_decoder_exports_and_matches_generate(tmp_path):
    """The whole decode loop compiles into one servable artifact."""
    from paddle_tpu import jit
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTGreedyDecoder)
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = np.random.RandomState(0).randint(0, 64, (2, 8)).astype(
        np.int64)
    ref = np.asarray(net.generate(jnp.asarray(ids), max_new_tokens=6))

    dec = GPTGreedyDecoder(net, max_new_tokens=6)
    out = np.asarray(dec(jnp.asarray(ids)))
    np.testing.assert_array_equal(out, ref)

    path = str(tmp_path / "decoder")
    jit.save(dec, path, input_spec=[jit.InputSpec([2, 8], "int64")])
    loaded = jit.load(path)
    np.testing.assert_array_equal(np.asarray(loaded(ids)), ref)


def test_fused_bias_matches_dense():
    hid, w, lb = _data(seed=7)
    bias = jnp.asarray(np.random.RandomState(8).randn(40) * 0.3,
                       jnp.float32)
    got = fused_linear_cross_entropy(hid, w, lb, -100, 8, bias)
    ref = F.cross_entropy(hid @ w.T + bias, lb)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    g_f = jax.grad(lambda b: fused_linear_cross_entropy(
        hid, w, lb, -100, 8, b))(bias)
    g_d = jax.grad(lambda b: F.cross_entropy(hid @ w.T + b, lb))(bias)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_d),
                               rtol=1e-4, atol=1e-6)


def test_bert_fused_pretraining_matches_dense():
    from paddle_tpu.models.bert import (BertConfig,
                                        BertForPretraining,
                                        BertFusedPretrainingCriterion,
                                        BertPretrainingCriterion)
    pt.seed(0)
    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
              max_position_embeddings=16, hidden_dropout=0.0,
              attention_dropout=0.0, use_flash=False)
    r = np.random.RandomState(0)
    ids = r.randint(0, 64, (2, 16))
    mlm = np.where(r.rand(2, 16) < 0.2, r.randint(0, 64, (2, 16)),
                   -100)
    nsp = np.array([0, 1])

    pt.seed(0)
    dnet = BertForPretraining(BertConfig(**kw))
    dm = pt.Model(dnet)
    dm.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.0,
                                          parameters=dnet),
               loss=BertPretrainingCriterion())
    dense = float(dm.train_batch([ids], [mlm, nsp])["loss"])

    pt.seed(0)
    fnet = BertForPretraining(BertConfig(fused_loss=True, **kw))
    fm = pt.Model(fnet)
    fm.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.0,
                                          parameters=fnet),
               loss=BertFusedPretrainingCriterion())
    fused = float(fm.train_batch([ids], [mlm, nsp])["loss"])
    np.testing.assert_allclose(fused, dense, rtol=1e-4)


def test_pipeline_composes_with_fused_loss():
    """pp x dp mesh + streaming vocab path: logits never in HBM while
    the decoder trunk is pipelined."""
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLMPipe,
                                       GPTFusedPretrainingCriterion)
    mesh = parallel.init_mesh(pp=2, dp=4)
    try:
        pt.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                        num_heads=2, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        use_flash=False, fused_loss=True)
        net = GPTForCausalLMPipe(cfg, num_microbatches=2,
                                 virtual_pp_degree=2, mesh=mesh)
        model = pt.Model(net)
        model.prepare(
            optimizer=pt.optimizer.AdamW(learning_rate=3e-3,
                                         parameters=net),
            loss=GPTFusedPretrainingCriterion())
        parallel.distributed_model(model, mesh=mesh)
        ids = np.random.RandomState(0).randint(0, 64, (8, 32))
        losses = [float(model.train_batch([ids], [ids])["loss"])
                  for _ in range(5)]
        assert losses[-1] < losses[0], losses
    finally:
        parallel.set_mesh(None)
