"""paddle.linalg parity (vs numpy/scipy) and vision detection ops
(vs torchvision's CPU reference when available).

Analogs: reference unittests/test_linalg_*.py, test_nms_op.py,
test_roi_align_op.py, test_deform_conv2d.py."""

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import linalg
from paddle_tpu.vision import ops as vops


def _spd(n=6, seed=0):
    a = np.random.RandomState(seed).randn(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


def test_cholesky_and_solve():
    a = _spd()
    l = np.asarray(linalg.cholesky(a))  # noqa: E741
    np.testing.assert_allclose(l @ l.T, a, rtol=1e-4, atol=1e-4)
    b = np.random.RandomState(1).randn(6, 2).astype(np.float32)
    x = np.asarray(linalg.cholesky_solve(b, jnp.asarray(l)))
    np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)


def test_qr_svd_eigh():
    a = np.random.RandomState(2).randn(5, 4).astype(np.float32)
    q, r = linalg.qr(a)
    np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a,
                               rtol=1e-4, atol=1e-4)
    u, s, vt = linalg.svd(a, full_matrices=False)
    np.testing.assert_allclose(
        np.asarray(u) * np.asarray(s) @ np.asarray(vt), a,
        rtol=1e-4, atol=1e-4)
    w, v = linalg.eigh(jnp.asarray(_spd()))
    assert np.all(np.asarray(w) > 0)  # SPD → positive spectrum


def test_lu_roundtrip():
    a = np.random.RandomState(3).randn(5, 5).astype(np.float32)
    lu_packed, piv, info = linalg.lu(jnp.asarray(a))
    assert np.all(np.asarray(info) == 0)
    p, l, u = linalg.lu_unpack(lu_packed, piv)
    np.testing.assert_allclose(
        np.asarray(p) @ np.asarray(l) @ np.asarray(u), a,
        rtol=1e-4, atol=1e-4)


def test_solve_det_inv_norm():
    a = _spd(4, seed=4)
    b = np.random.RandomState(5).randn(4).astype(np.float32)
    x = np.asarray(linalg.solve(a, b))
    np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(float(linalg.det(a)), np.linalg.det(a),
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(linalg.inv(a)),
                               np.linalg.inv(a), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(float(linalg.norm(a)),
                               np.linalg.norm(a), rtol=1e-5)


def test_matmul_transpose_flags_and_misc():
    a = np.random.RandomState(6).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(7).randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(linalg.matmul(a, b, transpose_x=True)), a.T @ b,
        rtol=1e-5, atol=1e-6)
    u, s, v = linalg.pca_lowrank(np.random.RandomState(8)
                                 .randn(20, 8).astype(np.float32), q=3)
    assert u.shape == (20, 3) and s.shape == (3,) and v.shape == (8, 3)


# -- vision ops -------------------------------------------------------------

def _boxes():
    return np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                     [21, 21, 29, 29], [50, 50, 60, 60]], np.float32)


def test_nms_matches_torchvision():
    scores = np.array([0.9, 0.8, 0.7, 0.95, 0.5], np.float32)
    kept = np.asarray(vops.nms(_boxes(), 0.3, scores=scores))
    try:
        from torchvision.ops import nms as tv_nms
        import torch
        ref = tv_nms(torch.from_numpy(_boxes()),
                     torch.from_numpy(scores), 0.3).numpy()
        np.testing.assert_array_equal(kept, ref)
    except ImportError:
        # manual expectation: box3 (0.95) suppresses box2; box0 (0.9)
        # suppresses box1; box4 kept
        np.testing.assert_array_equal(kept, [3, 0, 4])


def test_nms_categories_do_not_cross_suppress():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1])
    kept = np.asarray(vops.nms(boxes, 0.3, scores=scores,
                               category_idxs=cats,
                               categories=[0, 1]))
    assert sorted(kept.tolist()) == [0, 1]


def test_roi_align_matches_torchvision():
    r = np.random.RandomState(0)
    x = r.randn(1, 3, 16, 16).astype(np.float32)
    boxes = np.array([[2.0, 2.0, 10.0, 12.0],
                      [0.0, 0.0, 8.0, 8.0]], np.float32)
    out = np.asarray(vops.roi_align(x, boxes, [2], output_size=4,
                                    sampling_ratio=2, aligned=True))
    assert out.shape == (2, 3, 4, 4)
    try:
        import torch
        from torchvision.ops import roi_align as tv_roi
        ref = tv_roi(torch.from_numpy(x),
                     [torch.from_numpy(boxes)], output_size=4,
                     sampling_ratio=2, aligned=True).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    except ImportError:
        assert np.all(np.isfinite(out))


def test_roi_align_adaptive_sampling_matches_torchvision():
    """sampling_ratio=-1: ceil(roi/output) points per bin, per roi."""
    r = np.random.RandomState(5)
    x = r.randn(1, 2, 32, 32).astype(np.float32)
    boxes = np.array([[1.0, 1.0, 30.0, 25.0],   # big roi -> many points
                      [3.0, 3.0, 6.0, 6.0]], np.float32)
    out = np.asarray(vops.roi_align(x, boxes, [2], output_size=4,
                                    sampling_ratio=-1, aligned=True))
    try:
        import torch
        from torchvision.ops import roi_align as tv_roi
        ref = tv_roi(torch.from_numpy(x), [torch.from_numpy(boxes)],
                     output_size=4, sampling_ratio=-1,
                     aligned=True).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    except ImportError:
        assert np.all(np.isfinite(out))


def test_cross_default_axis_is_first_dim3():
    import paddle_tpu.tensor as T
    x = np.random.RandomState(6).randn(3, 5).astype(np.float32)
    y = np.random.RandomState(7).randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(T.cross(x, y)),
                               np.cross(x, y, axis=0), rtol=1e-5,
                               atol=1e-6)
    assert linalg.cross is T.cross  # linalg aliases tensor


def test_lu_unpack_batched():
    a = np.random.RandomState(8).randn(3, 4, 4).astype(np.float32)
    lu_packed, piv, info = linalg.lu(jnp.asarray(a))
    p, l, u = linalg.lu_unpack(lu_packed, piv)
    np.testing.assert_allclose(
        np.einsum("bij,bjk,bkl->bil", np.asarray(p), np.asarray(l),
                  np.asarray(u)), a, rtol=1e-4, atol=1e-4)


def test_deform_conv2d_zero_offset_equals_conv():
    """With zero offsets (and no mask) deform_conv2d must equal a
    standard convolution — the reference's defining identity."""
    from paddle_tpu.nn import functional as F
    r = np.random.RandomState(1)
    x = r.randn(2, 3, 8, 8).astype(np.float32)
    w = r.randn(6, 3, 3, 3).astype(np.float32)
    oh = ow = 8 - 2
    offset = np.zeros((2, 2 * 9, oh, ow), np.float32)
    out = np.asarray(vops.deform_conv2d(x, offset, w))
    ref = np.asarray(F.conv2d(jnp.asarray(x), jnp.asarray(w)))
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


def test_deform_conv2d_mask_scales_contribution():
    r = np.random.RandomState(2)
    x = r.randn(1, 2, 6, 6).astype(np.float32)
    w = r.randn(4, 2, 3, 3).astype(np.float32)
    oh = ow = 4
    offset = np.zeros((1, 18, oh, ow), np.float32)
    mask_half = np.full((1, 9, oh, ow), 0.5, np.float32)
    full = np.asarray(vops.deform_conv2d(x, offset, w))
    half = np.asarray(vops.deform_conv2d(x, offset, w, mask=mask_half))
    np.testing.assert_allclose(half, 0.5 * full, rtol=1e-4, atol=1e-4)
