"""Dynamic-shape policy (VERDICT r2 item 5): pad_sequence + length
bucketing bound the distinct-XLA-compile count for variable-length data,
and Model warns when a pipeline recompiles unboundedly.

Reference being replaced: LoDTensor ragged batches
(paddle/fluid/framework/lod_tensor.h) — on TPU the policy is dense
padding over a finite bucket set (paddle_tpu/io/sequence.py)."""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io, nn
from paddle_tpu.core import flags

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_pad_sequence_shapes_mask_truncation():
    seqs = [np.arange(3), np.arange(7), np.arange(5)]
    x, m = io.pad_sequence(seqs, return_mask=True)
    assert x.shape == (3, 7)
    np.testing.assert_allclose(m.sum(1), [3, 7, 5])
    assert io.pad_sequence(seqs, max_len=4).shape == (3, 4)
    np.testing.assert_allclose(io.pad_sequence(seqs, max_len=4)[1],
                               [0, 1, 2, 3])  # truncated
    assert io.pad_sequence(seqs, pad_to_multiple=8).shape == (3, 8)
    # trailing feature dims pass through
    x2 = io.pad_sequence([np.ones((2, 4)), np.ones((5, 4))])
    assert x2.shape == (2, 5, 4)


def test_bucket_sampler_batches_are_single_bucket():
    lengths = [3, 30, 5, 60, 7, 62, 4, 31, 6, 61]
    data = list(range(len(lengths)))
    s = io.LengthBucketBatchSampler(data, lengths, batch_size=2,
                                    boundaries=[8, 32, 64])
    batches = list(s)
    assert sum(len(b) for b in batches) == len(data)
    for b in batches:
        bl = {s.bucket_of[i] for i in b}
        assert len(bl) == 1, f"mixed-bucket batch {b}"
    assert len(s) == len(batches)
    with pytest.raises(ValueError, match="exceeds"):
        io.LengthBucketBatchSampler(data, [100], 2, boundaries=[8])


def _imdb_tree(tmp_path):
    rng = np.random.RandomState(0)
    words_pos = "great movie loved it wonderful superb".split()
    words_neg = "terrible movie hated it awful poor".split()
    for split in ("train", "test"):
        for label, words in (("pos", words_pos), ("neg", words_neg)):
            d = tmp_path / "aclImdb" / split / label
            os.makedirs(d)
            for i in range(16):
                n = int(rng.randint(3, 40))  # variable lengths
                (d / f"{i}.txt").write_text(
                    " ".join(rng.choice(words, n)))


def test_imdb_bucketed_training_bounded_compiles(tmp_path):
    """Imdb with ragged reviews: bucketed batches keep the jitted train
    step at <= n_buckets distinct shapes while the loss trains."""
    from paddle_tpu import text

    _imdb_tree(tmp_path)
    ds = text.Imdb(str(tmp_path), mode="train", cutoff=0)
    vocab = len(ds.word_idx)
    boundaries = [8, 16, 64]
    sampler = io.LengthBucketBatchSampler(
        ds, lengths=lambda item: len(item[0]), batch_size=4,
        boundaries=boundaries, shuffle=True, drop_last=True)
    loader = io.DataLoader(ds, batch_sampler=sampler,
                           collate_fn=io.bucket_collate(sampler))

    class Clf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, 16)
            self.fc = nn.Linear(16, 2)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    pt.seed(0)
    model = pt.Model(Clf())
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=5e-3,
                                              parameters=model.network),
                  loss=nn.CrossEntropyLoss())
    losses = []
    for _ in range(4):
        for batch in loader:
            ids, label = batch
            logs = model.train_batch([ids], [np.asarray(label)[:, None]])
            losses.append(float(logs["loss"]))
    # the compile-count bound: one signature per bucket, nothing else
    assert len(model._shape_signatures) <= len(boundaries), \
        model._shape_signatures
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_recompile_guard_warns_on_unbounded_shapes():
    pt.seed(0)
    net = nn.Linear(4, 2)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.MSELoss())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flags({"recompile_warn_threshold": 3})
    try:
        with pytest.warns(UserWarning, match="distinct input shapes"):
            for b in range(1, 6):   # 5 distinct batch sizes
                x = np.ones((b, 4), np.float32)
                y = np.zeros((b, 2), np.float32)
                model.train_batch([x], [y])
    finally:
        flags.set_flags({"recompile_warn_threshold": old})


def test_recompile_guard_silent_when_shapes_stable():
    pt.seed(0)
    net = nn.Linear(4, 2)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.MSELoss())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flags({"recompile_warn_threshold": 3})
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            for _ in range(8):
                x = np.ones((2, 4), np.float32)
                y = np.zeros((2, 2), np.float32)
                model.train_batch([x], [y])
    finally:
        flags.set_flags({"recompile_warn_threshold": old})
