"""Reliability layer (docs/RELIABILITY.md): deterministic fault
injection, the shared retry/deadline policy, and the hardened engine
failure semantics — deadlines, shed, cancel, admission timeout,
device-error retry budgets, and the health state machine."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.reliability import faults
from paddle_tpu.reliability.faults import FaultInjected
from paddle_tpu.reliability.retry import (Deadline, DeadlineExceeded,
                                          RetryExhausted, RetryPolicy,
                                          as_deadline, backoff_delay)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- deadlines ----------------------------------------------------------


def test_deadline_math_and_composition():
    dl = Deadline.after(10.0)
    assert 9.0 < dl.remaining() <= 10.0
    assert not dl.expired
    tight = dl.min(Deadline.after(0.5))
    assert tight.remaining() <= 0.5
    assert dl.min(None) is dl
    assert dl.clamp(1.0) == 1.0            # per-attempt cap holds
    assert tight.clamp(5.0) <= 0.5         # deadline wins
    past = Deadline.after(-1.0)
    assert past.expired and past.clamp(3.0) == 0.0
    with pytest.raises(DeadlineExceeded):
        past.raise_if_expired("unit test")
    assert Deadline.never().remaining() == float("inf")


def test_as_deadline_coercions():
    assert as_deadline(None) is None
    dl = Deadline.after(1.0)
    assert as_deadline(dl) is dl
    assert isinstance(as_deadline(2.5), Deadline)
    assert as_deadline(2.5).remaining() <= 2.5


# -- backoff curve ------------------------------------------------------


def test_backoff_delay_growth_cap_and_jitter():
    ds = [backoff_delay(i, 0.5, cap=4.0) for i in range(6)]
    assert ds == [0.5, 1.0, 2.0, 4.0, 4.0, 4.0]   # doubles, then caps
    import random
    rng = random.Random(7)
    jittered = [backoff_delay(1, 1.0, jitter=0.5, rng=rng)
                for _ in range(50)]
    assert all(1.0 <= d <= 3.0 for d in jittered)  # 2.0 ± 50%
    assert len(set(jittered)) > 1
    # seeded → reproducible
    a = [backoff_delay(i, 1.0, jitter=0.5, rng=random.Random(3))
         for i in range(4)]
    b = [backoff_delay(i, 1.0, jitter=0.5, rng=random.Random(3))
         for i in range(4)]
    assert a == b


# -- retry policy -------------------------------------------------------


def test_retry_policy_recovers_then_exhausts():
    pol = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0,
                      retry_on=(OSError,), scope="test")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 3

    def hopeless():
        raise OSError("down")

    with pytest.raises(RetryExhausted) as ei:
        pol.call(hopeless, describe="hopeless op")
    assert isinstance(ei.value.last, OSError)
    assert ei.value.__cause__ is ei.value.last
    assert ei.value.attempts == 3


def test_retry_policy_non_retryable_propagates_immediately():
    pol = RetryPolicy(max_attempts=5, base_delay=0.001,
                      retry_on=(OSError,))
    calls = {"n": 0}

    def wrong():
        calls["n"] += 1
        raise ValueError("protocol error, not a flaky socket")

    with pytest.raises(ValueError):
        pol.call(wrong)
    assert calls["n"] == 1


def test_retry_policy_raises_instead_of_sleeping_out_the_deadline():
    """A backoff longer than the remaining budget surfaces the
    verdict immediately — no sleep nobody is waiting for (review
    finding)."""
    pol = RetryPolicy(max_attempts=5, base_delay=10.0, jitter=0.0,
                      retry_on=(OSError,))

    def failing():
        raise OSError("down")

    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        pol.call(failing, deadline=2.0)
    assert time.monotonic() - t0 < 1.0    # did NOT sleep ~2s


def test_retry_policy_deadline_stops_the_loop():
    pol = RetryPolicy(max_attempts=50, base_delay=0.05, jitter=0.0,
                      retry_on=(OSError,))

    def failing():
        raise OSError("down")

    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        pol.call(failing, deadline=0.12)
    assert time.monotonic() - t0 < 2.0     # nowhere near 50 attempts
    with pytest.raises(DeadlineExceeded):
        pol.call(failing, deadline=Deadline.after(-1.0))


# -- fault injection ----------------------------------------------------


def test_faults_disabled_is_noop():
    # not enabled: no counting, no raising, even with a rule armed
    faults.inject("device.dispatch", nth=(1,))
    for _ in range(3):
        faults.check("device.dispatch")
    assert faults.call_count("device.dispatch") == 0
    assert faults.injected_log() == []


def test_faults_nth_rule_and_times_budget():
    faults.enable(seed=0)
    faults.inject("store.socket", nth=(2, 4), times=1)
    hits = []
    for i in range(1, 6):
        try:
            faults.check("store.socket")
        except FaultInjected as e:
            hits.append((i, e.call_index))
    assert hits == [(2, 2)]                # times=1 caps the nth pair
    assert faults.call_count("store.socket") == 5
    assert faults.injected_log() == [("store.socket", 2)]


def test_faults_probability_schedule_is_deterministic():
    faults.enable(seed=42)
    faults.inject("io.worker", p=0.3)
    want = faults.preview("io.worker", 50)
    assert want == faults.preview("io.worker", 50)   # pure
    assert 2 <= len(want) <= 30                      # sane density
    got = []
    for i in range(1, 51):
        try:
            faults.check("io.worker")
        except FaultInjected:
            got.append(i)
    assert got == want                               # live == schedule
    # a different seed moves the schedule
    assert faults.preview("io.worker", 50, seed=43) != want
    # re-enabling with the same seed replays it exactly
    faults.enable(seed=42)
    got2 = []
    for i in range(1, 51):
        try:
            faults.check("io.worker")
        except FaultInjected:
            got2.append(i)
    assert got2 == got


def test_faults_reenable_replays_times_budgets():
    """enable() must reset rule budgets: re-arming with the same
    registered rules replays the schedule (review finding)."""
    faults.inject("store.socket", nth=(1,), times=1)
    for _ in range(2):
        faults.enable(seed=7)
        with pytest.raises(FaultInjected):
            faults.check("store.socket")
        faults.check("store.socket")       # budget spent this run
        assert faults.injected_log() == [("store.socket", 1)]


def test_faults_custom_exception_factory():
    faults.enable(seed=0)
    faults.inject("store.socket", nth=(1,),
                  exc=lambda: ConnectionResetError("injected"))
    with pytest.raises(ConnectionResetError):
        faults.check("store.socket")


def test_faults_exc_factory_may_read_faults_state():
    """The factory runs OUTSIDE the module lock, so reading faults
    state from it must not deadlock (review finding)."""
    faults.enable(seed=0)
    faults.inject(
        "ckpt.write", nth=(1,),
        exc=lambda: RuntimeError(
            f"call {faults.call_count('ckpt.write')}"))
    import threading
    err = {}

    def run():
        try:
            faults.check("ckpt.write")
        except RuntimeError as e:
            err["e"] = str(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "exc factory deadlocked on the faults lock"
    assert err["e"] == "call 1"


# -- DataLoader io.worker site ------------------------------------------


def test_dataloader_io_worker_fault_reaches_consumer():
    from paddle_tpu.io import DataLoader, TensorDataset
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    ds = TensorDataset([x])
    faults.enable(seed=0)
    faults.inject("io.worker", nth=(2,))
    loader = DataLoader(ds, batch_size=4, to_device=False)
    got = []
    with pytest.raises(FaultInjected, match="io.worker"):
        for (b,) in loader:
            got.append(b)
    assert len(got) == 1                   # died on the second batch
    faults.disable()
    assert sum(1 for _ in DataLoader(ds, batch_size=4,
                                     to_device=False)) == 4


# -- checkpoint ckpt.write retry ----------------------------------------


def test_checkpoint_save_retries_injected_write_fault(tmp_path):
    from paddle_tpu.io.checkpoint import CheckpointManager
    faults.enable(seed=0)
    faults.inject("ckpt.write", nth=(1,), times=1)
    with CheckpointManager(str(tmp_path / "ck"),
                           async_save=False) as mgr:
        assert mgr.save(0, {"w": np.arange(8)})
        assert mgr.latest_step() == 0
        np.testing.assert_array_equal(mgr.restore(0)["w"], np.arange(8))
    assert ("ckpt.write", 1) in faults.injected_log()


# -- tcp store on the shared policy -------------------------------------


def test_tcp_store_client_kwarg_aliases_and_unreachable():
    from paddle_tpu.distributed.tcp_store import (StoreUnavailable,
                                                  TCPStoreClient)
    c = TCPStoreClient("127.0.0.1:1", timeout=0.2, retries=2,
                       retry_delay=0.01)
    assert c.policy.max_attempts == 2
    assert c.policy.base_delay == 0.01
    t0 = time.monotonic()
    with pytest.raises(StoreUnavailable, match="unreachable"):
        c.request({"op": "get", "k": "x"})
    assert time.monotonic() - t0 < 5.0


@pytest.mark.parametrize("exc", [None, lambda: ConnectionResetError(
    "injected")], ids=["default-FaultInjected", "ConnectionResetError"])
def test_tcp_store_request_rides_out_injected_socket_faults(exc):
    """Both the default FaultInjected AND an OSError-shaped injection
    take the same retry path (review finding: the default used to
    escape the policy untyped)."""
    from paddle_tpu.distributed.tcp_store import (TCPStoreClient,
                                                  TCPStoreServer)
    srv = TCPStoreServer(port=0)
    try:
        faults.enable(seed=0)
        faults.inject("store.socket", nth=(1,), exc=exc)
        c = TCPStoreClient(f"127.0.0.1:{srv.port}", retries=3,
                           retry_delay=0.01)
        c.request({"op": "set", "k": "a", "v": "1"})
        assert c.request({"op": "get", "k": "a"})["v"] == "1"
        assert ("store.socket", 1) in faults.injected_log()
    finally:
        faults.reset()
        srv.close()


# -- elastic restart backoff --------------------------------------------


def test_elastic_backoff_skips_graceful_preemptions():
    """A checkpointed preemption exit is healthy: it respawns with no
    delay and resets the crash-backoff curve (review finding)."""
    from paddle_tpu.distributed.elastic import ElasticManager
    mgr = ElasticManager(1, "x", [], restart_backoff=0.05,
                         restart_backoff_cap=0.2, backoff_reset_s=999.0)
    mgr._gen_start = time.time()
    assert mgr._respawn_backoff(healthy=True) == 0.0
    assert mgr._backoff_level == 0
    d1 = mgr._respawn_backoff(healthy=False)
    d2 = mgr._respawn_backoff(healthy=False)
    assert (d1, d2) == (0.05, 0.1)         # crash curve escalates
    assert mgr._respawn_backoff(healthy=True) == 0.0
    assert mgr._backoff_level == 0          # ... and healthy resets it


def test_elastic_manager_backs_off_between_restarts(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager
    script = tmp_path / "crash.py"
    script.write_text("import sys; sys.exit(3)\n")
    mgr = ElasticManager(1, str(script), [], max_restarts=2,
                         poll_interval=0.02, restart_backoff=0.25,
                         restart_backoff_cap=2.0, backoff_reset_s=999.0)
    t0 = time.monotonic()
    rc = mgr.run()
    dt = time.monotonic() - t0
    assert rc == 3
    assert mgr.restarts == 3               # budget spent
    # two respawns happened → at least base + 2*base of damping
    assert dt >= 0.25 + 0.5, dt
    assert mgr._backoff_level == 2


# -- engine failure semantics -------------------------------------------


def tiny_gpt():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def dense_ref(net, prompt, n_new):
    import jax.numpy as jnp
    out = net.generate(jnp.asarray([prompt]), max_new_tokens=n_new)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_engine_deadline_resolves_future_and_keeps_serving():
    from paddle_tpu.inference.llm import LLMEngine
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,)) as eng:
        doomed = eng.submit([1, 2, 3], max_new_tokens=8,
                            deadline=0.0005)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        ok = eng.submit([7, 8, 9], max_new_tokens=3).result(timeout=60)
        assert ok["output_ids"] == dense_ref(net, [7, 8, 9], 3)
    assert len(eng._free_pages) == eng.num_pages - 1


def test_engine_sheds_on_bounded_queue_overflow():
    from paddle_tpu.inference.llm import AdmissionShed, LLMEngine
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   prefill_buckets=(16,), max_pending=2) as eng:
        # the first submissions pin the loop in compile + decode; the
        # burst behind them overflows max_pending=2 and must shed
        futs = [eng.submit([i + 1, i + 2, i + 3], max_new_tokens=16)
                for i in range(8)]
        outcomes = {"ok": 0, "shed": 0}
        for f in futs:
            try:
                f.result(timeout=120)
                outcomes["ok"] += 1
            except AdmissionShed as e:
                assert "admission queue full" in str(e)
                outcomes["shed"] += 1
        assert outcomes["shed"] >= 1, outcomes
        assert outcomes["ok"] >= 1, outcomes
        assert outcomes["ok"] + outcomes["shed"] == 8
    assert len(eng._free_pages) == eng.num_pages - 1


def test_generate_batch_wider_than_max_pending_never_sheds():
    """generate() applies its own backpressure window, so the bounded
    admission queue can't shed the tail of a wide batch (review
    finding)."""
    from paddle_tpu.inference.llm import LLMEngine
    net = tiny_gpt()
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,), max_pending=2) as eng:
        outs = eng.generate(prompts, max_new_tokens=2)
    assert len(outs) == 6
    for p, o in zip(prompts, outs):
        assert o["output_ids"] == dense_ref(net, p, 2), (p, o)
    assert len(eng._free_pages) == eng.num_pages - 1


def test_device_retry_starts_a_fresh_admission_cycle():
    """admit_timeout bounds time-in-queue per admission cycle, not
    total request age — a device retry of an old request must not be
    instantly failed AdmissionTimeout (review finding)."""
    from paddle_tpu.inference.llm import LLMEngine
    net = tiny_gpt()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,), admit_timeout=0.3,
                    device_retry_budget=1)
    try:
        real = eng._decode_fn
        state = {"n": 0}

        def slow_then_flaky(*a, **kw):
            state["n"] += 1
            if state["n"] == 1:
                # make the request OLDER than admit_timeout before its
                # device error, without ever occupying the queue
                time.sleep(0.5)
                raise RuntimeError("transient PJRT failure")
            return real(*a, **kw)

        eng._decode_fn = slow_then_flaky
        out = eng.submit([1, 2, 3], max_new_tokens=3).result(timeout=120)
        assert out["output_ids"] == dense_ref(net, [1, 2, 3], 3)
    finally:
        eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1


def test_engine_cancel_resolves_and_frees_pages():
    from paddle_tpu.inference.llm import LLMEngine, RequestCancelled
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=4, page_size=4, num_pages=64,
                   prefill_buckets=(16,)) as eng:
        futs = [eng.submit([i + 1, i + 2], max_new_tokens=64)
                for i in range(4)]
        assert all(hasattr(f, "request_id") for f in futs)
        time.sleep(0.3)                    # let decode start
        for f in futs:
            eng.cancel(f.request_id)
        for f in futs:
            try:
                f.result(timeout=120)      # finished before cancel: ok
            except RequestCancelled:
                pass
        # unknown / already-resolved ids are a polite no-op
        assert eng.cancel(futs[0].request_id) is False
        assert eng.cancel(10 ** 9) is False
    assert len(eng._free_pages) == eng.num_pages - 1


def test_cancel_wins_over_a_simultaneous_device_error():
    """An accepted cancel() resolves RequestCancelled even when a
    device error delivers the outcome (review finding: the raw device
    exception used to leak to the cancelled caller)."""
    from paddle_tpu.inference.llm import LLMEngine, RequestCancelled
    net = tiny_gpt()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,))
    try:
        box = {}

        def dying(*a, **kw):
            # cancel lands while the request is slotted, in the same
            # tick the device dies — deterministic interleaving
            eng.cancel(box["fut"].request_id)
            raise RuntimeError("device died")

        eng._decode_fn = dying
        box["fut"] = eng.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(RequestCancelled):
            box["fut"].result(timeout=120)
    finally:
        eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1


def test_engine_admission_timeout_is_typed_not_an_infinite_spin():
    from paddle_tpu.inference.llm import AdmissionTimeout, LLMEngine
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   prefill_buckets=(16,), admit_timeout=0.15) as eng:
        hog = eng.submit([1, 2, 3], max_new_tokens=64)
        starved = eng.submit([4, 5, 6], max_new_tokens=4)
        with pytest.raises(AdmissionTimeout, match="admit_timeout"):
            starved.result(timeout=120)
        assert starved.exception().args    # typed + described
        assert hog.result(timeout=120)["output_ids"]
    assert len(eng._free_pages) == eng.num_pages - 1


def test_engine_device_retry_budget_reproduces_token_stream():
    """A device error mid-request re-admits it (budget) and the retry
    regenerates the IDENTICAL stream — the nonce pins the sampling
    keys, so a retry is invisible in the output."""
    from paddle_tpu.inference.llm import LLMEngine
    net = tiny_gpt()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,), device_retry_budget=2)
    try:
        real = eng._decode_fn
        state = {"n": 0}

        def flaky(*a, **kw):
            state["n"] += 1
            if state["n"] == 2:            # fail the 2nd decode step
                raise RuntimeError("transient PJRT failure")
            return real(*a, **kw)

        eng._decode_fn = flaky
        out = eng.submit([1, 2, 3, 4], max_new_tokens=6,
                         temperature=0.8).result(timeout=120)
        assert out["output_ids"] == run_clean(net, [1, 2, 3, 4], 6)
        assert not out["truncated"]
        assert eng.health == "healthy"     # success reset the streak
    finally:
        eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1


def run_clean(net, prompt, n_new):
    """Reference stream from an un-faulted engine (seeded sampling)."""
    from paddle_tpu.inference.llm import LLMEngine
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,)) as eng:
        return eng.submit(prompt, max_new_tokens=n_new,
                          temperature=0.8).result(
                              timeout=120)["output_ids"]


def test_spec_engine_inline_prefill_error_reclaims_pages_and_budgets():
    """Inline (speculative) prefill errors must reclaim the pages
    allocated before the device call raised AND consume the request's
    device-retry budget (review finding: the slot table owns the
    request before allocation)."""
    from paddle_tpu.inference.llm import LLMEngine
    pt.seed(0)
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    pt.seed(0)
    dcfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                      num_heads=2, vocab_size=97,
                      max_position_embeddings=64, hidden_dropout=0.0,
                      attention_dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    # spec_slab=False: only the LEGACY inline path still one-shots
    # prefill inside the round (slab engines chunk like everyone)
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=32,
                    prefill_buckets=(16,), draft_net=draft,
                    spec_tokens=2, device_retry_budget=1,
                    spec_slab=False)
    try:
        real = eng._prefill_fn
        state = {"n": 0}

        def flaky(*a, **kw):
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("transient PJRT failure")
            return real(*a, **kw)

        eng._prefill_fn = flaky
        out = eng.submit([1, 2, 3, 4, 5], max_new_tokens=4).result(
            timeout=120)
        assert out["output_ids"]           # retried and completed
        # a budget-0 engine propagates the error instead
        state["n"] = 0
        eng.device_retry_budget = 0
        eng._prefill_fn = flaky
        with pytest.raises(RuntimeError, match="transient"):
            eng.submit([6, 7, 8], max_new_tokens=2).result(timeout=120)
    finally:
        eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1, \
        "inline prefill error leaked KV pages"
    assert eng._n_queued == 0


def test_engine_health_walks_to_draining_and_sheds():
    from paddle_tpu.inference.llm import AdmissionShed, LLMEngine
    net = tiny_gpt()
    # mixed_tick off so prefill definitely routes through _chunk_fn
    # (the patched site); the mixed path has its own chaos coverage
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,), degraded_after=1,
                    drain_after=2, mixed_tick=False)
    try:
        real = eng._chunk_fn

        def broken(*a, **kw):
            raise RuntimeError("device wedged")

        eng._chunk_fn = broken
        for i in range(2):                 # one error per submission
            with pytest.raises(RuntimeError, match="wedged"):
                eng.submit([1, 2, 3], max_new_tokens=2).result(
                    timeout=60)
        deadline = time.monotonic() + 30
        while eng.health != "draining" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.health == "draining"
        # draining: new submissions shed at the submit boundary
        with pytest.raises(AdmissionShed, match="draining"):
            eng.submit([4, 5], max_new_tokens=2).result(timeout=60)
        # operator recovery: reset + fixed device → serving again
        eng._chunk_fn = real
        eng.reset_health()
        assert eng.health == "healthy"
        out = eng.submit([7, 8, 9], max_new_tokens=3).result(timeout=60)
        assert out["output_ids"] == dense_ref(net, [7, 8, 9], 3)
    finally:
        eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1


def test_healthz_surfaces_engine_health_state():
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.observability.server import DebugServer
    net = tiny_gpt()
    srv = DebugServer(port=0).start()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,))
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert "healthy" in body["components"].values()
        # draining flips /healthz to 503 (balancer pulls the process)
        eng._health = "draining"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=30)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "draining"
        eng.reset_health()
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
        eng.close()
        # a closed engine disappears from the health listing
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert not body.get("components")
    finally:
        eng.close()
        srv.stop()
