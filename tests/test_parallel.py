"""Parallel core tests on the 8-device virtual CPU mesh (SURVEY.md §4:
the reference tests collectives with 2-rank gloo-CPU runs,
test_collective_api_base.py; here the fake mesh plays that role)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, parallel
from paddle_tpu.parallel import collective

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    parallel.set_mesh(None)


def test_mesh_construction():
    m = parallel.init_mesh(dp=2, tp=4)
    assert m.size == 8
    assert m.axis_size("dp") == 2 and m.axis_size("tp") == 4
    assert m.axis_size("pp") == 1
    assert m.axis_names == ("dp", "tp")
    assert m.data_axes == ("dp",)


def test_mesh_wildcard():
    m = parallel.init_mesh(dp=-1, tp=2)
    assert m.axis_size("dp") == 4 and m.size == 8


def test_mesh_errors():
    with pytest.raises(ValueError):
        parallel.DeviceMesh(dp=3, tp=3)
    with pytest.raises(ValueError):
        parallel.DeviceMesh(bogus=2)


def test_sharding_rules_tp_fsdp():
    m = parallel.init_mesh(fsdp=2, tp=4)
    rules = parallel.LogicalRules()
    # column-parallel weight [embed, mlp]: embed→fsdp, mlp→tp
    spec = rules.mesh_axes(("embed", "mlp"), (256, 1024), m)
    assert spec == P("fsdp", "tp")
    # head dim not divisible by tp → left unsharded
    spec = rules.mesh_axes(("embed", "heads"), (256, 6), m)
    assert spec == P("fsdp")
    # one mesh axis may shard only one dim
    spec = rules.mesh_axes(("mlp", "heads"), (512, 512), m)
    assert spec == P("tp")


def test_shard_params_and_batch():
    m = parallel.init_mesh(dp=2, tp=4)
    lin = nn.Linear(16, 32, axes=("embed", "mlp"))
    params, _ = nn.layer.split_state(lin)
    meta = lin.param_meta()
    sharded = parallel.shard_params(params, meta, m)
    w = sharded["weight"]
    assert w.sharding.spec == P(None, "tp")
    batch = parallel.shard_batch(jnp.ones((8, 16)), m)
    assert batch.sharding.spec == P("dp")


def test_collective_psum_allgather_shift():
    m = parallel.init_mesh(dp=8)

    @jax.jit
    def f(x):
        def body(xs):
            s = collective.psum(xs, "dp")
            g = collective.all_gather(xs, "dp")
            sh = collective.shift(xs, "dp", 1)
            return s, g, sh
        return shard_map(body, mesh=m.mesh,
                         in_specs=P("dp"),
                         out_specs=(P("dp"), P("dp"), P("dp")))(x)

    x = jnp.arange(8.0)
    s, g, sh = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full((8,), 28.0))
    np.testing.assert_allclose(np.asarray(g).reshape(8, 8)[0], np.arange(8.0))
    # ring shift by 1: rank i's value lands on rank i+1
    np.testing.assert_allclose(np.asarray(sh), np.roll(np.arange(8.0), 1))


def test_host_all_reduce():
    stacked = jnp.arange(12.0).reshape(4, 3)
    out = parallel.all_reduce(stacked, "sum")
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(12.0).reshape(4, 3).sum(0))
    with pytest.raises(ValueError):
        parallel.all_reduce(stacked, "xor")


def test_strategy_roundtrip():
    s = parallel.DistributedStrategy()
    s.hybrid_configs.mp_degree = 4
    s.sharding.enable = True
    s.sharding.degree = 2
    axes = s.mesh_axes()
    assert axes == {"dp": -1, "tp": 4, "fsdp": 2}
    s2 = parallel.DistributedStrategy.from_dict(s.to_dict())
    assert s2.hybrid_configs.mp_degree == 4
    assert s2.sharding.degree == 2


def test_data_parallel_training_matches_single_device():
    """DP-sharded Model.fit reaches the same loss as unsharded (the
    reference's TestDistBase methodology, test_dist_base.py:786 —
    compare distributed vs single-process losses)."""
    def build():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net),
            loss=nn.CrossEntropyLoss())
        return model

    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = rng.randint(0, 4, (32, 1))

    losses = {}
    for mode in ("single", "dp"):
        model = build()
        if mode == "dp":
            parallel.init_mesh(dp=8)
            parallel.distributed_model(
                model, parallel.DistributedStrategy())
        out = [model.train_batch([xs], [ys])["loss"] for _ in range(5)]
        losses[mode] = out
        parallel.set_mesh(None)
    np.testing.assert_allclose(losses["single"], losses["dp"],
                               rtol=2e-4, atol=2e-5)


def test_tp_sharded_model_runs():
    m = parallel.init_mesh(dp=2, tp=4)
    net = nn.Sequential(nn.Linear(8, 32, axes=("embed", "mlp")),
                        nn.ReLU(),
                        nn.Linear(32, 4, axes=("mlp", "embed")))
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=1e-2, parameters=net), loss=nn.CrossEntropyLoss())
    parallel.distributed_model(model, mesh=m)
    xs = np.random.randn(16, 8).astype(np.float32)
    ys = np.random.randint(0, 4, (16, 1))
    l0 = model.train_batch([xs], [ys])["loss"]
    l1 = model.train_batch([xs], [ys])["loss"]
    assert np.isfinite(l0) and l1 < l0
    # params actually sharded on the tp axis
    w = model._params["0.weight"]
    assert w.sharding.spec == P(None, "tp")


def test_collective_broadcast_in_spmd():
    m = parallel.init_mesh(dp=8)

    @jax.jit
    def f(x):
        return shard_map(lambda xs: collective.broadcast(xs, "dp", src=3),
                         mesh=m.mesh, in_specs=P("dp"),
                         out_specs=P("dp"))(x)

    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 3.0))


def test_shard_batch_partial_batch_replicates():
    m = parallel.init_mesh(dp=8)
    out = parallel.shard_batch(jnp.ones((5, 4)), m)  # 5 % 8 != 0
    assert out.sharding.spec == P()
    out = parallel.shard_batch(jnp.ones((16, 4)), m)
    assert out.sharding.spec == P("dp")


def test_mesh_context_restores_global():
    m = parallel.init_mesh(dp=8)
    with parallel.DeviceMesh(dp=2, tp=4):
        assert parallel.get_mesh().axis_size("tp") == 4
    assert parallel.get_mesh() is m


def test_host_broadcast_stacked():
    stacked = jnp.arange(6.0).reshape(3, 2)
    out = parallel.broadcast(stacked, src=1)
    np.testing.assert_allclose(np.asarray(out),
                               np.tile([2.0, 3.0], (3, 1)))
