"""End-to-end `Model.fit` slice — the analog of the reference's MNIST book
test (ref: python/paddle/tests/test_model.py, tests/book/
test_recognize_digits.py): LeNet must learn a synthetic MNIST-like task."""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.models.lenet import LeNet
from paddle_tpu.optimizer import Adam

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def synthetic_mnist(n=256, seed=0):
    """Class-dependent blob patterns: learnable quickly, MNIST-shaped."""
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n)
    imgs = rs.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, c in enumerate(labels):
        r, col = divmod(c, 4)
        imgs[i, 0, 4 + r * 8: 10 + r * 8, 2 + col * 7: 8 + col * 7] += 2.0
    return imgs, labels.astype(np.int64)


def test_model_fit_learns():
    x, y = synthetic_mnist(256)
    ds = TensorDataset([x, y])
    model = pt.Model(LeNet())
    model.prepare(optimizer=Adam(learning_rate=1e-3,
                                 parameters=model.network),
                  loss=nn.CrossEntropyLoss(),
                  metrics=[Accuracy()])
    model.fit(ds, batch_size=64, epochs=6, verbose=0, shuffle=True)
    res = model.evaluate(ds, batch_size=64, verbose=0)
    assert res["acc"] > 0.9, f"did not learn: {res}"
    assert res["loss"] < 1.0


def test_model_save_load(tmp_path):
    x, y = synthetic_mnist(64)
    ds = TensorDataset([x, y])
    model = pt.Model(LeNet())
    model.prepare(optimizer=Adam(parameters=model.network),
                  loss=nn.CrossEntropyLoss())
    model.fit(ds, batch_size=32, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    model.save(path)

    model2 = pt.Model(LeNet())
    model2.prepare(optimizer=Adam(parameters=model2.network),
                   loss=nn.CrossEntropyLoss())
    model2.load(path)
    # identical predictions after round-trip
    import jax.numpy as jnp
    xb = jnp.asarray(x[:4])
    np.testing.assert_allclose(
        np.asarray(model.predict_batch((xb,))),
        np.asarray(model2.predict_batch((xb,))), rtol=1e-5, atol=1e-6)
    # optimizer state restored
    assert model2._step_count == model._step_count


def test_model_predict():
    x, y = synthetic_mnist(32)
    model = pt.Model(LeNet())
    model.prepare(loss=nn.CrossEntropyLoss())
    ds = TensorDataset([x])
    outs = model.predict(ds, batch_size=16, stack_outputs=True)
    assert np.asarray(outs).shape == (32, 10)


def test_early_stopping():
    from paddle_tpu.hapi.callbacks import EarlyStopping
    x, y = synthetic_mnist(64)
    ds = TensorDataset([x, y])
    model = pt.Model(LeNet())
    model.prepare(optimizer=Adam(learning_rate=0.0,
                                 parameters=model.network),
                  loss=nn.CrossEntropyLoss(), metrics=[Accuracy()])
    es = EarlyStopping(monitor="loss", patience=1, verbose=0)
    model.fit(ds, eval_data=ds, batch_size=32, epochs=10, verbose=0,
              callbacks=[es])
    assert model.stop_training  # lr=0 → no improvement → stopped early


def test_dataloader_shapes_and_order():
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    y = np.arange(20, dtype=np.int64)
    dl = DataLoader(TensorDataset([x, y]), batch_size=6, shuffle=False,
                    to_device=False)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 1)
    np.testing.assert_array_equal(batches[-1][1], [18, 19])


def test_dataloader_shuffle_reproducible():
    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    ds = TensorDataset([x])
    pt.seed(5)
    dl = DataLoader(ds, batch_size=16, shuffle=True, to_device=False)
    a = np.asarray(next(iter(dl))[0]).ravel()
    assert not np.array_equal(a, x.ravel())  # actually shuffled


def test_distributed_batch_sampler_partitions():
    from paddle_tpu.io import DistributedBatchSampler
    ds = TensorDataset([np.arange(24, dtype=np.float32)])
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                    rank=rank)
        for b in s:
            seen.extend(b)
    assert sorted(seen) == list(range(24))
