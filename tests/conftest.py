"""Test configuration: force an 8-device virtual CPU mesh so sharding /
collective tests run without TPU hardware (SURVEY.md §4: the reference's
analog is gloo-CPU collective tests + fake devices; here
xla_force_host_platform_device_count gives us N host 'chips').

Note: jax may already be imported by the interpreter (sitecustomize
registers the TPU plugin), so we must use jax.config.update rather than
env vars — it takes effect as long as the backend isn't initialized yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh; got "
    f"{jax.devices()}")
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.device_count()}")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu
    paddle_tpu.seed(42)
    np.random.seed(42)
    yield
