"""Round-3 functional fills: sequence_mask, channel_shuffle, upsample,
affine_grid, grid_sample (ref: python/paddle/nn/functional/vision.py,
fluid sequence_mask)."""

import numpy as np

import jax.numpy as jnp

from paddle_tpu.nn import functional as F


def test_sequence_mask():
    m = F.sequence_mask(np.array([1, 3, 2]), maxlen=4)
    np.testing.assert_array_equal(
        np.asarray(m),
        [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
    mf = F.sequence_mask(np.array([2]), maxlen=3, dtype="float32")
    assert np.asarray(mf).dtype == np.float32
    assert F.sequence_mask(np.array([2, 5])).shape == (2, 5)


def test_channel_shuffle():
    x = jnp.arange(8, dtype=jnp.float32).reshape(1, 8, 1, 1)
    out = np.asarray(F.channel_shuffle(x, 2)).ravel()
    np.testing.assert_allclose(out, [0, 4, 1, 5, 2, 6, 3, 7])


def test_upsample_aliases_interpolate():
    x = jnp.arange(4, dtype=jnp.float32).reshape(1, 1, 2, 2)
    out = F.upsample(x, size=(4, 4), mode="nearest")
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(np.asarray(out)[0, 0],
                               [[0, 0, 1, 1], [0, 0, 1, 1],
                                [2, 2, 3, 3], [2, 2, 3, 3]])


def test_affine_grid_identity_and_grid_sample_roundtrip():
    n, c, h, w = 2, 3, 5, 7
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, c, h, w), jnp.float32)
    theta = jnp.asarray(
        np.tile(np.array([[1.0, 0, 0], [0, 1.0, 0]], np.float32),
                (n, 1, 1)))
    grid = F.affine_grid(theta, (n, c, h, w), align_corners=True)
    assert grid.shape == (n, h, w, 2)
    # identity transform + bilinear sampling reproduces the input
    out = F.grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)
    # nearest mode too
    out_n = F.grid_sample(x, grid, mode="nearest", align_corners=True)
    np.testing.assert_allclose(np.asarray(out_n), np.asarray(x),
                               atol=1e-6)


def test_grid_sample_out_of_range_padding():
    x = jnp.ones((1, 1, 4, 4))
    far = jnp.full((1, 2, 2, 2), 3.0)   # way outside [-1, 1]
    np.testing.assert_allclose(
        np.asarray(F.grid_sample(x, far, padding_mode="zeros")), 0.0)
    np.testing.assert_allclose(
        np.asarray(F.grid_sample(x, far, padding_mode="border")), 1.0)
    np.testing.assert_allclose(
        np.asarray(F.grid_sample(x, far, padding_mode="reflection")), 1.0)


def test_grid_sample_translation():
    """Shift right by one pixel via the grid: out[..., j] = x[..., j-1]."""
    h = w = 4
    x = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    theta = jnp.asarray([[[1.0, 0.0, -2.0 / (w - 1)], [0.0, 1.0, 0.0]]])
    grid = F.affine_grid(theta, (1, 1, h, w), align_corners=True)
    out = np.asarray(F.grid_sample(x, grid, align_corners=True))
    np.testing.assert_allclose(out[0, 0, :, 1:],
                               np.asarray(x)[0, 0, :, :-1], atol=1e-5)
