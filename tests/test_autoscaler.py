"""Autoscaler tests (ISSUE 13 tentpole): flap damping and dwell on an
injectable clock, spawn-fault retry without double-counted capacity,
the drain → verify-empty → kill sequence, warming-hole routing and
occupancy accounting, min/max bounds, death-as-replacement, and the
/scalez surface.

The control loop runs against a scriptable FakeRouter (no engines, no
subprocesses, no sleeps — the injected ``sleep`` ADVANCES the fake
clock, so drain waits and spawn backoffs are instantaneous and
exact); two tests use the real Router over stub replicas to pin the
warming/drain lifecycle where it actually lives."""

import json
import threading
import time
from urllib.request import urlopen

import pytest

from paddle_tpu.inference.llm import AdmissionShed
from paddle_tpu.observability.metrics import MetricRegistry
from paddle_tpu.observability.slo import SLOTracker
from paddle_tpu.reliability import faults
from paddle_tpu.serving import Autoscaler, Router
from paddle_tpu.serving.router import affinity_key, rendezvous_pick


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------


class FakeClient:
    def __init__(self, healthy=True):
        self.healthy = healthy

    def health(self):
        return "healthy" if self.healthy else None


class FakeHandle:
    def __init__(self):
        self._alive = True
        self.terminated = 0

    def alive(self):
        return self._alive

    def terminate(self, grace_s=0.0):
        self.terminated += 1
        self._alive = False


class FakeRouter:
    """The exact Router surface the Autoscaler consumes, scriptable."""

    health_poll_interval = 0.0

    def __init__(self, slots=4):
        self.slots = slots
        self.replicas = {}          # name -> {"warming","draining"}
        self.inflight = {}          # name -> int OR callable(clock)
        self.expected = set()
        self.drained = []
        self.detached = []

    def expect_warming(self, name):
        self.expected.add(name)
        if name in self.replicas:
            self.replicas[name]["warming"] = True

    def attach(self, name, client, warming=False):
        self.replicas[name] = {
            "warming": warming or name in self.expected,
            "draining": False}
        self.inflight.setdefault(name, 0)

    def mark_ready(self, name):
        self.expected.discard(name)
        if name not in self.replicas:
            return False
        self.replicas[name]["warming"] = False
        return True

    def drain(self, name):
        if name not in self.replicas:
            return False
        self.replicas[name]["draining"] = True
        self.drained.append(name)
        return True

    def inflight_of(self, name):
        if name not in self.replicas:
            return None
        v = self.inflight.get(name, 0)
        return v() if callable(v) else v

    def detach(self, name):
        self.replicas.pop(name, None)
        self.expected.discard(name)
        self.detached.append(name)

    def fleet_load(self, slots=None):
        ready = [n for n, r in self.replicas.items()
                 if not r["warming"] and not r["draining"]]
        infl = sum(self.inflight_of(n) or 0 for n in ready)
        cap = (slots or self.slots) * len(ready)
        return {
            "attached": len(self.replicas),
            "ready": len(ready),
            "warming": sum(1 for r in self.replicas.values()
                           if r["warming"]),
            "draining": sum(1 for r in self.replicas.values()
                            if r["draining"]),
            "inflight": infl, "capacity": cap,
            "occupancy": (infl / cap) if cap else None,
            "ready_names": sorted(ready)}

    def add_poll_hook(self, fn):
        pass

    def remove_poll_hook(self, fn):
        pass


class Harness:
    """Fake clock + fake router + spawner, wired into a synchronous
    Autoscaler. ``sleep`` ADVANCES the clock, so every drain wait and
    spawn backoff resolves instantly and deterministically."""

    def __init__(self, **kw):
        self.t = [0.0]
        self.router = FakeRouter(slots=kw.get("replica_slots", 4))
        self.burn = {}               # window_status()-shaped dict
        self.spawn_calls = []
        self.handles = {}

        def spawner(name):
            self.spawn_calls.append(name)
            h = FakeHandle()
            self.handles[name] = h
            return FakeClient(), h

        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("backoff_base_s", 1.0)
        kw.setdefault("backoff_cap_s", 60.0)
        kw.setdefault("dwell_s", 8.0)
        kw.setdefault("low_water", 0.1)
        kw.setdefault("drain_deadline_s", 5.0)
        kw.setdefault("spawn_backoff_s", 0.01)
        self.scaler = Autoscaler(
            self.router, spawner, synchronous=True,
            clock=lambda: self.t[0],
            sleep=lambda s: self.t.__setitem__(0, self.t[0] + s),
            burn_fn=lambda: self.burn, **kw)

    def trip(self, cls="gold", burn=50.0):
        self.burn = {cls: {"tripped": True, "windows": {
            "short": {"burn_rate": burn, "requests": 99,
                      "eligible": True},
            "long": {"burn_rate": burn, "requests": 99,
                     "eligible": True}}}}

    def untrip(self):
        self.burn = {}

    def run(self, seconds, dt=0.25):
        """Tick on a cadence over fake time; returns actions taken."""
        actions = []
        end = self.t[0] + seconds
        while self.t[0] < end:
            a = self.scaler.tick()
            if a:
                actions.append((round(self.t[0], 3), a))
            self.t[0] += dt
        return actions


@pytest.fixture
def harness():
    faults.reset()
    h = Harness()
    h.router.attach("r0", FakeClient())   # the unmanaged seed replica
    yield h
    faults.reset()


# ---------------------------------------------------------------------------
# damping: square wave, backoff growth, dwell, curve reset
# ---------------------------------------------------------------------------


def test_square_wave_bounded_actions(harness):
    """A burn-rate square wave faster than the dwell must NOT produce
    one spawn/kill per edge: flips are dwell-gated and repeats ride
    the exponential curve, so the action count stays bounded."""
    h = harness
    edges = 0
    actions = []
    # 2s tripped / 2s calm for 120s of fake time = 60 edges
    for _cycle in range(30):
        h.trip()
        edges += 1
        actions += h.run(2.0, dt=0.25)
        h.untrip()
        edges += 1
        actions += h.run(2.0, dt=0.25)
    assert edges == 60
    n_actions = len([a for _, a in actions
                     if a in ("scale_out", "scale_in")])
    # the flap gate: same-direction repeats climb the exponential
    # curve and every flip waits out max(8s dwell, the climbed
    # curve), so the worst case is ~one action per dwell period
    # (120/8 = 15, plus the few extra the healthy-dwell reset allows
    # while the fleet idles at the min floor between episodes) — 60
    # edges collapse to that, never one spawn/kill per edge
    assert 1 <= n_actions <= 19, (n_actions, actions)
    load = h.router.fleet_load()
    assert 1 <= load["ready"] <= h.scaler.max_replicas, load


def test_consecutive_same_direction_backoff_grows(harness):
    """Repeated scale-outs back off exponentially: gaps between
    consecutive same-direction actions follow base · 2^(n-1)."""
    h = harness
    h.scaler.max_replicas = 8
    h.trip()
    actions = h.run(20.0, dt=0.05)
    outs = [t for t, a in actions if a == "scale_out"]
    assert len(outs) >= 4, actions
    gaps = [round(b - a, 2) for a, b in zip(outs, outs[1:])]
    # base=1.0: gaps must be >= 1, 2, 4 (small slack for tick grain)
    assert gaps[0] >= 0.95 and gaps[1] >= 1.95 and gaps[2] >= 3.95, \
        gaps


def test_direction_flip_waits_out_the_dwell(harness):
    h = harness
    h.trip()
    assert h.scaler.tick() == "scale_out"
    h.untrip()                      # occupancy 0 → wants scale-in
    acts = h.run(7.5, dt=0.25)      # still inside the 8s dwell
    assert not acts, acts
    acts = h.run(2.0, dt=0.25)      # dwell expires → the flip lands
    assert [a for _, a in acts] == ["scale_in"], acts
    assert h.scaler.n_scale_in == 1


def test_healthy_dwell_resets_backoff_curve(harness):
    h = harness
    h.scaler.max_replicas = 8
    h.trip()
    h.run(8.0, dt=0.1)              # builds an out-streak ≥ 3
    assert h.scaler.n_scale_out >= 3
    streak = h.scaler._streak
    assert streak >= 3
    # a quiet dwell (no trigger in either direction: occupancy in
    # band) resets the curve
    h.untrip()
    h.router.inflight["r0"] = 3      # occupancy above low_water
    h.run(9.0, dt=0.25)
    assert h.scaler._streak == 0
    # the next episode starts fresh — no leftover 2^n wait
    h.trip()
    assert h.scaler.tick() == "scale_out"


# ---------------------------------------------------------------------------
# SLO wiring: live windows, not the sticky latch
# ---------------------------------------------------------------------------


def test_latched_then_acked_breach_needs_windows_to_retrip():
    """The satellite pin: a latched breach that an operator
    acknowledged (POST /reset_health → reset_breach) must NOT
    re-trigger scale-out; only windows that RE-TRIP do."""
    faults.reset()
    t = [0.0]
    tracker = SLOTracker(targets={"gold": 0.99},
                         windows=(10.0, 40.0), min_samples=3,
                         breach_threshold=5.0,
                         registry=MetricRegistry(),
                         clock=lambda: t[0])
    router = FakeRouter()
    router.attach("r0", FakeClient())
    router.inflight["r0"] = 2       # mid-band: no occupancy trigger

    def spawner(name):
        return FakeClient(), FakeHandle()

    scaler = Autoscaler(router, spawner, synchronous=True,
                        min_replicas=1, max_replicas=4,
                        low_water=0.01, backoff_base_s=0.5,
                        dwell_s=2.0,
                        clock=lambda: t[0],
                        sleep=lambda s: t.__setitem__(0, t[0] + s),
                        burn_fn=tracker.window_status)
    for _ in range(5):
        tracker.record("gold", None, 1.0, "deadline",
                       had_deadline=True)
    assert tracker.window_status()["gold"]["tripped"]
    assert scaler.tick() == "scale_out"
    assert scaler.n_scale_out == 1
    # the storm ends; the windows decay but the LATCH stays sticky
    t[0] = 100.0
    assert tracker.breached() == ["gold"]
    assert not tracker.window_status()["gold"]["tripped"]
    assert scaler.tick() is None
    # operator acknowledges — still no re-trigger from the ack alone
    tracker.reset_breach()
    assert scaler.tick() is None
    assert scaler.n_scale_out == 1
    # a NEW storm re-trips the windows → the controller re-acts
    for _ in range(5):
        tracker.record("gold", None, 1.0, "deadline",
                       had_deadline=True)
    assert scaler.tick() == "scale_out"
    assert scaler.n_scale_out == 2


# ---------------------------------------------------------------------------
# spawn faults: retry with backoff, never double-count
# ---------------------------------------------------------------------------


def test_spawn_fault_retries_and_counts_capacity_once(harness):
    h = harness
    faults.enable(seed=7)
    faults.inject("autoscale.spawn", nth=(1,))
    assert faults.preview("autoscale.spawn", 3) == [1]
    h.trip()
    assert h.scaler.tick() == "scale_out"
    # attempt 1 faulted before the spawner ran; attempt 2 spawned
    assert len(h.spawn_calls) == 1
    d = h.scaler.decisions()[-1]
    assert d["action"] == "scale_out" and d["attempts"] == 2
    load = h.router.fleet_load()
    assert load["ready"] == 2 and load["warming"] == 0, load
    assert faults.injected_log() == [("autoscale.spawn", 1)]


def test_spawn_exhaustion_leaves_no_ghost_capacity(harness):
    h = harness
    faults.enable(seed=7)
    faults.inject("autoscale.spawn", nth=(1, 2, 3))
    h.trip()
    h.scaler.tick()
    assert h.scaler.n_scale_out == 0
    d = h.scaler.decisions()[-1]
    assert d["action"] == "scale_out_failed" and d["attempts"] == 3
    load = h.router.fleet_load()
    # the failed name must not linger as a warming hole or an
    # expected-warming entry
    assert load["ready"] == 1 and load["warming"] == 0, load
    assert not h.router.expected
    assert not h.spawn_calls


def test_spawned_but_never_healthy_is_torn_down(harness):
    h = harness

    def bad_spawner(name):
        h.spawn_calls.append(name)
        handle = FakeHandle()
        h.handles[name] = handle
        return FakeClient(healthy=False), handle

    h.scaler.spawner = bad_spawner
    h.scaler.ready_timeout_s = 1.0
    h.trip()
    h.scaler.tick()
    assert h.scaler.n_scale_out == 0
    assert h.scaler.decisions()[-1]["action"] == "scale_out_failed"
    name = h.spawn_calls[0]
    assert h.handles[name].terminated  # the half-up process was ended
    assert name not in h.router.replicas
    assert h.router.fleet_load()["warming"] == 0


# ---------------------------------------------------------------------------
# scale-in: drain → verify-empty → kill
# ---------------------------------------------------------------------------


def scaled_out(h):
    """One managed replica up (via a real scale-out), damping aged
    past the dwell so a scale-in is immediately available. The drain
    tests park a victim with stragglers, so the low-water mark is
    raised to keep the occupancy trigger live."""
    h.trip()
    assert h.scaler.tick() == "scale_out"
    h.untrip()
    h.scaler.low_water = 0.6
    name = h.scaler.decisions()[-1]["replica"]
    h.t[0] += h.scaler.dwell_s + 1
    return name


def test_drain_verifies_empty_before_kill(harness):
    h = harness
    name = scaled_out(h)
    t_empty = h.t[0] + 0.8
    h.router.inflight[name] = lambda: 3 if h.t[0] < t_empty else 0
    assert h.scaler.tick() == "scale_in"
    d = h.scaler.decisions()[-1]
    assert d["action"] == "scale_in" and d["replica"] == name
    assert d["stragglers"] == 0
    assert d["drain_s"] >= 0.8          # waited for the drain
    assert h.handles[name].terminated   # then killed
    assert name in h.router.drained and name in h.router.detached
    assert h.scaler.n_scale_in == 1


def test_drain_deadline_kills_with_stragglers(harness):
    h = harness
    name = scaled_out(h)
    h.router.inflight[name] = 2          # never drains
    h.scaler.drain_deadline_s = 1.5
    assert h.scaler.tick() == "scale_in"
    d = h.scaler.decisions()[-1]
    assert d["stragglers"] == 2, d
    assert 1.5 <= d["drain_s"] <= 2.5, d
    assert h.handles[name].terminated
    # the stragglers' recovery is the router's nonce-pinned failover
    # (pinned end-to-end in chaos_soak --autoscale)


def test_drain_fault_expires_deadline_immediately(harness):
    h = harness
    name = scaled_out(h)
    h.router.inflight[name] = 4
    h.scaler.drain_deadline_s = 1e9      # the fault IS the deadline
    faults.enable(seed=11)
    faults.inject("autoscale.drain", nth=(1,))
    assert h.scaler.tick() == "scale_in"
    d = h.scaler.decisions()[-1]
    assert d["stragglers"] == 4
    assert d["drain_s"] < 5.0
    assert h.handles[name].terminated
    assert faults.injected_log() == [("autoscale.drain", 1)]


# ---------------------------------------------------------------------------
# bounds + replacement
# ---------------------------------------------------------------------------


def test_min_max_bounds_hold(harness):
    h = harness
    h.scaler.max_replicas = 2
    h.trip()
    h.run(60.0, dt=0.5)
    assert h.scaler.n_scale_out == 1     # 1 seed + 1 managed = max
    assert h.router.fleet_load()["ready"] == 2
    assert any(d["action"] == "hold" and d["reason"] == "at_max"
               for d in h.scaler.decisions())
    # at min: occupancy 0 wants in, but ready == min_replicas
    h.untrip()
    h.t[0] += 100
    name = [n for n in h.router.replicas if n != "r0"][0]
    h.router.inflight[name] = 0
    h.scaler.tick()                      # drains the one managed
    h.t[0] += 100
    assert h.scaler.tick() is None       # ready=1=min: never below
    assert h.router.fleet_load()["ready"] == 1
    assert h.scaler.n_scale_in == 1


def test_dead_managed_replica_respawns_as_replacement(harness):
    h = harness
    name = scaled_out(h)
    h.handles[name]._alive = False       # SIGKILL'd out-of-band
    h.router.inflight["r0"] = 2          # mid-band: no other trigger
    assert h.scaler.tick() == "replace"
    assert h.scaler.n_replaced == 1
    assert h.scaler.n_scale_out == 1     # NOT counted as scale-out
    assert name in h.router.detached
    d = h.scaler.decisions()[-1]
    assert d["action"] == "replace" and d["reason"] == "replica_died"
    new = d["replica"]
    assert new != name and new in h.router.replicas
    assert h.router.fleet_load()["ready"] == 2


def test_bootstrap_to_min_replicas():
    faults.reset()
    t = [0.0]
    router = FakeRouter()                # EMPTY fleet

    def spawner(name):
        return FakeClient(), FakeHandle()

    scaler = Autoscaler(router, spawner, synchronous=True,
                        min_replicas=2, max_replicas=4,
                        backoff_base_s=0.1,
                        clock=lambda: t[0],
                        sleep=lambda s: t.__setitem__(0, t[0] + s),
                        burn_fn=lambda: {})
    for _ in range(8):
        scaler.tick()
        t[0] += 0.5
    assert router.fleet_load()["ready"] == 2
    assert all(d["reason"] == "min_replicas"
               for d in scaler.decisions()
               if d["action"] == "scale_out")


# ---------------------------------------------------------------------------
# the real Router: warming holes + admin drain
# ---------------------------------------------------------------------------


class StubReplica:
    def __init__(self):
        self.calls = []
        self._mu = threading.Lock()

    def submit(self, prompt_ids, **kw):
        with self._mu:
            self.calls.append(list(prompt_ids))
        return {"output_ids": [1] * kw.get("max_new_tokens", 1)}

    def health(self):
        return "healthy"

    def cancel(self, request_id):
        return False

    def close(self):
        pass


def test_real_router_warming_is_a_hole():
    """Satellite pin: a spawned-but-not-READY replica absorbs no
    dispatches AND stays out of the occupancy denominator."""
    ready_stub, warm_stub = StubReplica(), StubReplica()
    with Router({"a": ready_stub}, health_poll_interval=0.05) as r:
        r.expect_warming("w")
        r.attach("w", warm_stub)          # expectation → warming
        # a warming replica absorbs no dispatches, even ones whose
        # affinity prefers it
        names = ("a", "w")
        rng_prompts, found = [], 0
        for i in range(200):
            p = [i % 97, (3 * i) % 97, (7 * i) % 97]
            if rendezvous_pick(affinity_key(p, 16, 2), names) == "w":
                rng_prompts.append(p)
                found += 1
                if found == 4:
                    break
        for p in rng_prompts:
            assert r.submit(p, max_new_tokens=1).result(timeout=30)
        assert not warm_stub.calls
        assert len(ready_stub.calls) == len(rng_prompts)
        # occupancy: denominator counts ONLY the ready replica
        load = r.fleet_load(slots_per_replica=4)
        assert load["ready"] == 1 and load["warming"] == 1
        assert load["capacity"] == 4
        # promote → it joins rotation
        assert r.mark_ready("w")
        assert r.fleet_load(slots_per_replica=4)["capacity"] == 8
        for p in rng_prompts:
            r.submit(p, max_new_tokens=1).result(timeout=30)
        assert warm_stub.calls, "promoted replica still got nothing"


def test_real_router_admin_drain_sticks_across_polls():
    """drain() must exclude the replica immediately AND survive the
    next health poll (the replica itself still answers healthy)."""
    a, b = StubReplica(), StubReplica()
    with Router({"a": a, "b": b}, health_poll_interval=0.03) as r:
        assert r.drain("b")
        time.sleep(0.12)                  # several poll cycles
        st = r._status()["replicas"]["b"]
        assert st["health"] == "draining" and st["admin_draining"]
        n_before = len(b.calls)
        for i in range(6):
            r.submit([i, i + 1, i + 2], max_new_tokens=1) \
                .result(timeout=30)
        assert len(b.calls) == n_before, "admin-draining got traffic"
        assert r.inflight_of("b") == 0
        assert r.inflight_of("nope") is None
        # a drained-out fleet sheds typed, reason draining
        assert r.drain("a")
        with pytest.raises(AdmissionShed):
            r.submit([9, 9, 9]).result(timeout=30)


# ---------------------------------------------------------------------------
# /scalez
# ---------------------------------------------------------------------------


def test_scalez_payload_and_http_endpoint():
    faults.reset()
    from paddle_tpu.observability.server import DebugServer
    t = [0.0]
    router = FakeRouter()
    router.attach("r0", FakeClient())

    def spawner(name):
        return FakeClient(), FakeHandle()

    scaler = Autoscaler(router, spawner, synchronous=True,
                        min_replicas=1, max_replicas=3,
                        clock=lambda: t[0],
                        sleep=lambda s: t.__setitem__(0, t[0] + s),
                        burn_fn=lambda: {})
    scaler.start()
    dbg = DebugServer(port=0).start()
    try:
        scaler.tick()
        t[0] += 1.0
        scaler.tick()
        with urlopen(f"http://127.0.0.1:{dbg.port}/scalez",
                     timeout=10) as resp:
            payload = json.loads(resp.read())
        (_name, sz), = payload["autoscalers"].items()
        assert sz["config"]["min_replicas"] == 1
        assert sz["config"]["max_replicas"] == 3
        assert sz["state"]["scale_out"] == 0
        assert sz["load"]["ready"] == 1
        assert isinstance(sz["decisions"], list)
        # replica-seconds integrate across ticks
        assert sz["state"]["replica_seconds"] >= 1.0
    finally:
        dbg.stop()
        scaler.close()
    # after close the provider self-unregisters (404)
    dbg2 = DebugServer(port=0).start()
    try:
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            urlopen(f"http://127.0.0.1:{dbg2.port}/scalez", timeout=10)
        assert ei.value.code == 404
    finally:
        dbg2.stop()
