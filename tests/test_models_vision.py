"""Vision model zoo tests (ref: python/paddle/tests/test_vision_models.py
— instantiate each family, forward a small input, check logits shape)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import models
from paddle_tpu.nn.layer import functional_call, split_state


pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _x(n=1, size=64):
    return jnp.asarray(
        np.random.RandomState(0).randn(n, 3, size, size), jnp.float32)


@pytest.mark.parametrize("ctor", [models.resnet18, models.resnet34,
                                  models.resnet50])
def test_resnet_forward(ctor):
    net = ctor(num_classes=10)
    net.eval()
    out = net(_x())
    assert out.shape == (1, 10)
    assert np.all(np.isfinite(np.asarray(out)))


def test_resnet_deep_constructs():
    # 101/152: construct + param count only (forward is slow on CPU)
    net = models.resnet101(num_classes=10)
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    assert n_params > 40e6


def test_resnet50_param_count_imagenet():
    net = models.resnet50()
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    # torchvision/paddle resnet50: 25.557M params
    assert abs(n_params - 25_557_032) / 25_557_032 < 0.01, n_params


def test_resnet_batchnorm_stats_update():
    net = models.resnet18(num_classes=4)
    params, buffers = split_state(net)
    out, new_buf = functional_call(net, params, buffers, _x(2),
                                   training=True)
    changed = [k for k in buffers
               if not np.allclose(buffers[k], new_buf[k])]
    assert any("_mean" in k or "_variance" in k for k in changed)


def test_vgg_forward():
    net = models.vgg11(num_classes=7)
    net.eval()
    out = net(_x(1, 224))
    assert out.shape == (1, 7)


def test_mobilenet_v1_forward():
    net = models.mobilenet_v1(scale=0.25, num_classes=5)
    net.eval()
    out = net(_x())
    assert out.shape == (1, 5)


def test_mobilenet_v2_forward():
    net = models.mobilenet_v2(scale=0.5, num_classes=5)
    net.eval()
    out = net(_x())
    assert out.shape == (1, 5)


def test_resnet_train_step_grads():
    import paddle_tpu as pt
    from paddle_tpu import nn
    net = models.resnet18(num_classes=4)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=net),
        loss=nn.CrossEntropyLoss())
    xs = np.random.RandomState(0).randn(4, 3, 32, 32).astype(np.float32)
    ys = np.random.RandomState(1).randint(0, 4, (4, 1))
    logs1 = model.train_batch([xs], [ys])
    logs2 = model.train_batch([xs], [ys])
    assert np.isfinite(logs1["loss"]) and np.isfinite(logs2["loss"])


# -- round-2 extra families (ref: vision/models/{alexnet,squeezenet,
#    densenet,googlenet,shufflenetv2}.py) ----------------------------------

import pytest as _pytest


@_pytest.mark.parametrize("ctor,size", [
    ("alexnet", 224), ("squeezenet1_1", 64), ("densenet121", 64),
    ("googlenet", 64), ("shufflenet_v2_x0_5", 64),
    # round-3 zoo completions (ref: vision/models/__init__.py __all__)
    ("resnext50_32x4d", 64), ("wide_resnet50_2", 64),
    ("mobilenet_v3_small", 64), ("mobilenet_v3_large", 64),
    ("shufflenet_v2_x0_25", 64), ("shufflenet_v2_swish", 64),
    ("densenet169", 64), ("inception_v3", 75),
])
def test_extra_vision_family_forward(ctor, size):
    import numpy as _np
    import paddle_tpu as _pt
    from paddle_tpu import models as _models
    _pt.seed(0)
    net = getattr(_models, ctor)(num_classes=10)
    net.eval()
    x = _np.random.RandomState(0).randn(2, 3, size, size).astype("float32")
    out = net(x)
    assert out.shape == (2, 10)
    assert _np.all(_np.isfinite(_np.asarray(out)))


def test_extra_vision_trains_one_step():
    import numpy as _np
    import paddle_tpu as _pt
    from paddle_tpu import models as _models
    _pt.seed(0)
    net = _models.squeezenet1_1(num_classes=4)
    model = _pt.Model(net)
    model.prepare(
        optimizer=_pt.optimizer.SGD(learning_rate=0.01, parameters=net),
        loss=_pt.nn.CrossEntropyLoss())
    x = _np.random.RandomState(0).randn(4, 3, 64, 64).astype("float32")
    y = _np.array([0, 1, 2, 3])
    logs = model.train_batch([x], [y])
    assert _np.isfinite(logs["loss"])


def test_channel_shuffle_inverts_grouping():
    import numpy as _np
    import jax.numpy as _jnp
    from paddle_tpu.models.vision_extra import channel_shuffle
    x = _jnp.arange(2 * 8 * 1 * 1, dtype=_jnp.float32).reshape(2, 8, 1, 1)
    y = channel_shuffle(x, 2)
    # interleaves the two halves: [0..3],[4..7] -> [0,4,1,5,2,6,3,7]
    got = _np.asarray(y[0, :, 0, 0]).astype(int).tolist()
    assert got == [0, 4, 1, 5, 2, 6, 3, 7]
