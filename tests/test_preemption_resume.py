"""Preemption-safe training (ISSUE 8): async checkpoints, exact
resume, integrity-verified restore.

In-process counterpart of the kill-anywhere chaos gate
(``tools/chaos_soak.py --ci --train``): CheckpointManager async/
manifest/verify/GC semantics, the DataLoader resume cursor, the new
fault sites' seeded determinism, flight-recorder dumps on verify
failure, ``Model.fit(resume=...)`` bit-identity, and the
ElasticManager resume-step threading + stall damping.
"""

import glob
import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.io.checkpoint import (CheckpointCorrupt, CheckpointManager,
                                      digest_tree, latest_manifest_step)
from paddle_tpu.reliability import faults
from paddle_tpu.reliability.faults import FaultInjected
from paddle_tpu.reliability.retry import Deadline


def _tree(v=0.0):
    return {"w": np.full((32, 8), v, np.float32),
            "b": np.arange(8, dtype=np.float32) + v}


def _tamper_manifest(directory, step):
    """Rewrite one digest in the step's manifest: restore then succeeds
    at the byte level but fails integrity verification."""
    path = os.path.join(directory, f"manifest-{step}.json")
    man = json.load(open(path))
    key = sorted(man["digests"])[0]
    man["digests"][key] = "0" * 32
    json.dump(man, open(path, "w"))
    return key


# -- manifests, latest_step, GC ---------------------------------------------

def test_manifest_state_rides_the_checkpoint(tmp_path):
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(3, _tree(1.0), state={"step": 3, "loader": {"pass": 0,
                                                             "batch": 7}})
        tree, state = mgr.restore_with_state()
        assert state == {"step": 3, "loader": {"pass": 0, "batch": 7}}
        assert mgr.read_state(3) == state
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      _tree(1.0)["w"])


def test_latest_step_never_surfaces_unmanifested_data(tmp_path):
    """A committed data dir whose manifest never landed (kill between
    data-commit and manifest-write) is invisible and swept."""
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(1, _tree())
        mgr.save(2, _tree())
    os.unlink(str(tmp_path / "manifest-2.json"))  # "killed mid-commit"
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    assert mgr.latest_step() == 1
    assert latest_manifest_step(str(tmp_path)) == 1
    assert not os.path.exists(str(tmp_path / "2")), \
        "unmanifested debris should be swept at open"
    mgr.save(2, _tree(2.0))  # the name is reusable after the sweep
    assert mgr.latest_step() == 2
    mgr.close()


def test_gc_keeps_newest_verified_and_skips_quarantined(tmp_path):
    with CheckpointManager(str(tmp_path), max_to_keep=2,
                           async_save=False) as mgr:
        for s in (1, 2, 3):
            mgr.save(s, _tree(float(s)), state={"step": s})
        _tamper_manifest(str(tmp_path), 3)
        _t, state = mgr.restore_with_state()   # quarantines 3, falls back
        assert state["step"] == 2
        assert mgr.latest_step() == 2
        # GC budget counts VERIFIED steps only; the newest verified
        # step is always in the keep set
        for s in (4, 5):
            mgr.save(s, _tree(float(s)), state={"step": s})
        steps = mgr.all_steps()
        assert 4 in steps and 5 in steps
        assert 1 not in steps
        assert mgr.latest_step() == 5


def test_explicit_step_restore_raises_checkpoint_corrupt(tmp_path):
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(1, _tree(1.0))
        mgr.save(2, _tree(2.0))
        key = _tamper_manifest(str(tmp_path), 2)
        with pytest.raises(CheckpointCorrupt) as ei:
            mgr.restore(2)
        assert ei.value.step == 2
        assert key in ei.value.diff
        assert ei.value.diff[key]["expected"] == "0" * 32
        # auto falls back instead of raising
        tree = mgr.restore()
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      _tree(1.0)["w"])


def test_byte_rot_unreadable_step_falls_back(tmp_path):
    """Corruption severe enough that orbax can't read the step gets the
    same quarantine+fallback verdict as a digest mismatch."""
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(1, _tree(1.0), state={"step": 1})
        mgr.save(2, _tree(2.0), state={"step": 2})
        for f in glob.glob(str(tmp_path / "2" / "**"), recursive=True):
            if os.path.isfile(f):
                blob = bytearray(open(f, "rb").read())
                for i in range(0, len(blob), 32):
                    blob[i] ^= 0xFF
                open(f, "wb").write(bytes(blob))
        _t, state = mgr.restore_with_state()
        assert state["step"] == 1
        assert mgr.latest_step() == 1
        assert os.path.exists(str(tmp_path / "manifest-2.json.corrupt"))


def test_digest_tree_keys_and_determinism():
    t = {"a": np.arange(4.0), "b": {"c": np.ones((2, 2))}}
    d1, d2 = digest_tree(t), digest_tree(t)
    assert d1 == d2 and len(d1) == 2
    t["b"]["c"][0, 0] = 5.0
    assert digest_tree(t) != d1


# -- async save path --------------------------------------------------------

def test_async_save_stall_bounded_by_snapshot(tmp_path):
    """save() returns in device→host snapshot time; the (slowed)
    commit overlaps and is barriered by wait_until_finished."""
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    orig = mgr._commit
    mgr._commit = lambda *a, **kw: (time.sleep(0.3), orig(*a, **kw))[-1]
    t0 = time.perf_counter()
    mgr.save(1, _tree(), state={"step": 1})
    stall = time.perf_counter() - t0
    mgr.wait_until_finished()
    assert stall < 0.15, f"async save stalled {stall:.3f}s"
    assert mgr.latest_step() == 1
    mgr._commit = orig
    mgr.close()


def test_async_commit_failure_surfaces_at_next_barrier(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    orig = mgr._commit
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk gone")
        return orig(*a, **kw)

    mgr._commit = flaky
    mgr.save(1, _tree())
    with pytest.raises(OSError):
        mgr.wait_until_finished()
    # the failure is consumed: the manager keeps working
    mgr.save(2, _tree())
    mgr.wait_until_finished()
    assert mgr.latest_step() == 2
    mgr._commit = orig
    mgr.close()


def test_flush_outcomes(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    assert mgr.flush() == "noop"
    mgr.save(1, _tree(), state={"step": 1})
    assert mgr.flush(Deadline.after(30.0)) == "committed"
    # a commit slower than the grace budget → timeout, previous
    # manifested step stands
    orig = mgr._commit
    release = threading.Event()
    mgr._commit = lambda *a, **kw: (release.wait(5.0), orig(*a, **kw))[-1]
    mgr.save(2, _tree())
    assert mgr.flush(Deadline.after(0.05)) == "timeout"
    assert mgr.latest_step() == 1
    release.set()
    mgr.wait_until_finished()
    assert mgr.latest_step() == 2
    mgr._commit = orig
    mgr.close()


def test_sync_save_barriers_inflight_async(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(1, _tree(1.0), state={"step": 1})
    mgr.save(2, _tree(2.0), async_=False, state={"step": 2})
    # the sync save implies the async one is committed
    assert sorted(mgr.all_steps()) == [1, 2]
    assert mgr.latest_step() == 2
    mgr.close()


# -- fault sites (satellite 1) ----------------------------------------------

def test_new_fault_sites_preview_determinism():
    for site in ("ckpt.snapshot", "ckpt.async_commit", "loader.state"):
        faults.reset()
        faults.enable(seed=77)
        faults.inject(site, p=0.3)
        want = faults.preview(site, 40)
        assert want == faults.preview(site, 40), site
        assert want, f"p=0.3 over 40 calls injected nothing at {site}"
        assert faults.preview(site, 40, seed=78) != want, site
        # live checks fire exactly on the previewed schedule
        hits = []
        for n in range(1, 41):
            try:
                faults.check(site)
            except FaultInjected:
                hits.append(n)
        assert hits == want, site
    faults.reset()


def test_loader_state_site_guards_capture_and_restore():
    faults.reset()
    faults.enable(seed=5)
    faults.inject("loader.state", nth=(1,), times=1)
    loader = DataLoader(TensorDataset([np.arange(8.0)[:, None]]),
                        batch_size=2)
    try:
        with pytest.raises(FaultInjected):
            loader.state_dict()
        loader.state_dict()  # budget consumed
    finally:
        faults.reset()


def test_verify_failure_dumps_flight_record_with_digest_diff(tmp_path):
    from paddle_tpu.observability import flight
    rec = flight.FlightRecorder(str(tmp_path / "dumps")).install()
    try:
        with CheckpointManager(str(tmp_path / "ck"),
                               async_save=False) as mgr:
            mgr.save(1, _tree(1.0))
            mgr.save(2, _tree(2.0))
            key = _tamper_manifest(str(tmp_path / "ck"), 2)
            mgr.restore()  # quarantines 2, falls back to 1
        dumps = glob.glob(str(tmp_path / "dumps" / "*ckpt_verify*"))
        assert len(dumps) == 1
        rows = [json.loads(l) for l in open(dumps[0])]
        extra = [r for r in rows if r.get("kind") == "extra"]
        assert extra and extra[0]["what"] == "checkpoint_verify_failure"
        assert extra[0]["step"] == 2
        assert key in extra[0]["digest_diff"]
        assert extra[0]["digest_diff"][key]["expected"] == "0" * 32
    finally:
        rec.uninstall()


# -- DataLoader resume cursor (satellite 3) ---------------------------------

def _batches(it, n=None):
    out = []
    for b in it:
        out.append(np.asarray(b[0]).copy())
        if n is not None and len(out) >= n:
            break
    return out


def _loader(n=24, batch_size=4, shuffle=True, **kw):
    x = np.arange(n, dtype=np.float32)[:, None]
    return DataLoader(TensorDataset([x]), batch_size=batch_size,
                      shuffle=shuffle, **kw)


def test_cursor_resumes_mid_epoch_exactly():
    pt.seed(11)
    ref = _batches(iter(_loader()))          # pass 0, uninterrupted
    pt.seed(11)
    a = _loader()
    it = iter(a)
    head = _batches(it, 3)
    st = a.state_dict()
    assert st == {"pass": 0, "batch": 3}
    it.close()
    pt.seed(11)
    b = _loader()
    b.load_state_dict(st)
    tail = _batches(iter(b))
    got = head + tail
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_cursor_counts_consumed_not_prefetched():
    """Prefetched-but-unconsumed batches must re-produce on resume."""
    a = _loader(shuffle=False, prefetch_factor=4)
    it = iter(a)
    next(it)
    time.sleep(0.2)  # let the prefetch thread run far ahead
    assert a.state_dict()["batch"] == 1
    it.close()


def test_shuffle_reproducibility_across_passes():
    """Pass e of a resumed run shuffles exactly like pass e of an
    uninterrupted one — including passes AFTER the resumed one."""
    pt.seed(13)
    a = _loader()
    ref = [_batches(iter(a)) for _ in range(3)]      # passes 0,1,2
    assert not np.array_equal(ref[0][0], ref[1][0]), \
        "shuffle should differ across passes"
    pt.seed(13)
    b = _loader()
    it = iter(b)          # pass 0
    _batches(it, 5)
    st = b.state_dict()
    it.close()
    pt.seed(13)
    c = _loader()
    c.load_state_dict(st)
    tail0 = _batches(iter(c))                        # rest of pass 0
    for r, g in zip(ref[0][5:], tail0):
        np.testing.assert_array_equal(r, g)
    for e in (1, 2):                                 # subsequent passes
        for r, g in zip(ref[e], _batches(iter(c))):
            np.testing.assert_array_equal(r, g)


def test_cursor_resumes_mid_superbatch():
    """A cursor not aligned to steps_per_loop restacks slabs from the
    resume point: slab boundaries shift, per-step contents don't."""
    pt.seed(17)
    a = _loader(n=32)
    ref = []
    for slab in a.superbatches(4):                   # pass 0: 2 slabs
        ref.extend(np.asarray(slab[0]))
    pt.seed(17)
    b = _loader(n=32)
    it = b.superbatches(4)
    first = next(it)
    got = list(np.asarray(first[0]))
    st = b.state_dict()
    assert st["batch"] == 4
    it.close()
    # checkpoint "mid-superbatch": pretend only 2 of the slab's 4
    # steps were retained (the manifest cursor can say so)
    st = {"pass": st["pass"], "batch": 2}
    got = got[:2]
    pt.seed(17)
    c = _loader(n=32)
    c.load_state_dict(st)
    for slab in c.superbatches(4):
        got.extend(np.asarray(slab[0]))
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_cursor_with_multiprocess_workers():
    """Worker seeds derive from the pass index, so a resumed pass
    re-produces the interrupted run's exact stream over mp workers."""
    pt.seed(19)
    a = _loader(n=32, num_workers=2)
    ref = _batches(iter(a))
    pt.seed(19)
    b = _loader(n=32, num_workers=2)
    it = iter(b)
    head = _batches(it, 3)
    st = b.state_dict()
    it.close()
    pt.seed(19)
    c = _loader(n=32, num_workers=2)
    c.load_state_dict(st)
    tail = _batches(iter(c))
    got = head + tail
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_cursor_across_ragged_tail_flush():
    """drop_last=False ragged tails flush short slabs; the batch-level
    cursor stays exact across the shape change."""
    pt.seed(23)
    a = _loader(n=26, shuffle=False)     # 6 full batches + tail of 2
    ref = []
    for slab in a.superbatches(4):
        ref.extend(np.asarray(slab[0]))
    assert len(ref) == 7
    pt.seed(23)
    b = _loader(n=26, shuffle=False)
    it = b.superbatches(4)
    next(it)                              # consume slab 1 (4 batches)
    st = b.state_dict()
    assert st["batch"] == 4
    it.close()
    pt.seed(23)
    c = _loader(n=26, shuffle=False)
    c.load_state_dict(st)
    got = ref[:4]
    for slab in c.superbatches(4):
        got.extend(np.asarray(slab[0]))
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


# -- Model.fit resume (tentpole, in-process) --------------------------------

class _LossTap(pt.callbacks.Callback):
    def __init__(self, epoch_steps):
        self.losses = {}
        self._epoch_steps = epoch_steps

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        g = self._epoch * self._epoch_steps + step
        self.losses[g] = float(logs["loss"]).hex()


def _fit_model(tap, ckpt_dir=None, epochs=2, resume=None, k=1,
               stop_after=None, freq=3):
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.AdamW(learning_rate=1e-2, parameters=net),
        loss=nn.CrossEntropyLoss(), metrics=pt.metric.Accuracy())
    rng = np.random.RandomState(3)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, (32, 1))
    cbs = [tap]
    if stop_after is not None:
        class _Die(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if len(tap.losses) >= stop_after:
                    raise RuntimeError("synthetic preemption")
        cbs.append(_Die())
    kw = {}
    if ckpt_dir is not None:
        kw = dict(checkpoint_dir=ckpt_dir, checkpoint_freq=freq,
                  resume=resume, keep_checkpoints=3)
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=epochs,
              shuffle=True, verbose=0, steps_per_loop=k,
              callbacks=cbs, **kw)
    return model


@pytest.mark.parametrize("k", [1, 4])
def test_fit_resume_bit_identical(tmp_path, k):
    """A fit interrupted mid-epoch-0 and resumed (fresh Model, fresh
    process-equivalent state) replays a loss stream bit-identical to
    the uninterrupted run at any steps_per_loop."""
    base = _LossTap(8)
    _fit_model(base, epochs=2, k=k)
    assert sorted(base.losses) == list(range(16))

    tap = _LossTap(8)
    d = str(tmp_path / f"ck{k}")
    with pytest.raises(RuntimeError, match="synthetic preemption"):
        _fit_model(tap, ckpt_dir=d, epochs=2, k=k, stop_after=5)
    resumed = _LossTap(8)
    _fit_model(resumed, ckpt_dir=d, epochs=2, resume="auto", k=k)
    combined = dict(tap.losses)
    combined.update(resumed.losses)
    assert sorted(combined) == list(range(16))
    for s, h in base.losses.items():
        assert combined[s] == h, f"step {s}: {combined[s]} != {h}"
        if s in resumed.losses:
            assert resumed.losses[s] == h


def test_fit_resume_restores_metric_accumulators(tmp_path):
    """Resume mid-epoch keeps the epoch's metric state: the resumed
    epoch's final accuracy equals the uninterrupted run's."""
    base = _LossTap(8)
    m1 = _fit_model(base, epochs=1)
    acc_ref = float(m1._metrics[0].accumulate())

    tap = _LossTap(8)
    d = str(tmp_path / "ck")
    with pytest.raises(RuntimeError):
        _fit_model(tap, ckpt_dir=d, epochs=1, stop_after=5)
    m2 = _fit_model(_LossTap(8), ckpt_dir=d, epochs=1, resume="auto")
    assert float(m2._metrics[0].accumulate()) == acc_ref


def test_fit_resume_env_pin_falls_back_when_corrupt(tmp_path, monkeypatch):
    """$PADDLE_ELASTIC_RESUME_STEP names the step an elastic respawn
    was handed; if that step has rotted, resume="auto" falls back to
    the newest verified step instead of dying."""
    tap = _LossTap(8)
    d = str(tmp_path / "ck")
    with pytest.raises(RuntimeError):
        _fit_model(tap, ckpt_dir=d, epochs=2, stop_after=7, freq=3)
    steps = sorted(int(s) for s in
                   CheckpointManager(d, async_save=False).all_steps())
    assert len(steps) >= 2
    _tamper_manifest(d, steps[-1])
    monkeypatch.setenv("PADDLE_ELASTIC_RESUME_STEP", str(steps[-1]))
    resumed = _LossTap(8)
    _fit_model(resumed, ckpt_dir=d, epochs=2, resume="auto")
    base = _LossTap(8)
    _fit_model(base, epochs=2)
    for s, h in resumed.losses.items():
        assert base.losses[s] == h, f"step {s} diverged after fallback"


# -- ElasticManager resume threading (satellite 2) --------------------------

def test_elastic_threads_resume_step_and_detects_stalls(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager

    def manifest(step):
        json.dump({"format": 1, "step": step, "digests": {}},
                  open(str(tmp_path / f"manifest-{step}.json"), "w"))

    mgr = ElasticManager(nproc=1, training_script="x.py",
                         script_args=[], checkpoint_dir=str(tmp_path))
    assert mgr._latest_verified() is None
    manifest(5)
    assert mgr._latest_verified() == 5
    manifest(9)
    assert mgr._latest_verified() == 9

    # spawn handed step 9; death without progress is a stall
    mgr._spawn_resume_step = 9
    assert mgr._note_resume_progress() is True
    assert mgr._resume_stalls == 1
    assert mgr._note_resume_progress() is True
    assert mgr._resume_stalls == 2
    manifest(12)   # checkpoint advanced: stall streak resets
    assert mgr._note_resume_progress() is False
    assert mgr._resume_stalls == 0


_ELASTIC_TRAIN = """
import json, os, sys
work = sys.argv[1]
resume = os.environ.get("PADDLE_ELASTIC_RESUME_STEP")
incarnation = int(os.environ.get("PADDLE_ELASTIC_RESTART_COUNT", 0))
with open(os.path.join(work, "log.txt"), "a") as f:
    f.write(json.dumps({"inc": incarnation, "resume": resume}) + "\\n")
start = 0 if resume is None else int(resume)
for step in range(start + 1, start + 4):
    man = os.path.join(work, "ckpt", f"manifest-{step}.json")
    json.dump({"format": 1, "step": step, "digests": {}},
              open(man + ".tmp", "w"))
    os.replace(man + ".tmp", man)
if incarnation == 0:
    os._exit(17)   # crash after committing 3 steps
"""


def test_elastic_restart_resumes_from_newest_verified(tmp_path):
    """Regression (satellite 2): a respawned generation is handed the
    newest verified step via $PADDLE_ELASTIC_RESUME_STEP — no script
    changes — and a crash that DID advance the checkpoint does not
    count as a resume stall."""
    from paddle_tpu.distributed.elastic import ElasticManager
    script = tmp_path / "train.py"
    script.write_text(_ELASTIC_TRAIN)
    (tmp_path / "ckpt").mkdir()
    mgr = ElasticManager(
        nproc=1, training_script=str(script),
        script_args=[str(tmp_path)], max_restarts=2,
        poll_interval=0.05, restart_backoff=0.05,
        checkpoint_dir=str(tmp_path / "ckpt"))
    assert mgr.run() == 0
    log = [json.loads(l)
           for l in (tmp_path / "log.txt").read_text().splitlines()]
    assert log[0] == {"inc": 0, "resume": None}
    # incarnation 0 committed manifests 1..3 then crashed: the respawn
    # is pinned to the newest verified step
    assert log[1] == {"inc": 1, "resume": "3"}
    assert latest_manifest_step(str(tmp_path / "ckpt")) == 6
    assert mgr._resume_stalls == 0


def test_elastic_damps_respawns_into_stalled_checkpoint(tmp_path):
    """A 'graceful' exit-67 loop that never advances the verified step
    (resume dying into a corrupt newest checkpoint) must damp like a
    crash loop instead of hot-looping respawns."""
    from paddle_tpu.distributed.elastic import (ElasticManager,
                                                RESTART_EXIT_CODE)
    script = tmp_path / "train.py"
    script.write_text(
        "import os, sys\n"
        "path = os.path.join(sys.argv[1], 'runs.txt')\n"
        "n = os.path.getsize(path) if os.path.exists(path) else 0\n"
        "open(path, 'a').write('x')\n"
        f"os._exit(0 if n >= 3 else {RESTART_EXIT_CODE})\n")
    (tmp_path / "ckpt").mkdir()
    json.dump({"format": 1, "step": 4, "digests": {}},
              open(str(tmp_path / "ckpt" / "manifest-4.json"), "w"))
    mgr = ElasticManager(
        nproc=1, training_script=str(script),
        script_args=[str(tmp_path)], max_restarts=0,
        poll_interval=0.02, restart_backoff=0.2,
        restart_backoff_cap=0.4,
        checkpoint_dir=str(tmp_path / "ckpt"))
    t0 = time.perf_counter()
    assert mgr.run() == 0
    elapsed = time.perf_counter() - t0
    # 3 preemption exits, all stalled on manifest-4: stalls 2 and 3
    # must pay escalating backoff (2 sleeps from the damping curve)
    assert mgr._resume_stalls == 3
    assert elapsed >= 0.4, (
        f"stalled exit-67 loop respawned in {elapsed:.2f}s — "
        f"restart-storm damping did not engage")


# -- review-pass regressions ------------------------------------------------

def test_fit_resume_explicit_int_one_is_not_auto(tmp_path):
    """resume=1 means STEP 1. 1 == True in Python, so a containment
    gate like ``resume in (True, "auto")`` silently turns it into
    "auto" and restores the newest step instead."""
    tap = _LossTap(8)
    d = str(tmp_path / "ck")
    with pytest.raises(RuntimeError, match="synthetic preemption"):
        _fit_model(tap, ckpt_dir=d, epochs=1, stop_after=5, freq=1)
    with CheckpointManager(d, async_save=False) as mgr:
        steps = [s for s in mgr.all_steps() if mgr.read_state(s)]
    assert 1 not in steps and 2 in steps
    # no step-1 checkpoint exists: an explicit resume=1 must raise,
    # not silently auto-resume from the newest step
    with pytest.raises(FileNotFoundError):
        _fit_model(_LossTap(8), ckpt_dir=d, epochs=1, resume=1)
    # and an explicit step that DOES exist restores that step
    resumed = _LossTap(8)
    _fit_model(resumed, ckpt_dir=d, epochs=1, resume=2)
    assert min(resumed.losses) == 2, (
        f"resume=2 restored step {min(resumed.losses)}")
    base = _LossTap(8)
    _fit_model(base, epochs=1)
    for s, h in resumed.losses.items():
        assert base.losses[s] == h


def test_fit_resume_explicit_zero_is_not_skipped(tmp_path):
    """resume=0 is an EXPLICIT step, not falsy "don't resume": when no
    step-0 checkpoint exists it must raise, never silently retrain
    from scratch."""
    d = str(tmp_path / "ck")
    with pytest.raises(RuntimeError, match="synthetic preemption"):
        _fit_model(_LossTap(8), ckpt_dir=d, epochs=1, stop_after=5)
    with pytest.raises(FileNotFoundError):
        _fit_model(_LossTap(8), ckpt_dir=d, epochs=1, resume=0)


class _FakeRemoteShard(np.ndarray):
    """Stands in for a multi-host sharded jax.Array: bytes not visible
    to this process."""
    @property
    def is_fully_addressable(self):
        return False


def test_async_save_falls_back_to_sync_for_non_addressable(tmp_path):
    """A tree with non-fully-addressable leaves can't be host-
    snapshotted by one process — save(async_=True) must take the sync
    per-shard path instead of raising, and the step restores
    (unverified, per digest_tree's contract)."""
    leaf = np.arange(8, dtype=np.float32).view(_FakeRemoteShard)
    assert not leaf.is_fully_addressable
    with CheckpointManager(str(tmp_path), async_save=True) as mgr:
        mgr.save(1, {"w": leaf})
        assert mgr._writer is None, "async writer ran on a remote shard"
        assert mgr.latest_step() == 1
        tree, _state = mgr.restore_with_state()
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      np.arange(8, dtype=np.float32))


def test_quarantined_step_never_restores_as_legacy(tmp_path):
    """Quarantine renames the manifest, which must not demote the step
    to a 'legacy unverified' directory: explicit restore raises, auto
    raises when nothing else verifies, latest_step surfaces nothing."""
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(1, _tree(1.0))
        mgr.save(2, _tree(2.0))
        _tamper_manifest(str(tmp_path), 2)
        mgr.restore()                      # quarantines 2, falls back
        with pytest.raises(CheckpointCorrupt):
            mgr.restore(2)                 # not "legacy", still corrupt
    d2 = str(tmp_path / "all_corrupt")
    with CheckpointManager(d2, async_save=False) as mgr:
        mgr.save(1, _tree(1.0))
        _tamper_manifest(d2, 1)
        with pytest.raises(CheckpointCorrupt):
            mgr.restore()                  # quarantines the only step
        assert mgr.latest_step() is None
        with pytest.raises(CheckpointCorrupt):
            mgr.restore()                  # and STAYS corrupt reopened


def test_gc_and_sweep_keep_legacy_steps_at_migration_boundary(tmp_path):
    """Pre-manifest checkpoints are rollback points, not debris: the
    first manifested save must rotate them through the keep-last-N
    budget, and reopening must not sweep them."""
    d = str(tmp_path)
    with CheckpointManager(d, async_save=False) as mgr:
        for s in (1, 2, 3):
            mgr.save(s, _tree(float(s)))
    for s in (1, 2, 3):
        os.unlink(os.path.join(d, f"manifest-{s}.json"))  # legacy era
    # a genuine pre-manifest directory has no era marker either — with
    # it, manifest-less dirs are (correctly) debris from a crashed
    # first commit, not legacy rollback points
    os.unlink(os.path.join(d, ".manifest-era"))
    with CheckpointManager(d, max_to_keep=3, async_save=False) as mgr:
        assert mgr.latest_step() == 3      # legacy fallback
        mgr.save(5, _tree(5.0))
        steps = mgr.all_steps()
        assert steps == [2, 3, 5], (
            f"migration-boundary GC kept {steps}, wanted newest 3 "
            f"counting legacy rollback points")
    with CheckpointManager(d, max_to_keep=3, async_save=False) as mgr:
        assert mgr.all_steps() == [2, 3, 5], "reopen swept legacy steps"
        assert mgr.latest_step() == 5


def test_first_commit_crash_debris_not_legacy(tmp_path):
    """A kill between the FIRST-ever data commit and its manifest
    write leaves an unmanifested data dir in a directory with zero
    manifests. Without the era marker that dir read as a pre-manifest
    LEGACY checkpoint and was resurrected unverified — with no resume
    state bundle, silently diverging the loss stream (chaos-soak
    flake). It must classify as debris: swept at open, never restored,
    latest_step None."""
    d = str(tmp_path)
    with CheckpointManager(d, async_save=False) as mgr:
        mgr.save(1, _tree(1.0))
    os.unlink(os.path.join(d, "manifest-1.json"))  # the crash window
    assert os.path.isdir(os.path.join(d, "1"))
    with CheckpointManager(d, async_save=False) as mgr:
        assert mgr.latest_step() is None
        assert mgr.all_steps() == []       # swept at open, not legacy
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def test_duplicate_step_save_skips_like_legacy(tmp_path):
    """Re-saving an already-manifested step is a silent skip (the old
    orbax-backed behavior), not an error — AutoCheckpoint's multi-rank
    agreed-older-step resume re-commits a step some ranks already
    hold. force=True still overwrites."""
    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        assert mgr.save(1, _tree(1.0)) is True
        assert mgr.save(1, _tree(9.0)) is False      # skipped, no raise
        tree = mgr.restore(1)
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      _tree(1.0)["w"])
        assert mgr.save(1, _tree(9.0), force=True) is True
        tree = mgr.restore(1)
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      _tree(9.0)["w"])


def test_close_does_not_reblock_after_flush_timeout(tmp_path):
    """Once a deadline-budgeted flush has timed out, the grace budget
    is SPENT: close() (fit's finally on the preemption exit path) must
    return immediately instead of waiting out the stuck commit."""
    release = threading.Event()
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    orig = mgr._dispatch_save

    def slow_dispatch(step, tree):
        release.wait(timeout=30.0)
        return orig(step, tree)

    mgr._dispatch_save = slow_dispatch
    try:
        mgr.save(1, _tree(1.0))
        assert mgr.flush(Deadline.after(0.2)) == "timeout"
        t0 = time.perf_counter()
        mgr.close()
        assert time.perf_counter() - t0 < 2.0, (
            "close() re-blocked on the commit the flush gave up on")
    finally:
        release.set()


def test_loader_does_not_clobber_user_set_epoch():
    """DistributedBatchSampler.set_epoch is the USER's contract: once
    called, the loader's pass-index sync must not overwrite it; without
    a user call, the loader keys shuffle to the pass index (exact
    resume)."""
    from paddle_tpu.io import DistributedBatchSampler

    def order(sampler):
        return [i for batch in sampler for i in batch]

    data = list(range(16))
    ds = TensorDataset([np.arange(16, dtype=np.float32)[:, None]])
    # loader-managed: shuffle varies by pass, pass e reproduces pass e
    s1 = DistributedBatchSampler(data, batch_size=4, num_replicas=1,
                                 rank=0, shuffle=True)
    dl = DataLoader(ds, batch_sampler=s1, to_device=False)
    p0 = [int(np.asarray(b[0])[0, 0]) for b in dl]
    assert s1.epoch == 0
    p1 = [int(np.asarray(b[0])[0, 0]) for b in dl]
    assert s1.epoch == 1 and p0 != p1
    dl.load_state_dict({"pass": 0, "batch": 0})
    assert [int(np.asarray(b[0])[0, 0]) for b in dl] == p0
    # user-managed: the pin survives loader passes
    s2 = DistributedBatchSampler(data, batch_size=4, num_replicas=1,
                                 rank=0, shuffle=True)
    s2.set_epoch(7)
    ref = order(s2)
    dl2 = DataLoader(ds, batch_sampler=s2, to_device=False)
    for _ in dl2:
        pass
    assert s2.epoch == 7, "loader clobbered the user's set_epoch"
    assert order(s2) == ref
