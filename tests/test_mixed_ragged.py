"""One ragged kernel for mixed prefill+decode, on an int8-quantized
KV pool (ISSUE 15).

Contracts under test:

- ``ragged_paged_attention`` is THE entry point: the decode, chunk and
  ragged wrappers are exact aliases of it (xla AND pallas impls), and
  it serves a mixed batch of prefill rows and decode rows in one call.
- int8 KV (``QuantizedKV``: quantize-on-write, per-token scales,
  dequantize-in-kernel) stays within the documented tolerance of the
  f32-accumulate reference path at the op level, and quantization is
  DETERMINISTIC — cache on/off, fused slabs and the mixed tick all
  produce identical int8 streams.
- ``mixed_tick=True`` collapses the alternating prefill/decode tick
  loop into one fused dispatch whose streams are TOKEN-IDENTICAL to
  the legacy two-op tick path (greedy AND seeded, cache on/off,
  N in {1, 8}), with a prompt admitted mid-slab decoding on device
  (zero host dispatches between its phases).
- ~2x page capacity at fixed HBM: int8 page bytes (scale table
  included) buy >= 1.8x the pages of bf16, and the memory ledger's
  kv_pool rows split dtype bytes from scale-table bytes while still
  tiling the pool exactly.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import LLMEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.ops.paged_attention import (
    QuantizedKV, kv_layer, kv_write, kv_zeros, paged_attention,
    paged_attention_chunk, paged_attention_ragged,
    ragged_paged_attention, ragged_paged_attention_reference)

# the documented int8 quantization tolerances (PERF.md "Ragged mixed
# tick + int8 KV"): op-level attention output within ATOL of the f32
# reference on unit-variance KV; engine-level greedy token agreement
# vs an f32-pool engine at least AGREE on the pinned workload
INT8_ATOL = 0.05
INT8_GREEDY_AGREE = 0.9


def tiny_gpt():
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


# ---------------------------------------------------------------------------
# op level: one entry point, int8 tolerance
# ---------------------------------------------------------------------------


def _filled_stores(rng, L=1, NP=12, PS=4, KVH=2, D=16, pages=(1, 2, 3)):
    q8 = kv_zeros((L, NP, PS, KVH, D), "int8")
    f32 = kv_zeros((L, NP, PS, KVH, D), jnp.float32)
    for page in pages:
        rows = jnp.asarray(rng.randn(PS, KVH, D), jnp.float32)
        idx = jnp.full((PS,), page, jnp.int32)
        offs = jnp.arange(PS)
        q8 = kv_write(q8, 0, idx, offs, rows)
        f32 = kv_write(f32, 0, idx, offs, rows)
    return q8, f32


def test_ragged_entry_subsumes_decode_chunk_and_ragged():
    """The three legacy ops are exact aliases of the ONE ragged entry
    point, on both impls."""
    rng = np.random.RandomState(0)
    _, f32 = _filled_stores(rng)
    kp = kv_layer(f32, 0)
    B, K, H, D = 3, 2, 4, 16
    tables = jnp.asarray([[1, 2, 3], [2, 3, 0], [0, 0, 0]], jnp.int32)
    lens = jnp.asarray([7, 4, 0], jnp.int32)
    q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
    for impl in ("xla", "pallas"):
        dec = np.asarray(paged_attention(q, kp, kp, tables, lens,
                                         impl=impl))
        rag = np.asarray(ragged_paged_attention(q, kp, kp, tables,
                                                lens, impl=impl))
        np.testing.assert_array_equal(dec, rag)
    qc = jnp.asarray(rng.randn(B, K, H, D), jnp.float32)
    base = jnp.asarray([5, 2, 0], jnp.int32)
    chunk = np.asarray(paged_attention_chunk(qc, kp, kp, tables, base))
    lims = jnp.where(base[:, None] > 0,
                     base[:, None] + jnp.arange(K)[None, :] + 1,
                     0).reshape(-1)
    rag = np.asarray(ragged_paged_attention(
        qc.reshape(B * K, H, D), kp, kp,
        jnp.repeat(tables, K, axis=0), lims))
    np.testing.assert_array_equal(chunk, rag.reshape(B, K, H, D))
    old = np.asarray(paged_attention_ragged(q, kp, kp, tables, lens))
    np.testing.assert_array_equal(
        old, np.asarray(ragged_paged_attention(q, kp, kp, tables,
                                               lens)))


def test_mixed_batch_rows_equal_separate_dispatches():
    """A batch mixing prefill-style rows and decode-style rows gives
    each row EXACTLY what the separate dispatches gave it — the
    property that lets the engine serve both phases in one call."""
    rng = np.random.RandomState(1)
    _, f32 = _filled_stores(rng)
    kp = kv_layer(f32, 0)
    H, D = 4, 16
    # "decode" rows: one token per sequence, full-context limits
    qd = jnp.asarray(rng.randn(2, H, D), jnp.float32)
    td = jnp.asarray([[1, 2, 3], [2, 3, 0]], jnp.int32)
    ld = jnp.asarray([9, 5], jnp.int32)
    # "prefill" rows: successive positions of one sequence
    qp = jnp.asarray(rng.randn(3, H, D), jnp.float32)
    tp = jnp.asarray([[3, 1, 0]] * 3, jnp.int32)
    lp = jnp.asarray([2, 3, 4], jnp.int32)
    sep_d = np.asarray(ragged_paged_attention(qd, kp, kp, td, ld))
    sep_p = np.asarray(ragged_paged_attention(qp, kp, kp, tp, lp))
    mixed = np.asarray(ragged_paged_attention(
        jnp.concatenate([qp, qd]), kp, kp,
        jnp.concatenate([tp, td]), jnp.concatenate([lp, ld])))
    np.testing.assert_array_equal(mixed[:3], sep_p)
    np.testing.assert_array_equal(mixed[3:], sep_d)


def test_int8_within_tolerance_of_f32_reference():
    """int8 quantize-on-write + dequantize-in-kernel stays within the
    documented tolerance of the f32-accumulate reference path, on
    both impls; masked rows stay exactly zero."""
    rng = np.random.RandomState(2)
    q8, f32 = _filled_stores(rng)
    q = jnp.asarray(rng.randn(5, 4, 16), jnp.float32)
    tbl = jnp.asarray(np.tile([[1, 2, 3]], (5, 1)), jnp.int32)
    lens = jnp.asarray([1, 4, 7, 11, 0], jnp.int32)
    ref = np.asarray(ragged_paged_attention_reference(
        q, kv_layer(f32, 0), kv_layer(f32, 0), tbl, lens))
    for impl in ("xla", "pallas", "reference"):
        got = np.asarray(ragged_paged_attention(
            q, kv_layer(q8, 0), kv_layer(q8, 0), tbl, lens,
            impl=impl))
        err = np.max(np.abs(got - ref))
        assert err < INT8_ATOL, (impl, err)
        np.testing.assert_allclose(got[4], 0.0)


def test_quantization_is_deterministic():
    """Identical KV values quantize to identical bytes AND identical
    scales — the property cache-sharing and nonce-pinned replay lean
    on."""
    rng = np.random.RandomState(3)
    rows = jnp.asarray(rng.randn(4, 2, 16), jnp.float32)
    s1 = kv_zeros((1, 8, 4, 2, 16), "int8")
    s2 = kv_zeros((1, 8, 4, 2, 16), "int8")
    idx = jnp.full((4,), 2, jnp.int32)
    offs = jnp.arange(4)
    s1 = kv_write(s1, 0, idx, offs, rows)
    s2 = kv_write(s2, 0, idx, offs, rows)
    np.testing.assert_array_equal(np.asarray(s1.pages),
                                  np.asarray(s2.pages))
    np.testing.assert_array_equal(np.asarray(s1.scales),
                                  np.asarray(s2.scales))


# ---------------------------------------------------------------------------
# engine level: mixed tick parity, int8 parity/tolerance, capacity
# ---------------------------------------------------------------------------


def run_engine(net, prompts, gen, *, mixed, n=1, kv=None,
               temperature=0.0, cache=True, page_size=4,
               num_pages=128, chunk=8, seed=3, eos=None,
               max_seqs=4, warm_first=0):
    """One engine pass. ``warm_first``: run that many head prompts to
    completion BEFORE the burst (their pages are registered, so the
    burst's shared prefixes genuinely hit the cache)."""
    eng = LLMEngine(net, max_seqs=max_seqs, page_size=page_size,
                    num_pages=num_pages, prefill_buckets=(32,),
                    prefix_cache=cache, prefill_chunk=chunk,
                    eos_token_id=eos, seed=seed,
                    decode_ticks_per_dispatch=n, mixed_tick=mixed,
                    kv_dtype=kv)
    with eng:
        outs = []
        if warm_first:
            outs += eng.generate(prompts[:warm_first],
                                 max_new_tokens=gen,
                                 temperature=temperature)
        outs += eng.generate(prompts[warm_first:],
                             max_new_tokens=gen,
                             temperature=temperature)
    # leak audit rides every run: the pool is whole after close
    assert len(eng._free_pages) == eng.num_pages - 1, "KV pages leaked"
    return [o["output_ids"] for o in outs], outs, eng


@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "seeded"])
@pytest.mark.parametrize("cache", [True, False],
                         ids=["cache-on", "cache-off"])
def test_mixed_tick_token_identity_vs_legacy(cache, temperature):
    """The ISSUE-15 acceptance pin: one batch mixing cache-hit
    prefill (shared prefix), cold prefill chunks and decodes through
    the MIXED tick is token-identical to the legacy two-op tick path,
    greedy and seeded, cache on/off, N in {1, 8}."""
    net = tiny_gpt()
    rng = np.random.RandomState(0)
    prefix = rng.randint(0, 97, 8).tolist()          # 2 full pages
    prompts = [prefix + rng.randint(0, 97, 5).tolist(),   # warm
               prefix + rng.randint(0, 97, 3).tolist(),   # cache hit
               rng.randint(0, 97, 21).tolist(),           # cold, long
               rng.randint(0, 97, 4).tolist()]            # cold, short
    ref, _, _ = run_engine(net, prompts, 10, mixed=False,
                           temperature=temperature, cache=cache,
                           warm_first=1)
    for n in (1, 8):
        got, outs, eng = run_engine(net, prompts, 10, mixed=True, n=n,
                                    temperature=temperature,
                                    cache=cache, warm_first=1)
        assert got == ref, f"mixed tick diverged at N={n}"
        assert eng.n_mixed_slabs > 0, "mixed path never engaged"
        assert all(o["ttft_s"] is not None for o in outs)
    if cache:
        assert eng.n_cached_tokens > 0, \
            "shared prefix never hit the cache through the mixed tick"


def test_mixed_slab_admits_prefill_without_host_dispatches():
    """A long prompt submitted mid-decode rides INTO the slab: the
    tick history shows mixed slabs ('m'), the mixed-prefill counter
    advances, and the combined streams still match the legacy run —
    with strictly fewer host dispatches than the legacy alternating
    loop needed."""
    net = tiny_gpt()
    rng = np.random.RandomState(6)
    short = rng.randint(0, 97, 4).tolist()
    long = rng.randint(0, 97, 40).tolist()

    def interleaved(mixed, n):
        eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=128,
                        prefill_buckets=(64,), prefill_chunk=8,
                        decode_ticks_per_dispatch=n, mixed_tick=mixed)
        with eng:
            f1 = eng.submit(short, max_new_tokens=24)
            while not (eng.n_decode_ticks or eng.n_mixed_slabs):
                time.sleep(0.002)
            f2 = eng.submit(long, max_new_tokens=8)
            outs = [f1.result(timeout=120), f2.result(timeout=120)]
            hist = "".join(eng.tick_history)
            dispatches = eng.n_host_dispatches
        assert len(eng._free_pages) == eng.num_pages - 1
        return [o["output_ids"] for o in outs], hist, dispatches

    ref, _, d_ref = interleaved(False, 4)
    got, hist, d_mixed = interleaved(True, 4)
    assert got == ref
    assert "m" in hist, hist
    assert d_mixed < d_ref, (d_mixed, d_ref)


def test_mixed_eos_and_page_pressure_match_legacy():
    """EOS landing mid-slab and a pool too small to cover the slab
    both resolve exactly as the legacy path does (the shrink /
    truncation decisions re-plan at slab entry)."""
    net = tiny_gpt()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, 5).tolist(),
               rng.randint(0, 97, 7).tolist()]
    base, _, _ = run_engine(net, prompts, 12, mixed=False)
    eos = base[0][5]
    ref, _, _ = run_engine(net, prompts, 12, mixed=False, eos=eos)
    got, _, _ = run_engine(net, prompts, 12, mixed=True, n=8, eos=eos)
    assert got == ref
    assert len(got[0]) < 12 and got[0][-1] == eos
    # page pressure: tiny pool forces shrink/truncation decisions
    tight = [rng.randint(0, 97, 5).tolist()]
    for pages in (9, 16):
        r, routs, _ = run_engine(net, tight, 20, mixed=False, n=1,
                                 page_size=2, num_pages=pages,
                                 cache=False)
        g, gouts, _ = run_engine(net, tight, 20, mixed=True, n=8,
                                 page_size=2, num_pages=pages,
                                 cache=False)
        assert g == r, pages
        assert [o["truncated"] for o in gouts] == \
            [o["truncated"] for o in routs], pages


def test_mixed_guard_kind_coherent():
    """Satellite: the mixed program registers under its own
    ``mixed_tick`` recompile-guard kind (decode_step|decode_loop|
    prefill collapse into it while the queue is served mixed)."""
    net = tiny_gpt()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 97, 5).tolist()]
    _, _, eng = run_engine(net, prompts, 8, mixed=True, n=8)
    kinds = {s[0] for s in eng._shape_signatures}
    assert "mixed_tick" in kinds, kinds
    # the realized mixed-slab length tracks the prefill schedule (a
    # short prompt packs into one tick; decode continues in the
    # cheaper pure-decode slab), always within the N bound
    lengths = [s[1] for s in eng._shape_signatures
               if s[0] == "mixed_tick"]
    assert lengths and all(1 <= n <= 8 for n in lengths), lengths
    # the legacy per-phase prefill program never compiled
    assert "prefill" not in kinds, kinds


def test_int8_engine_parity_and_tolerance():
    """int8 KV engine: cache on/off, fused slabs (N=8) and the mixed
    tick all produce IDENTICAL int8 streams (quantization is
    deterministic), and greedy agreement vs the f32-pool engine
    meets the documented tolerance."""
    net = tiny_gpt()
    rng = np.random.RandomState(4)
    prefix = rng.randint(0, 97, 8).tolist()
    prompts = [prefix + rng.randint(0, 97, 5).tolist(),
               prefix + rng.randint(0, 97, 3).tolist(),
               rng.randint(0, 97, 11).tolist()]
    base, _, eng = run_engine(net, prompts, 10, mixed=False,
                              kv="int8")
    assert isinstance(eng.k_pages, QuantizedKV)
    for kwargs in (dict(mixed=False, cache=False),
                   dict(mixed=False, n=8),
                   dict(mixed=True, n=8)):
        got, _, _ = run_engine(net, prompts, 10, kv="int8", **kwargs)
        assert got == base, f"int8 streams diverged under {kwargs}"
    f32, _, _ = run_engine(net, prompts, 10, mixed=False)
    agree = np.mean([np.mean([a == b for a, b in zip(x, y)])
                     for x, y in zip(base, f32)])
    assert agree >= INT8_GREEDY_AGREE, (
        f"int8 greedy agreement {agree:.3f} below the documented "
        f"tolerance {INT8_GREEDY_AGREE}")


def test_int8_capacity_and_ledger_split():
    """~2x page capacity at fixed HBM: int8 page bytes (scale table
    included) are <= 1/1.8 of bf16's; the memory ledger's kv_pool
    rows gain the dtype/scale split and still tile the pool
    exactly."""
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.observability import memory as memobs
    net = tiny_gpt()
    engines = {}
    for kv in ("bf16", "int8"):
        engines[kv] = LLMEngine(net, max_seqs=2, page_size=4,
                                num_pages=32, prefill_buckets=(16,),
                                kv_dtype=kv)
    try:
        ratio = engines["bf16"]._page_bytes / \
            engines["int8"]._page_bytes
        assert ratio >= 1.8, (
            f"int8 pages must buy >=1.8x capacity at fixed HBM; "
            f"page bytes give only {ratio:.2f}x")
        eng = engines["int8"]
        assert eng._page_scale_bytes > 0
        if memobs.enabled():
            rows = [r for r in memobs.instance().rows()
                    if r["owner"] == "kv_pool"]
            kinds = {r["kind"] for r in rows}
            assert "scale_table" in kinds, kinds
            total = sum(r["bytes"] for r in rows)
            # one engine is bf16 (no scale row), one int8: each
            # engine's rows tile ITS pool; sum over both
            expect = sum(e.num_pages * e._page_bytes
                         for e in engines.values())
            assert total == expect, (total, expect)
    finally:
        for e in engines.values():
            e.close()


def test_kv_dtype_and_mixed_knob_validation():
    net = tiny_gpt()
    with pytest.raises(ValueError, match="kv_dtype"):
        LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                  prefill_buckets=(16,), kv_dtype="int4")
    with pytest.raises(ValueError, match="lookahead"):
        LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                  prefill_buckets=(16,), mixed_tick=True, lookahead=2)
    pt.seed(1)
    dcfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                      num_heads=2, vocab_size=97,
                      max_position_embeddings=96, hidden_dropout=0.0,
                      attention_dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    # int8 + draft_net composes on the slab path (the quantized draft
    # pool); ONLY the legacy inline path still raises its typed error
    with pytest.raises(ValueError, match="spec_slab"):
        LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                  prefill_buckets=(16,), draft_net=draft,
                  kv_dtype="int8", spec_slab=False)
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=32,
                    prefill_buckets=(16,), draft_net=draft,
                    kv_dtype="int8")
    assert eng.spec_slab and isinstance(eng.draft_k_pages, QuantizedKV)
    assert eng.decode_ticks_per_dispatch >= 1   # no legacy ticks clamp
    eng.close()
    # a slab spec engine RIDES mixed_tick; a LEGACY spec engine
    # silently clamps it off (its rounds are their own fusion),
    # mirroring the slab-knob clamp
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=32,
                    prefill_buckets=(16,), draft_net=draft,
                    mixed_tick=True)
    assert eng.mixed_tick is True
    eng.close()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=32,
                    prefill_buckets=(16,), draft_net=draft,
                    mixed_tick=True, spec_slab=False)
    assert eng.mixed_tick is False
    eng.close()
    # the legacy path ALSO still clamps decode_ticks_per_dispatch
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=32,
                    prefill_buckets=(16,), draft_net=draft,
                    decode_ticks_per_dispatch=4, spec_slab=False)
    assert eng.decode_ticks_per_dispatch == 1
    eng.close()
    # flags feed the defaults
    from paddle_tpu.core import flags
    flags.set_flags({"mixed_tick": True, "kv_dtype": "int8"})
    try:
        eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                        prefill_buckets=(16,))
        assert eng.mixed_tick is True
        assert eng.kv_dtype == "int8"
        assert isinstance(eng.k_pages, QuantizedKV)
        eng.close()
    finally:
        flags.set_flags({"mixed_tick": True, "kv_dtype": ""})
    # the flipped default: mixed_tick is ON unless opted out
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                    prefill_buckets=(16,))
    assert eng.mixed_tick is True
    eng.close()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=16,
                    prefill_buckets=(16,), mixed_tick=False)
    assert eng.mixed_tick is False
    eng.close()
