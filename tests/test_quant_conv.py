"""Int8 conv quantization + conv-bn folding (VERDICT r4 item 5; ref:
the reference's CNN int8 serving path — fluid/inference/api/
mkldnn_quantizer.cc assumes fused conv-bn, slim quantization_pass.py
_fuse_conv_bn — rebuilt as trace-discovered folding + a layer swap).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, quant
from paddle_tpu.models.resnet import resnet18
from paddle_tpu.nn.layers.norm import _BatchNormBase

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


class ConvBnNet(nn.Layer):
    """conv→bn→relu→conv→relu→bn: the second BN does NOT directly
    follow its conv (relu between), so only the first pair may fold."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2D(3, 8, 3, padding=1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(8)
        self.conv2 = nn.Conv2D(8, 8, 3, padding=1)
        self.bn2 = nn.BatchNorm2D(8)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        return self.bn2(self.relu(self.conv2(x)))


def _trained_stats(net, x):
    """Run a few train-mode batches so BN stats are non-trivial."""
    net.train()
    for _ in range(3):
        net(x + jnp.asarray(
            np.random.RandomState(0).randn(*x.shape) * 0.1,
            jnp.float32))
    net.eval()


def test_fold_conv_bn_exact_and_structural():
    pt.seed(0)
    net = ConvBnNet()
    x = jnp.asarray(np.random.RandomState(1).randn(2, 3, 8, 8),
                    jnp.float32)
    _trained_stats(net, x)
    ref = np.asarray(net(x))
    n = quant.fold_conv_bn(net, x)
    assert n == 1  # only conv1-bn1 is directly adjacent
    bns = [l for l in net.sublayers() if isinstance(l, _BatchNormBase)]
    assert len(bns) == 1  # bn2 (behind relu) survives
    np.testing.assert_allclose(np.asarray(net(x)), ref, rtol=1e-4,
                               atol=1e-5)
    # conv1 gained the folded bias
    assert net.conv1.bias is not None


def test_fold_conv_bn_resnet18_all_pairs():
    """Every BN in the resnet follows its conv directly — all fold,
    outputs match, and the folded net has no BatchNorm left."""
    pt.seed(0)
    net = resnet18(num_classes=10)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 32, 32),
                    jnp.float32)
    _trained_stats(net, x)
    ref = np.asarray(net(x))
    n_bns = sum(1 for l in net.sublayers()
                if isinstance(l, _BatchNormBase))
    n = quant.fold_conv_bn(net, x)
    assert n == n_bns
    assert not any(isinstance(l, _BatchNormBase)
                   for l in net.sublayers())
    np.testing.assert_allclose(np.asarray(net(x)), ref, rtol=5e-4,
                               atol=5e-4)


def test_quantized_conv_weight_only_close():
    pt.seed(0)
    conv = nn.Conv2D(3, 16, 3, stride=2, padding=1)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 3, 16, 16),
                    jnp.float32)
    ref = np.asarray(conv(x))
    q = quant.QuantizedConv2D(conv)
    out = np.asarray(q(x))
    assert q.qweight.dtype == jnp.int8
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert err < 0.02, err


def test_quantized_conv_int8_activations_int32_accum():
    """Calibrated path: activations quantize, conv accumulates int8 x
    int8 in int32 (exactness at int scale), output stays close."""
    pt.seed(0)
    conv = nn.Conv2D(8, 16, 3, padding=1, groups=2)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 12, 12),
                    jnp.float32)
    ref = np.asarray(conv(x))
    qmax = 127.0
    q = quant.QuantizedConv2D(conv,
                              act_scale=float(np.abs(x).max()) / qmax)
    out = np.asarray(q(x))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
    assert err < 0.05, err


def test_ptq_resnet_fold_then_quantize_topk_preserved(tmp_path):
    """The CV serving recipe end-to-end: fold BN -> PTQ (weights +
    calibrated activations) -> logits stay close enough to preserve
    top-1 on random-init logits; artifact shrinks through jit.save."""
    import os

    from paddle_tpu import jit

    pt.seed(0)
    net = resnet18(num_classes=10)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 3, 32, 32), jnp.float32)
    _trained_stats(net, x)
    ref = np.asarray(net(x))

    spec = [jit.InputSpec([4, 3, 32, 32], "float32")]
    p32 = str(tmp_path / "fp32")
    jit.save(net, p32, input_spec=spec)

    quant.fold_conv_bn(net, x)
    n = quant.quantize_post_training(
        net, calibration_batches=[(x,)],
        skip=lambda l: isinstance(l, nn.Linear))  # int8 convs, fp head
    assert n >= 20  # resnet18: 20 convs
    got = np.asarray(net(x))
    assert np.array_equal(got.argmax(-1), ref.argmax(-1))
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.1, rel

    p8 = str(tmp_path / "int8")
    jit.save(net, p8, input_spec=spec)
    sz32 = os.path.getsize(os.path.join(p32, "params.pbin"))
    sz8 = os.path.getsize(os.path.join(p8, "params.pbin"))
    assert sz8 < 0.45 * sz32, (sz8, sz32)
    loaded = jit.load(p8)
    np.testing.assert_allclose(np.asarray(loaded(x)), got, rtol=1e-4,
                               atol=1e-4)
