"""Worker functions for the real multi-process distributed tests
(tests/test_dist_multiprocess.py). Top-level module so spawn's pickle
can import them in the child.

Every worker pins the CPU backend IN-CODE before any device query —
the sandbox's sitecustomize pre-imports jax with the TPU plugin and a
child process must never touch the (single-client) TPU tunnel."""

import json
import os


def _pin_cpu_single_device():
    import jax
    # in-code config beats inherited XLA_FLAGS/JAX_PLATFORMS (those are
    # too late/too weak once sitecustomize has imported jax)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)
    return jax


def allreduce_and_dp_train(result_dir: str, steps: int = 10):
    """Rank body: cross-process all-reduce + a short DP training run.
    The analog of the reference's subprocess trainer bodies
    (fluid/tests/unittests/test_dist_base.py:786 TestDistRunnerBase /
    test_collective_api_base.py:19) — rank 0 records results for the
    parent to compare against a single-process baseline."""
    jax = _pin_cpu_single_device()
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, parallel
    from paddle_tpu.parallel import collective

    parallel.init_parallel_env()   # PADDLE_* env → jax.distributed
    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc == 2, nproc
    assert jax.device_count() == 2, jax.devices()

    mesh = parallel.init_mesh(dp=2)

    # 1) cross-process all-reduce (psum over the dp axis): each process
    # contributes its local shard of a global [2] array
    from jax.sharding import NamedSharding, PartitionSpec as P
    local = np.asarray([float(rank + 1)], np.float32)
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh.mesh, P("dp")), local)

    summed = jax.jit(
        jax.shard_map(lambda v: collective.psum(v, "dp"),
                      mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp")),
    )(x)
    allreduce_val = float(np.asarray(
        summed.addressable_data(0)).ravel()[0])   # 1 + 2 = 3 everywhere

    # 2) short DP training run, loss parity with single process
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())
    parallel.distributed_model(model, mesh=mesh)
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, 8, 8).astype(np.float32)
    ys = rng.randint(0, 4, (steps, 8, 1))
    losses = []
    for i in range(steps):
        logs = model.train_batch([xs[i]], [ys[i]])
        losses.append(float(logs["loss"]))

    if rank == 0:
        with open(os.path.join(result_dir, "rank0.json"), "w") as f:
            json.dump({"allreduce": allreduce_val, "losses": losses}, f)


def baseline_losses(steps: int = 10):
    """Single-process dense reference for the DP parity check — run in
    the PARENT process (already CPU-pinned by conftest)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, 8, 8).astype(np.float32)
    ys = rng.randint(0, 4, (steps, 8, 1))
    return [float(model.train_batch([xs[i]], [ys[i]])["loss"])
            for i in range(steps)]
