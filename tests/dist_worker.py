"""Worker functions for the real multi-process distributed tests
(tests/test_dist_multiprocess.py). Top-level module so spawn's pickle
can import them in the child.

Every worker pins the CPU backend IN-CODE before any device query —
the sandbox's sitecustomize pre-imports jax with the TPU plugin and a
child process must never touch the (single-client) TPU tunnel."""

import json
import os


def _pin_cpu_single_device():
    import jax
    # in-code config beats inherited XLA_FLAGS/JAX_PLATFORMS (those are
    # too late/too weak once sitecustomize has imported jax)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)
    return jax


def allreduce_and_dp_train(result_dir: str, steps: int = 10):
    """Rank body: cross-process all-reduce + a short DP training run.
    The analog of the reference's subprocess trainer bodies
    (fluid/tests/unittests/test_dist_base.py:786 TestDistRunnerBase /
    test_collective_api_base.py:19) — rank 0 records results for the
    parent to compare against a single-process baseline."""
    jax = _pin_cpu_single_device()
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, parallel
    from paddle_tpu.parallel import collective

    parallel.init_parallel_env()   # PADDLE_* env → jax.distributed
    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc == 2, nproc
    assert jax.device_count() == 2, jax.devices()

    mesh = parallel.init_mesh(dp=2)

    # 1) cross-process all-reduce (psum over the dp axis): each process
    # contributes its local shard of a global [2] array
    from jax.sharding import NamedSharding, PartitionSpec as P
    local = np.asarray([float(rank + 1)], np.float32)
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh.mesh, P("dp")), local)

    summed = jax.jit(
        jax.shard_map(lambda v: collective.psum(v, "dp"),
                      mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp")),
    )(x)
    allreduce_val = float(np.asarray(
        summed.addressable_data(0)).ravel()[0])   # 1 + 2 = 3 everywhere

    # 2) short DP training run, loss parity with single process
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())
    parallel.distributed_model(model, mesh=mesh)
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, 8, 8).astype(np.float32)
    ys = rng.randint(0, 4, (steps, 8, 1))
    losses = []
    for i in range(steps):
        logs = model.train_batch([xs[i]], [ys[i]])
        losses.append(float(logs["loss"]))

    if rank == 0:
        with open(os.path.join(result_dir, "rank0.json"), "w") as f:
            json.dump({"allreduce": allreduce_val, "losses": losses}, f)


def _widedeep_ctr(nn_mod, jnp, table):
    """WideDeep tower shared by the sharded-embedding worker and its
    single-process baseline (same structure as test_host_embedding)."""
    nn = nn_mod

    class WideDeep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.sparse = table
            self.deep = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                      nn.Linear(16, 1))

        def forward(self, ids, dense):
            return self.deep(dense) + self.sparse(ids) @ jnp.ones((8, 1))

    return WideDeep()


def _ctr_data(steps):
    import numpy as np
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 1_000_000, (steps, 64, 4))
    dense = rng.randn(steps, 64, 8).astype(np.float32)
    y = ((ids.sum(2, keepdims=True) % 7) > 3).astype(np.float32)
    return ids, dense, y


def sharded_embedding_train(result_dir: str, steps: int = 12,
                            resume_at: int = 8, budget: int = 2000):
    """Rank body for the key-range-sharded embedding test (VERDICT r3
    ask #2): WideDeep over ShardedHostEmbedding on a 2-process dp mesh,
    with a mid-run generation restart from per-process shard snapshots.
    The per-host row budget is set BELOW the global touched-row count:
    only the sharded table fits (each host stores ~1/2 the rows)."""
    jax = _pin_cpu_single_device()
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, parallel

    parallel.init_parallel_env()
    rank = jax.process_index()
    mesh = parallel.init_mesh(dp=2)

    def build():
        pt.seed(0)
        table = nn.ShardedHostEmbedding(
            1_000_000, 8, optimizer="adagrad", learning_rate=0.1,
            hash_ids=True, host_budget_rows=budget)
        model = pt.Model(_widedeep_ctr(nn, jnp, table))
        model.prepare(optimizer=pt.optimizer.Adam(
            learning_rate=5e-3, parameters=model.network),
            loss=nn.BCEWithLogitsLoss())
        parallel.distributed_model(model, mesh=mesh)
        return model, table

    ids, dense, y = _ctr_data(steps)
    model, table = build()
    losses = [float(model.train_batch([ids[i], dense[i]], [y[i]])["loss"])
              for i in range(resume_at)]
    jax.effects_barrier()
    rows_live = table.touched_rows_local

    # generation restart: per-process shard snapshot + model state
    table.snapshot_shard(os.path.join(result_dir, "table"))
    state_path = os.path.join(result_dir, f"model{rank}.npz")
    model._sync_state_out()  # reclaim donated params before reading
    pt.save(model.network.state_dict(), state_path)
    parallel.barrier()

    model2, table2 = build()
    model2.network.set_state_dict(pt.load(state_path))
    table2.restore_shards(
        [os.path.join(result_dir, f"table.shard{r}of2.npz")
         for r in range(2)])
    assert table2.touched_rows_local == rows_live, \
        (table2.touched_rows_local, rows_live)
    losses += [float(model2.train_batch([ids[i], dense[i]],
                                        [y[i]])["loss"])
               for i in range(resume_at, steps)]
    jax.effects_barrier()

    with open(os.path.join(result_dir, f"rank{rank}.json"), "w") as f:
        json.dump({"losses": losses, "rows_step8": rows_live,
                   "rows_final": table2.touched_rows_local}, f)


def sharded_embedding_baseline(steps: int = 12, resume_at: int = 8):
    """Single-process UNSHARDED reference doing the same restart dance
    (state_dict + table snapshot/restore), so parity isolates the
    sharding machinery — run in the parent process."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import tempfile

    import paddle_tpu as pt
    from paddle_tpu import nn

    def build(table):
        model = pt.Model(_widedeep_ctr(nn, jnp, table))
        model.prepare(optimizer=pt.optimizer.Adam(
            learning_rate=5e-3, parameters=model.network),
            loss=nn.BCEWithLogitsLoss())
        return model

    ids, dense, y = _ctr_data(steps)
    pt.seed(0)
    table = nn.HostOffloadedEmbedding(1_000_000, 8, optimizer="adagrad",
                                      learning_rate=0.1, hash_ids=True)
    model = build(table)
    losses = [float(model.train_batch([ids[i], dense[i]], [y[i]])["loss"])
              for i in range(resume_at)]
    jax.effects_barrier()
    with tempfile.TemporaryDirectory() as td:
        table.snapshot(os.path.join(td, "t.npz"))
        model._sync_state_out()  # reclaim donated params before reading
        pt.save(model.network.state_dict(), os.path.join(td, "m.npz"))
        pt.seed(0)
        table2 = nn.HostOffloadedEmbedding(
            1_000_000, 8, optimizer="adagrad", learning_rate=0.1,
            hash_ids=True)
        model2 = build(table2)
        model2.network.set_state_dict(pt.load(os.path.join(td, "m.npz")))
        table2.restore(os.path.join(td, "t.npz"))
        losses += [float(model2.train_batch([ids[i], dense[i]],
                                            [y[i]])["loss"])
                   for i in range(resume_at, steps)]
        jax.effects_barrier()
        total_rows = table2.touched_rows
    return losses, total_rows


def baseline_losses(steps: int = 10):
    """Single-process dense reference for the DP parity check — run in
    the PARENT process (already CPU-pinned by conftest)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, 8, 8).astype(np.float32)
    ys = rng.randint(0, 4, (steps, 8, 1))
    return [float(model.train_batch([xs[i]], [ys[i]])["loss"])
            for i in range(steps)]


def _tiny_gpt(pt):
    """Shared tiny GPT for the cross-process tp/fsdp parity workers —
    small enough for a 1-core-per-process compile, big enough that the
    rule table shards vocab/mlp/heads over tp and everything over fsdp."""
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=16,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLM(cfg)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(
        learning_rate=1e-3, parameters=net, weight_decay=0.01),
        loss=GPTPretrainingCriterion())
    return model


def _gpt_data(steps):
    import numpy as np
    rng = np.random.RandomState(0)
    return rng.randint(0, 64, (steps, 4, 16))


def model_axis_train(result_dir: str, axis: str, steps: int = 6):
    """Rank body for cross-process MODEL-parallel parity (VERDICT r3
    weak #6: the multi-process tests only ever exercised dp): a tiny
    GPT trained on a 2-process tp=2 or fsdp=2 mesh. tp shards the
    vocab/mlp/heads weight dims across the two OS processes (every
    block's activation all-reduce crosses the process boundary);
    fsdp=2 gathers params at use and reduce-scatters grads. EVERY rank
    writes its losses and its local shard shape of the first MLP
    weight, so the parent can assert from both sides that the weights
    really lived split across processes."""
    jax = _pin_cpu_single_device()
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import parallel

    parallel.init_parallel_env()
    rank = jax.process_index()
    assert jax.process_count() == 2
    mesh = parallel.init_mesh(**{axis: 2})

    model = _tiny_gpt(pt)
    parallel.distributed_model(model, mesh=mesh)
    ids = _gpt_data(steps)
    losses = [float(model.train_batch([ids[i]], [ids[i]])["loss"])
              for i in range(steps)]

    # find the first transformer-block MLP weight and record the
    # LOCAL shard shape this process holds
    model._sync_state_in()
    shard_shape = None
    full_shape = None
    for name in sorted(model._params):
        p = model._params[name]
        if "mlp" in name and name.endswith("weight") and p.ndim == 2:
            full_shape = tuple(int(d) for d in p.shape)
            shard_shape = tuple(
                int(d) for d in p.addressable_shards[0].data.shape)
            break

    with open(os.path.join(result_dir, f"rank{rank}.json"), "w") as f:
        json.dump({"losses": losses, "shard_shape": shard_shape,
                   "full_shape": full_shape}, f)


import functools


@functools.lru_cache(maxsize=None)
def model_axis_baseline(steps: int = 6):
    """Single-process dense reference for the tp/fsdp parity checks —
    run in the parent process."""
    import paddle_tpu as pt

    model = _tiny_gpt(pt)
    ids = _gpt_data(steps)
    return [float(model.train_batch([ids[i]], [ids[i]])["loss"])
            for i in range(steps)]
