"""MoE layer, recompute, gradient merge (SURVEY.md §2.3 EP/recompute/
gradient-merge rows; ref tests: unittests/test_moe_api.py,
test_recompute.py, test_gradient_merge pass tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.nn.layer import functional_call, split_state
from paddle_tpu.nn.layers.moe import MoELayer, collect_aux_losses

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _x(b=2, s=16, d=8, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(b, s, d), jnp.float32)


@pytest.mark.parametrize("gate", ["naive", "gshard", "switch"])
def test_moe_forward_shape(gate):
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate=gate)
    out = moe(_x())
    assert out.shape == (2, 16, 8)
    assert np.all(np.isfinite(np.asarray(out)))


def test_moe_dispatch_is_capacity_bounded():
    """With generous capacity every token routes; combine weights per
    token sum to the top-k gate mass (<= 1, > 0)."""
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="gshard",
                   capacity_factor=4.0)
    x = _x()
    out, aux = moe.forward_with_aux(x)
    assert float(aux) > 0.0
    # zero input rows produce zero output (routing is linear in combine)
    x0 = jnp.zeros_like(x)
    out0, _ = moe.forward_with_aux(x0)
    # softmax gate on zeros still routes but expert(0 + b) may be nonzero
    # (biases); just check shape/finiteness here
    assert np.all(np.isfinite(np.asarray(out0)))


def test_moe_capacity_drops_tokens():
    """Tiny capacity must not crash; dropped tokens produce zero output
    rows (GShard static-capacity semantics)."""
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=2, gate="switch",
                   capacity_factor=0.01)
    moe.eval()
    out = moe(_x())
    assert out.shape == (2, 16, 8)


def test_moe_grads_flow_to_all_parts():
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="gshard",
                   capacity_factor=2.0)
    params, buffers = split_state(moe)

    def loss_fn(p):
        with collect_aux_losses() as get_aux:
            out, _ = functional_call(moe, p, buffers, _x())
        return (out ** 2).mean() + get_aux()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    for name in ["gate.weight", "experts.w_in", "experts.w_out"]:
        g = grads[name]
        assert float(jnp.abs(g).sum()) > 0, name


def test_moe_ep_sharded_runs_on_mesh():
    """Experts sharded over the ep axis: same numbers as unsharded."""
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="naive",
                   capacity_factor=4.0)
    moe.eval()
    x = _x()
    ref = np.asarray(moe(x))
    mesh = parallel.init_mesh(dp=2, ep=4)
    try:
        params, buffers = split_state(moe)
        meta = moe.param_meta()
        sharded = parallel.shard_params(params, meta, mesh,
                                        parallel.LogicalRules())

        @jax.jit
        def fwd(p, x):
            out, _ = functional_call(moe, p, buffers, x, training=False)
            return out

        out = np.asarray(fwd(sharded, x))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_recompute_matches_plain_grads():
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 8))
    params, buffers = split_state(net)
    x = _x(4, 1, 8).reshape(4, 8)

    def loss_plain(p):
        out, _ = functional_call(net, p, buffers, x)
        return (out ** 2).mean()

    def loss_rc(p):
        def fwd(p):
            out, _ = functional_call(net, p, buffers, x)
            return out
        return (parallel.recompute(fwd, p) ** 2).mean()

    g1 = jax.grad(loss_plain)(params)
    g2 = jax.grad(loss_rc)(params)
    for k in g1:
        np.testing.assert_allclose(g1[k], g2[k], atol=1e-6)


def test_recompute_sequential_forward():
    net = parallel.RecomputeSequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8), segments=2)
    x = _x(2, 1, 8).reshape(2, 8)
    out = net(x)
    assert out.shape == (2, 8)
    # grads flow
    params, buffers = split_state(net)

    def loss(p):
        o, _ = functional_call(net, p, buffers, x)
        return (o ** 2).sum()
    g = jax.grad(loss)(params)
    assert all(float(jnp.abs(v).sum()) > 0 for v in g.values())


def test_gradient_merge_steps_every_k():
    net = nn.Linear(4, 4)
    params, _ = split_state(net)
    inner = pt.optimizer.SGD(learning_rate=1.0, parameters=net)
    opt = parallel.GradientMerge(inner, k_steps=2, avg=True)
    state = opt.init_state(params)
    g = jax.tree_util.tree_map(jnp.ones_like, params)

    p1, state = opt.apply_gradients(params, g, state, 0)
    # first microbatch: accumulate only — params unchanged
    for k in params:
        np.testing.assert_allclose(p1[k], params[k])
    p2, state = opt.apply_gradients(p1, g, state, 1)
    # second: apply averaged grad once → params -= lr * mean(g) = 1.0
    for k in params:
        np.testing.assert_allclose(p2[k], params[k] - 1.0, atol=1e-6)
    assert int(state["count"]) == 0
    # and the accumulator was reset
    assert all(float(jnp.abs(v).sum()) == 0.0
               for v in jax.tree_util.tree_leaves(state["acc"]))


def test_gradient_merge_inside_jit():
    net = nn.Linear(4, 4)
    params, _ = split_state(net)
    inner = pt.optimizer.SGD(learning_rate=0.5, parameters=net)
    opt = parallel.GradientMerge(inner, k_steps=2)
    state = opt.init_state(params)

    @jax.jit
    def step(params, state, i):
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        return opt.apply_gradients(params, g, state, i)

    p, s = params, state
    for i in range(4):
        p, s = step(p, s, i)
    # 4 microbatches / k=2 → exactly 2 real steps of lr*mean = 0.5
    for k in params:
        np.testing.assert_allclose(p[k], params[k] - 1.0, atol=1e-6)
