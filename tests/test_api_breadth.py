"""Distribution, autograd/PyLayer, regularizer, device, static facade,
launch CLI (ref: unittests test_distribution*, test_pylayer_op,
test_regularizer, launch tests — SURVEY.md §2.2)."""

import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as pt
from paddle_tpu import autograd, distribution as D, regularizer


# -- distributions ---------------------------------------------------------

def test_normal_sample_logprob_entropy():
    d = D.Normal(loc=1.0, scale=2.0)
    s = d.sample([20000])
    assert abs(float(s.mean()) - 1.0) < 0.1
    assert abs(float(s.std()) - 2.0) < 0.1
    v = jnp.asarray([0.0, 1.0, 3.0])
    np.testing.assert_allclose(d.log_prob(v),
                               sps.norm(1.0, 2.0).logpdf(np.asarray(v)),
                               atol=1e-5)
    np.testing.assert_allclose(float(d.entropy()),
                               sps.norm(1.0, 2.0).entropy(), atol=1e-5)


def test_normal_kl():
    p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
    ref = (np.log(2.0) + (1 + 1) / (2 * 4) - 0.5)
    np.testing.assert_allclose(float(D.kl_divergence(p, q)), ref,
                               atol=1e-6)


def test_uniform_and_kl_cross_family():
    u = D.Uniform(0.0, 2.0)
    np.testing.assert_allclose(float(u.mean), 1.0)
    lp = u.log_prob(jnp.asarray([1.0, 3.0]))
    assert np.isneginf(np.asarray(lp)[1])
    kl = D.kl_divergence(u, D.Normal(0.0, 1.0))
    assert np.isfinite(float(kl)) and float(kl) > 0


def test_categorical():
    d = D.Categorical(probs=jnp.asarray([0.1, 0.2, 0.7]))
    s = np.asarray(d.sample([5000]))
    freq = np.bincount(s, minlength=3) / 5000
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.03)
    np.testing.assert_allclose(float(d.log_prob(2)), np.log(0.7),
                               atol=1e-5)
    ref_ent = -(0.1 * np.log(0.1) + 0.2 * np.log(0.2) +
                0.7 * np.log(0.7))
    np.testing.assert_allclose(float(d.entropy()), ref_ent, atol=1e-5)


@pytest.mark.parametrize("dist,mean", [
    (lambda: D.Bernoulli(0.3), 0.3),
    (lambda: D.Beta(2.0, 3.0), 0.4),
    (lambda: D.Laplace(0.5, 1.0), 0.5),
])
def test_moments_match(dist, mean):
    d = dist()
    s = np.asarray(d.sample([20000]))
    assert abs(s.mean() - mean) < 0.05


def test_dirichlet_multinomial_gumbel():
    di = D.Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
    s = np.asarray(di.sample([1000]))
    np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.05)
    m = D.Multinomial(10, jnp.asarray([0.5, 0.5]))
    sm = np.asarray(m.sample([500]))
    assert (sm.sum(-1) == 10).all()
    g = D.Gumbel(0.0, 1.0)
    assert abs(float(np.asarray(g.sample([20000])).mean()) -
               0.5772) < 0.05


def test_normal_rsample_pathwise_grad():
    def loss(mu):
        pt.seed(0)
        return (D.Normal(mu, 1.0).rsample([100]) ** 2).mean()
    g = jax.grad(loss)(jnp.asarray(2.0))
    assert abs(float(g) - 4.0) < 0.5  # d/dmu E[(mu+eps)^2] = 2mu


# -- autograd / PyLayer ----------------------------------------------------

def test_vjp_jvp():
    f = lambda x: (x ** 2).sum()
    x = jnp.asarray([1.0, 2.0])
    out, g = autograd.vjp(f, x)
    np.testing.assert_allclose(g, 2 * np.asarray(x))
    out, t = autograd.jvp(f, x, jnp.asarray([1.0, 0.0]))
    np.testing.assert_allclose(float(t), 2.0)


def test_jacobian_hessian():
    f = lambda x: jnp.stack([x[0] * x[1], x[0] ** 2])
    x = jnp.asarray([2.0, 3.0])
    J = autograd.Jacobian(f, x)
    np.testing.assert_allclose(J[:], [[3.0, 2.0], [4.0, 0.0]])
    H = autograd.Hessian(lambda x: (x ** 3).sum(), x)
    np.testing.assert_allclose(H[:], np.diag([12.0, 18.0]))


def test_pylayer_custom_grad():
    class ScaledTanh(autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = jnp.tanh(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, g):
            (y,) = ctx.saved_tensor()
            return g * 2.0 * (1 - y ** 2)  # deliberately 2x true grad

    x = jnp.asarray([0.3, -0.7])
    out = ScaledTanh.apply(x)
    np.testing.assert_allclose(out, np.tanh(np.asarray(x)), atol=1e-6)
    g = jax.grad(lambda x: ScaledTanh.apply(x).sum())(x)
    np.testing.assert_allclose(g, 2.0 * (1 - np.tanh(np.asarray(x)) ** 2),
                               atol=1e-6)
    # works under jit too
    g2 = jax.jit(jax.grad(lambda x: ScaledTanh.apply(x).sum()))(x)
    np.testing.assert_allclose(g, g2, atol=1e-6)


# -- regularizer / device / static ----------------------------------------

def test_regularizers():
    params = {"w": jnp.asarray([1.0, -2.0])}
    grads = {"w": jnp.zeros(2)}
    l1 = regularizer.L1Decay(0.1)
    np.testing.assert_allclose(float(l1.penalty(params)), 0.3, atol=1e-6)
    g = l1.grad_transform(grads, params)
    np.testing.assert_allclose(g["w"], [0.1, -0.1], atol=1e-6)
    l2 = regularizer.L2Decay(0.1)
    np.testing.assert_allclose(float(l2.penalty(params)), 0.25,
                               atol=1e-6)
    g = l2.grad_transform(grads, params)
    np.testing.assert_allclose(g["w"], [0.1, -0.2], atol=1e-6)


def test_device_api():
    from paddle_tpu import device
    assert device.device_count() >= 1
    assert ":" in device.get_device()
    device.synchronize()
    e1, e2 = device.Event(), device.Event()
    e1.record()
    (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    e2.record()
    assert e1.elapsed_time(e2) >= 0
    with pytest.raises(ValueError):
        device.set_device("rocm:0")


def test_static_facade_roundtrip(tmp_path):
    from paddle_tpu import nn, static
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    x = jnp.asarray(np.random.RandomState(0).randn(3, 4), jnp.float32)
    ref = np.asarray(net(x))
    path = str(tmp_path / "inf")
    static.save_inference_model(path, net,
                                input_spec=[static.InputSpec([3, 4])])
    exe = static.Executor()
    prog = static.load_inference_model(path, exe)
    out = exe.run(prog, feed=[x])
    np.testing.assert_allclose(np.asarray(out[0]), ref, atol=1e-5)


# -- launcher --------------------------------------------------------------

def test_launch_spawns_ranks(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        master = os.environ["PADDLE_MASTER"]
        open(os.path.join(sys.argv[1], f"rank{rank}.txt"), "w").write(
            f"{rank}/{n}@{master}")
    """))
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    from paddle_tpu.distributed.launch import launch
    rc = launch(3, str(script), [str(out_dir)])
    assert rc == 0
    files = sorted(os.listdir(out_dir))
    assert files == ["rank0.txt", "rank1.txt", "rank2.txt"]
    body = open(out_dir / "rank2.txt").read()
    assert body.startswith("2/3@")


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import os, sys; "
                      "sys.exit(3 if os.environ['PADDLE_TRAINER_ID'] "
                      "== '1' else 0)")
    from paddle_tpu.distributed.launch import launch
    rc = launch(2, str(script), [])
    assert rc == 3
