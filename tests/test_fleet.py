"""fleet facade (ref: unittests test_fleet_base.py — init/worker
queries/distributed_model shapes)."""

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.distributed import fleet


def test_init_and_worker_queries():
    fleet.init(is_collective=True)
    assert fleet.worker_num() >= 1
    assert fleet.worker_index() == 0
    assert fleet.is_first_worker()
    assert fleet.is_worker()


def test_distributed_model_layer_and_hapi():
    fleet.init(is_collective=True)
    try:
        net = nn.Linear(4, 2)
        wrapped = fleet.distributed_model(net)
        assert isinstance(wrapped, parallel.DataParallel)
        out = wrapped(jnp.ones((8, 4)))
        assert out.shape == (8, 2)

        pt.seed(0)
        net2 = nn.Linear(4, 2)
        model = pt.Model(net2)
        model.prepare(
            optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                       parameters=net2),
            loss=nn.MSELoss())
        got = fleet.distributed_model(model)
        assert got is model and model._mesh is not None
        logs = model.train_batch([np.ones((8, 4), np.float32)],
                                 [np.zeros((8, 2), np.float32)])
        assert np.isfinite(logs["loss"])
    finally:
        parallel.set_mesh(None)


def test_distributed_optimizer_records_strategy():
    strat = parallel.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strat)
    net = nn.Linear(2, 2)
    opt = fleet.distributed_optimizer(
        pt.optimizer.SGD(learning_rate=0.1, parameters=net))
    assert opt._fleet_strategy is strat
    assert fleet.get_strategy() is strat


def test_ps_lifecycle_guides_to_collective():
    fleet.init(is_collective=True)
    with pytest.raises(NotImplementedError, match="SparseEmbedding"):
        fleet.init_worker()
    with pytest.raises(NotImplementedError, match="collective"):
        fleet.run_server()


def test_role_makers():
    rm = fleet.PaddleCloudRoleMaker(is_collective=True)
    assert rm.current_id == 0 and rm.worker_num_ >= 1
    rm2 = fleet.UserDefinedRoleMaker(current_id=1, worker_num=4)
    assert rm2.current_id == 1 and rm2.worker_num_ == 4


def test_local_fs_roundtrip(tmp_path):
    """distributed.fs.LocalFS — the functional half of the reference's
    fleet/utils/fs.py; HDFS/AFS are declined with decision records."""
    import pytest

    from paddle_tpu.distributed import fs

    lfs = fs.LocalFS()
    d = str(tmp_path / "a")
    lfs.mkdirs(d)
    assert lfs.is_dir(d) and lfs.is_exist(d)
    f = str(tmp_path / "a" / "x.txt")
    lfs.touch(f)
    assert lfs.is_file(f)
    with open(f, "w") as fh:
        fh.write("hello")
    assert lfs.cat(f) == "hello"
    dirs, files = lfs.ls_dir(str(tmp_path))
    assert dirs == ["a"] and files == []
    lfs.mv(f, str(tmp_path / "y.txt"))
    assert lfs.is_file(str(tmp_path / "y.txt"))
    with pytest.raises(fs.FSFileNotExistsError):
        lfs.mv(str(tmp_path / "missing"), str(tmp_path / "z"))
    lfs.delete(d)
    assert not lfs.is_exist(d)
    assert not lfs.need_upload_download()
    with pytest.raises(NotImplementedError, match="orbax"):
        fs.HDFSClient()
    # fleet.utils namespace parity
    from paddle_tpu.distributed import fleet
    assert fleet.utils.LocalFS is fs.LocalFS
