"""Self-healing training (ISSUE 9): the on-device numeric guard
(reliability/guard.py) + its Model.fit integration.

Pinned contracts:
- device-side mask parity vs a host recompute (the verdict the jitted
  step computed matches what numpy says about the same loss/grads);
- skip determinism: a run that skips poisoned step s is BIT-IDENTICAL
  (final params hex) to a clean run over the stream with batch s
  removed, at steps_per_loop ∈ {1, 4};
- rollback fast-forward cursor math + escalating stride;
- budget-exhausted escalation to abort;
- fault-site preview == live schedules for data.poison/grad.nonfinite;
- guard-disabled zero overhead: the compiled program carries no guard
  ops (lowered HLO text) and the train path buffers nothing;
- the deferred check_nan_inf drain (K=1 no per-step sync, K>1 exact
  in-slab step index);
- amp/debugging reentrant tensor-checker stack + context manager;
- GradScaler skips feeding the shared guard metrics.
"""

import hashlib
import os

import jax
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.core import flags
from paddle_tpu.io import TensorDataset, stack_batches
from paddle_tpu.reliability import faults, guard


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _build(policy=None, lr=1e-2, seed=0):
    pt.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=lr, parameters=net),
        loss=nn.CrossEntropyLoss(), numeric_guard=policy)
    return model


def _batches(n=8, batch=4, seed=5):
    rs = np.random.RandomState(seed)
    return [(rs.randn(batch, 8).astype(np.float32),
             rs.randint(0, 4, (batch, 1)))
            for _ in range(n)]


def _params_hex(model) -> str:
    model.sync_weights()
    h = hashlib.blake2b(digest_size=16)
    for name, v in sorted(model.network.state_dict().items()):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
    return h.hexdigest()


def _run_k1(model, batches, skip_idx=()):
    for i, (x, y) in enumerate(batches):
        if i in skip_idx:
            continue
        model.train_batch([x], [y])
    model.drain_metrics()
    return model


def _run_k4(model, batches):
    for lo in range(0, len(batches), 4):
        slab = stack_batches(batches[lo:lo + 4])
        model.train_loop_batch([slab[0]], [slab[1]])
    model.drain_metrics()
    return model


# ---------------------------------------------------------------------------
# device-side verdicts
# ---------------------------------------------------------------------------

def test_device_mask_parity_vs_host_recompute():
    """The device verdict/grad-norm must match a host recompute of the
    same quantities, and a tripped step must leave params bit-equal to
    their pre-step values (exact no-op)."""
    pol = guard.GuardPolicy(on_nonfinite="skip", budget=8)
    model = _build(pol)
    batches = _batches(3)
    model.train_batch([batches[0][0]], [batches[0][1]])
    model.sync_weights()
    before = {k: np.array(v) for k, v in
              sorted(model.network.state_dict().items())}
    # poison the next batch end-to-end
    faults.enable(seed=1)
    faults.inject("data.poison", nth=(1,))
    model.train_batch([batches[1][0]], [batches[1][1]])
    faults.disable()
    verdicts, gnorms, losses, step0, k = model._guard_pending[-1]
    v = int(np.asarray(verdicts))
    assert v == 1  # nonfinite, exactly what numpy says about the loss
    assert not np.isfinite(np.asarray(losses)).all()
    assert not np.isfinite(np.asarray(gnorms))
    model.drain_metrics()
    model.sync_weights()
    after = {k2: np.array(v2) for k2, v2 in
             sorted(model.network.state_dict().items())}
    for name in before:
        np.testing.assert_array_equal(before[name], after[name])
    # healthy step: verdict 0 and the device grad norm matches a host
    # recompute through the SAME jitted step math
    model2 = _build(guard.GuardPolicy(on_nonfinite="skip"))
    model2.train_batch([batches[2][0]], [batches[2][1]])
    verdicts, gnorms, _losses, _s, _k = model2._guard_pending[-1]
    assert int(np.asarray(verdicts)) == 0
    assert np.isfinite(float(np.asarray(gnorms)))
    assert float(np.asarray(gnorms)) > 0.0


def test_spike_detection_and_skip():
    """A loss far above the EMA trips verdict 2 once warmed up; with
    on_spike="skip" the update is masked, with the default "allow" it
    is applied and only recorded."""
    def build_linear(policy):
        # no Tanh: a saturating activation would clamp the blowup the
        # spike detector is supposed to see
        pt.seed(0)
        net = nn.Linear(8, 4)
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.Adam(
            learning_rate=1e-2, parameters=net),
            loss=nn.CrossEntropyLoss(), numeric_guard=policy)
        return model

    pol = guard.GuardPolicy(on_spike="skip", spike_factor=3.0,
                            warmup_steps=3, budget=8)
    model = build_linear(pol)
    batches = _batches(6)
    for x, y in batches[:5]:
        model.train_batch([x], [y])
    model.drain_metrics()
    assert pol.n_trips == 0
    before = _params_hex(model)
    # a wildly out-of-distribution batch: loss explodes vs the EMA
    model.train_batch([batches[5][0] * 1e4], [batches[5][1]])
    model.drain_metrics()
    assert pol.n_trips == 1 and pol.last_trip_kind == "spike"
    assert pol.n_skipped == 1
    assert _params_hex(model) == before  # masked on device

    allow = guard.GuardPolicy(on_spike="allow", spike_factor=3.0,
                              warmup_steps=3)
    m2 = build_linear(allow)
    for x, y in batches[:5]:
        m2.train_batch([x], [y])
    before = _params_hex(m2)
    m2.train_batch([batches[5][0] * 1e4], [batches[5][1]])
    m2.drain_metrics()
    assert allow.n_trips == 1 and allow.n_allowed_spikes == 1
    assert _params_hex(m2) != before  # allow: the update applied


def test_spike_threshold_sign_safe_for_negative_losses():
    """A negative-loss objective (log-likelihood style) must not trip
    on every normal step: the threshold scales with |ema| above the
    baseline, not ema * factor (which flips below the baseline when
    the EMA is negative)."""
    import jax.numpy as jnp
    state = {"ema": jnp.float32(-10.0), "n": jnp.int32(100)}
    grads = {"w": jnp.ones((2,))}
    v, _ = guard.inspect(jnp.float32(-10.0), grads, state,
                         spike_factor=4.0, spike_margin=0.0,
                         warmup_steps=16)
    assert int(v) == 0          # a normal step is not a spike
    v, _ = guard.inspect(jnp.float32(25.0), grads, state,
                         spike_factor=4.0, spike_margin=0.0,
                         warmup_steps=16)
    assert int(v) == 2          # blowup past -10 + 3*10 = 20 trips
    # positive-EMA behavior unchanged: threshold == ema * factor
    state = {"ema": jnp.float32(2.0), "n": jnp.int32(100)}
    v, _ = guard.inspect(jnp.float32(7.9), grads, state,
                         spike_factor=4.0, spike_margin=0.0,
                         warmup_steps=16)
    assert int(v) == 0
    v, _ = guard.inspect(jnp.float32(8.1), grads, state,
                         spike_factor=4.0, spike_margin=0.0,
                         warmup_steps=16)
    assert int(v) == 2


# ---------------------------------------------------------------------------
# skip determinism (the acceptance-pinned invariant)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["data.poison", "grad.nonfinite"])
def test_skip_bit_identical_to_stream_minus_batch_k1(site):
    batches = _batches(8)
    faults.enable(seed=11)
    faults.inject(site, nth=(3,))
    poisoned = _run_k1(_build(guard.GuardPolicy(on_nonfinite="skip")),
                       batches)
    assert poisoned._guard.n_skipped == 1
    faults.reset()
    clean = _run_k1(_build(guard.GuardPolicy(on_nonfinite="skip")),
                    batches, skip_idx=(2,))
    assert _params_hex(poisoned) == _params_hex(clean)


def test_skip_bit_identical_to_stream_minus_batch_k4():
    batches = _batches(8)
    faults.enable(seed=11)
    faults.inject("data.poison", nth=(3,))
    poisoned = _run_k4(_build(guard.GuardPolicy(on_nonfinite="skip")),
                       batches)
    assert poisoned._guard.n_skipped == 1
    faults.reset()
    clean = _run_k1(_build(guard.GuardPolicy(on_nonfinite="skip")),
                    batches, skip_idx=(2,))
    assert _params_hex(poisoned) == _params_hex(clean)
    # K=4 poisoned ≡ K=1 poisoned too (scan/per-step parity holds
    # through the masked update)
    faults.enable(seed=11)
    faults.inject("data.poison", nth=(3,))
    p1 = _run_k1(_build(guard.GuardPolicy(on_nonfinite="skip")),
                 batches)
    assert _params_hex(p1) == _params_hex(poisoned)


def test_guard_armed_single_step_slab():
    """A K=1 slab through the guarded scan path: the poison input must
    keep its leading axis (a scalar crashes lax.scan), and the guard
    verdict/skip machinery works at k=1."""
    batches = _batches(2)
    faults.enable(seed=13)
    faults.inject("grad.nonfinite", nth=(1,))
    m = _build(guard.GuardPolicy(on_nonfinite="skip"))
    slab = stack_batches(batches[:1])
    logs = m.train_loop_batch([slab[0]], [slab[1]])
    m.drain_metrics()
    assert len(logs) == 1
    assert m._guard.n_skipped == 1
    slab2 = stack_batches(batches[1:2])
    m.train_loop_batch([slab2[0]], [slab2[1]])
    m.drain_metrics()
    assert m._guard.n_skipped == 1  # second slab healthy


@pytest.mark.parametrize("k", [1, 4])
def test_skip_drops_tripped_metric_rows(k):
    """A skipped step's forward ran on the poisoned batch (NaN
    logits): its metric row must be DROPPED at the drain, so the
    accumulators match the clean run minus that batch — like the
    params do."""
    from paddle_tpu.metric import Accuracy

    def build_acc(policy):
        pt.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                            nn.Linear(16, 4))
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-2,
                                              parameters=net),
                  loss=nn.CrossEntropyLoss(), metrics=[Accuracy()],
                  numeric_guard=policy)
        return m

    batches = _batches(8)
    faults.enable(seed=11)
    faults.inject("data.poison", nth=(3,))
    m = build_acc(guard.GuardPolicy(on_nonfinite="skip"))
    if k == 1:
        _run_k1(m, batches)
    else:
        _run_k4(m, batches)
    assert m._guard.n_skipped == 1
    poisoned_acc = m._metrics[0].accumulate()
    faults.reset()
    clean = _run_k1(build_acc(guard.GuardPolicy(on_nonfinite="skip")),
                    batches, skip_idx=(2,))
    assert np.isfinite(poisoned_acc)
    assert poisoned_acc == clean._metrics[0].accumulate()


def test_mid_slab_poison_does_not_corrupt_rest_of_slab():
    """The old failure mode: one poisoned batch inside a K-slab
    corrupted params for the K-1 steps after it. The masked carry
    makes the post-poison steps match the clean-minus run exactly —
    asserted by the K=4 parity above; here we additionally pin that
    the healthy steps' losses in the SAME slab are bit-equal."""
    batches = _batches(4)
    faults.enable(seed=11)
    faults.inject("data.poison", nth=(2,))
    m = _build(guard.GuardPolicy(on_nonfinite="skip"))
    slab = stack_batches(batches)
    logs = m.train_loop_batch([slab[0]], [slab[1]])
    m.drain_metrics()
    poisoned_losses = [float(lg["loss"]) for lg in logs]
    faults.reset()
    m2 = _build(guard.GuardPolicy(on_nonfinite="skip"))
    clean_losses = []
    for i, (x, y) in enumerate(batches):
        if i == 1:
            continue
        clean_losses.append(float(np.asarray(
            m2.train_batch([x], [y])["loss"])))
    assert not np.isfinite(poisoned_losses[1])
    assert [poisoned_losses[0], poisoned_losses[2],
            poisoned_losses[3]] == clean_losses


# ---------------------------------------------------------------------------
# policy engine: budget, rollback math, escalation
# ---------------------------------------------------------------------------

def test_budget_exhausted_escalates_to_abort():
    pol = guard.GuardPolicy(on_nonfinite="skip", budget=2)
    model = _build(pol)
    batches = _batches(6)
    faults.enable(seed=3)
    faults.inject("data.poison", nth=(1, 2, 3))
    with pytest.raises(guard.GuardAbort, match="skip budget exhausted"):
        _run_k1(model, batches)
    assert pol.n_skipped == 3  # the third skip crossed budget=2


def test_rollback_stride_escalates_and_budget_aborts():
    """process() doubles the fast-forward stride on each repeat trip
    and aborts past max_rollbacks."""
    pol = guard.GuardPolicy(on_nonfinite="rollback", max_rollbacks=3,
                            rollback_stride=1)
    strides = []
    for step in (5, 9, 13):
        with pytest.raises(guard.GuardRollback) as ei:
            pol.process(np.asarray([1]), np.asarray([np.nan]),
                        np.asarray([np.nan]), step)
        strides.append(ei.value.stride)
        assert ei.value.step == step
    assert strides == [1, 2, 4]
    with pytest.raises(guard.GuardAbort,
                       match="rollback budget exhausted"):
        pol.process(np.asarray([1]), np.asarray([np.nan]),
                    np.asarray([np.nan]), 17)


def test_rollback_restores_verified_step_and_fast_forwards(tmp_path):
    """End-to-end through fit: the trip restores the newest verified
    checkpoint and the cursor jumps past the poisoned batch; training
    completes and later checkpoints commit."""
    from paddle_tpu.io.checkpoint import CheckpointManager
    pol = guard.GuardPolicy(on_nonfinite="rollback", max_rollbacks=3)
    model = _build(pol)
    rs = np.random.RandomState(3)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(0, 4, (32, 1))
    faults.enable(seed=7)
    faults.inject("data.poison", nth=(6,))
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=2,
              shuffle=False, verbose=0,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_freq=2,
              keep_checkpoints=4)
    assert pol.n_rollbacks == 1
    # trip at step 5 (6th call) restored step 4, discarded step 5's
    # window and skipped the poisoned batch: 16 batches - 1 discarded
    # - 1 skipped = 14 optimizer steps
    assert model._step_count == 14
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
    steps = mgr.verified_steps()
    mgr.close()
    assert steps and steps[-1] == 14


def test_rollback_ignores_elastic_resume_env_pin(tmp_path,
                                                 monkeypatch):
    """An elastic respawn leaves $PADDLE_ELASTIC_RESUME_STEP set for
    the whole process. A mid-run guard rollback must NOT honor that
    stale pin (resume="auto" semantics) — it restores the newest
    verified step at or below the trip explicitly."""
    pol = guard.GuardPolicy(on_nonfinite="rollback", max_rollbacks=3)
    model = _build(pol)
    rs = np.random.RandomState(3)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(0, 4, (32, 1))
    faults.enable(seed=7)
    faults.inject("data.poison", nth=(6,))
    # pin the env at a VERIFIED but stale step (2): the old auto-path
    # rollback restored it and re-trained the 2->4 window
    monkeypatch.setenv("PADDLE_ELASTIC_RESUME_STEP", "2")
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=2,
              shuffle=False, verbose=0,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_freq=2,
              keep_checkpoints=8)
    assert pol.n_rollbacks == 1
    # identical to the un-pinned rollback test: restored step 4, not
    # the env's step 2 (which would land at 16 steps in epoch 1)
    assert model._step_count == 14


def test_rollback_without_checkpoint_dir_escalates():
    pol = guard.GuardPolicy(on_nonfinite="rollback")
    model = _build(pol)
    rs = np.random.RandomState(3)
    x = rs.randn(16, 8).astype(np.float32)
    y = rs.randint(0, 4, (16, 1))
    faults.enable(seed=7)
    faults.inject("data.poison", nth=(2,))
    with pytest.raises(guard.GuardAbort, match="no checkpoint_dir"):
        model.fit(TensorDataset([x, y]), batch_size=4, epochs=1,
                  shuffle=False, verbose=0)


def test_abort_message_carries_report_and_replay():
    pol = guard.GuardPolicy(on_nonfinite="abort")
    model = _build(pol)
    batches = _batches(2)
    faults.enable(seed=9)
    faults.inject("data.poison", nth=(2,))
    with pytest.raises(guard.GuardAbort) as ei:
        _run_k1(model, batches)
    msg = str(ei.value)
    assert "nonfinite at step 1" in msg
    assert "non-finite tensors" in msg
    assert "replay" in msg and "--seed 9" in msg


# ---------------------------------------------------------------------------
# fault sites: preview == live
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["data.poison", "grad.nonfinite"])
def test_fault_site_preview_matches_live(site):
    batches = _batches(8)
    faults.enable(seed=21)
    faults.inject(site, p=0.3, times=3)
    model = _build(guard.GuardPolicy(on_nonfinite="skip", budget=8))
    _run_k1(model, batches)
    n = faults.call_count(site)
    assert n == 8  # one check per optimizer step
    want = faults.preview(site, n)
    got = [c for s, c in faults.injected_log() if s == site]
    assert got == want
    assert model._guard.n_skipped == len(want)


def test_grad_nonfinite_preview_matches_live_k4():
    batches = _batches(8)
    faults.enable(seed=22)
    faults.inject("grad.nonfinite", nth=(2, 7))
    model = _build(guard.GuardPolicy(on_nonfinite="skip", budget=8))
    _run_k4(model, batches)
    assert faults.call_count("grad.nonfinite") == 8
    got = [c for s, c in faults.injected_log()
           if s == "grad.nonfinite"]
    assert got == faults.preview("grad.nonfinite", 8) == [2, 7]
    assert model._guard.n_skipped == 2


# ---------------------------------------------------------------------------
# disabled: zero overhead
# ---------------------------------------------------------------------------

def test_guard_disabled_compiles_no_guard_ops():
    """Guard off ⇒ the lowered program contains no finite-checks and
    the train path buffers nothing — the disabled cost is the one
    `self._guard is None` attribute check."""
    model = _build(None)
    x, y = _batches(1)[0]
    model.train_batch([x], [y])
    assert model._guard is None
    assert model._guard_pending == [] and model._nan_pending == []
    lowered = model._train_step_fn.lower(
        model._params, model._frozen, model._opt_state,
        model._buffers, model._step_count,
        jax.random.key(0), (x,), (y,)).as_text()
    assert "is_finite" not in lowered

    armed = _build(guard.GuardPolicy())
    armed.train_batch([x], [y])
    lowered = armed._train_step_fn.lower(
        armed._params, armed._frozen, armed._opt_state,
        dict(armed._buffers), armed._guard_state, armed._step_count,
        jax.random.key(0), (x,), (y,), np.float32(1.0)).as_text()
    assert "is_finite" in lowered


def test_numeric_guard_flag_arms_default_policy():
    flags.set_flags({"numeric_guard": True})
    try:
        model = _build(None)
        assert isinstance(model._guard, guard.GuardPolicy)
    finally:
        flags.set_flags({"numeric_guard": False})
    model = _build(None)
    assert model._guard is None


# ---------------------------------------------------------------------------
# deferred check_nan_inf (legacy flag, satellite)
# ---------------------------------------------------------------------------

def test_check_nan_inf_deferred_no_per_step_sync():
    """K=1: the flag buffers the device loss instead of np.isfinite
    per step; the raise lands at the drain boundary with the exact
    step index."""
    flags.set_flags({"check_nan_inf": True})
    try:
        model = _build(None)
        batches = _batches(3)
        faults.enable(seed=2)
        faults.inject("data.poison", nth=(2,))
        model.train_batch([batches[0][0]], [batches[0][1]])
        model.train_batch([batches[1][0]], [batches[1][1]])
        assert len(model._nan_pending) == 2  # buffered, not synced
        with pytest.raises(FloatingPointError, match="step 1"):
            model.drain_metrics()
        assert model._nan_pending == []
    finally:
        flags.set_flags({"check_nan_inf": False})


def test_check_nan_inf_reports_exact_in_slab_index():
    flags.set_flags({"check_nan_inf": True})
    try:
        model = _build(None)
        batches = _batches(4)
        faults.enable(seed=2)
        faults.inject("data.poison", nth=(3,))
        slab = stack_batches(batches)
        model.train_loop_batch([slab[0]], [slab[1]])
        with pytest.raises(FloatingPointError,
                           match=r"step 2 \(step 2 of a 4-step slab\)"):
            model.drain_metrics()
    finally:
        flags.set_flags({"check_nan_inf": False})


# ---------------------------------------------------------------------------
# amp/debugging: reentrant checker stack (satellite)
# ---------------------------------------------------------------------------

def test_tensor_checker_stack_is_reentrant():
    from paddle_tpu.amp import debugging
    assert not jax.config.jax_debug_nans
    debugging.enable_tensor_checker()
    assert jax.config.jax_debug_nans
    debugging.enable_tensor_checker()  # nested enable
    assert jax.config.jax_debug_nans
    debugging.disable_tensor_checker()
    # the old single-slot impl restored True's saved value here and
    # left debug-nans stuck ON after the outer disable
    assert jax.config.jax_debug_nans
    debugging.disable_tensor_checker()
    assert not jax.config.jax_debug_nans


def test_tensor_checker_context_manager():
    from paddle_tpu.amp import debugging
    with debugging.tensor_checker():
        assert jax.config.jax_debug_nans
        with debugging.tensor_checker():
            assert jax.config.jax_debug_nans
        assert jax.config.jax_debug_nans
    assert not jax.config.jax_debug_nans
    # a disabled config is a no-op scope
    cfg = debugging.TensorCheckerConfig(enable=False)
    with debugging.tensor_checker(cfg):
        assert not jax.config.jax_debug_nans


def test_tensor_checker_disabled_scope_stays_balanced():
    """An enable/disable pair with a DISABLED config nested inside an
    active scope must not pop the outer scope's saved value — every
    enable pushes, flipping only when enabled."""
    from paddle_tpu.amp import debugging
    cfg = debugging.TensorCheckerConfig(enable=False)
    debugging.enable_tensor_checker()
    assert jax.config.jax_debug_nans
    debugging.enable_tensor_checker(cfg)   # no-op scope, still pushes
    assert jax.config.jax_debug_nans
    debugging.disable_tensor_checker()
    assert jax.config.jax_debug_nans       # outer scope intact
    debugging.disable_tensor_checker()
    assert not jax.config.jax_debug_nans


# ---------------------------------------------------------------------------
# GradScaler observability (satellite)
# ---------------------------------------------------------------------------

def test_grad_scaler_feeds_guard_metrics():
    import jax.numpy as jnp
    from paddle_tpu import amp
    from paddle_tpu.observability import metrics as obs
    reg = obs.default_registry()

    def series(name, *labels):
        fam = reg.get(name)
        if fam is None:
            return 0.0
        child = fam.labels(*labels) if labels else fam
        return child.value

    pt.seed(0)
    net = nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net)
    scaler = amp.GradScaler(init_loss_scaling=8.0,
                            decr_every_n_nan_or_inf=1)
    inf0 = series("amp_found_inf_total")
    skip0 = series("guard_skipped_steps_total")
    trip0 = series("guard_trips_total", "scaler_inf", "skip")
    good = {"weight": jnp.ones((4, 2)), "bias": jnp.ones((2,))}
    scaler.step(opt, good)
    assert series("amp_found_inf_total") == inf0
    assert series("amp_loss_scale") == 8.0
    bad = {"weight": jnp.full((4, 2), jnp.nan), "bias": jnp.ones((2,))}
    scaler.step(opt, bad)
    assert series("amp_found_inf_total") == inf0 + 1
    assert series("guard_skipped_steps_total") == skip0 + 1
    assert series("guard_trips_total", "scaler_inf", "skip") == trip0 + 1
    assert series("amp_loss_scale") == 4.0  # halved on the inf step


# ---------------------------------------------------------------------------
# checkpoint/guard state plumbing
# ---------------------------------------------------------------------------

def test_guard_state_rides_checkpoint_tree(tmp_path):
    """The EMA carry checkpoints and restores — resume keeps the spike
    baseline instead of re-warming."""
    pol = guard.GuardPolicy(on_nonfinite="skip", warmup_steps=2)
    model = _build(pol)
    rs = np.random.RandomState(3)
    x = rs.randn(16, 8).astype(np.float32)
    y = rs.randint(0, 4, (16, 1))
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=1,
              shuffle=False, verbose=0,
              checkpoint_dir=str(tmp_path / "ck"), checkpoint_freq=2)
    ema = float(np.asarray(model._guard_state["ema"]))
    n = int(np.asarray(model._guard_state["n"]))
    assert n == 4 and np.isfinite(ema) and ema > 0.0
    fresh = _build(guard.GuardPolicy(on_nonfinite="skip"))
    fresh.fit(TensorDataset([x, y]), batch_size=4, epochs=1,
              shuffle=False, verbose=0,
              checkpoint_dir=str(tmp_path / "ck"), resume="auto")
    assert int(np.asarray(fresh._guard_state["n"])) >= n


def test_statusz_provider_reports_guard():
    from paddle_tpu.observability import server as dbgsrv
    pol = guard.GuardPolicy(on_nonfinite="skip")
    model = _build(pol)
    batches = _batches(2)
    faults.enable(seed=4)
    faults.inject("data.poison", nth=(1,))
    _run_k1(model, batches)
    name = f"train_model_{id(model):x}"
    status = dbgsrv._collect_status()[name]
    assert status["numeric_guard"]["trips"] == 1
    assert status["numeric_guard"]["skipped"] == 1
