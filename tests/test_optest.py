"""Systematic op sweep: every numeric op vs a NumPy reference + a
directional finite-difference gradient check.

The analog of the reference's per-op OpTest subclasses
(unittests/test_activation_op.py, test_elementwise_*_op.py,
test_reduce_op.py, ... — each calling check_output/check_grad,
op_test.py:309/:1892), collapsed into one declarative table driven by
paddle_tpu.testing."""

import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as pt
import paddle_tpu.tensor as T
from paddle_tpu.nn import functional as F
from paddle_tpu.testing import OpSpec, arr, run_spec

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

S = (3, 4)          # default shape
POS = dict(low=0.1, high=2.0)      # positive domain (log, sqrt, ...)
SAFE = dict(low=-0.9, high=0.9)    # inside (-1, 1) (asin, atanh, ...)
OFF = dict(low=0.15, high=1.0)     # away from piecewise kinks at 0


def _np_gelu(x):
    return 0.5 * x * (1 + sps.erf(x / np.sqrt(2)))


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _np_logsumexp(x, axis=None):
    return sps.logsumexp(x, axis=axis)


def _np_layer_norm(x, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    return (x - m) / np.sqrt(v + eps)


def _np_xent(logits, labels):
    ls = logits - sps.logsumexp(logits, axis=-1, keepdims=True)
    return -ls[np.arange(len(labels)), labels].mean()


_X = arr(S, seed=0)
_Y = arr(S, seed=1)
_XP = arr(S, seed=2, **POS)
_YP = arr(S, seed=3, **POS)
_XS = arr(S, seed=4, **SAFE)
_XO = arr(S, seed=5, **OFF)
_M1 = arr((3, 5), seed=6)
_M2 = arr((5, 4), seed=7)
_V1 = arr((6,), seed=8)
_V2 = arr((6,), seed=9)
_LG = arr((6, 5), seed=10)
_LB = np.array([0, 2, 4, 1, 3, 2])

SPECS = [
    # -- elementwise unary (test_activation_op.py family) ---------------
    OpSpec("abs", T.abs, np.abs, (_XO,)),
    OpSpec("exp", T.exp, np.exp, (_X,)),
    OpSpec("expm1", T.expm1, np.expm1, (_X,)),
    OpSpec("log", T.log, np.log, (_XP,)),
    OpSpec("log2", T.log2, np.log2, (_XP,)),
    OpSpec("log10", T.log10, np.log10, (_XP,)),
    OpSpec("log1p", T.log1p, np.log1p, (_XP,)),
    OpSpec("sqrt", T.sqrt, np.sqrt, (_XP,)),
    OpSpec("rsqrt", T.rsqrt, lambda x: 1 / np.sqrt(x), (_XP,)),
    OpSpec("square", T.square, np.square, (_X,)),
    OpSpec("reciprocal", T.reciprocal, np.reciprocal, (_XP,)),
    OpSpec("sin", T.sin, np.sin, (_X,)),
    OpSpec("cos", T.cos, np.cos, (_X,)),
    OpSpec("tan", T.tan, np.tan, (_XS,)),
    OpSpec("asin", T.asin, np.arcsin, (_XS,)),
    OpSpec("acos", T.acos, np.arccos, (_XS,)),
    OpSpec("atan", T.atan, np.arctan, (_X,)),
    OpSpec("sinh", T.sinh, np.sinh, (_X,)),
    OpSpec("cosh", T.cosh, np.cosh, (_X,)),
    OpSpec("tanh", T.tanh, np.tanh, (_X,)),
    OpSpec("asinh", T.asinh, np.arcsinh, (_X,)),
    OpSpec("acosh", T.acosh, np.arccosh, (arr(S, low=1.5, high=3.0),)),
    OpSpec("atanh", T.atanh, np.arctanh, (_XS,)),
    OpSpec("erf", T.erf, sps.erf, (_X,)),
    OpSpec("digamma", T.digamma, sps.digamma, (_XP,), grad_rtol=0.1),
    OpSpec("lgamma", T.lgamma, sps.gammaln, (_XP,), grad_rtol=0.1),
    OpSpec("sigmoid", F.sigmoid, sps.expit, (_X,)),
    OpSpec("sign", T.sign, np.sign, (_XO,), grad=False),
    OpSpec("floor", T.floor, np.floor, (_X,), grad=False),
    OpSpec("ceil", T.ceil, np.ceil, (_X,), grad=False),
    OpSpec("round", T.round, np.round, (_X,), grad=False),
    OpSpec("trunc", T.trunc, np.trunc, (_X,), grad=False),
    OpSpec("scale", T.scale, lambda x: 2.5 * x + 1.0, (_X,),
           kwargs=dict(scale=2.5, bias=1.0)),
    OpSpec("clip", T.clip, lambda x: np.clip(x, -0.5, 0.5), (_X,),
           kwargs=dict(min=-0.5, max=0.5)),
    OpSpec("nan_to_num", T.nan_to_num, np.nan_to_num,
           (np.array([[np.nan, 1.0], [np.inf, -np.inf]], np.float32),),
           grad=False),

    # -- activations (nn.functional) ------------------------------------
    OpSpec("relu", F.relu, lambda x: np.maximum(x, 0), (_XO,)),
    OpSpec("relu6", F.relu6, lambda x: np.clip(x, 0, 6), (_XO,)),
    OpSpec("gelu", F.gelu, _np_gelu, (_X,)),
    OpSpec("gelu.tanh", lambda x: F.gelu(x, approximate=True),
           _np_gelu, (_X,), rtol=1e-3, atol=1e-3),
    OpSpec("silu", F.silu, lambda x: x * sps.expit(x), (_X,)),
    OpSpec("swish", F.swish, lambda x: x * sps.expit(x), (_X,)),
    OpSpec("mish", F.mish,
           lambda x: x * np.tanh(np.log1p(np.exp(x))), (_X,)),
    OpSpec("softplus", F.softplus, lambda x: np.log1p(np.exp(x)), (_X,)),
    OpSpec("softsign", F.softsign, lambda x: x / (1 + np.abs(x)), (_XO,)),
    OpSpec("leaky_relu", F.leaky_relu,
           lambda x: np.where(x >= 0, x, 0.01 * x), (_XO,)),
    OpSpec("elu", F.elu,
           lambda x: np.where(x >= 0, x, np.expm1(x)), (_XO,)),
    OpSpec("selu", F.selu,
           lambda x: 1.0507009873554805 * np.where(
               x >= 0, x, 1.6732632423543772 * np.expm1(x)), (_XO,)),
    OpSpec("hardtanh", F.hardtanh, lambda x: np.clip(x, -1, 1), (_X,)),
    OpSpec("hardsigmoid", F.hardsigmoid,
           lambda x: np.clip(x / 6 + 0.5, 0, 1), (_X,)),
    OpSpec("hardswish", F.hardswish,
           lambda x: x * np.clip(x + 3, 0, 6) / 6, (_X,)),
    OpSpec("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x), (_X,)),
    OpSpec("softshrink", F.softshrink,
           lambda x: np.where(x > 0.5, x - 0.5,
                              np.where(x < -0.5, x + 0.5, 0)), (_X,)),
    OpSpec("hardshrink", F.hardshrink,
           lambda x: np.where(np.abs(x) > 0.5, x, 0), (_X,)),
    OpSpec("glu", F.glu,
           lambda x: x[:, :2] * sps.expit(x[:, 2:]), (_X,)),

    # -- binary elementwise (test_elementwise_*_op.py) ------------------
    OpSpec("add", T.add, np.add, (_X, _Y), grad_wrt=(0, 1)),
    OpSpec("subtract", T.subtract, np.subtract, (_X, _Y),
           grad_wrt=(0, 1)),
    OpSpec("multiply", T.multiply, np.multiply, (_X, _Y),
           grad_wrt=(0, 1)),
    OpSpec("divide", T.divide, np.divide, (_X, _YP), grad_wrt=(0, 1)),
    OpSpec("pow", T.pow, np.power, (_XP, _YP), grad_wrt=(0, 1)),
    OpSpec("maximum", T.maximum, np.maximum, (_X, _Y)),
    OpSpec("minimum", T.minimum, np.minimum, (_X, _Y)),
    OpSpec("fmax", T.fmax, np.fmax, (_X, _Y)),
    OpSpec("fmin", T.fmin, np.fmin, (_X, _Y)),
    OpSpec("mod", T.mod, np.mod, (_XP, _YP), grad=False),
    OpSpec("floor_divide", T.floor_divide, np.floor_divide,
           (_XP, _YP), grad=False),
    OpSpec("atan2", T.atan2, np.arctan2, (_XP, _YP), grad_wrt=(0, 1)),
    OpSpec("hypot", T.hypot, np.hypot, (_XP, _YP), grad_wrt=(0, 1)),
    OpSpec("logaddexp", T.logaddexp, np.logaddexp, (_X, _Y),
           grad_wrt=(0, 1)),
    OpSpec("lerp", lambda x, y: T.lerp(x, y, 0.3),
           lambda x, y: x + 0.3 * (y - x), (_X, _Y), grad_wrt=(0, 1)),
    OpSpec("dist", T.dist, lambda x, y: np.linalg.norm(x - y), (_X, _Y),
           grad_wrt=(0, 1)),

    # -- comparison / logical (forward only) ----------------------------
    OpSpec("equal", T.equal, np.equal, (_X, _X), grad=False),
    OpSpec("not_equal", T.not_equal, np.not_equal, (_X, _Y), grad=False),
    OpSpec("less_than", T.less_than, np.less, (_X, _Y), grad=False),
    OpSpec("less_equal", T.less_equal, np.less_equal, (_X, _Y),
           grad=False),
    OpSpec("greater_than", T.greater_than, np.greater, (_X, _Y),
           grad=False),
    OpSpec("greater_equal", T.greater_equal, np.greater_equal, (_X, _Y),
           grad=False),
    OpSpec("isfinite", T.isfinite, np.isfinite,
           (np.array([1.0, np.inf, np.nan], np.float32),), grad=False),
    OpSpec("isnan", T.isnan, np.isnan,
           (np.array([1.0, np.inf, np.nan], np.float32),), grad=False),
    OpSpec("isinf", T.isinf, np.isinf,
           (np.array([1.0, np.inf, np.nan], np.float32),), grad=False),
    OpSpec("logical_and", T.logical_and, np.logical_and,
           (_X > 0, _Y > 0), grad=False),
    OpSpec("logical_or", T.logical_or, np.logical_or,
           (_X > 0, _Y > 0), grad=False),
    OpSpec("logical_xor", T.logical_xor, np.logical_xor,
           (_X > 0, _Y > 0), grad=False),
    OpSpec("logical_not", T.logical_not, np.logical_not,
           (_X > 0,), grad=False),
    OpSpec("bitwise_and", T.bitwise_and, np.bitwise_and,
           (np.array([5, 12]), np.array([3, 10])), grad=False),
    OpSpec("bitwise_or", T.bitwise_or, np.bitwise_or,
           (np.array([5, 12]), np.array([3, 10])), grad=False),
    OpSpec("bitwise_xor", T.bitwise_xor, np.bitwise_xor,
           (np.array([5, 12]), np.array([3, 10])), grad=False),
    OpSpec("bitwise_not", T.bitwise_not, np.bitwise_not,
           (np.array([5, 12]),), grad=False),

    # -- reductions (test_reduce_op.py family) --------------------------
    OpSpec("sum", T.sum, np.sum, (_X,)),
    OpSpec("sum.axis", lambda x: T.sum(x, axis=1),
           lambda x: np.sum(x, axis=1), (_X,)),
    OpSpec("mean", T.mean, np.mean, (_X,)),
    OpSpec("prod", T.prod, np.prod, (_XP,)),
    OpSpec("max", T.max, np.max, (_X,)),
    OpSpec("min", T.min, np.min, (_X,)),
    OpSpec("amax", T.amax, np.amax, (_X,)),
    OpSpec("amin", T.amin, np.amin, (_X,)),
    OpSpec("std", T.std, lambda x: np.std(x, ddof=1), (_X,)),
    OpSpec("var", T.var, lambda x: np.var(x, ddof=1), (_X,)),
    OpSpec("median", T.median, np.median, (_V1,), grad=False),
    OpSpec("logsumexp", T.logsumexp, _np_logsumexp, (_X,)),
    OpSpec("logcumsumexp", T.logcumsumexp,
           lambda x: np.log(np.cumsum(np.exp(x))), (_V1,)),
    OpSpec("cumsum", T.cumsum, lambda x: np.cumsum(x), (_V1,)),
    OpSpec("cumprod", lambda x: T.cumprod(x, dim=0),
           lambda x: np.cumprod(x), (arr((6,), seed=11, **POS),)),
    OpSpec("norm", T.norm, np.linalg.norm, (_X,)),
    OpSpec("all", T.all, np.all, (_X > 0,), grad=False),
    OpSpec("any", T.any, np.any, (_X > 0,), grad=False),
    OpSpec("numel", T.numel, lambda x: np.asarray(x.size), (_X,),
           grad=False),
    OpSpec("quantile", T.quantile,
           lambda x: np.quantile(x, 0.3), (_V1,),
           kwargs=dict(q=0.3), grad=False),

    # -- matmul family (test_matmul_v2_op.py, test_mul_op.py) -----------
    OpSpec("matmul", T.matmul, np.matmul, (_M1, _M2), grad_wrt=(0, 1)),
    OpSpec("mm", T.mm, np.matmul, (_M1, _M2), grad_wrt=(0, 1)),
    OpSpec("bmm", T.bmm, np.matmul,
           (arr((2, 3, 5), seed=12), arr((2, 5, 4), seed=13)),
           grad_wrt=(0, 1)),
    OpSpec("dot", T.dot, np.dot, (_V1, _V2), grad_wrt=(0, 1)),
    OpSpec("inner", T.inner, np.inner, (_V1, _V2), grad_wrt=(0, 1)),
    OpSpec("outer", T.outer, np.outer, (_V1, _V2), grad_wrt=(0, 1)),
    OpSpec("cross", T.cross, np.cross,
           (arr((3,), seed=14), arr((3,), seed=15)), grad_wrt=(0, 1)),
    OpSpec("kron", T.kron, np.kron,
           (arr((2, 2), seed=16), arr((2, 3), seed=17)),
           grad_wrt=(0, 1)),
    OpSpec("addmm", T.addmm,
           lambda i, a, b: i + a @ b, (arr((3, 4), seed=18), _M1, _M2),
           grad_wrt=(0, 1, 2)),
    OpSpec("trace", T.trace, np.trace, (arr((4, 4), seed=19),)),
    OpSpec("einsum", lambda a, b: T.einsum("ij,jk->ik", a, b),
           np.matmul, (_M1, _M2), grad_wrt=(0, 1)),
    OpSpec("linear", F.linear, lambda x, w: x @ w, (_M1, arr((5, 4),
           seed=20)), grad_wrt=(0, 1)),

    # -- softmax / losses (test_softmax_op.py, test_cross_entropy_op.py)
    OpSpec("softmax", F.softmax, _np_softmax, (_X,)),
    OpSpec("log_softmax", F.log_softmax,
           lambda x: np.log(_np_softmax(x)), (_X,)),
    OpSpec("cross_entropy", F.cross_entropy, _np_xent, (_LG, _LB),
           grad_wrt=(0,)),
    OpSpec("nll_loss", F.nll_loss,
           lambda lp, t: -lp[np.arange(len(t)), t].mean(),
           (np.log(_np_softmax(_LG)), _LB), grad_wrt=(0,)),
    OpSpec("mse_loss", F.mse_loss,
           lambda a, b: ((a - b) ** 2).mean(), (_X, _Y), grad_wrt=(0,)),
    OpSpec("l1_loss", F.l1_loss,
           lambda a, b: np.abs(a - b).mean(), (_X, _Y), grad_wrt=(0,)),
    OpSpec("smooth_l1_loss", F.smooth_l1_loss,
           lambda a, b: np.where(np.abs(a - b) < 1,
                                 0.5 * (a - b) ** 2,
                                 np.abs(a - b) - 0.5).mean(),
           (_X, 3.0 + _Y), grad_wrt=(0,)),
    OpSpec("kl_div", F.kl_div,
           lambda lp, t: (t * (np.log(t) - lp)).mean(),
           (np.log(_np_softmax(_LG)), _np_softmax(arr((6, 5), seed=21)),),
           grad_wrt=(0,)),
    OpSpec("binary_cross_entropy", F.binary_cross_entropy,
           lambda p, t: -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean(),
           (sps.expit(_X), (arr(S, seed=22) > 0).astype(np.float32)),
           grad_wrt=(0,)),
    OpSpec("bce_with_logits", F.binary_cross_entropy_with_logits,
           lambda x, t: (np.maximum(x, 0) - x * t +
                         np.log1p(np.exp(-np.abs(x)))).mean(),
           (_X, (arr(S, seed=23) > 0).astype(np.float32)),
           grad_wrt=(0,)),
    OpSpec("label_smooth", F.label_smooth,
           lambda x: x * 0.9 + 0.1 / x.shape[-1],
           (_np_softmax(_LG),), grad=False),
    OpSpec("square_error_cost", F.square_error_cost,
           lambda a, b: (a - b) ** 2, (_X, _Y), grad_wrt=(0,)),
    OpSpec("cosine_similarity", F.cosine_similarity,
           lambda a, b: (a * b).sum(-1) /
           (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)),
           (_M1, arr((3, 5), seed=24)), grad_wrt=(0, 1)),

    # -- norms ----------------------------------------------------------
    OpSpec("layer_norm", lambda x: F.layer_norm(x, (4,)),
           _np_layer_norm, (_X,)),
    OpSpec("rms_norm", F.rms_norm,
           lambda x: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6),
           (_X,), rtol=1e-4, atol=1e-4),
    OpSpec("normalize", F.normalize,
           lambda x: x / np.maximum(
               np.linalg.norm(x, axis=-1, keepdims=True), 1e-12), (_X,)),

    # -- shape / indexing (forward only where integer) ------------------
    OpSpec("reshape", lambda x: T.reshape(x, [4, 3]),
           lambda x: x.reshape(4, 3), (_X,)),
    OpSpec("transpose", lambda x: T.transpose(x, [1, 0]),
           lambda x: x.T, (_X,)),
    OpSpec("flatten", T.flatten, lambda x: x.reshape(-1), (_X,)),
    OpSpec("squeeze", T.squeeze, np.squeeze, (arr((3, 1, 4), seed=25),)),
    OpSpec("unsqueeze", lambda x: T.unsqueeze(x, 1),
           lambda x: x[:, None], (_X,)),
    OpSpec("concat", lambda a, b: T.concat([a, b]),
           lambda a, b: np.concatenate([a, b]), (_X, _Y),
           grad_wrt=(0, 1)),
    OpSpec("stack", lambda a, b: T.stack([a, b]),
           lambda a, b: np.stack([a, b]), (_X, _Y), grad_wrt=(0, 1)),
    OpSpec("split", lambda x: T.split(x, 2, axis=1),
           lambda x: np.split(x, 2, axis=1), (_X,)),
    OpSpec("chunk", lambda x: T.chunk(x, 2, axis=1),
           lambda x: np.split(x, 2, axis=1), (_X,)),
    OpSpec("tile", lambda x: T.tile(x, [2, 1]),
           lambda x: np.tile(x, [2, 1]), (_X,)),
    OpSpec("expand", lambda x: T.expand(x, [2, 3, 4]),
           lambda x: np.broadcast_to(x, (2, 3, 4)), (_X,)),
    OpSpec("broadcast_to", lambda x: T.broadcast_to(x, [2, 3, 4]),
           lambda x: np.broadcast_to(x, (2, 3, 4)), (_X,)),
    OpSpec("flip", lambda x: T.flip(x, axis=0),
           lambda x: np.flip(x, axis=0), (_X,)),
    OpSpec("roll", lambda x: T.roll(x, 1, axis=0),
           lambda x: np.roll(x, 1, axis=0), (_X,)),
    OpSpec("rot90", T.rot90, np.rot90, (_X,)),
    OpSpec("tril", T.tril, np.tril, (arr((4, 4), seed=26),)),
    OpSpec("triu", T.triu, np.triu, (arr((4, 4), seed=27),)),
    OpSpec("diag", T.diag, np.diag, (_V1,)),
    OpSpec("moveaxis", lambda x: T.moveaxis(x, 0, 1),
           lambda x: np.moveaxis(x, 0, 1), (_X,)),
    OpSpec("swapaxes", lambda x: T.swapaxes(x, 0, 1),
           lambda x: np.swapaxes(x, 0, 1), (_X,)),
    OpSpec("t", T.t, np.transpose, (_X,)),
    OpSpec("gather", lambda x: T.gather(x, np.array([2, 0]), axis=0),
           lambda x: x[[2, 0]], (_X,)),
    OpSpec("index_select",
           lambda x: T.index_select(x, np.array([2, 0]), axis=0),
           lambda x: x[[2, 0]], (_X,)),
    OpSpec("take_along_axis",
           lambda x: T.take_along_axis(
               x, np.array([[0, 1, 0, 1]]), 0),
           lambda x: np.take_along_axis(
               x, np.array([[0, 1, 0, 1]]), 0), (_X,)),
    OpSpec("masked_fill",
           lambda x: T.masked_fill(x, np.asarray(_X > 0), -1.0),
           lambda x: np.where(_X > 0, -1.0, x), (_X,)),
    OpSpec("where", lambda a, b: T.where(np.asarray(_X > 0), a, b),
           lambda a, b: np.where(_X > 0, a, b), (_X, _Y),
           grad_wrt=(0, 1)),
    OpSpec("one_hot", lambda: F.one_hot(np.array([0, 2, 1]), 4),
           lambda: np.eye(4, dtype=np.float32)[[0, 2, 1]], (),
           grad=False),
    OpSpec("diff", T.diff, lambda x: np.diff(x), (_V1,)),
    OpSpec("sort", lambda x: T.sort(x, axis=0),
           lambda x: np.sort(x, axis=0), (_X,)),
    OpSpec("argsort", lambda x: T.argsort(x, axis=0),
           lambda x: np.argsort(x, axis=0, kind="stable"), (_X,),
           grad=False),
    OpSpec("argmax", T.argmax, np.argmax, (_X,), grad=False),
    OpSpec("argmin", T.argmin, np.argmin, (_X,), grad=False),

    # -- integer / counting ---------------------------------------------
    # dynamic output shape: eager-only on TPU (no static shape for XLA)
    OpSpec("bincount", T.bincount, np.bincount,
           (np.array([0, 1, 1, 3, 2, 1]),), grad=False, jit=False),
    OpSpec("unique", T.unique, np.unique,
           (np.array([3, 1, 2, 1, 3]),), grad=False, jit=False),
    OpSpec("masked_select",
           lambda x: T.masked_select(x, np.asarray(_X > 0)),
           lambda x: x[_X > 0], (_X,), grad=False, jit=False),
    OpSpec("nonzero", T.nonzero,
           lambda x: np.stack(np.nonzero(x), -1),
           ((_X > 0).astype(np.float32),), grad=False, jit=False),
    OpSpec("histogram",
           lambda x: T.histogram(x, bins=4, min=-1.0, max=1.0),
           lambda x: np.histogram(x, bins=4, range=(-1, 1))[0], (_X,),
           grad=False),
    OpSpec("searchsorted", T.searchsorted, np.searchsorted,
           (np.array([1.0, 3.0, 5.0]), np.array([0.5, 3.5])),
           grad=False),

    # -- round-3 op-coverage fills (tools/op_coverage.py gaps) ----------
    OpSpec("erfinv", T.erfinv, sps.erfinv, (_XS,), grad_rtol=0.1),
    OpSpec("logit", lambda x: T.logit(x, eps=1e-6),
           lambda x: np.log(x / (1 - x)), (arr(S, low=0.1, high=0.9),)),
    OpSpec("mv", T.mv, lambda m, v: m @ v, (_M1, arr((5,), seed=11)),
           grad_wrt=(0, 1)),
    OpSpec("inverse", T.inverse, np.linalg.inv,
           (np.eye(3, dtype=np.float32) + 0.1 *
            arr((3, 3), seed=12),)),
    OpSpec("kthvalue", lambda x: T.kthvalue(x, 2, axis=1),
           lambda x: (np.sort(x, 1)[:, 1], np.argsort(x, 1)[:, 1]),
           (_X,), grad=False),
    OpSpec("mode", lambda x: T.mode(x)[0],
           lambda x: np.array([1.0, 3.0]),
           (np.array([[1.0, 2.0, 1.0], [3.0, 3.0, 0.5]]),), grad=False),
    OpSpec("diagonal", T.diagonal, lambda x: np.diagonal(x), (_X,)),
    OpSpec("diag_embed", T.diag_embed,
           lambda x: np.stack([np.diag(r) for r in x]), (_X,)),
    OpSpec("diag_embed.off",
           lambda x: T.diag_embed(x, offset=1),
           lambda x: np.stack([np.diag(r, k=1) for r in x]), (_X,)),
    OpSpec("expand_as", lambda x: T.expand_as(x, np.zeros((5, 3, 4))),
           lambda x: np.broadcast_to(x, (5, 3, 4)), (_X,)),
    OpSpec("increment", T.increment, lambda x: x + 1.0, (_X,)),
    OpSpec("add_n", lambda a, b: T.add_n([a, b]),
           lambda a, b: a + b, (_X, _Y), grad_wrt=(0, 1)),
    OpSpec("clip_by_norm", lambda x: T.clip_by_norm(x, 1.0),
           lambda x: x * (1.0 / np.maximum(
               np.sqrt((x ** 2).sum()), 1.0)), (_X,)),
    OpSpec("frobenius_norm", T.frobenius_norm,
           lambda x: np.linalg.norm(x), (_X,)),
    OpSpec("p_norm", lambda x: T.p_norm(x, porder=3.0),
           lambda x: (np.abs(x) ** 3).sum() ** (1 / 3), (_X,)),
    OpSpec("conj", T.conj, np.conj,
           (np.array([1 + 2j, 3 - 4j], np.complex64),), grad=False),
    OpSpec("real", T.real, np.real,
           (np.array([1 + 2j, 3 - 4j], np.complex64),), grad=False),
    OpSpec("imag", T.imag, np.imag,
           (np.array([1 + 2j, 3 - 4j], np.complex64),), grad=False),
    OpSpec("angle", T.angle, np.angle,
           (np.array([1 + 2j, 3 - 4j], np.complex64),), grad=False),
    OpSpec("complex", T.complex,
           lambda r, i: r + 1j * i, (_X, _Y), grad=False),
    OpSpec("multiplex",
           lambda a, b: T.multiplex([a, b], np.array([0, 1, 0])),
           lambda a, b: np.stack([a[0], b[1], a[2]]),
           (_X, _Y), grad_wrt=(0, 1)),
    OpSpec("slice",
           lambda x: T.slice(x, axes=[0, 1], starts=[1, 0],
                             ends=[3, 2]),
           lambda x: x[1:3, 0:2], (_X,)),
    OpSpec("strided_slice",
           lambda x: T.strided_slice(x, axes=[1], starts=[3],
                                     ends=[0], strides=[-2]),
           lambda x: x[:, 3:0:-2], (_X,)),
    OpSpec("segment_sum",
           lambda x: T.segment_sum(x, np.array([0, 0, 1]),
                                   num_segments=2),
           lambda x: np.stack([x[0] + x[1], x[2]]), (_X,)),
    OpSpec("segment_mean",
           lambda x: T.segment_mean(x, np.array([0, 0, 1]),
                                    num_segments=2),
           lambda x: np.stack([(x[0] + x[1]) / 2, x[2]]), (_X,)),
    OpSpec("segment_max",
           lambda x: T.segment_max(x, np.array([0, 0, 1]),
                                   num_segments=2),
           lambda x: np.stack([np.maximum(x[0], x[1]), x[2]]), (_X,)),
    OpSpec("segment_min",
           lambda x: T.segment_min(x, np.array([0, 0, 1]),
                                   num_segments=2),
           lambda x: np.stack([np.minimum(x[0], x[1]), x[2]]), (_X,)),
    OpSpec("tril_indices", lambda: T.tril_indices(3, 3),
           lambda: np.stack(np.tril_indices(3)), (), grad=False),
    OpSpec("triu_indices", lambda: T.triu_indices(3, 3),
           lambda: np.stack(np.triu_indices(3)), (), grad=False),
    OpSpec("unique_consecutive", T.unique_consecutive,
           lambda x: np.array([1.0, 2.0, 1.0]),
           (np.array([1.0, 1.0, 2.0, 2.0, 1.0]),),
           grad=False, jit=False),
    OpSpec("empty", lambda: T.empty((2, 3)),
           lambda: np.zeros((2, 3), np.float32), (), grad=False),
    OpSpec("empty_like", T.empty_like, np.zeros_like, (_X,), grad=False),
    OpSpec("log_loss",
           lambda p: F.log_loss(p, (_XP < 1.0).astype(np.float32)),
           lambda p: -(((_XP < 1.0)) * np.log(p + 1e-4) +
                       (1 - (_XP < 1.0)) * np.log(1 - p + 1e-4)),
           (arr(S, low=0.1, high=0.9, seed=13),)),
    OpSpec("log_sigmoid", F.log_sigmoid,
           lambda x: np.log(sps.expit(x)), (_X,)),
    OpSpec("shape", T.shape, lambda x: np.asarray(x.shape),
           (_X,), grad=False, jit=False),
    # back-trace by hand: final parents [1,0] swap the beams at t=1
    OpSpec("gather_tree",
           lambda: T.gather_tree(
               np.array([[[2, 2]], [[6, 1]], [[7, 8]]]),
               np.array([[[0, 0]], [[1, 0]], [[1, 0]]])),
           lambda: np.array([[[2, 2]], [[1, 6]], [[7, 8]]]),
           (), grad=False),
]

_IDS = []
for s in SPECS:
    n = s.name
    while n in _IDS:
        n += "'"
    _IDS.append(n)


@pytest.mark.parametrize("spec", SPECS, ids=_IDS)
def test_op(spec):
    run_spec(spec)


# smoke-tier representative slice: one op per structural family in
# THIS file's table (MXU matmul, elementwise, reduction, norm, shape,
# gather, scan — convs live in test_optest_extended's own smoke pick),
# so `ci.sh --smoke` still numerically checks the op layer
_SMOKE_NAMES = ("matmul", "add", "softmax", "mean", "layer_norm",
                "reshape", "gather", "cumsum")
_SMOKE_SPECS = [s for s in SPECS if s.name in _SMOKE_NAMES]
assert len(_SMOKE_SPECS) >= len(_SMOKE_NAMES), \
    "smoke slice silently lost an op"


@pytest.mark.smoke
@pytest.mark.parametrize("spec", _SMOKE_SPECS,
                         ids=[s.name for s in _SMOKE_SPECS])
def test_op_smoke(spec):
    run_spec(spec)


# bf16 sweep over the differentiable numeric ops: same table, inputs
# quantized through bfloat16, loose tolerances (the reference's
# per-dtype OpTest dimension)
_BF16_SPECS = [s for s in SPECS
               if s.grad and s.ref is not None and s.jit]
_BF16_IDS = []
for s in _BF16_SPECS:
    n = s.name + "-bf16"
    while n in _BF16_IDS:
        n += "'"
    _BF16_IDS.append(n)


@pytest.mark.parametrize("spec", _BF16_SPECS, ids=_BF16_IDS)
def test_op_bf16(spec):
    from paddle_tpu.testing import check_forward_bf16
    if spec.name in ("digamma", "lgamma", "acosh", "atanh", "tan",
                     "expm1", "cumprod", "logcumsumexp", "dist",
                     "norm", "prod", "logit", "erfinv"):
        pytest.skip("ill-conditioned at bf16 input resolution")
    if spec.name == "inverse":
        pytest.skip("XLA LU decomposition has no bf16 kernel")
    check_forward_bf16(spec)
