"""RNN/LSTM/GRU — numeric parity against torch's CPU reference (the
same cuDNN gate conventions the reference's rnn.py implements) plus
shape/state/mask behavior. Analog of unittests/rnn/test_rnn_nets.py
(which compares against a numpy rnn_numpy.py reference)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _copy_lstm_weights_from_torch(tlstm, cell):
    # torch packs gates i,f,g,o rows in weight_ih_l0 [4H, in]
    cell.weight_ih = jnp.asarray(
        tlstm.weight_ih_l0.detach().numpy().T)
    cell.weight_hh = jnp.asarray(
        tlstm.weight_hh_l0.detach().numpy().T)
    cell.bias_ih = jnp.asarray(tlstm.bias_ih_l0.detach().numpy())
    cell.bias_hh = jnp.asarray(tlstm.bias_hh_l0.detach().numpy())


def test_lstm_matches_torch():
    import torch
    torch.manual_seed(0)
    B, T, I, H = 3, 5, 4, 6
    tl = torch.nn.LSTM(I, H, batch_first=True)
    pt.seed(0)
    ours = nn.LSTM(I, H)
    _copy_lstm_weights_from_torch(tl, ours.layers[0].cell)
    x = np.random.RandomState(0).randn(B, T, I).astype(np.float32)
    with torch.no_grad():
        t_out, (t_h, t_c) = tl(torch.from_numpy(x))
    out, (h, c) = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), t_h.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c), t_c.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_gru_matches_torch():
    import torch
    torch.manual_seed(1)
    B, T, I, H = 2, 4, 3, 5
    tg = torch.nn.GRU(I, H, batch_first=True)
    pt.seed(0)
    ours = nn.GRU(I, H)
    cell = ours.layers[0].cell
    cell.weight_ih = jnp.asarray(tg.weight_ih_l0.detach().numpy().T)
    cell.weight_hh = jnp.asarray(tg.weight_hh_l0.detach().numpy().T)
    cell.bias_ih = jnp.asarray(tg.bias_ih_l0.detach().numpy())
    cell.bias_hh = jnp.asarray(tg.bias_hh_l0.detach().numpy())
    x = np.random.RandomState(1).randn(B, T, I).astype(np.float32)
    with torch.no_grad():
        t_out, t_h = tg(torch.from_numpy(x))
    out, h = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), t_h.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_simple_rnn_matches_torch():
    import torch
    torch.manual_seed(2)
    B, T, I, H = 2, 3, 4, 5
    tr = torch.nn.RNN(I, H, batch_first=True)
    pt.seed(0)
    ours = nn.SimpleRNN(I, H)
    cell = ours.layers[0].cell
    cell.weight_ih = jnp.asarray(tr.weight_ih_l0.detach().numpy().T)
    cell.weight_hh = jnp.asarray(tr.weight_hh_l0.detach().numpy().T)
    cell.bias_ih = jnp.asarray(tr.bias_ih_l0.detach().numpy())
    cell.bias_hh = jnp.asarray(tr.bias_hh_l0.detach().numpy())
    x = np.random.RandomState(2).randn(B, T, I).astype(np.float32)
    with torch.no_grad():
        t_out, t_h = tr(torch.from_numpy(x))
    out, h = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_bidirectional_multilayer_shapes():
    pt.seed(0)
    net = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = jnp.asarray(np.random.RandomState(0).randn(4, 10, 8),
                    jnp.float32)
    out, (h, c) = net(x)
    assert out.shape == (4, 10, 32)        # 2 directions concat
    assert h.shape == (4, 4, 16)           # [L*D, B, H]
    assert c.shape == (4, 4, 16)


def test_time_major_and_initial_state():
    pt.seed(0)
    net = nn.GRU(4, 8, time_major=True)
    x = jnp.asarray(np.random.RandomState(1).randn(6, 2, 4),
                    jnp.float32)
    h0 = jnp.ones((1, 2, 8), jnp.float32)
    out, h = net(x, h0)
    assert out.shape == (6, 2, 8) and h.shape == (1, 2, 8)
    # initial state is actually consumed
    out2, _ = net(x, jnp.zeros((1, 2, 8), jnp.float32))
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_sequence_length_masks_padding():
    """Final state of a padded sequence equals the final state of the
    truncated sequence (the reference's mask semantics)."""
    pt.seed(0)
    net = nn.LSTM(4, 8)
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(2, 6, 4), jnp.float32)
    seq_len = jnp.asarray([6, 3])
    out, (h, c) = net(x, sequence_length=seq_len)
    out_t, (h_t, c_t) = net(x[1:2, :3])
    np.testing.assert_allclose(np.asarray(h[0, 1]), np.asarray(h_t[0, 0]),
                               rtol=1e-5, atol=1e-6)
    # outputs past the valid length are zero
    assert np.allclose(np.asarray(out[1, 3:]), 0.0)


def test_rnn_cell_driver_and_birnn():
    pt.seed(0)
    cell = nn.LSTMCell(4, 6)
    rnn = nn.RNN(cell)
    x = jnp.asarray(np.random.RandomState(4).randn(2, 5, 4), jnp.float32)
    out, (h, c) = rnn(x)
    assert out.shape == (2, 5, 6) and h.shape == (2, 6)
    bi = nn.BiRNN(nn.GRUCell(4, 6), nn.GRUCell(4, 6))
    out, (hf, hb) = bi(x)
    assert out.shape == (2, 5, 12)


def test_lstm_trains_under_jit():
    """End-to-end: LSTM regression under jit + grad converges."""
    from paddle_tpu.nn.layer import functional_call, split_state
    pt.seed(0)
    net = nn.Sequential(("rnn", nn.LSTM(4, 16)),)

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.rnn = nn.LSTM(4, 16)
            self.fc = nn.Linear(16, 1)

        def forward(self, x):
            out, _ = self.rnn(x)
            return self.fc(out[:, -1])

    net = Head()
    params, buffers = split_state(net)
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(16, 6, 4), jnp.float32)
    y = jnp.asarray(x.sum(axis=(1, 2), keepdims=False)[:, None] * 0.1)

    @jax.jit
    def step(p):
        def loss_fn(p):
            out, _ = functional_call(net, p, buffers, x)
            return ((out - y) ** 2).mean()
        l, g = jax.value_and_grad(loss_fn)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.05 * b, p, g)

    losses = []
    for _ in range(60):
        l, params = step(params)
        losses.append(float(l))
    assert losses[-1] < 0.4 * losses[0], losses[:2] + losses[-2:]
