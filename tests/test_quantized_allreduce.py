"""Quantized ring all-reduce vs exact psum (EQuARX technique shape)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu import parallel
from paddle_tpu.parallel import collective as C

import pytest

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _run(fn, x, mesh):
    mapped = jax.shard_map(fn, mesh=mesh.mesh, in_specs=P("dp"),
                           out_specs=P("dp"), check_vma=False)
    return np.asarray(jax.jit(mapped)(x))


def test_matches_exact_psum_within_quant_error():
    mesh = parallel.init_mesh(dp=8)
    try:
        r = np.random.RandomState(0)
        # per-rank shard of gradients (shard_map splits dim 0)
        x = jnp.asarray(r.randn(8, 4, 1000) * 0.01, jnp.float32)

        exact = _run(lambda v: jax.lax.psum(v, "dp"), x, mesh)
        quant = _run(lambda v: C.quantized_ring_allreduce(v, "dp"), x,
                     mesh)
        scale = np.abs(exact).max()
        err = np.abs(quant - exact).max() / scale
        assert err < 0.05, err
        # all ranks agree (it IS an allreduce)
        assert np.allclose(quant[0], quant[1], atol=1e-6)
    finally:
        parallel.set_mesh(None)


def test_odd_sizes_and_identity_at_n1():
    mesh = parallel.init_mesh(dp=8)
    try:
        x = jnp.asarray(np.random.RandomState(1).randn(8, 37),
                        jnp.float32)  # 37 not divisible by 8 -> padding
        exact = _run(lambda v: jax.lax.psum(v, "dp"), x, mesh)
        quant = _run(lambda v: C.quantized_ring_allreduce(v, "dp"), x,
                     mesh)
        np.testing.assert_allclose(quant, exact, rtol=0.1, atol=0.02)
    finally:
        parallel.set_mesh(None)


def test_training_with_quantized_grad_sync_converges():
    """LocalSGD-style harness with quantized gradient reduction."""
    mesh = parallel.init_mesh(dp=8)
    try:
        r = np.random.RandomState(2)
        w0 = jnp.asarray(r.randn(8, 4) * 0.3, jnp.float32)
        x = jnp.asarray(r.randn(32, 8), jnp.float32)
        y = jnp.asarray(r.randn(32, 4), jnp.float32)

        def make_step(reduce_fn):
            def per_shard(w, xb, yb):
                def loss(w):
                    return ((xb @ w - yb) ** 2).mean()
                g = jax.grad(loss)(w)
                g = reduce_fn(g) / 8.0
                return w - 0.05 * g, loss(w)

            return jax.jit(jax.shard_map(
                per_shard, mesh=mesh.mesh,
                in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
                check_vma=False))

        q_step = make_step(
            lambda g: C.quantized_ring_allreduce(g, "dp"))
        e_step = make_step(lambda g: jax.lax.psum(g, "dp"))
        wq = we = w0
        for _ in range(25):
            wq, lq = q_step(wq, x, y)
            we, le = e_step(we, x, y)
        lq, le = float(jnp.mean(lq)), float(jnp.mean(le))
        # same optimization trajectory within quantization noise
        assert abs(lq - le) < 0.05 * le, (lq, le)
        assert lq < float(jnp.mean(
            ((x @ w0 - y) ** 2).mean()))  # actually descended
    finally:
        parallel.set_mesh(None)
