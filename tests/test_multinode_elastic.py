"""Multi-node launcher + elastic across simulated hosts (VERDICT r3
item 5): two NodeAgent process groups as "nodes", whole-node SIGKILL →
peer-lost detection → HOLD until the node is rescheduled → rendezvous
rotation → lossless resume with loss parity; plus two consecutive
graceful preemptions proving the budget-free path at generation depth
≥ 2 (ref: launch/controllers/collective.py Pod watch;
fleet/elastic/manager.py:131 etcd watcher)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multinode_worker.py")
TOTAL = 8


def _agent(node_rank, rdzv_dir, workdir, max_restarts=0, env_extra=None):
    """Launch one node agent in its own session (so a 'node loss' can
    SIGKILL the whole process group, agent + ranks, like a VM eviction)."""
    # agents never touch a device; belt-and-braces pin so no generation
    # can ever contend for the single-client TPU tunnel (workers also
    # pin CPU in-code, see multinode_worker.py)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--node_rank", str(node_rank),
         "--nproc_per_node", "1", "--rdzv_dir", rdzv_dir,
         "--max_restarts", str(max_restarts), "--node_timeout", "4",
         WORKER, workdir, str(TOTAL)],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _read_losses(path):
    """step → last written loss (re-run steps legitimately repeat)."""
    out = {}
    if os.path.exists(path):
        for line in open(path):
            s, v, _gen = line.split()
            out[int(s)] = float(v)
    return out


def _wait(proc, timeout=420):
    out, _ = proc.communicate(timeout=timeout)
    return proc.returncode, out.decode()


def _run_job(tmp_path, tag, max_restarts=0, env_extra=None):
    rdzv = str(tmp_path / f"rdzv_{tag}")
    work = str(tmp_path / f"work_{tag}")
    os.makedirs(work)
    agents = [_agent(n, rdzv, work, max_restarts, env_extra)
              for n in range(2)]
    results = [_wait(a) for a in agents]
    for rc, out in results:
        assert rc == 0, out
    return work, rdzv


@pytest.fixture(scope="module")
def reference_losses(tmp_path_factory):
    """Uninterrupted 2-node run — the parity baseline AND the happy-path
    completion test."""
    tmp = tmp_path_factory.mktemp("mn_ref")
    work, _ = _run_job(tmp, "ref")
    losses = _read_losses(os.path.join(work, "losses.txt"))
    assert sorted(losses) == list(range(TOTAL))
    return losses


def test_uninterrupted_multinode_completes(reference_losses):
    assert len(reference_losses) == TOTAL


def test_node_loss_hold_rejoin_resume_parity(tmp_path, reference_losses):
    """SIGKILL an entire node's process group mid-training; the
    survivor flags peer-lost and HOLDs; 'rescheduling' the node (a
    fresh agent, same rendezvous dir) rotates the master and the job
    resumes from the agreed checkpoint to a loss sequence matching the
    uninterrupted run."""
    rdzv = str(tmp_path / "rdzv")
    work = str(tmp_path / "work")
    os.makedirs(work)
    # max_restarts=0 on purpose: losing a whole node is the PLATFORM's
    # fault (peer-lost) and must not consume the failure budget
    a0 = _agent(0, rdzv, work, max_restarts=0)
    a1 = _agent(1, rdzv, work, max_restarts=0)
    loss_file = os.path.join(work, "losses.txt")
    deadline = time.time() + 240
    while time.time() < deadline:
        if len(_read_losses(loss_file)) >= 3:
            break
        time.sleep(0.2)
    else:
        for a in (a0, a1):
            os.killpg(a.pid, signal.SIGKILL)
        raise AssertionError("job never reached step 3")

    os.killpg(a1.pid, signal.SIGKILL)   # the node is gone, whole group
    a1.wait()
    time.sleep(5)                       # > --node_timeout: survivor
    rc0 = a0.poll()                     # must HOLD, not exit
    assert rc0 is None, f"survivor exited {rc0} instead of holding"

    a1b = _agent(1, rdzv, work, max_restarts=0)  # platform reschedules
    rc, out = _wait(a0)
    assert rc == 0, out
    rc, out = _wait(a1b)
    assert rc == 0, out

    state = json.load(open(os.path.join(rdzv, "rdzv.json")))
    assert state["generation"] >= 1        # rendezvous rotated
    final = _read_losses(loss_file)
    assert sorted(final) == list(range(TOTAL))
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], reference_losses[s],
                                   rtol=1e-6,
                                   err_msg=f"step {s} diverged")


def test_two_consecutive_preemptions_budget_free(tmp_path,
                                                 reference_losses):
    """Graceful preemption at generation 0 AND again at generation 1,
    with max_restarts=0: both restarts must be budget-free and the job
    still completes losslessly (generation counter depth ≥ 2)."""
    work, rdzv = _run_job(tmp_path, "preempt", max_restarts=0,
                          env_extra={"MN_PREEMPT": "2@0,4@1"})
    state = json.load(open(os.path.join(rdzv, "rdzv.json")))
    assert state["generation"] == 2
    final = _read_losses(os.path.join(work, "losses.txt"))
    assert sorted(final) == list(range(TOTAL))
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], reference_losses[s],
                                   rtol=1e-6,
                                   err_msg=f"step {s} diverged")


def test_hard_crash_burns_budget_then_errors(tmp_path):
    """A non-preemption failure consumes the budget; with
    max_restarts=0 every agent must exit non-zero (ERROR), not loop."""
    rdzv = str(tmp_path / "rdzv")
    work = str(tmp_path / "work")
    os.makedirs(work)
    agents = [_agent(n, rdzv, work, max_restarts=0,
                     env_extra={"MN_CRASH": "2@0,2@1,2@2"})
              for n in range(2)]
    results = [_wait(a) for a in agents]
    assert all(rc != 0 for rc, _ in results), results


def test_rendezvous_store_unit(tmp_path):
    """FileRendezvous derivation logic, no subprocesses: generation
    stepping past flags and budget accounting by flag reason."""
    from paddle_tpu.distributed.multinode import FileRendezvous
    r0 = FileRendezvous(str(tmp_path), 0, 2)
    r1 = FileRendezvous(str(tmp_path), 1, 2)
    try:
        assert r0.next_generation() == 0
        r0.publish(0, "127.0.0.1:1", 1)
        r1.request_restart(0, "preempt", 67)
        assert r0.next_generation() == 1
        assert r0.burned_restarts(1) == 0          # preempt is free
        r0.publish(1, "127.0.0.1:2", 1)
        r0.request_restart(1, "peer-lost", -1)     # platform's fault:
        r1.request_restart(1, "preempt", 67)       # ...also free
        assert r1.next_generation() == 2
        assert r1.burned_restarts(2) == 0
        r0.request_restart(2, "failure", 3)        # genuine crash burns
        r1.request_restart(2, "peer-lost", -1)
        assert r1.next_generation() == 3
        assert r1.burned_restarts(3) == 1
        assert r0.stale_peers(timeout=60) == []    # both beating
        r1.stop()
        time.sleep(0.05)
        assert r0.stale_peers(timeout=1e-9) == [1]
    finally:
        r0.stop()
        r1.stop()


# ---- TCP rendezvous backend (VERDICT r4 item 6: clusters without a
# shared filesystem; ref: paddle/fluid/distributed/store/tcp_store.h)


def _tcp_agent(node_rank, endpoint, workdir, max_restarts=0,
               env_extra=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--node_rank", str(node_rank),
         "--nproc_per_node", "1", "--rdzv_backend", "tcp",
         "--rdzv_endpoint", endpoint,
         "--max_restarts", str(max_restarts), "--node_timeout", "4",
         WORKER, workdir, str(TOTAL)],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def test_tcp_rendezvous_store_unit():
    """TCPRendezvous speaks the same protocol as FileRendezvous:
    server-side ages, generation stepping, budget accounting — over
    localhost sockets, leader-hosted."""
    from paddle_tpu.distributed.launch import find_free_port
    from paddle_tpu.distributed.tcp_store import (StoreUnavailable,
                                                  TCPRendezvous)
    ep = f"127.0.0.1:{find_free_port()}"
    r0 = TCPRendezvous(ep, 0, 2)          # leader hosts the store
    r1 = TCPRendezvous(r0.endpoint, 1, 2)
    try:
        assert r0.peers_all_fresh(5.0)
        assert r1.peers_all_fresh(5.0)
        assert r0.next_generation() == 0
        r0.publish(0, "127.0.0.1:1", 1)
        assert r1.read()["master"] == "127.0.0.1:1"
        r1.request_restart(0, "preempt", 67)
        assert r0.next_generation() == 1
        assert r0.burned_restarts(1) == 0          # preempt is free
        r0.publish(1, "127.0.0.1:2", 1)
        r0.request_restart(1, "failure", 1)
        assert r1.next_generation() == 2
        assert r1.burned_restarts(2) == 1          # failure burns
        r0.mark_done(2)
        assert not r0.all_done(2)
        r1.mark_done(2)
        assert r1.all_done(2)
    finally:
        r1.stop()
        r0.stop()
    # with the server gone, clients surface StoreUnavailable
    import pytest as _pytest
    with _pytest.raises(StoreUnavailable):
        r1.read()


def test_tcp_backend_job_with_preemption(tmp_path, reference_losses):
    """End-to-end over sockets: a 2-node job with one graceful
    preemption completes losslessly on the TCP rendezvous — the
    test_multinode_elastic story with no shared filesystem."""
    from paddle_tpu.distributed.launch import find_free_port
    ep = f"127.0.0.1:{find_free_port()}"
    work = str(tmp_path / "work_tcp")
    os.makedirs(work)
    agents = [_tcp_agent(n, ep, work, max_restarts=0,
                         env_extra={"MN_PREEMPT": "2@0"})
              for n in range(2)]
    results = [_wait(a) for a in agents]
    for rc, out in results:
        assert rc == 0, out
    final = _read_losses(os.path.join(work, "losses.txt"))
    assert sorted(final) == list(range(TOTAL))
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], reference_losses[s],
                                   rtol=1e-6,
                                   err_msg=f"step {s} diverged")


def test_tcp_backend_follower_loss_hold_rejoin(tmp_path,
                                               reference_losses):
    """SIGKILL the FOLLOWER node's whole group mid-training on the TCP
    backend: the leader (who hosts the store) flags peer-lost, HOLDs,
    and the rescheduled follower rejoins through the same endpoint to
    a lossless finish."""
    from paddle_tpu.distributed.launch import find_free_port
    ep = f"127.0.0.1:{find_free_port()}"
    work = str(tmp_path / "work")
    os.makedirs(work)
    a0 = _tcp_agent(0, ep, work, max_restarts=0)
    a1 = _tcp_agent(1, ep, work, max_restarts=0)
    loss_file = os.path.join(work, "losses.txt")
    deadline = time.time() + 240
    while time.time() < deadline:
        if len(_read_losses(loss_file)) >= 3:
            break
        time.sleep(0.2)
    else:
        for a in (a0, a1):
            os.killpg(a.pid, signal.SIGKILL)
        raise AssertionError("job never reached step 3")

    os.killpg(a1.pid, signal.SIGKILL)
    a1.wait()
    time.sleep(5)                       # > --node_timeout
    assert a0.poll() is None, "leader exited instead of holding"

    a1b = _tcp_agent(1, ep, work, max_restarts=0)
    rc, out = _wait(a0)
    assert rc == 0, out
    rc, out = _wait(a1b)
    assert rc == 0, out
    final = _read_losses(loss_file)
    assert sorted(final) == list(range(TOTAL))
    for s in range(TOTAL):
        np.testing.assert_allclose(final[s], reference_losses[s],
                                   rtol=1e-6,
                                   err_msg=f"step {s} diverged")
