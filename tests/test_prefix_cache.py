"""Prefix caching + chunked ragged prefill (ISSUE 2 tentpole).

Strategy mirrors test_llm_engine.py: EXACTNESS first (cache on == off,
chunked == one-shot, engine == dense generate — the paged machinery
recomputes identical math over shared memory), then the behaviors only
this subsystem can express: page-granular copy-on-write divergence,
LRU eviction of refcount-zero pages under pressure (and never of live
shared pages), and prefill/decode tick interleaving (a long prompt no
longer stalls in-flight decodes; admission never host-syncs)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import LLMEngine
from paddle_tpu.inference.prefix_cache import PrefixCache, page_digests
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config


def tiny_gpt(max_pos=96):
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=max_pos,
                     hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def dense_ref(net, prompt, n):
    return np.asarray(net.generate(jnp.asarray([prompt]),
                                   max_new_tokens=n))[0,
                                                      len(prompt):].tolist()


# -- host-side cache mechanics (no device) ------------------------------


def test_page_digests_roll_and_diverge():
    ps = 4
    a = list(range(10))                   # 2 full pages + tail
    b = a[:6] + [99, 98, 97, 96]          # diverges MID page 1
    da, db = page_digests(a, ps), page_digests(b, ps)
    assert len(da) == 2 and len(db) == 2
    assert da[0] == db[0]                 # identical first page
    assert da[1] != db[1]                 # divergent second page
    # rolling: the digest commits to history, not just its own chunk
    c = [5, 5, 5, 5] + a[4:8]
    assert page_digests(c, ps)[1] != da[1]


def test_prefix_cache_refcounts_lru_and_eviction():
    c = PrefixCache(4)
    d = page_digests(list(range(12)), 4)
    assert c.lookup(d) == []
    assert c.register(d[0], 7) and c.register(d[1], 8)
    assert c.lookup(d) == [7, 8]
    assert c.shared_page_count == 2 and c.evictable_count == 0
    # second sequence maps both; owner releases; pages stay cached
    c.acquire(7), c.acquire(8)
    c.release(7), c.release(8)            # owner done
    assert c.evictable_count == 0         # second holder still live
    c.release(7), c.release(8)
    assert c.evictable_count == 2         # refcount 0: evictable, cached
    assert c.lookup(d) == [7, 8]          # ... and still matchable
    # duplicate digest: second page stays private
    assert not c.register(d[0], 9)
    # LRU: 7 was released first -> evicted first
    assert c.evict_one() == 7
    assert c.lookup(d) == []              # chain broken at page 0
    assert c.flush() == [8]
    assert c.shared_page_count == 0


# -- exactness ----------------------------------------------------------


def run_engine(net, prompts, n_new, temperature=0.0, sequential=True,
               **kw):
    kw.setdefault("max_seqs", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 128)
    kw.setdefault("prefill_buckets", (64,))
    with LLMEngine(net, **kw) as eng:
        if sequential:
            outs = [eng.submit(p, max_new_tokens=n_new,
                               temperature=temperature).result(
                                   timeout=300) for p in prompts]
        else:
            outs = eng.generate(prompts, max_new_tokens=n_new,
                                temperature=temperature)
        stats = (eng.n_cached_tokens, eng.n_prompt_tokens,
                 len(eng._free_pages))
    # close() flushed the cache: every page must be back in the pool
    assert len(eng._free_pages) == eng.num_pages - 1, \
        "pages leaked through the prefix cache"
    return outs, stats


@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_generations_identical_cache_on_vs_off(temperature):
    """The tentpole exactness pin: shared-prefix workload, cache on ==
    cache off, token for token — greedy AND seeded sampling (sampling
    keys derive from request nonce + position, not scheduler state)."""
    net = tiny_gpt()
    rng = np.random.RandomState(0)
    common = rng.randint(0, 97, 16).tolist()
    prompts = [common + rng.randint(0, 97, 3 + i).tolist()
               for i in range(4)]
    on, (cached_on, total_on, _) = run_engine(
        net, prompts, 8, temperature, prefix_cache=True)
    off, (cached_off, _, _) = run_engine(
        net, prompts, 8, temperature, prefix_cache=False)
    assert cached_off == 0
    # sequential submission: requests 2..4 each reuse the 4 full
    # common-prefix pages (16 tokens) the first request registered
    assert cached_on == 3 * 16, cached_on
    for a, b in zip(on, off):
        assert a["output_ids"] == b["output_ids"]
        assert not a["truncated"]
    if temperature == 0.0:
        for a, p in zip(on, prompts):
            assert a["output_ids"] == dense_ref(net, p, 8)


def test_chunked_prefill_matches_one_shot_and_dense():
    """Logit parity across chunkings: a 3-token chunk (page-misaligned
    on purpose: pages fill across chunk boundaries) produces the same
    tokens as a one-shot chunk and as the dense reference."""
    net = tiny_gpt()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, n).tolist() for n in (13, 7, 18)]
    want = [dense_ref(net, p, 6) for p in prompts]
    small, _ = run_engine(net, prompts, 6, sequential=False,
                          prefill_chunk=3)
    big, _ = run_engine(net, prompts, 6, sequential=False,
                        prefill_chunk=64)
    for s, b, w in zip(small, big, want):
        assert s["output_ids"] == w
        assert b["output_ids"] == w


def test_copy_on_write_divergence_mid_page():
    """Two prompts share 6 tokens then diverge INSIDE page 1: only the
    fully-identical page 0 is shared; the divergent page is a private
    copy (hash miss -> recompute), and both generations stay exact."""
    net = tiny_gpt()
    rng = np.random.RandomState(2)
    a = rng.randint(0, 97, 9).tolist()
    b = a[:6] + [(t + 1) % 97 for t in a[6:]]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,)) as eng:
        out_a = eng.submit(a, max_new_tokens=6).result(timeout=300)
        hits_after_a = eng.n_cached_tokens
        out_b = eng.submit(b, max_new_tokens=6).result(timeout=300)
        hits_after_b = eng.n_cached_tokens
        # a third request repeating A hits BOTH of A's full pages
        out_a2 = eng.submit(a, max_new_tokens=6).result(timeout=300)
        hits_after_a2 = eng.n_cached_tokens
    assert hits_after_a == 0
    assert hits_after_b - hits_after_a == 4    # page 0 only (4 tokens)
    assert hits_after_a2 - hits_after_b == 8   # pages 0 and 1
    assert out_a["output_ids"] == dense_ref(net, a, 6)
    assert out_b["output_ids"] == dense_ref(net, b, 6)
    assert out_a2["output_ids"] == out_a["output_ids"]


def test_eviction_reclaims_dead_pages_never_live_ones():
    """Page pressure: refcount-zero cached pages are reclaimed (LRU),
    pages mapped by a LIVE sequence never are — the competing request
    truncates instead, and the live request's stream stays exact."""
    net = tiny_gpt(max_pos=64)
    rng = np.random.RandomState(3)
    a = rng.randint(0, 97, 8).tolist()
    big = rng.randint(0, 97, 16).tolist()

    # phase 1: A completes; its 2 full pages stay cached at refcount 0
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=6,
                   prefill_buckets=(16,)) as eng:
        out_a = eng.submit(a, max_new_tokens=4).result(timeout=300)
        assert out_a["output_ids"] == dense_ref(net, a, 4)
        assert eng._cache.shared_page_count == 2
        assert eng._cache.evictable_count == 2
        # phase 2: BIG needs 4 of 5 usable pages -> evicts A's pages
        out_big = eng.submit(big, max_new_tokens=4).result(timeout=300)
        assert out_big["output_ids"] == dense_ref(net, big, 4)
        assert eng._cache.n_evicted >= 1
        # phase 3: A again — its pages are gone (miss), output exact
        cached0 = eng.n_cached_tokens
        out_a2 = eng.submit(a, max_new_tokens=4).result(timeout=300)
        assert out_a2["output_ids"] == out_a["output_ids"]
        assert eng.n_cached_tokens == cached0   # evicted -> full miss

    # live pages: A decodes while BIG starves the pool — BIG truncates
    # (or finishes short), A's tokens are NEVER corrupted
    net2 = tiny_gpt(max_pos=64)
    with LLMEngine(net2, max_seqs=2, page_size=4, num_pages=6,
                   prefill_buckets=(16,)) as eng:
        fa = eng.submit(a, max_new_tokens=4)
        fb = eng.submit(big, max_new_tokens=8)
        out_a = fa.result(timeout=300)
        out_b = fb.result(timeout=300)
    assert out_a["output_ids"] == dense_ref(net2, a, 4)
    ref_b = dense_ref(net2, big, 8)
    assert out_b["output_ids"] == ref_b[:len(out_b["output_ids"])]


# -- scheduling ---------------------------------------------------------


def test_long_prompt_interleaves_with_decode():
    """The acceptance pin: a prompt longer than one chunk no longer
    blocks in-flight decodes — decode ticks land BETWEEN its prefill
    chunks (tick history shows p..d..p), the tick-ratio metric is
    populated, and admission performed no blocking device fetch (the
    whole point of the async first-token harvest)."""
    from paddle_tpu.observability import metrics as obs

    net = tiny_gpt(max_pos=96)
    rng = np.random.RandomState(4)
    short = rng.randint(0, 97, 4).tolist()
    long_p = rng.randint(0, 97, 40).tolist()
    # mixed_tick off: this pin witnesses the TWO-OP interleave
    # (p..d..p); the fused ragged tick is gated in test_mixed_ragged
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=128,
                   prefill_buckets=(64,), prefill_chunk=4,
                   mixed_tick=False) as eng:
        fa = eng.submit(short, max_new_tokens=40)
        time.sleep(0.3)      # let the short request enter decode
        fb = eng.submit(long_p, max_new_tokens=4)   # 10 prefill chunks
        out_a = fa.result(timeout=300)
        out_b = fb.result(timeout=300)
        hist = "".join(eng.tick_history)
        assert eng.n_prefill_ticks >= 10
        assert eng.n_decode_ticks > 0
    assert out_a["output_ids"] == dense_ref(net, short, 40)
    assert out_b["output_ids"] == dense_ref(net, long_p, 4)
    # a decode tick strictly between two prefill chunks
    first_p = hist.index("p", hist.index("d"))  # a chunk after decode began
    assert "d" in hist[first_p:hist.rindex("p")], hist
    snap = obs.default_registry().snapshot()
    assert snap["llm_prefill_ticks"] >= 10
    assert snap["llm_decode_ticks"] > 0
    assert snap["llm_prefill_decode_tick_ratio"] > 0
    assert snap["llm_prefix_cache_hit_rate"] >= 0


def test_submit_validates_total_length_against_max_len():
    """submit() must bound prompt + max_new_tokens by the page-table
    horizon (max_len), independently of the prefill-bucket bound."""
    net = tiny_gpt(max_pos=96)
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   max_len=32, prefill_buckets=(64,)) as eng:
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(list(range(20)), max_new_tokens=20)
        # fits the horizon exactly -> admitted and completes
        out = eng.submit(list(range(1, 17)),
                         max_new_tokens=16).result(timeout=300)
        assert len(out["output_ids"]) == 16
        assert not out["truncated"]


def test_prefill_queue_and_inflight_survive_device_error():
    """A device error during a prefill chunk fails the queued request
    cleanly (future resolves, pages reclaimed, cache flushed) and the
    engine keeps serving."""
    net = tiny_gpt()
    # mixed_tick off so the chunk lands on _chunk_fn (the patched
    # site) rather than riding a mixed slab
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,), mixed_tick=False)
    real = eng._chunk_fn
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient PJRT failure")
        return real(*a, **kw)

    eng._chunk_fn = flaky
    bad = eng.submit([1, 2, 3, 4, 5, 6], max_new_tokens=4)
    with pytest.raises(RuntimeError, match="transient"):
        bad.result(timeout=60)
    assert not eng._prefill_q          # no dangling queue entry
    ok = eng.submit([7, 8, 9], max_new_tokens=3).result(timeout=60)
    assert ok["output_ids"] == dense_ref(net, [7, 8, 9], 3)
    eng.close()
    assert len(eng._free_pages) == eng.num_pages - 1
