"""Request-scoped tracing, debug server, flight recorder (observability
tentpole 2): span identity/nesting semantics (incl. cross-thread
trees), ring-buffer bounds, the merged chrome-trace export with
metadata + per-profiler window filtering, a live /metrics + /statusz
round-trip on an ephemeral port, the LLM request span-tree acceptance
(children tile submit→finish), and the crash paths — SIGTERM and
atexit dumps via real subprocesses."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.observability import (export_chrome_tracing, flight,
                                      server, tracing)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.clear()
    tracing.enable()
    yield
    tracing.disable()
    tracing.clear()
    tracing.set_capacity(tracing.DEFAULT_TABLE_CAP)


def _run_py(code: str, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


# ---------------------------------------------------------------------------
# span semantics
# ---------------------------------------------------------------------------

def test_span_ids_attrs_events_and_thread_local_nesting():
    with tracing.span("outer", attrs={"a": 1}) as outer:
        assert tracing.current_span() is outer
        with tracing.span("inner") as inner:
            inner.add_event("tick", {"n": 1})
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
    assert tracing.current_span() is None
    fin = {s["name"]: s for s in tracing.finished_spans()}
    assert fin["outer"]["parent_id"] is None
    assert fin["outer"]["attrs"] == {"a": 1}
    assert fin["inner"]["events"][0]["name"] == "tick"
    assert fin["inner"]["dur"] >= 0
    # inner ended first: ring order is end order
    names = [s["name"] for s in tracing.finished_spans()]
    assert names == ["inner", "outer"]


def test_span_nesting_across_threads_via_explicit_parent():
    """The LLM pattern: root on the submitter thread, phases on the
    engine loop thread, linked by carrying the parent explicitly."""
    root = tracing.start_span("req", parent=None)
    done = threading.Event()
    out = {}

    def worker():
        child = tracing.start_span("phase", parent=root)
        grand = tracing.start_span("sub", parent=child)
        grand.end()
        child.end()
        out["child"], out["grand"] = child, grand
        done.set()

    threading.Thread(target=worker, name="engine-loop").start()
    assert done.wait(10)
    root.end()
    assert out["child"].parent_id == root.span_id
    assert out["child"].trace_id == root.trace_id
    assert out["grand"].parent_id == out["child"].span_id
    assert out["grand"].trace_id == root.trace_id
    by_name = {s["name"]: s for s in tracing.finished_spans()}
    assert by_name["phase"]["tname"] == "engine-loop"
    assert by_name["req"]["tname"] != "engine-loop"


def test_span_end_is_idempotent_and_error_status_recorded():
    sp = tracing.start_span("x")
    sp.end()
    t1 = sp.t1
    sp.end()                      # second end: no-op
    assert sp.t1 == t1
    assert len(tracing.finished_spans()) == 1
    with pytest.raises(RuntimeError):
        with tracing.span("boom"):
            raise RuntimeError("dead")
    fin = [s for s in tracing.finished_spans() if s["name"] == "boom"][0]
    assert fin["status"] == "error"
    assert "dead" in fin["attrs"]["error"]


def test_ring_buffer_overflow_keeps_newest():
    tracing.set_capacity(8)
    for i in range(30):
        tracing.start_span(f"s{i}").end()
    fin = tracing.finished_spans()
    assert len(fin) == 8
    assert [s["name"] for s in fin] == [f"s{i}" for i in range(22, 30)]
    # live spans are not bounded by the ring and survive overflow
    live = tracing.start_span("still-going")
    assert [s["name"] for s in tracing.live_spans()] == ["still-going"]
    live.end()


def test_per_span_event_cap():
    sp = tracing.start_span("chatty")
    for i in range(tracing.MAX_EVENTS_PER_SPAN + 50):
        sp.add_event("e", {"i": i})
    sp.end()
    d = tracing.finished_spans()[-1]
    assert len(d["events"]) == tracing.MAX_EVENTS_PER_SPAN
    assert d["dropped_events"] == 50


def test_disabled_tracing_is_noop():
    tracing.disable()
    sp = tracing.start_span("ghost")
    assert sp is tracing.NOOP_SPAN
    sp.add_event("x").set_attr("y", 1)
    sp.end()
    with tracing.span("ghost2"):
        assert tracing.current_span() is None
    assert tracing.finished_spans() == []
    assert tracing.live_spans() == []


def test_rollup_aggregates_by_name():
    for _ in range(3):
        tracing.start_span("llm.prefill").end()
    tracing.start_span("llm.decode").end()
    tracing.start_span("llm.request").end()
    r = tracing.rollup(prefix="llm.")
    assert r["llm.prefill"]["count"] == 3
    assert r["llm.decode"]["count"] == 1
    assert abs(sum(v["share"] for v in r.values()) - 1.0) < 0.01
    # exclude drops a name from output AND the share denominator
    # (phase shares over the spans that tile a root must sum to 1)
    r = tracing.rollup(prefix="llm.", exclude=("llm.request",))
    assert "llm.request" not in r
    assert abs(sum(v["share"] for v in r.values()) - 1.0) < 0.01


# ---------------------------------------------------------------------------
# chrome export: merged timeline, metadata, window filter
# ---------------------------------------------------------------------------

def test_chrome_export_merges_spans_with_metadata(tmp_path):
    from paddle_tpu import profiler
    prof = profiler.Profiler(log_dir=str(tmp_path / "prof"))
    prof.start()
    with profiler.RecordEvent("host_ann"):
        pass
    root = tracing.start_span("req", attrs={"k": "v"})
    child = tracing.start_span("phase", parent=root)
    child.add_event("mark", {"n": 3})
    child.end()
    root.end()
    prof.stop()
    path = export_chrome_tracing(prof, str(tmp_path / "trace.json"))
    trace = json.load(open(path))
    evs = trace["traceEvents"]
    md = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in md)
    tnames = [e for e in md if e["name"] == "thread_name"]
    assert tnames and all(e["args"]["name"] for e in tnames)
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert "host_ann" in xs                      # RecordEvent stream
    assert xs["req"]["cat"] == "span"
    assert xs["phase"]["args"]["parent_id"] == \
        xs["req"]["args"]["span_id"]             # parent link survives
    assert xs["req"]["args"]["k"] == "v"
    instants = [e for e in evs if e["ph"] == "i"]
    assert any(e["name"] == "phase:mark" and e["args"]["n"] == 3
               for e in instants)
    # span fed summary() stats (one timeline, one aggregate table)...
    assert "req" in prof.summary()
    # ...but renders exactly once in the trace
    assert sum(1 for e in evs if e["ph"] == "X" and e["name"] == "req") \
        == 1


def test_chrome_export_filters_to_profiler_window(tmp_path):
    from paddle_tpu import profiler
    prof = profiler.Profiler(
        scheduler=profiler.make_scheduler(closed=1, ready=0, record=1),
        log_dir=str(tmp_path / "prof"))
    prof.start()                       # step 0: CLOSED (no window)
    with profiler.RecordEvent("outside"):
        pass
    tracing.start_span("span_outside").end()
    prof.step()                        # step 1: RECORD_AND_RETURN
    with profiler.RecordEvent("inside"):
        pass
    tracing.start_span("span_inside").end()
    prof.stop()
    filtered = json.load(open(export_chrome_tracing(
        prof, str(tmp_path / "f.json"))))
    names = {e["name"] for e in filtered["traceEvents"]
             if e["ph"] == "X"}
    assert "inside" in names and "span_inside" in names
    assert "outside" not in names and "span_outside" not in names
    everything = json.load(open(export_chrome_tracing(
        None, str(tmp_path / "all.json"))))
    names = {e["name"] for e in everything["traceEvents"]
             if e["ph"] == "X"}
    assert {"inside", "outside", "span_inside",
            "span_outside"} <= names


# ---------------------------------------------------------------------------
# debug server round-trip (ephemeral port)
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


def test_debug_server_roundtrip(tmp_path):
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    reg.counter("debug_server_test_total", "probe").inc(7)
    server.register_status_provider(
        "test_component", lambda: {"answer": 42})
    tracing.start_span("visible.span").end()
    srv = server.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"

        code, body = _get(base + "/metrics")
        text = body.decode()
        assert code == 200
        assert "debug_server_test_total 7.0" in text
        for line in text.splitlines():        # 0.0.4 exposition parses
            if not line or line.startswith("#"):
                continue
            _, value = line.rsplit(" ", 1)
            float(value if value != "+Inf" else "inf")

        code, body = _get(base + "/statusz")
        st = json.loads(body)
        assert code == 200
        assert st["providers"]["test_component"] == {"answer": 42}
        assert st["tracing_enabled"] is True
        assert "device_memory" in st

        code, body = _get(base + "/tracez?limit=10")
        tz = json.loads(body)
        assert code == 200
        assert any(s["name"] == "visible.span" for s in tz["finished"])

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()
        server.unregister_status_provider("test_component")


def test_debug_server_profilez_arms_one_window(tmp_path):
    srv = server.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = json.dumps({"duration_s": 0.4,
                           "log_dir": str(tmp_path / "od")}).encode()
        req = urllib.request.Request(base + "/profilez", data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            armed = json.loads(r.read())["armed"]
        assert armed["duration_s"] == 0.4
        # second arm while the window is open → 409
        req2 = urllib.request.Request(base + "/profilez", data=body,
                                      method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req2, timeout=30)
        assert ei.value.code == 409
        deadline = time.time() + 15
        while time.time() < deadline:       # window closes on its own
            code, b = _get(base + "/profilez")
            if json.loads(b)["armed"] is None:
                break
            time.sleep(0.1)
        else:
            pytest.fail("profiler window never disarmed")
        assert os.path.isdir(str(tmp_path / "od"))  # trace dir created
    finally:
        srv.stop()


def test_dead_component_drops_out_of_statusz():
    class Thing:
        pass

    import weakref
    t = Thing()
    ref = weakref.ref(t)
    server.register_status_provider(
        "ephemeral", lambda: {"up": 1} if ref() is not None else None)
    assert server._collect_status()["ephemeral"] == {"up": 1}
    del t
    assert "ephemeral" not in server._collect_status()
    assert "ephemeral" not in server._providers   # self-unregistered


# ---------------------------------------------------------------------------
# LLM request span-tree acceptance
# ---------------------------------------------------------------------------

def _tiny_gpt():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def test_llm_request_span_tree_parents_and_latency_sum(tmp_path):
    """Acceptance: with tracing enabled, each request leaves a
    queue→prefill→first_token→decode tree parented under one
    llm.request root whose children tile the request's observed
    end-to-end latency (±5%), and the chrome export carries it."""
    from paddle_tpu.inference.llm import LLMEngine
    net = _tiny_gpt()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 11, 3)]
    with LLMEngine(net, max_seqs=4, page_size=4, num_pages=128,
                   prefill_buckets=(16,)) as eng:
        outs = eng.generate(prompts, max_new_tokens=8)
    spans = tracing.finished_spans()
    roots = [s for s in spans if s["name"] == "llm.request"]
    assert len(roots) == 3
    for root, out in zip(sorted(roots,
                                key=lambda s: s["attrs"]["nonce"]),
                         outs):
        kids = [s for s in spans
                if s["parent_id"] == root["span_id"]]
        by_name = {k["name"]: k for k in kids}
        assert set(by_name) == {"llm.queue", "llm.prefill",
                                "llm.first_token", "llm.decode"}
        for k in kids:
            assert k["trace_id"] == root["trace_id"]
        # phases tile: each child starts where the previous ended
        order = [by_name[n] for n in ("llm.queue", "llm.prefill",
                                      "llm.first_token", "llm.decode")]
        for a, b in zip(order, order[1:]):
            assert b["ts"] == pytest.approx(a["ts"] + a["dur"])
        child_sum = sum(k["dur"] for k in kids)
        assert child_sum == pytest.approx(root["dur"], rel=1e-6)
        assert child_sum == pytest.approx(out["latency_s"], rel=0.05)
        assert root["attrs"]["outcome"] == "completed"
        assert root["attrs"]["output_tokens"] == 8
        # prefill carries per-chunk + cache annotations
        assert "cache_hit_tokens" in by_name["llm.prefill"]["attrs"]
        assert any(e["name"] == "chunk"
                   for e in by_name["llm.prefill"]["events"])
        assert any(e["name"] == "first_token"
                   for e in root["events"])
    # the chrome export renders the tree with parent links in args
    trace = json.load(open(export_chrome_tracing(
        None, str(tmp_path / "llm.json"))))
    xs = [e for e in trace["traceEvents"] if e.get("cat") == "span"]
    root_ids = {e["args"]["span_id"] for e in xs
                if e["name"] == "llm.request"}
    decode_parents = {e["args"]["parent_id"] for e in xs
                      if e["name"] == "llm.decode"}
    assert decode_parents <= root_ids
    # no live spans left behind after a clean engine shutdown
    assert tracing.live_spans() == []


def test_llm_failed_admission_closes_span_tree_with_error():
    from paddle_tpu.inference.llm import LLMEngine
    net = _tiny_gpt()
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=4,
                   prefill_buckets=(16,)) as eng:
        fut = eng.submit(list(range(20)), max_new_tokens=2)
        with pytest.raises(ValueError, match="cannot fit"):
            fut.result(timeout=120)
    roots = [s for s in tracing.finished_spans()
             if s["name"] == "llm.request"]
    assert len(roots) == 1
    assert roots[0]["status"] == "error"
    assert roots[0]["attrs"]["outcome"] == "failed"
    assert tracing.live_spans() == []


def test_llm_statusz_provider_lifecycle():
    from paddle_tpu.inference.llm import LLMEngine
    net = _tiny_gpt()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(8,))
    st = server._collect_status()
    mine = [v for k, v in st.items() if k.startswith("llm_engine_")]
    assert any(v["max_seqs"] == 2 and "prefix_cache" in v
               for v in mine)
    eng.close()
    st = server._collect_status()
    assert eng._status_name not in st


# ---------------------------------------------------------------------------
# train-loop spans
# ---------------------------------------------------------------------------

def test_model_fit_epoch_dispatch_drain_spans():
    from paddle_tpu import nn
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.metric import Accuracy

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss(), metrics=[Accuracy()])
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))
    m.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0,
          steps_per_loop=2)
    spans = tracing.finished_spans()
    epochs = [s for s in spans if s["name"] == "train.epoch"]
    assert [s["attrs"]["epoch"] for s in epochs] == [0, 1]
    dispatches = [s for s in spans if s["name"] == "train.dispatch"]
    assert len(dispatches) == 4                    # 2 slabs × 2 epochs
    epoch_ids = {s["span_id"] for s in epochs}
    assert all(d["parent_id"] in epoch_ids for d in dispatches)
    assert all(d["attrs"]["k"] == 2 for d in dispatches)
    # first dispatch compiled → recompile event attached
    first = min(dispatches, key=lambda s: s["ts"])
    assert any(e["name"] == "recompile" for e in first["events"])
    assert sum(1 for d in dispatches
               for e in d["events"] if e["name"] == "recompile") == 1
    drains = [s for s in spans if s["name"] == "train.metric_drain"]
    assert drains and all(d["parent_id"] in epoch_ids or
                          d["parent_id"] is None for d in drains)
    # loader waits surfaced as spans too
    assert any(s["name"] == "io.next_wait" for s in spans)
    # the /statusz provider reflects trained state
    st = server._collect_status()
    mine = [v for k, v in st.items() if k.startswith("train_model_")]
    assert any(v["step_count"] == 8 and v["loop_compiled"]
               for v in mine)


def test_chrome_export_keeps_spans_overlapping_window(tmp_path):
    """A long-lived root that STARTED before the RECORD window but
    runs through it must export (interval overlap, not point-in-
    window), or its in-window children would carry dangling
    parent_ids; a profiler that never opened a window exports
    everything it recorded instead of an empty file."""
    from paddle_tpu import profiler
    prof = profiler.Profiler(
        scheduler=profiler.make_scheduler(closed=1, ready=0, record=1),
        log_dir=str(tmp_path / "prof"))
    prof.start()                        # step 0: CLOSED
    root = tracing.start_span("long.root")     # starts pre-window
    prof.step()                         # step 1: window opens
    tracing.start_span("child", parent=root).end()
    root.end()                          # ends inside the window
    prof.stop()
    trace = json.load(open(export_chrome_tracing(
        prof, str(tmp_path / "t.json"))))
    xs = {e["name"]: e for e in trace["traceEvents"]
          if e.get("cat") == "span"}
    assert "long.root" in xs and "child" in xs
    assert xs["child"]["args"]["parent_id"] == \
        xs["long.root"]["args"]["span_id"]
    # windowless profiler (never reached RECORD): export everything
    prof2 = profiler.Profiler(
        scheduler=lambda step: profiler.ProfilerState.CLOSED,
        log_dir=str(tmp_path / "p2"))
    prof2.start()
    tracing.start_span("recorded.anyway").end()
    prof2.stop()
    trace = json.load(open(export_chrome_tracing(
        prof2, str(tmp_path / "t2.json"))))
    assert any(e["name"] == "recorded.anyway"
               for e in trace["traceEvents"])


def test_profiler_stop_does_not_kill_newer_profiler(tmp_path):
    """A stale stop() (the /profilez timed disarm pattern) must not
    deactivate a profiler started after it."""
    from paddle_tpu import profiler
    a = profiler.Profiler(log_dir=str(tmp_path / "a"))
    a.start()
    a._stop_trace()                     # release the jax trace slot
    b = profiler.Profiler(log_dir=str(tmp_path / "b"))
    b.start()                           # b now owns the event stream
    b._stop_trace()
    a.stop()                            # stale stop: must be a no-op
    assert profiler._events.active is True
    b.stop()
    assert profiler._events.active is False


def test_train_batch_exception_closes_step_span():
    """A dispatch failure must not leak a live span (the _live
    registry is uncapped) when the caller catches and continues."""
    from paddle_tpu import nn
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 4))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss())
    x = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 1), np.int64)
    m.train_batch([x], [y])             # compile the good shape
    m._train_step_fn = None             # force rebuild...

    def boom(*a, **kw):
        raise RuntimeError("device fell over")

    m._build_train_step = lambda: boom
    with pytest.raises(RuntimeError, match="fell over"):
        m.train_batch([x], [y])
    assert not any(s["name"] == "train.step"
                   for s in tracing.live_spans())
    bad = [s for s in tracing.finished_spans()
           if s["name"] == "train.step" and s["status"] == "error"]
    assert len(bad) == 1


def test_fit_exception_closes_epoch_span():
    """A step failure unwinding out of fit() must not leave the epoch
    span on the thread-local stack (a caller catching the error and
    re-running fit would otherwise parent under a dead epoch) or in
    the live-span registry."""
    from paddle_tpu import nn
    from paddle_tpu.hapi.callbacks import Callback
    from paddle_tpu.io import TensorDataset

    class Bomb(Callback):
        def on_train_batch_end(self, step, logs=None):
            raise RuntimeError("boom")

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 4))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss())
    x = np.zeros((16, 8), np.float32)
    y = np.zeros((16, 1), np.int64)
    with pytest.raises(RuntimeError, match="boom"):
        m.fit(TensorDataset([x, y]), batch_size=8, epochs=1, verbose=0,
              callbacks=[Bomb()])
    assert tracing.current_span() is None
    assert not any(s["name"] == "train.epoch"
                   for s in tracing.live_spans())
    ep = [s for s in tracing.finished_spans()
          if s["name"] == "train.epoch"]
    assert len(ep) == 1 and ep[0]["status"] == "error"


def test_profilez_refuses_while_job_profiler_records(tmp_path):
    """Arming the on-demand window while the job's own Profiler is
    recording would clear (then disable) the process-wide event
    tables — the arm must refuse instead."""
    from paddle_tpu import profiler
    prof = profiler.Profiler(log_dir=str(tmp_path / "job"))
    prof.start()
    try:
        srv = server.DebugServer(port=0)
        assert srv._arm.arm(0.2, str(tmp_path / "od")) is None
        srv._httpd.server_close()
    finally:
        prof.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_dump_format(tmp_path):
    from paddle_tpu.observability import default_registry
    default_registry().counter("flight_probe_total").inc(2)
    tracing.start_span("done.work").end()
    live = tracing.start_span("inflight.work", attrs={"slot": 3})
    rec = flight.FlightRecorder(str(tmp_path))
    path = rec.dump("unit")
    live.end()
    assert path and os.path.exists(path)
    rows = [json.loads(ln) for ln in open(path)]
    header = rows[0]
    assert header["kind"] == "header" and header["reason"] == "unit"
    assert header["metrics"]["flight_probe_total"] == 2
    by_kind = {}
    for r in rows[1:]:
        by_kind.setdefault(r["kind"], []).append(r)
    live_names = [r["name"] for r in by_kind["span"] if r["live"]]
    done_names = [r["name"] for r in by_kind["span"] if not r["live"]]
    assert "inflight.work" in live_names
    assert "done.work" in done_names
    assert all("ts_wall" in r for r in by_kind["span"])


def test_flight_recorder_thread_exception_hook(tmp_path, monkeypatch):
    # silence the default hook's traceback print for this test
    monkeypatch.setattr(threading, "excepthook", lambda args: None)
    rec = flight.FlightRecorder(str(tmp_path)).install()
    try:
        t = threading.Thread(target=lambda: 1 / 0)
        t.start()
        t.join(timeout=30)
        files = os.listdir(str(tmp_path))
        assert any("thread_exception" in f for f in files)
    finally:
        rec.uninstall()


def test_sigterm_dumps_inflight_spans_subprocess(tmp_path):
    """Acceptance: kill a worker with SIGTERM → a flight-recorder
    JSONL containing the in-flight spans is left behind, and the
    process still dies BY SIGTERM (supervisors key off the wait
    status)."""
    out = str(tmp_path)
    code = f"""
import os, signal, sys, time
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.observability import tracing, flight
tracing.enable()
flight.install_flight_recorder({out!r})
tracing.start_span("request.inflight", attrs={{"slot": 1}})
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(60)   # unreachable: the re-raised SIGTERM kills us
"""
    p = _run_py(code)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    dumps = [f for f in os.listdir(out) if f.endswith(".jsonl")]
    assert len(dumps) == 1 and "sigterm" in dumps[0]
    rows = [json.loads(ln) for ln in open(os.path.join(out, dumps[0]))]
    assert rows[0]["reason"] == "sigterm"
    live = [r for r in rows if r.get("kind") == "span" and r["live"]]
    assert any(r["name"] == "request.inflight" for r in live)


def test_preemption_guard_dumps_flight_record(tmp_path):
    from paddle_tpu.distributed.elastic import PreemptionGuard
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        guard = PreemptionGuard(install=False)
        tracing.start_span("step.inflight")
        guard.trigger()
        assert guard.check(exit=False) is True
        files = [f for f in os.listdir(str(tmp_path))
                 if "preemption" in f]
        assert len(files) == 1
        rows = [json.loads(ln) for ln in
                open(os.path.join(str(tmp_path), files[0]))]
        assert any(r.get("kind") == "span" and r["live"] and
                   r["name"] == "step.inflight" for r in rows)
    finally:
        rec.uninstall()


def test_jsonl_reporter_atexit_flush_subprocess(tmp_path):
    """Satellite: a reporter never stopped still writes its final
    snapshot at interpreter exit — short-lived jobs whose whole life
    fits inside one interval lose nothing."""
    path = str(tmp_path / "m.jsonl")
    code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu import observability as obs
obs.default_registry().counter("atexit_probe_total").inc(3)
rep = obs.JSONLReporter({path!r}, interval=3600)
# exit WITHOUT stop(): atexit must flush the final snapshot
"""
    p = _run_py(code)
    assert p.returncode == 0, p.stderr
    rows = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert len(rows) >= 1
    assert rows[-1]["metrics"]["atexit_probe_total"] == 3
