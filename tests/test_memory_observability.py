"""HBM attribution ledger (observability/memory.py, ISSUE 14): owners
register attributed reservations at allocation boundaries, every read
reconciles against device.memory_stats() with an explicit unattributed
residual, the engine's KV-pool split tracks the page table EXACTLY,
and RESOURCE_EXHAUSTED anywhere produces a flight dump carrying the
per-owner table — an OOM is a diffable accounting, not a stack trace.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import flags as _flags
from paddle_tpu.observability import memory as memobs
from paddle_tpu.observability import tracing
from paddle_tpu.observability.metrics import default_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_ledger():
    """Process-global singleton isolation: every test gets a fresh
    ledger and a clean mem_* gauge namespace."""
    memobs.reset()
    was = memobs.enabled()
    memobs.enable()
    reg = default_registry()
    for fam in ("mem_bytes", "mem_watermark_bytes",
                "mem_headroom_pages", "host_rss_bytes"):
        reg.unregister(fam)
    yield
    memobs.reset()
    (memobs.enable if was else memobs.disable)()


def tiny_gpt():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def kv_rows(led=None):
    led = led or memobs.instance()
    return {r["kind"]: r["bytes"] for r in led.rows()
            if r["owner"] == "kv_pool"}


# ---------------------------------------------------------------------------
# ledger core
# ---------------------------------------------------------------------------


def test_tree_bytes_by_dtype_abstract():
    tree = {"a": np.zeros((4, 8), np.float32),
            "b": np.zeros((16,), np.int8),
            "c": {"d": np.zeros((2, 2), np.float32)},
            "e": "not-an-array"}
    out = memobs.tree_bytes_by_dtype(tree)
    assert out == {"float32": 4 * 8 * 4 + 2 * 2 * 4, "int8": 16}


def test_reconciliation_residual_is_the_closing_line(monkeypatch):
    """The acceptance pin: sum(attributed device bytes) +
    unattributed residual == device bytes_in_use, exactly; host rows
    stay OUT of the device reconciliation."""
    led = memobs.MemoryLedger()
    led.set_entry("s0", "params", "float32", 1000)
    led.set_entry("s0", "kv_pool", "free", 2000)
    led.set_entry("s0", "staging", "host", 777, placement="host")
    monkeypatch.setattr(
        memobs, "_collect_device_stats",
        lambda: {"bytes_in_use": 5000.0, "bytes_limit": 10000.0,
                 "peak_bytes_in_use": 6000.0, "devices": 1})
    p = led.payload()
    assert p["attributed_device_bytes"] == 3000
    assert p["attributed_host_bytes"] == 777
    assert p["unattributed_bytes"] == 2000
    assert p["attributed_device_bytes"] + p["unattributed_bytes"] \
        == p["device"]["bytes_in_use"]
    assert "fragmentation" in p["unattributed_note"]


def test_no_device_stats_is_a_hole_not_zero():
    """CPU backends: the residual is explicit None + note, never a
    fabricated 0 (which would read as 'perfectly attributed')."""
    led = memobs.MemoryLedger()
    led.set_entry("s0", "params", "float32", 1000)
    p = led.payload()       # real CPU backend: no memory_stats
    assert "unattributed_bytes" in p
    assert p["unattributed_bytes"] is None
    assert "memory_stats" in p["unattributed_note"]
    assert p["host_rss_bytes"] is None or p["host_rss_bytes"] > 0


def test_inactive_ledger_never_queries_devices(monkeypatch):
    """A router-only process (no registered device rows) answering
    /memz must not initialize a jax backend."""
    led = memobs.MemoryLedger()

    def boom():
        raise AssertionError("device query from an inactive ledger")

    monkeypatch.setattr(memobs, "_collect_device_stats", boom)
    assert led.payload()["device"] is None
    led.set_entry("s0", "staging", "host", 10, placement="host")
    assert led.payload()["device"] is None   # host rows don't activate


def test_provider_rows_live_and_self_unregister():
    led = memobs.MemoryLedger()
    state = {"n": 1, "alive": True}

    def prov():
        if not state["alive"]:
            return None
        return {"rows": [{"owner": "pool", "kind": "free",
                          "bytes": state["n"] * 100.0}],
                "headroom_pages": state["n"], "page_bytes": 100.0}

    led.register_provider("s1", prov)
    assert led.rows()[0]["bytes"] == 100.0
    state["n"] = 3      # LIVE: the read recomputes, no re-registration
    assert led.rows()[0]["bytes"] == 300.0
    assert led.headroom()["kv_pages_addable"] == 3
    state["alive"] = False
    assert led.rows() == [] and led.headroom() is None
    state["alive"] = True   # dead providers stay unregistered
    assert led.rows() == []


def test_remove_scope_drops_entries_and_provider():
    led = memobs.MemoryLedger()
    led.set_entry("s1", "a", "k", 1)
    led.set_entry("s2", "b", "k", 2)
    led.register_provider("s1", lambda: {"rows": []})
    assert led.remove_scope("s1") == 2
    assert [r["owner"] for r in led.rows()] == ["b"]


def test_watermarks_tagged_by_active_span_and_peak_rows():
    led = memobs.MemoryLedger()
    tracing.enable()
    try:
        led.set_entry("s0", "params", "float32", 1000)
        with tracing.span("train.dispatch"):
            led.payload()
        led.set_entry("s0", "params", "float32", 5000)
        with tracing.span("llm.decode"):
            p = led.payload()
    finally:
        tracing.disable()
    assert p["watermarks"]["train.dispatch"]["bytes"] == 1000
    assert p["watermarks"]["llm.decode"]["bytes"] == 5000
    assert led.watermark_bytes() == 5000
    # delta-since-watermark baselines on the peak's row snapshot
    led.set_entry("s0", "params", "float32", 4000)
    led.set_entry("s0", "kv_pool", "free", 250)
    delta = led._delta_since_watermark(led.rows())
    assert {(d["owner"], d["delta_bytes"]) for d in delta} == \
        {("params", -1000.0), ("kv_pool", 250.0)}


def test_near_oom_one_shot_flight_dump(tmp_path, monkeypatch):
    from paddle_tpu.observability import flight
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        led = memobs.MemoryLedger()
        led.set_entry("s0", "params", "float32", 9500)
        monkeypatch.setattr(
            memobs, "_collect_device_stats",
            lambda: {"bytes_in_use": 9500.0, "bytes_limit": 10000.0,
                     "peak_bytes_in_use": 9500.0, "devices": 1})
        led.payload()
        dumps = [f for f in os.listdir(tmp_path) if "near_oom" in f]
        assert len(dumps) == 1, dumps
        rows = [json.loads(ln) for ln in open(tmp_path / dumps[0])]
        extra = next(r for r in rows if r.get("kind") == "extra")
        assert extra["used_fraction"] >= 0.9
        assert extra["memz"]["attributed_device_bytes"] == 9500
        led.payload()       # one-shot: a second crossing stays quiet
        assert len([f for f in os.listdir(tmp_path)
                    if "near_oom" in f]) == 1
        led.reset_one_shots()
        led.payload()       # re-armed (the dedupe-less dump path
        # overwrites the same file — still exactly one on disk)
        assert len([f for f in os.listdir(tmp_path)
                    if "near_oom" in f]) == 1
    finally:
        rec.uninstall()


def test_near_oom_arms_at_metrics_prescrape_too(tmp_path, monkeypatch):
    """update_gauges (the /metrics prescrape path) is a ledger read:
    crossing the threshold there must arm the snapshot — a replica
    scraped only via /metrics still gets its pre-crash baseline."""
    from paddle_tpu.observability import flight
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        led = memobs.MemoryLedger()
        led.set_entry("s0", "params", "float32", 9800)
        monkeypatch.setattr(
            memobs, "_collect_device_stats",
            lambda: {"bytes_in_use": 9800.0, "bytes_limit": 10000.0,
                     "peak_bytes_in_use": 9800.0, "devices": 1})
        led.update_gauges()
        assert [f for f in os.listdir(tmp_path) if "near_oom" in f]
    finally:
        rec.uninstall()


def test_headroom_mixed_page_sizes_bytes_exact():
    """Two pools with different page_bytes: the byte estimate stays
    exact (per-provider pages x its page size), page-denominated
    fields go None instead of lying in the larger pool's units."""
    led = memobs.MemoryLedger()
    led.register_provider("a", lambda: {
        "rows": [], "headroom_pages": 100, "page_bytes": 1024.0})
    led.register_provider("b", lambda: {
        "rows": [], "headroom_pages": 10, "page_bytes": 4096.0})
    h = led.headroom()
    assert h["kv_pages_addable"] == 110
    assert h["bytes_addable"] == 100 * 1024 + 10 * 4096
    assert h["page_bytes"] is None
    led.remove_scope("b")
    h = led.headroom()
    assert h["page_bytes"] == 1024.0 and h["bytes_addable"] == 102400


def test_is_oom_matching():
    assert memobs.is_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 2.5G"))
    assert memobs.is_oom(MemoryError("out of memory"))
    assert not memobs.is_oom(ValueError("shapes mismatch"))


def test_maybe_dump_oom_carries_table_and_is_one_shot(tmp_path):
    from paddle_tpu.observability import flight
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        memobs.set_entry("s0", "kv_pool", "free", 4096)
        exc = RuntimeError("RESOURCE_EXHAUSTED: failed to allocate")
        path = memobs.maybe_dump_oom(exc, component="llm")
        assert path and os.path.exists(path)
        rows = [json.loads(ln) for ln in open(path)]
        assert rows[0]["reason"] == "oom"
        extra = next(r for r in rows if r.get("kind") == "extra")
        assert extra["component"] == "llm"
        assert any(r["owner"] == "kv_pool"
                   for r in extra["memz"]["owners"])
        assert "delta_since_watermark" in extra
        # one dump per process; non-OOMs never dump
        assert memobs.maybe_dump_oom(exc) is None
        assert memobs.maybe_dump_oom(ValueError("x")) is None
    finally:
        rec.uninstall()


def test_oom_one_shot_not_consumed_without_recorder(tmp_path):
    """A recorder-less process hitting an OOM must NOT burn the
    one-shot: once a recorder is installed, the NEXT OOM still
    produces the forensic dump (same for the near-OOM latch)."""
    from paddle_tpu.observability import flight
    assert flight.get_flight_recorder() is None
    exc = RuntimeError("RESOURCE_EXHAUSTED: allocation failed")
    memobs.set_entry("s0", "kv_pool", "free", 64)
    assert memobs.maybe_dump_oom(exc) is None        # no recorder yet
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        path = memobs.maybe_dump_oom(exc)            # still armed
        assert path and os.path.exists(path)
    finally:
        rec.uninstall()


def test_disabled_is_one_flag_check(tmp_path):
    from paddle_tpu.observability import flight
    rec = flight.install_flight_recorder(str(tmp_path))
    try:
        memobs.disable()
        assert memobs.maybe_dump_oom(
            RuntimeError("RESOURCE_EXHAUSTED")) is None
        assert not os.listdir(tmp_path)
        # a disabled engine registers nothing
        from paddle_tpu.inference.llm import LLMEngine
        with LLMEngine(tiny_gpt(), max_seqs=2, page_size=4,
                       num_pages=16, prefill_buckets=(8,)) as eng:
            assert memobs.instance().rows() == []
            assert memobs.instance().headroom() is None
            del eng
    finally:
        memobs.enable()
        rec.uninstall()


# ---------------------------------------------------------------------------
# engine: attribution vs pool accounting, exactly
# ---------------------------------------------------------------------------


def test_engine_kv_attribution_tracks_page_table_exactly():
    """Ledger kv rows == page-table math across the cache lifecycle:
    admit (shared map + private suffix), divergence (page-granular
    CoW: a mid-page divergent prompt computes a private copy), cancel
    (pages reclaimed at the boundary), eviction (refcount-zero LRU
    residents reclaimed under pressure = headroom, counted once)."""
    from paddle_tpu.inference.llm import LLMEngine
    net = tiny_gpt()
    rng = np.random.RandomState(0)
    base = rng.randint(0, 97, 8).tolist()       # 2 full pages of 4
    led = memobs.instance()
    with LLMEngine(net, max_seqs=4, page_size=4, num_pages=32,
                   prefill_buckets=(16,)) as eng:
        usable = eng.num_pages - 1
        pb = eng._page_bytes

        def check():
            rows = kv_rows(led)
            free = len(eng._free_pages)
            shared = eng._cache.shared_page_count
            assert rows["free"] == free * pb
            assert rows["prefix_shared"] == shared * pb
            assert rows["private"] == (usable - free - shared) * pb
            assert rows["scratch"] == pb
            assert sum(rows.values()) == eng.num_pages * pb
            h = led.headroom()
            assert h["kv_pages_addable"] == \
                free + eng._cache.evictable_count

        check()                                   # idle pool
        r1 = eng.submit(base, max_new_tokens=4).result(timeout=240)
        check()                                   # prompt pages shared
        assert eng._cache.shared_page_count == 2
        # admit a prefix-sharing sibling and a mid-page divergent
        # prompt (CoW at page granularity: it misses the second
        # page's digest and computes a private copy)
        divergent = list(base)
        divergent[6] = (divergent[6] + 1) % 97
        r2 = eng.submit(base + base[:3],
                        max_new_tokens=4).result(timeout=240)
        r3 = eng.submit(divergent, max_new_tokens=4).result(timeout=240)
        assert r1["output_ids"] and r2["output_ids"] and \
            r3["output_ids"]
        check()
        # cancel mid-generation: pages come back at the drain boundary
        f = eng.submit(rng.randint(0, 97, 8).tolist(),
                       max_new_tokens=64)
        eng.cancel(f.request_id)
        with pytest.raises(Exception):
            f.result(timeout=240)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(s is None for s in eng._slots):
                break
            time.sleep(0.01)
        check()
        # quiescent: everything not cached is free again
        rows = kv_rows(led)
        assert rows["private"] == 0, rows


def test_engine_close_removes_rows_and_unexports_headroom():
    from paddle_tpu.inference.llm import LLMEngine
    led = memobs.instance()
    eng = LLMEngine(tiny_gpt(), max_seqs=2, page_size=4, num_pages=16,
                    prefill_buckets=(8,), decode_ticks_per_dispatch=4)
    led.update_gauges()
    assert default_registry().get("mem_headroom_pages") is not None
    assert any(r["owner"] == "decode_carry" for r in led.rows())
    eng.close()
    assert led.rows() == [] and led.headroom() is None
    led.update_gauges()
    # the family is GONE (a hole in federation), and stale mem_bytes
    # children are zeroed
    assert default_registry().get("mem_headroom_pages") is None
    fam = default_registry().get("mem_bytes")
    assert all(c.value == 0 for c in fam.children())


def test_forced_resource_exhausted_flight_dump_subprocess(tmp_path):
    """The OOM forensics acceptance, end to end in a real engine
    loop: a decode dispatch raising RESOURCE_EXHAUSTED produces a
    flight dump whose extra row carries the per-owner ledger table
    (kv_pool split included) — from a subprocess, like a real crash."""
    code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu.inference.llm import LLMEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.observability import flight

flight.install_flight_recorder({str(tmp_path)!r})
pt.seed(0)
cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                 num_heads=4, vocab_size=97,
                 max_position_embeddings=96, hidden_dropout=0.0,
                 attention_dropout=0.0)
eng = LLMEngine(GPTForCausalLM(cfg), max_seqs=2, page_size=4,
                num_pages=32, prefill_buckets=(8,))

def oom(*a, **kw):
    raise RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9663676416 bytes.")

eng._chunk_fn = oom
eng._decode_fn = oom
f = eng.submit(np.random.RandomState(0).randint(0, 97, 6).tolist(),
               max_new_tokens=4)
exc = None
try:
    f.result(timeout=240)
except Exception as e:
    exc = e
assert exc is not None and "RESOURCE_EXHAUSTED" in str(exc), exc
eng.close()
print("WORKER OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=560)
    assert p.returncode == 0 and "WORKER OK" in p.stdout, \
        (p.returncode, p.stdout[-500:], p.stderr[-2000:])
    dumps = [f for f in os.listdir(tmp_path) if "_oom" in f]
    assert dumps, os.listdir(tmp_path)
    rows = [json.loads(ln) for ln in open(tmp_path / dumps[0])]
    assert rows[0]["reason"] == "oom"
    extra = next(r for r in rows if r.get("kind") == "extra")
    assert extra["component"] == "llm"
    owners = {r["owner"] for r in extra["memz"]["owners"]}
    assert "kv_pool" in owners, owners
    assert "RESOURCE_EXHAUSTED" in extra["error"]


# ---------------------------------------------------------------------------
# model + checkpoint owners
# ---------------------------------------------------------------------------


def test_model_registers_params_buffers_opt_state_per_dtype():
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net),
        loss=nn.CrossEntropyLoss())
    rows = {(r["owner"], r["kind"]): r["bytes"]
            for r in memobs.instance().rows()}
    n_param_bytes = (8 * 16 + 16 + 16 * 2 + 2) * 4
    assert rows[("train_params", "float32")] == n_param_bytes
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (16, 1))
    model.train_batch([x], [y])
    rows = {(r["owner"], r["kind"]): r["bytes"]
            for r in memobs.instance().rows()}
    # Adam: m + v per param (+ scalar step counters, dtype-dependent)
    assert rows[("train_opt_state", "float32")] >= 2 * n_param_bytes
    # re-prepare resets the scope: exactly one generation of rows
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.1, parameters=net),
        loss=nn.CrossEntropyLoss())
    rows2 = [r for r in memobs.instance().rows()
             if r["owner"] == "train_params"]
    assert len(rows2) == 1 and rows2[0]["bytes"] == n_param_bytes


def test_checkpoint_staging_registers_host_bytes(tmp_path):
    from paddle_tpu.io.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    tree = {"w": np.ones((1000,), np.float32)}
    mgr.save(1, tree)
    row = next(r for r in memobs.instance().rows()
               if r["owner"] == "ckpt_staging")
    assert row["placement"] == "host" and row["bytes"] in (0.0, 4000.0)
    mgr.wait_until_finished()
    row = next(r for r in memobs.instance().rows()
               if r["owner"] == "ckpt_staging")
    assert row["bytes"] == 0.0
    p = memobs.instance().payload()
    assert p["attributed_host_bytes"] == 0.0


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def test_memz_statusz_metrics_over_http(monkeypatch):
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.observability import server as dbg
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with LLMEngine(tiny_gpt(), max_seqs=2, page_size=4,
                       num_pages=16, prefill_buckets=(8,)) as eng:
            eng.generate([[1, 2, 3, 4, 5]], max_new_tokens=3)
            mz = _get_json(base, "/memz")
            assert mz["enabled"] is True
            kinds = {(r["owner"], r["kind"]) for r in mz["owners"]}
            assert ("kv_pool", "free") in kinds
            assert "unattributed_bytes" in mz
            assert mz["headroom"]["kv_pages_addable"] > 0
            assert mz["watermarks"]
            st = _get_json(base, "/statusz")
            assert st["memory"]["enabled"] is True
            assert st["memory"]["attributed_device_bytes"] > 0
            assert st["memory"]["kv_pages_addable"] > 0
            # CPU: device_memory must be the explicit fallback dict,
            # not a misleading {}
            assert st["device_memory"], st
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=30) as r:
                text = r.read().decode()
            assert "mem_headroom_pages" in text
            assert 'mem_bytes{owner="kv_pool",kind="free"}' in text
            assert "mem_watermark_bytes" in text
    finally:
        srv.stop()


def test_statusz_device_memory_sample_cached_1s(monkeypatch):
    from paddle_tpu.observability import server as dbg
    calls = {"n": 0}

    def fake_sample(registry=None):
        calls["n"] += 1
        return {}

    monkeypatch.setattr(dbg, "sample_device_memory", fake_sample)
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for _ in range(5):          # a scrape storm
            st = _get_json(base, "/statusz")
        assert calls["n"] == 1, calls   # one sample per TTL window
        # and the CPU fallback replaced the empty dict
        assert "host_rss_bytes" in st["device_memory"], st
    finally:
        srv.stop()


def test_sample_device_memory_cpu_sets_host_rss_fallback():
    from paddle_tpu.observability.exporters import sample_device_memory
    out = sample_device_memory()
    assert out == {}                       # CPU: a hole, no device gauge
    fam = default_registry().get("device_memory_bytes")
    assert fam is None or not fam.children()
    rss = default_registry().get("host_rss_bytes")
    if memobs.host_rss_bytes() is not None:
        assert rss is not None and rss.value > 0


# ---------------------------------------------------------------------------
# fleet federation + bench ledger satellites
# ---------------------------------------------------------------------------


def test_fleet_headroom_federation_hole_semantics():
    """A replica that exports mem_headroom_pages enters the sum; one
    without the family (warming / no pool) and a down replica are
    HOLES — absent from sum AND denominator."""
    from paddle_tpu.serving.fleet import FleetScraper
    s = FleetScraper()
    s.record("r0", "# TYPE mem_headroom_pages gauge\n"
                   "mem_headroom_pages 40.0\n")
    s.record("r1", "# TYPE llm_tokens_generated counter\n"
                   "llm_tokens_generated 5\n")     # no pool yet
    s.record("r2", None)                           # down
    agg = s.aggregates()
    assert agg["mem_headroom_pages"] == 40.0
    assert agg["mem_headroom_replicas"] == 1
    reg = default_registry()
    assert reg.get("fleet_headroom_pages").value == 40.0
    assert reg.get("fleet_headroom_replicas").value == 1
    # nobody reports: sum is None (not 0-with-denominator)
    s.forget("r0")
    agg = s.aggregates()
    assert agg["mem_headroom_pages"] is None
    assert agg["mem_headroom_replicas"] == 0
    # per-replica federation rides the mem_ prefix
    s.record("r0", "# TYPE mem_headroom_pages gauge\n"
                   "mem_headroom_pages 12.0\n")
    text = s.render_prometheus()
    assert 'fleet_mem_headroom_pages{replica="r0"} 12.0' in text
    rep = s.replica_report()
    assert rep["r0"]["mem_headroom_pages"] == 12.0
    assert rep["r1"]["mem_headroom_pages"] is None


def test_bench_ledger_peak_mem_bytes_roundtrip(tmp_path, monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_ledger as bl
    path = str(tmp_path / "ledger.jsonl")
    # old-schema row (no peak_mem_bytes key at all) + new row
    old = bl.make_row("llm_bench", "wl", 10.0, "tok/s", backend="cpu")
    old.pop("peak_mem_bytes")
    bl.append_row(old, path=path)
    new = bl.make_row("llm_bench", "wl", 11.0, "tok/s", backend="cpu",
                      peak_mem_bytes=123456.0)
    assert new["peak_mem_bytes"] == 123456.0
    bl.append_row(new, path=path)
    rows = bl.read_ledger(path)
    assert len(rows) == 2
    assert "peak_mem_bytes" not in rows[0]
    assert rows[1]["peak_mem_bytes"] == 123456.0
    # --compare tolerates the absent field on the old row
    verdicts = bl.compare(rows)
    assert len(verdicts) == 1
    assert verdicts[0]["newest_peak_mem_bytes"] == 123456.0
    assert verdicts[0]["status"] in ("ok", "regressed")
    # and a row with peak populated still passes required validation
    assert bl.ci_gate(path=path) in (0, 3)


def test_llm_bench_peak_helper_reads_watermark():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import llm_bench
    memobs.set_entry("s0", "kv_pool", "free", 8192)
    peak = llm_bench._peak_mem_bytes()
    assert peak == 8192
