"""vision transforms/datasets, text ViterbiDecoder, hub
(ref: test_transforms.py, test_datasets.py, test_viterbi_decode_op.py,
test_hub.py)."""

import gzip
import os
import pickle
import struct
import textwrap

import numpy as np
import pytest

from paddle_tpu import hub
from paddle_tpu.text import ViterbiDecoder, viterbi_decode
from paddle_tpu.vision import datasets, transforms as T


# -- transforms ------------------------------------------------------------

def test_to_tensor_and_normalize():
    img = (np.arange(2 * 3 * 3) % 255).astype(np.uint8).reshape(3, 3, 2)
    t = T.Compose([T.ToTensor(),
                   T.Normalize(mean=[0.5, 0.5], std=[0.5, 0.5])])
    out = t(img)
    assert out.shape == (2, 3, 3)
    assert out.dtype == np.float32
    assert out.min() >= -1.0 and out.max() <= 1.0


def test_resize_bilinear_and_nearest():
    img = np.zeros((4, 4, 3), np.float32)
    img[2:, 2:] = 1.0
    out = T.Resize((8, 8))._apply_image(img)
    assert out.shape == (8, 8, 3)
    assert 0.0 < out[3, 3, 0] < 1.0  # interpolated edge
    outn = T.Resize((8, 8), "nearest")._apply_image(img)
    assert set(np.unique(outn)) == {0.0, 1.0}


def test_crops_and_flip():
    img = np.arange(36, dtype=np.float32).reshape(6, 6)
    assert T.CenterCrop(4)._apply_image(img).shape == (4, 4)
    assert T.RandomCrop(4)._apply_image(img).shape == (4, 4)
    assert T.RandomCrop(8)._apply_image(img).shape == (8, 8)  # padded
    flipped = T.RandomHorizontalFlip(prob=1.0)._apply_image(img)
    np.testing.assert_allclose(flipped, img[:, ::-1])
    rrc = T.RandomResizedCrop(5)._apply_image(
        np.random.rand(16, 16, 3).astype(np.float32))
    assert rrc.shape == (5, 5, 3)


# -- datasets --------------------------------------------------------------

def _write_mnist(root, n=10):
    os.makedirs(root, exist_ok=True)
    imgs = (np.arange(n * 28 * 28) % 256).astype(np.uint8)
    with gzip.open(os.path.join(
            root, "train-images-idx3-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">i", 2051) +
                struct.pack(">iii", n, 28, 28) + imgs.tobytes())
    labels = (np.arange(n) % 10).astype(np.uint8)
    with gzip.open(os.path.join(
            root, "train-labels-idx1-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">i", 2049) + struct.pack(">i", n) +
                labels.tobytes())


def test_mnist_idx_reader(tmp_path):
    _write_mnist(str(tmp_path))
    ds = datasets.MNIST(str(tmp_path), mode="train")
    assert len(ds) == 10
    img, lbl = ds[3]
    assert img.shape == (1, 28, 28) and img.dtype == np.float32
    assert lbl == 3


def test_mnist_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no network"):
        datasets.MNIST(str(tmp_path / "nope"))


def test_cifar_reader(tmp_path):
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    for i in range(1, 6):
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": np.zeros((5, 3072), np.uint8),
                         b"labels": [i % 10] * 5}, f)
    ds = datasets.Cifar10(str(tmp_path), mode="train")
    assert len(ds) == 25
    img, lbl = ds[0]
    assert img.shape == (3, 32, 32)


def test_dataset_folder_npy(tmp_path):
    for cls in ["cat", "dog"]:
        os.makedirs(tmp_path / cls)
        for i in range(3):
            np.save(tmp_path / cls / f"{i}.npy",
                    np.ones((8, 8, 3), np.float32))
    ds = datasets.DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    img, lbl = ds[5]
    assert img.shape == (8, 8, 3) and lbl == 1


# -- viterbi ---------------------------------------------------------------

def _brute_force_viterbi(pot, trans):
    s, n = pot.shape
    import itertools
    best, path = -1e30, None
    for tags in itertools.product(range(n), repeat=s):
        sc = pot[0, tags[0]] + sum(
            trans[tags[t - 1], tags[t]] + pot[t, tags[t]]
            for t in range(1, s))
        if sc > best:
            best, path = sc, tags
    return best, list(path)


def test_viterbi_matches_brute_force():
    rs = np.random.RandomState(0)
    pot = rs.randn(2, 5, 3).astype(np.float32)
    trans = rs.randn(3, 3).astype(np.float32)
    scores, paths = viterbi_decode(pot, trans)
    for b in range(2):
        ref_s, ref_p = _brute_force_viterbi(pot[b], trans)
        assert abs(float(scores[b]) - ref_s) < 1e-4
        assert list(np.asarray(paths[b])) == ref_p


def test_viterbi_decoder_layer():
    trans = np.eye(3, dtype=np.float32)
    dec = ViterbiDecoder(trans)
    pot = np.zeros((1, 4, 3), np.float32)
    pot[0, :, 1] = 1.0  # tag 1 always best
    scores, paths = dec(pot)
    assert list(np.asarray(paths[0])) == [1, 1, 1, 1]


# -- hub -------------------------------------------------------------------

def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        def tiny_model(width=4):
            "builds a tiny model"
            from paddle_tpu import nn
            return nn.Linear(width, width)
        def _private():
            pass
    """))
    assert hub.list(str(tmp_path)) == ["tiny_model"]
    assert "tiny" in hub.help(str(tmp_path), "tiny_model")
    m = hub.load(str(tmp_path), "tiny_model", width=6)
    assert m.weight.shape == (6, 6)
    with pytest.raises(NotImplementedError, match="zero-egress"):
        hub.load(str(tmp_path), "tiny_model", source="github")


def test_cifar100_reader(tmp_path):
    d = tmp_path / "cifar-100-python"
    d.mkdir()
    with open(d / "train", "wb") as f:
        pickle.dump({b"data": np.zeros((7, 3072), np.uint8),
                     b"fine_labels": list(range(7))}, f)
    ds = datasets.Cifar100(str(tmp_path), mode="train")
    assert len(ds) == 7
    img, lbl = ds[2]
    assert img.shape == (3, 32, 32) and int(lbl) == 2
