"""paddle_tpu.jit (to_static/save/load) and checkpoint manager tests
(ref: unittests/test_jit_save_load.py, dygraph_to_static suite,
auto_checkpoint tests — SURVEY.md §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit, nn
from paddle_tpu.io.checkpoint import (AutoCheckpoint, CheckpointManager,
                                      load_checkpoint, save_checkpoint)
from paddle_tpu.models import LeNet


def _net():
    pt.seed(7)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_to_static_layer_matches_eager():
    net = _net()
    net.eval()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8), jnp.float32)
    eager = np.asarray(net(x))
    static = jit.to_static(net)
    np.testing.assert_allclose(np.asarray(static(x)), eager, atol=1e-6)


def test_to_static_respects_train_mode():
    """Training mode keeps dropout live and updates BN buffers."""
    from paddle_tpu import nn as _nn
    net = _nn.Sequential(_nn.Linear(8, 8), _nn.BatchNorm1D(8))
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    static = jit.to_static(net)
    net.train()
    mean_before = np.asarray(net.state_dict()["1._mean"])
    static(x)
    mean_after = np.asarray(net.state_dict()["1._mean"])
    assert not np.allclose(mean_before, mean_after), \
        "BN running stats must update in train mode"
    net.eval()
    out1, out2 = static(x), static(x)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


def test_to_static_function_decorator():
    @jit.to_static
    def f(x):
        return jnp.sin(x) * 2

    x = jnp.ones((3,))
    np.testing.assert_allclose(np.asarray(f(x)),
                               np.sin(np.ones(3)) * 2, atol=1e-6)


def test_jit_save_load_roundtrip(tmp_path):
    net = _net()
    net.eval()
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    ref = np.asarray(net(x))
    path = str(tmp_path / "saved")
    jit.save(net, path, input_spec=[jit.InputSpec([4, 8], "float32")])
    assert os.path.exists(os.path.join(path, "program.stablehlo"))
    loaded = jit.load(path)
    out = np.asarray(loaded(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # params are swappable (serve-time update)
    state = {k: np.zeros_like(np.asarray(v))
             for k, v in loaded.state_dict().items()}
    loaded.set_state_dict(state)
    out0 = np.asarray(loaded(x))
    assert not np.allclose(out0, ref)


def test_jit_save_lenet(tmp_path):
    net = LeNet()
    net.eval()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 1, 28, 28),
                    jnp.float32)
    ref = np.asarray(net(x))
    path = str(tmp_path / "lenet")
    jit.save(net, path, input_spec=[jit.InputSpec([2, 1, 28, 28])])
    out = np.asarray(jit.load(path)(x))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_checkpoint_manager_save_restore(tmp_path):
    with CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2,
                           async_save=False) as mgr:
        tree = {"w": jnp.arange(8.0), "step": np.asarray(3)}
        mgr.save(0, tree)
        mgr.save(1, {"w": jnp.arange(8.0) * 2, "step": np.asarray(4)})
        assert mgr.latest_step() == 1
        got = mgr.restore(1)
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.arange(8.0) * 2)
        # rotation: keep last 2 of 3
        mgr.save(2, tree)
        mgr.wait_until_finished()
        assert 0 not in mgr.all_steps()


def test_checkpoint_sharded_roundtrip(tmp_path):
    """Sharded params save, restore into the same sharding."""
    from paddle_tpu import parallel
    mesh = parallel.init_mesh(dp=8)
    try:
        w = jax.device_put(
            jnp.arange(32.0).reshape(8, 4),
            jax.sharding.NamedSharding(
                mesh.mesh, jax.sharding.PartitionSpec("dp")))
        with CheckpointManager(str(tmp_path / "s"), async_save=False) as m:
            m.save(0, {"w": w})
            like = {"w": w}
            got = m.restore(0, like=like)
        np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(w))
        assert got["w"].sharding == w.sharding
    finally:
        parallel.set_mesh(None)


def test_save_load_checkpoint_full_state(tmp_path):
    net = _net()
    opt_state = {"m": jnp.zeros(4), "v": jnp.ones(4)}
    save_checkpoint(str(tmp_path / "full"), net,
                    optimizer_state=opt_state, step=17)
    net2 = _net()
    # perturb then restore
    sd = net2.state_dict()
    net2.set_state_dict({k: np.asarray(v) * 0 for k, v in sd.items()})
    tree = load_checkpoint(str(tmp_path / "full"), model=net2)
    assert int(tree["step"]) == 17
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(np.asarray(net2.state_dict()[k]),
                                   np.asarray(v))


def test_auto_checkpoint_resumes(tmp_path):
    """Simulated restart: epochs() skips completed epochs and restores
    the model (ref: TrainEpochRange semantics)."""
    d = str(tmp_path / "auto")
    net = _net()
    acp = AutoCheckpoint(d, net)
    seen = []
    for e in acp.epochs(4):
        seen.append(e)
        # mutate a param each epoch so restore is observable
        w = np.asarray(net.state_dict()["0.weight"]) + 1.0
        net.set_state_dict({**net.state_dict(), "0.weight": w},
                           strict=False)
        acp.commit(e)
        if e == 1:
            break  # "crash" after epoch 1 committed
    assert seen == [0, 1]
    w_after_crash = np.asarray(net.state_dict()["0.weight"])

    net2 = _net()
    acp2 = AutoCheckpoint(d, net2)
    seen2 = list(acp2.epochs(4))
    assert seen2 == [2, 3]
    np.testing.assert_allclose(np.asarray(net2.state_dict()["0.weight"]),
                               w_after_crash)
