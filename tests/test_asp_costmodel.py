"""ASP n:m sparsity + XLA-backed cost model.

Analogs: reference ASP tests (unittests/asp/test_asp_pruning_*,
test_asp_optimize.py — masks survive optimizer steps) and the
cost-model test (test_cost_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.incubate import asp


def test_create_mask_2_4_pattern():
    w = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    mask = asp.create_mask(w, n=2, m=4)
    groups = np.asarray(mask).reshape(-1, 4)
    assert (groups.sum(axis=1) == 2).all()
    # kept entries are the two largest magnitudes per group
    aw = np.abs(np.asarray(w)).reshape(-1, 4)
    for g in range(len(groups)):
        kept = set(np.where(groups[g])[0])
        top2 = set(np.argsort(aw[g])[-2:])
        assert kept == top2


def test_prune_model_and_density():
    pt.seed(0)
    net = nn.Sequential(("fc1", nn.Linear(16, 32)),
                        ("fc2", nn.Linear(32, 8)))
    assert asp.calculate_density(net.fc1.weight) == 1.0
    masks = asp.prune_model(net)
    assert set(masks) == {"fc1.weight", "fc2.weight"}
    for name in masks:
        w = net._get_by_path(name)
        assert asp.check_sparsity(np.asarray(w))
        np.testing.assert_allclose(asp.calculate_density(w), 0.5)
    # biases untouched
    assert asp.calculate_density(net.fc1.bias) in (0.0, 1.0)


def test_decorated_optimizer_preserves_masks():
    """Fine-tuning with asp.decorate keeps pruned weights at exactly 0
    (ref: test_asp_optimize)."""
    pt.seed(0)
    net = nn.Sequential(("fc1", nn.Linear(8, 16)), ("act", nn.ReLU()),
                        ("fc2", nn.Linear(16, 4)))
    asp.prune_model(net)
    opt = asp.decorate(pt.optimizer.Adam(learning_rate=0.05,
                                         parameters=net))
    from paddle_tpu import autograd
    crit = nn.MSELoss()
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(16, 8), jnp.float32)
    y = jnp.asarray(r.randn(16, 4), jnp.float32)
    losses = []
    for _ in range(10):
        tape = autograd.record(net)
        losses.append(float(tape.run(lambda: crit(net(x), y))))
        opt.step(tape.backward())
    assert losses[-1] < losses[0]
    for name in ("fc1.weight", "fc2.weight"):
        w = np.asarray(net._get_by_path(name))
        assert asp.check_sparsity(w), name
        assert asp.calculate_density(w) <= 0.5 + 1e-6


def test_embedding_weights_not_pruned():
    pt.seed(0)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(32, 16)
            self.fc = nn.Linear(16, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids))

    net = M()
    masks = asp.prune_model(net)
    assert "fc.weight" in masks and "emb.weight" not in masks


def test_mask_2d_algorithms_rejected_with_rationale():
    net = nn.Linear(8, 8)
    with pytest.raises(NotImplementedError, match="tensor cores"):
        asp.prune_model(net, mask_algo="mask_2d_best")


# -- cost model -------------------------------------------------------------

def test_cost_model_counts_matmul_flops():
    cm = pt.cost_model.CostModel()
    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    cost = cm.profile(lambda x, y: x @ y, (a, b))
    # 2*M*N*K = 2*128*64*256 = 4.19 MFLOP (XLA counts fused extras too)
    expected = 2 * 128 * 64 * 256
    assert 0.5 * expected <= cost.flops <= 2.0 * expected, cost.flops
    assert cost.bytes_accessed > 0
    assert "GFLOP" in cost.describe()


def test_cost_model_measures_wall_time():
    cm = pt.cost_model.CostModel()
    a = jnp.ones((64, 64), jnp.float32)
    cost = cm.profile_measure(lambda x: x @ x, (a,), iters=3)
    assert cost.measured_seconds is not None
    assert cost.measured_seconds > 0


def test_cost_model_ranks_big_vs_small():
    cm = pt.cost_model.CostModel()
    small = cm.profile(lambda x: x @ x, (jnp.ones((32, 32)),))
    big = cm.profile(lambda x: x @ x, (jnp.ones((256, 256)),))
    assert big.flops > 100 * small.flops


def test_conv_weights_pruned_via_2d_view():
    """Conv kernels [O, I, kh, kw] prune through the [O, I*kh*kw] view
    (the reference's reshape-then-mask convention)."""
    pt.seed(0)

    class C(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(8, 16, 3)
            self.fc = nn.Linear(16, 4)

        def forward(self, x):
            h = self.conv(x).mean(axis=(2, 3))
            return self.fc(h)

    net = C()
    masks = asp.prune_model(net)
    assert "conv.weight" in masks and "fc.weight" in masks
    w = np.asarray(net.conv.weight)
    assert asp.check_sparsity(w)
    np.testing.assert_allclose(asp.calculate_density(w), 0.5)


def test_asp_survives_jitted_model_fit():
    """Masks must hold through the hapi Model's compiled train step
    (decorate wraps apply_gradients, not just .step)."""
    pt.seed(0)
    net = nn.Sequential(("fc1", nn.Linear(8, 16)), ("act", nn.ReLU()),
                        ("fc2", nn.Linear(16, 4)))
    asp.prune_model(net)
    opt = asp.decorate(pt.optimizer.Adam(learning_rate=0.05,
                                         parameters=net))
    model = pt.Model(net)
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    r = np.random.RandomState(0)
    for _ in range(3):
        model.train_batch([r.randn(8, 8).astype("float32")],
                          [r.randn(8, 4).astype("float32")])
    # pull trained params back out of the compiled-step state
    model._sync_state_out()
    sd = model.network.state_dict()
    for name in ("fc1.weight", "fc2.weight"):
        w = np.asarray(sd[name])
        assert asp.check_sparsity(w), name
        assert abs(asp.calculate_density(w) - 0.5) < 1e-6


def test_frozen_param_training_via_record():
    """Optimizer.step updates only grad-bearing params — frozen
    (trainable=False) weights survive the dygraph idiom untouched."""
    from paddle_tpu import autograd
    from paddle_tpu.nn.layer import Parameter
    pt.seed(0)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.scale_frozen = Parameter(
                jnp.ones((4,)), trainable=False)

        def forward(self, x):
            return self.fc(x) * self.scale_frozen

    net = M()
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net)
    x = jnp.ones((2, 4))
    tape = autograd.record(net)
    tape.run(lambda: net(x).sum())
    assert "scale_frozen" not in tape.grads
    opt.step(tape.backward())
    np.testing.assert_allclose(np.asarray(net.scale_frozen),
                               np.ones(4))
