"""spawn + small top-level parity shims (ref: test_spawn_and_launch.py,
test_iinfo_and_finfo.py)."""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import spawn


def _worker_write(out_dir):
    import json
    rank = os.environ["PADDLE_TRAINER_ID"]
    n = os.environ["PADDLE_TRAINERS_NUM"]
    with open(os.path.join(out_dir, f"r{rank}.json"), "w") as f:
        json.dump({"rank": int(rank), "n": int(n)}, f)


def _worker_fail():
    raise ValueError("rank exploded")


def test_spawn_runs_ranks_with_env(tmp_path):
    spawn(_worker_write, args=(str(tmp_path),), nprocs=3)
    import json
    got = sorted(json.load(open(tmp_path / f"r{r}.json"))["rank"]
                 for r in range(3))
    assert got == [0, 1, 2]


def test_spawn_propagates_worker_error(tmp_path):
    with pytest.raises(RuntimeError, match="rank exploded"):
        spawn(_worker_fail, nprocs=2)


def test_iinfo_finfo():
    assert pt.iinfo("int8").max == 127
    assert pt.iinfo("int64").min < 0
    assert float(pt.finfo("float32").max) > 1e38
    assert float(pt.finfo("bfloat16").eps) == pytest.approx(0.0078125)


def test_version_and_sysconfig():
    assert pt.version.full_version.count(".") == 2
    assert os.path.isdir(pt.sysconfig.get_include())
    assert any(f.endswith(".cc") for f in
               os.listdir(pt.sysconfig.get_include()))


def test_callbacks_namespace_and_metric_accuracy():
    import jax.numpy as jnp
    assert pt.callbacks.EarlyStopping is not None
    logits = jnp.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    labels = jnp.asarray([1, 0, 0])
    acc = float(pt.metric.accuracy(logits, labels))
    assert acc == pytest.approx(2 / 3)
    acc2 = float(pt.metric.accuracy(logits, labels, k=2))
    assert acc2 == 1.0
