"""Overload brownout controller tests (ISSUE 20): AIMD limiter math
on an injected clock, ladder hysteresis/dwell/flap bounds, estimator
edges (a cold start never sheds), the gold-never-degraded pin, the
router integration with /overloadz over real HTTP, and the goodput
"shed" attribution.

Everything here runs on stub replicas and injected clocks — no
compiles; the seeded end-to-end storm lives in
``tools/chaos_soak.py --ci --overload`` and the CI comparison gate in
``tools/llm_bench.py --ci --overload``."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.inference.llm import AdmissionShed, OverloadShed
from paddle_tpu.observability import goodput
from paddle_tpu.observability.metrics import MetricRegistry
from paddle_tpu.serving import (AIMDLimiter, BrownoutLadder,
                                LocalReplica, OverloadController,
                                Router, ServiceTimeEstimator, SLOClass)
from paddle_tpu.serving.overload import LEVELS, TRANSITION_LOG_CAP


def ticking(start=100.0):
    """Injected monotonic clock: a one-cell list the test advances."""
    t = [start]
    return t, (lambda: t[0])


# ---------------------------------------------------------------------------
# AIMD limiter
# ---------------------------------------------------------------------------


def test_aimd_raise_cut_and_bounds():
    t, clk = ticking()
    lim = AIMDLimiter(floor=1, ceiling=8, initial=4, raise_step=1.0,
                      cut_factor=0.5, cut_interval_s=0.25, clock=clk)
    assert lim.limit("r0") == 4            # fresh name starts at initial
    lim.on_success("r0")
    assert lim.limit("r0") == 5
    for _ in range(10):
        lim.on_success("r0")
    assert lim.limit("r0") == 8            # ceiling clamp
    assert lim.on_miss("r0") is True
    assert lim.limit("r0") == 4            # multiplicative cut
    assert lim.has_room("r0", 3) and not lim.has_room("r0", 4)


def test_aimd_miss_storm_is_one_congestion_signal():
    t, clk = ticking()
    lim = AIMDLimiter(floor=1, ceiling=32, cut_interval_s=0.25,
                      clock=clk)
    assert lim.on_miss("r0") is True
    # 50 more misses inside the cooldown: the SAME overload event,
    # priced exactly once (the TCP discipline)
    assert not any(lim.on_miss("r0") for _ in range(50))
    assert lim.limit("r0") == 16 and lim.n_cuts == 1
    t[0] += 0.25                           # cooldown over → next cut
    assert lim.on_miss("r0") is True
    assert lim.limit("r0") == 8


def test_aimd_sustained_misses_converge_to_floor_not_below():
    t, clk = ticking()
    lim = AIMDLimiter(floor=2, ceiling=32, cut_interval_s=0.1,
                      clock=clk)
    for _ in range(20):
        t[0] += 0.2
        lim.on_miss("r0")
    assert lim.limit("r0") == 2            # floor, never 0: a floored
    lim.on_success("r0")                   # replica still probes up
    assert lim.limit("r0") == 3


def test_aimd_forget_restarts_from_initial():
    lim = AIMDLimiter(floor=1, ceiling=8)
    lim.on_miss("r0")
    assert lim.limit("r0") == 4
    lim.forget("r0")
    assert lim.limit("r0") == 8            # re-attached name re-earns
    assert lim.state() == {}               # no phantom entries


def test_aimd_rejects_bad_params():
    with pytest.raises(ValueError):
        AIMDLimiter(floor=4, ceiling=2)
    with pytest.raises(ValueError):
        AIMDLimiter(cut_factor=1.0)


# ---------------------------------------------------------------------------
# brownout ladder: one level per step, dwell, flap bound
# ---------------------------------------------------------------------------


def test_ladder_moves_one_level_per_dwell():
    t, clk = ticking()
    lad = BrownoutLadder(up_dwell_s=0.5, down_dwell_s=2.0, clock=clk)
    assert lad.step(True) == 1             # first move is immediate
    assert lad.step(True) == 1             # up dwell not served
    t[0] += 0.5
    assert lad.step(True) == 2
    t[0] += 0.5
    assert lad.step(True) == 3
    t[0] += 10.0
    assert lad.step(True) == 3             # max level, stays
    # dwell is measured from the last TRANSITION: the long quiet
    # stretch at max already served the flip backoff and down dwell,
    # so recovery starts now — but still one deliberate level per step
    assert lad.step(False) == 2
    assert lad.step(False) == 2            # down dwell (2s) not served
    t[0] += 1.0
    assert lad.step(False) == 2
    t[0] += 1.0
    assert lad.step(False) == 1
    t[0] += 2.0
    assert lad.step(False) == 0
    assert all(abs(e["to"] - e["from"]) == 1 for e in lad.transitions())


def test_ladder_square_wave_flap_count_is_logarithmic():
    t, clk = ticking()
    lad = BrownoutLadder(up_dwell_s=0.1, down_dwell_s=0.1,
                         backoff_base_s=1.0, backoff_cap_s=1e9,
                         healthy_dwell_s=1e9, max_level=1, clock=clk)
    # adversarial square wave: pressure toggles every tick for 200
    # simulated seconds. On a 1-level ladder EVERY move is a direction
    # flip; without the backoff curve this flaps ~2000 transitions —
    # the doubling quiet time makes the count logarithmic in elapsed.
    for i in range(2000):
        t[0] += 0.1
        lad.step(i % 2 == 0)
    assert lad.n_transitions <= 12, lad.transitions()
    assert all(abs(e["to"] - e["from"]) == 1 for e in lad.transitions())


def test_ladder_healthy_dwell_forgives_flip_history():
    t, clk = ticking()
    lad = BrownoutLadder(up_dwell_s=0.1, down_dwell_s=0.1,
                         backoff_base_s=0.5, backoff_cap_s=1e9,
                         healthy_dwell_s=3.0, clock=clk)
    lad.step(True)                         # 0 → 1
    t[0] += 1.0
    lad.step(False)                        # flip 1: 1 → 0
    t[0] += 1.0
    lad.step(True)                         # flip 2: 0 → 1
    assert lad._flips == 2
    t[0] += 10.0                           # quiet >> healthy_dwell
    lad.step(True)                         # a NEW storm, not a flip:
    assert lad._flips == 0                 # the streak is forgiven
    assert lad.level == 2


def test_ladder_force_clamps_and_walks_back():
    t, clk = ticking()
    lad = BrownoutLadder(up_dwell_s=0.1, down_dwell_s=0.1,
                         backoff_base_s=0.1, backoff_cap_s=0.1,
                         clock=clk)
    assert lad.force(99, reason="op_override") == 3    # clamped
    n = lad.n_transitions
    assert lad.force(7, reason="again") == 3           # no-op: no record
    assert lad.n_transitions == n
    assert lad.force(-5, reason="floor") == 0          # clamped low
    lad.force(2, reason="chaos")
    for _ in range(8):                     # live signal disagrees →
        t[0] += 1.0                        # hysteresis walks it back
        lad.step(False)
    assert lad.level == 0
    reasons = [e["reason"] for e in lad.transitions()]
    assert "op_override" in reasons and "chaos" in reasons


def test_ladder_transition_log_is_bounded():
    t, clk = ticking()
    lad = BrownoutLadder(clock=clk)
    for i in range(3 * TRANSITION_LOG_CAP):
        lad.force(i % 2 + 1, reason=f"swing{i}")
    assert len(lad.transitions()) == TRANSITION_LOG_CAP
    assert lad.n_transitions == 3 * TRANSITION_LOG_CAP


# ---------------------------------------------------------------------------
# service-time estimator
# ---------------------------------------------------------------------------


def test_estimator_cold_start_never_sheds():
    est = ServiceTimeEstimator(source=lambda: None)
    assert est.predict(100, 100) is None
    assert est.hopeless(None, 0.001) is False
    assert est.hopeless(5.0, None) is False    # no deadline, no verdict


def test_estimator_predict_math_and_safety_factor():
    est = ServiceTimeEstimator(safety_factor=3.0,
                               source=lambda: (100.0, 10.0))
    p = est.predict(50, 20, queue_s=1.0)
    assert p == pytest.approx(50 / 100 + 20 / 10 + 1.0)    # 3.5s
    assert est.hopeless(p, 1.0) is True        # 3.5 > 3.0
    assert est.hopeless(p, 1.2) is False       # 3.5 <= 3.6: conservative
    assert ServiceTimeEstimator(
        source=lambda: (0.0, 10.0)).predict(4, 4) is None


def test_estimator_rejects_optimistic_safety_factor():
    with pytest.raises(ValueError):
        ServiceTimeEstimator(safety_factor=0.5)


# ---------------------------------------------------------------------------
# controller: admission verdicts, the gold pin, outcome feedback
# ---------------------------------------------------------------------------


def mk_ctrl(**kw):
    t, clk = ticking()
    kw.setdefault("clock", clk)
    kw.setdefault("registry", MetricRegistry())
    kw.setdefault("estimator", ServiceTimeEstimator(source=lambda: None))
    return t, OverloadController(**kw)


def test_gold_is_never_degraded_at_any_level():
    _, ctrl = mk_ctrl(
        estimator=ServiceTimeEstimator(source=lambda: (1.0, 1.0)))
    for level in range(len(LEVELS)):
        ctrl.ladder.force(level, reason="pin")
        # gold with an absurd request and a hopeless deadline: still {}
        assert ctrl.admit("gold", 10_000, 10_000, 0.001) == {}
    assert ctrl.n_shed == {}


def test_brownout_l3_sheds_bronze_with_escalating_retry_after():
    _, ctrl = mk_ctrl(retry_after_base_s=0.1)
    assert ctrl.retry_after_s() == pytest.approx(0.1)    # level 0
    ctrl.ladder.force(3, reason="pin")
    out = ctrl.admit("bronze", 8, 8, 10.0)
    shed = out["shed"]
    assert isinstance(shed, OverloadShed)
    assert isinstance(shed, AdmissionShed)   # typed under the old base
    assert shed.reason == "brownout"
    assert shed.retry_after_s == pytest.approx(0.1 * 2 ** 3)
    assert ctrl.n_shed == {"brownout": 1}


def test_clamp_bronze_l2_trims_tokens_and_deadline():
    _, ctrl = mk_ctrl(bronze_max_new_tokens=16,
                      bronze_deadline_factor=0.5)
    ctrl.ladder.force(2, reason="pin")
    out = ctrl.admit("bronze", 8, 64, 10.0)
    assert out["max_new_tokens"] == 16
    assert out["deadline_factor"] == 0.5
    assert "shed" not in out
    # under the clamp cap: no clamp key, nothing to undo
    assert "max_new_tokens" not in ctrl.admit("bronze", 8, 4, 10.0)


def test_hopeless_shed_carries_prediction():
    _, ctrl = mk_ctrl(
        estimator=ServiceTimeEstimator(source=lambda: (100.0, 1.0)))
    out = ctrl.admit("bronze", 100, 50, 1.0)   # predicted ~51s >> 3s
    shed = out["shed"]
    assert shed.reason == "hopeless"
    assert shed.predicted_s == pytest.approx(51.0)
    assert shed.deadline_s == pytest.approx(1.0)
    # a feasible request sails through WITH its prediction attached
    ok = ctrl.admit("bronze", 100, 50, 120.0)
    assert "shed" not in ok and ok["predicted_s"] == pytest.approx(51.0)


def test_on_outcome_drives_limiter_ewma_and_histogram():
    reg = MetricRegistry()
    _, ctrl = mk_ctrl(registry=reg,
                      limiter=AIMDLimiter(floor=1, ceiling=8, initial=4))
    ctrl.on_outcome("r0", "ok", predicted_s=2.0, latency_s=1.0)
    assert ctrl.limiter.limit("r0") == 5
    ctrl.on_outcome("r0", "deadline", predicted_s=2.0, latency_s=4.0)
    assert ctrl.limiter.limit("r0") == 2
    ctrl.on_outcome(None, "ok", predicted_s=None, latency_s=0.5)
    h = reg.get("overload_estimate_error_ratio")
    # two observations carried predictions (ok + deadline); the
    # predictionless one is not the estimator's error to own
    (child,) = h.children()
    assert child.count == 2


# ---------------------------------------------------------------------------
# router integration: shed futures, cooldown, /overloadz over HTTP
# ---------------------------------------------------------------------------


class StubReplica:
    """Echo replica for router-integration tests (no compiles)."""

    def __init__(self):
        self.calls = []
        self._mu = threading.Lock()

    def submit(self, prompt_ids, **kw):
        with self._mu:
            self.calls.append(dict(kw, prompt_ids=list(prompt_ids)))
        return {"output_ids": [1] * kw.get("max_new_tokens", 1),
                "prompt_ids": list(prompt_ids)}

    def health(self):
        return "healthy"

    def cancel(self, request_id):
        return False

    def close(self):
        pass


def mk_router(replicas, **kw):
    kw.setdefault("health_poll_interval", 0.05)
    kw.setdefault("slo_classes", {
        "gold": SLOClass("gold", deadline_s=30.0, target=0.999),
        "bronze": SLOClass("bronze", deadline_s=30.0, target=0.9),
    })
    return Router(replicas, **kw)


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def test_router_overload_end_to_end_with_overloadz_http():
    from paddle_tpu.observability import server as dbg
    _, ctrl = mk_ctrl()
    stubs = {"r0": StubReplica(), "r1": StubReplica()}
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # no controller bound anywhere → explicit 404, never {}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base, "/overloadz")
        assert ei.value.code == 404
        with mk_router(stubs, overload=ctrl) as router:
            ctrl.ladder.force(3, reason="test_pin")
            out = router.submit([1, 2, 3], max_new_tokens=2,
                                slo="gold", tenant="acme",
                                deadline=30.0).result(timeout=30)
            assert out["output_ids"] == [1, 1]     # gold rides through
            fut = router.submit([4, 5, 6], max_new_tokens=2,
                                slo="bronze", tenant="hobby",
                                deadline=30.0)
            with pytest.raises(OverloadShed) as shed:
                fut.result(timeout=30)
            assert shed.value.reason == "brownout"
            assert shed.value.retry_after_s > 0
            oz = _get(base, "/overloadz")
            (payload,) = oz["overload"].values()
            assert payload["level"] == 3
            assert payload["level_name"] == "gold_only"
            assert payload["shed"]["brownout"] >= 1
            assert payload["protected_classes"] == ["gold"]
            assert any(e["reason"] == "test_pin"
                       for e in payload["transitions"])
            # the poll hook is actually ticking the controller
            deadline = time.monotonic() + 10
            while ctrl.n_ticks == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ctrl.n_ticks > 0
        # close() unbinds: the provider is gone and the page 404s again
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base, "/overloadz")
        assert ei.value.code == 404
        assert ctrl._overloadz() is None
    finally:
        srv.stop()


def test_router_honors_replica_retry_after_cooldown():
    stubs = {"r0": StubReplica(), "r1": StubReplica()}
    with mk_router(stubs) as router:
        with router._mu:
            router._retry_until["r0"] = time.monotonic() + 60.0
        for i in range(6):
            router.submit([i, i + 1, i + 2], max_new_tokens=1,
                          slo="bronze", tenant="t").result(timeout=30)
        assert stubs["r0"].calls == []     # cooling replica skipped
        assert len(stubs["r1"].calls) == 6
        # cooldown state dies with the fleet entry
        router.detach("r0")
        assert router._retry_until == {}


def test_l2_clamp_applies_inside_router_submit():
    _, ctrl = mk_ctrl()
    ctrl.ladder.force(2, reason="pin")
    stubs = {"r0": StubReplica()}
    with mk_router(stubs, overload=ctrl) as router:
        out = router.submit([1, 2, 3], max_new_tokens=64,
                            slo="bronze", tenant="hobby",
                            deadline=30.0).result(timeout=30)
        assert len(out["output_ids"]) == ctrl.bronze_max_new_tokens
        gold = router.submit([1, 2, 3], max_new_tokens=64,
                             slo="gold", tenant="acme",
                             deadline=30.0).result(timeout=30)
        assert len(gold["output_ids"]) == 64   # gold never clamped


# ---------------------------------------------------------------------------
# goodput attribution: a shed is badput with a name
# ---------------------------------------------------------------------------


def test_shed_requests_attribute_goodput_shed_bucket():
    assert "shed" in goodput.BUCKETS
    goodput.reset()
    was = goodput.enabled()
    goodput.enable()
    try:
        _, ctrl = mk_ctrl()
        ctrl.ladder.force(3, reason="pin")
        with mk_router({"r0": StubReplica()}, overload=ctrl) as router:
            fut = router.submit([9, 8, 7], max_new_tokens=2,
                                slo="bronze", tenant="hobby",
                                deadline=30.0)
            with pytest.raises(OverloadShed):
                fut.result(timeout=30)
        totals = goodput.instance().totals()
        # the shed interval is tiny (admission check, not service) but
        # it is NOTED: badput with a name, never an unattributed hole
        assert totals["shed"] > 0.0
    finally:
        goodput.reset()
        (goodput.enable if was else goodput.disable)()
