"""Device-resident decode loop (ISSUE 10): N decode ticks fused into
ONE ``lax.scan`` dispatch (`LLMEngine(decode_ticks_per_dispatch=N)`).

Contract under test: fused slabs are TOKEN-IDENTICAL to the per-tick
path (N=1) — greedy and seeded sampling, prefix cache on or off,
EOS/length finishing mid-slab, page boundaries crossed inside a slab,
slabs interleaved with chunked prefill — because the scan body IS the
per-tick program and sampling keys fold (nonce, position) only.
Failure semantics degrade by at most one slab: cancel/deadline
submitted mid-slab resolve at the slab boundary with their KV pages
freed. N=1 must keep the per-tick program (no scan op compiled)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import (DecodeCarry, LLMEngine,
                                      RequestCancelled)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.reliability.retry import DeadlineExceeded


def tiny_gpt():
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def run(net, prompts, gen, n, *, temperature=0.0, cache=True,
        eos=None, page_size=4, num_pages=128, chunk=None, seed=0,
        max_seqs=4):
    eng = LLMEngine(net, max_seqs=max_seqs, page_size=page_size,
                    num_pages=num_pages, prefill_buckets=(16,),
                    prefix_cache=cache, prefill_chunk=chunk,
                    eos_token_id=eos, seed=seed,
                    decode_ticks_per_dispatch=n)
    with eng:
        outs = eng.generate(prompts, max_new_tokens=gen,
                            temperature=temperature)
    # leak audit rides every parity run: the pool is whole after close
    assert len(eng._free_pages) == eng.num_pages - 1, \
        f"KV pages leaked at N={n}"
    return outs, eng


@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "seeded"])
@pytest.mark.parametrize("cache", [True, False],
                         ids=["cache-on", "cache-off"])
def test_token_identity_across_n(cache, temperature):
    """N ∈ {1, 4, 8} × prefix cache on/off × greedy/seeded sampling:
    fused slabs reproduce the per-tick stream exactly."""
    net = tiny_gpt()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 11, 3)]
    ref, _ = run(net, prompts, 10, 1, temperature=temperature,
                 cache=cache, seed=3)
    for n in (4, 8):
        got, eng = run(net, prompts, 10, n, temperature=temperature,
                       cache=cache, seed=3)
        assert [o["output_ids"] for o in got] == \
            [o["output_ids"] for o in ref], f"stream diverged at N={n}"
        assert not any(o["truncated"] for o in got)
        # the knob did what it says: fewer host dispatches than ticks
        assert eng.n_host_dispatches < eng.n_decode_ticks


def test_mid_slab_eos_masking():
    """A slot hitting EOS mid-slab stops there: ticks past its EOS
    are masked no-ops on device (budget zeroed), the host never
    surfaces them, and the stream equals N=1 with the same EOS."""
    net = tiny_gpt()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, 5).tolist(),
               rng.randint(0, 97, 7).tolist()]
    # pick an eos each prompt actually emits mid-generation at N=1
    base, _ = run(net, prompts, 12, 1)
    eos = base[0]["output_ids"][5]
    ref, _ = run(net, prompts, 12, 1, eos=eos)
    got, eng = run(net, prompts, 12, 8, eos=eos)
    assert [o["output_ids"] for o in got] == \
        [o["output_ids"] for o in ref]
    # prompt 0 genuinely finished early (mid-slab), not at the limit
    assert len(got[0]["output_ids"]) < 12
    assert got[0]["output_ids"][-1] == eos


def test_page_boundary_crossing_inside_slab():
    """page_size=2 with N=8: every slab crosses multiple page
    boundaries; pre-reservation at slab entry keeps the scan body
    shape-stable and the stream identical to N=1."""
    net = tiny_gpt()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 97, 5).tolist()]
    ref, _ = run(net, prompts, 16, 1, page_size=2, num_pages=64)
    got, _ = run(net, prompts, 16, 8, page_size=2, num_pages=64)
    assert got[0]["output_ids"] == ref[0]["output_ids"]
    assert not got[0]["truncated"]


def test_slab_shrinks_under_page_pressure():
    """A pool too small to pre-reserve N tokens shrinks the slab to
    the coverable boundary instead of truncating: the request still
    completes (or truncates) exactly as N=1 does."""
    net = tiny_gpt()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 97, 5).tolist()]
    # single request: prompt needs 3 pages (ps=2), generation wants
    # 20 more tokens through a pool holding only 16 positions — the
    # second slab can cover just 3 of its 8 ticks (pool dry at the
    # 9th page), so it must shrink, and the request then truncates
    # exactly where N=1 does
    for pages in (9, 16):
        ref, _ = run(net, prompts, 20, 1, page_size=2,
                     num_pages=pages, cache=False)
        got, eng = run(net, prompts, 20, 8, page_size=2,
                       num_pages=pages, cache=False)
        assert got[0]["output_ids"] == ref[0]["output_ids"], pages
        assert got[0]["truncated"] == ref[0]["truncated"], pages
        if pages == 9:
            # the tight pool really did force shrunk slabs: more than
            # one distinct decode_loop signature compiled
            loops = [s for s in eng._shape_signatures
                     if s[0] == "decode_loop"]
            assert len(loops) > 1, loops


def test_max_new_tokens_not_multiple_of_slab():
    """gen_len % N != 0: the tail slab runs with a partial budget
    (masked ticks beyond it) and emits exactly the requested count."""
    net = tiny_gpt()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 97, 5).tolist()]
    ref, _ = run(net, prompts, 10, 1)
    got, eng = run(net, prompts, 10, 8)
    assert got[0]["output_ids"] == ref[0]["output_ids"]
    assert len(got[0]["output_ids"]) == 10
    # one compiled slab program serves both full and partial slabs
    # (budgets are data, not shapes)
    assert [s for s in eng._shape_signatures
            if s[0] == "decode_loop"] == [("decode_loop", 8)]


def test_cancel_and_deadline_resolve_within_slab_boundary():
    """Cancel/deadline submitted mid-slab resolve at the next slab
    boundary (not after the full generation) and free their pages."""
    net = tiny_gpt()
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,),
                    decode_ticks_per_dispatch=8)
    with eng:
        rng = np.random.RandomState(5)
        fut = eng.submit(rng.randint(0, 97, 5).tolist(),
                         max_new_tokens=80)
        while eng.n_decode_ticks < 8:     # generation underway
            time.sleep(0.005)
        assert eng.cancel(fut.request_id)
        with pytest.raises(RequestCancelled):
            fut.result(timeout=60)
        ticks_at_cancel = eng.n_decode_ticks
        # an expired deadline resolves typed at the next boundary —
        # hopeless by construction (the chaos-soak idiom): a small-
        # but-positive budget races the slab wall clock and a warm
        # engine can legitimately finish 80 tokens inside it
        fut2 = eng.submit(rng.randint(0, 97, 5).tolist(),
                          max_new_tokens=80, deadline=-1.0)
        with pytest.raises(DeadlineExceeded):
            fut2.result(timeout=60)
        # the cancelled request stopped within ~one slab of the
        # cancel (the loop never ran fut's remaining ~70 tokens)
        assert eng.n_decode_ticks < ticks_at_cancel + 8 + 70
    assert len(eng._free_pages) == eng.num_pages - 1, "pages leaked"


def test_fused_ticks_interleave_with_chunked_prefill():
    """A long prompt admitted mid-decode prefills in chunks BETWEEN
    slabs (tick history brackets 'p' with 'D'), and both requests'
    streams match the per-tick run."""
    net = tiny_gpt()
    rng = np.random.RandomState(6)
    short = rng.randint(0, 97, 4).tolist()
    long = rng.randint(0, 97, 40).tolist()

    def interleaved(n):
        # mixed_tick off: this test witnesses the two-op interleave
        # ('p' chunks bracketed by 'D' slabs); the ragged mixed tick
        # has its own gate in test_mixed_ragged.py
        eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=128,
                        prefill_buckets=(64,), prefill_chunk=8,
                        decode_ticks_per_dispatch=n, mixed_tick=False)
        with eng:
            f1 = eng.submit(short, max_new_tokens=24)
            while not eng.n_decode_ticks:   # f1 decoding
                time.sleep(0.002)
            f2 = eng.submit(long, max_new_tokens=8)
            outs = [f1.result(timeout=120), f2.result(timeout=120)]
            hist = "".join(eng.tick_history)
        assert len(eng._free_pages) == eng.num_pages - 1
        return outs, hist

    ref, _ = interleaved(1)
    got, hist = interleaved(4)
    assert [o["output_ids"] for o in got] == \
        [o["output_ids"] for o in ref]
    # witness: at least one prefill chunk ran between decode slabs
    assert "DpD" in hist.replace("pp", "p") or "Dp" in hist, hist


def test_n1_compiles_zero_scan_ops():
    """The HLO pin (PR 9 discipline): at N=1 the engine keeps the
    per-tick program — the slab jit is NEVER traced (zero scan
    programs compiled), and the per-tick decode HLO carries only the
    RNG's internal loops. Positive control: the N>1 slab program adds
    EXACTLY ONE loop op over the per-tick body — the scan."""
    net = tiny_gpt()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 97, 5).tolist()]
    _, eng1 = run(net, prompts, 8, 1)
    assert not any(s[0] == "decode_loop"
                   for s in eng1._shape_signatures)
    assert eng1._slab_fn._cache_size() == 0, \
        "N=1 engine compiled a slab program"
    b = eng1.max_seqs
    zeros = jnp.zeros((b,), jnp.int32)
    tick_hlo = eng1._decode_fn.lower(
        eng1._params, eng1._buffers, zeros, zeros,
        jnp.zeros((b, eng1.pages_per_seq), jnp.int32), zeros,
        eng1.k_pages, eng1.v_pages, jnp.zeros((b,), jnp.float32),
        zeros, eng1._key).as_text()

    _, eng4 = run(net, prompts, 8, 4)
    carry = DecodeCarry(
        tokens=zeros, positions=zeros, budgets=zeros,
        k_pages=eng4.k_pages, v_pages=eng4.v_pages)
    slab_hlo = eng4._slab_fn.lower(
        eng4._params, eng4._buffers, carry,
        jnp.zeros((b, eng4.pages_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.float32), zeros, eng4._key, 4).as_text()
    n_tick = tick_hlo.count("stablehlo.while")
    n_slab = slab_hlo.count("stablehlo.while")
    assert n_slab == n_tick + 1, (
        f"slab program should add exactly the scan loop over the "
        f"per-tick body: {n_tick} vs {n_slab} while ops")


def test_recompile_guard_counts_slab_kinds_separately():
    """Satellite: decode_loop signatures are their own kind — an
    N-knob sweep adds decode_loop entries without consuming
    decode_step ones, so the 4096 cap can't be blown silently."""
    net = tiny_gpt()
    rng = np.random.RandomState(8)
    prompts = [rng.randint(0, 97, 5).tolist()]
    _, eng1 = run(net, prompts, 6, 1)
    kinds1 = {s[0] for s in eng1._shape_signatures}
    assert "decode_step" in kinds1 and "decode_loop" not in kinds1
    _, eng8 = run(net, prompts, 6, 8)
    kinds8 = {s[0] for s in eng8._shape_signatures}
    assert "decode_loop" in kinds8 and "decode_step" not in kinds8
    assert ("decode_loop", 8) in eng8._shape_signatures


def test_lookahead_conflict_raises():
    net = tiny_gpt()
    with pytest.raises(ValueError, match="lookahead"):
        LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                  prefill_buckets=(16,), lookahead=2,
                  decode_ticks_per_dispatch=4)


def test_flag_default_feeds_engine():
    from paddle_tpu.core import flags
    net = tiny_gpt()
    flags.set_flags({"decode_ticks_per_dispatch": 4})
    try:
        eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                        prefill_buckets=(16,))
        assert eng.decode_ticks_per_dispatch == 4
        eng.close()
    finally:
        flags.set_flags({"decode_ticks_per_dispatch": 1})


def test_inline_prefill_first_token_is_async():
    """Satellite: the speculative (inline-prefill) path no longer
    blocks on int(nxt) at admission — the first token arrives through
    the drain, TTFT is observed at fetch, and a 1-token request
    resolves through the drain path."""
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    pt.seed(1)
    dcfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                      num_heads=2, vocab_size=97,
                      max_position_embeddings=96, hidden_dropout=0.0,
                      attention_dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 97, 6).tolist()]
    want = [np.asarray(net.generate(jnp.asarray([p]),
                                    max_new_tokens=8))[0, len(p):]
            .tolist() for p in prompts]
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(16,), draft_net=draft,
                    spec_tokens=3)
    with eng:
        outs = eng.generate(prompts, max_new_tokens=8)
        assert outs[0]["output_ids"] == want[0]
        assert outs[0]["ttft_s"] is not None
        # the 1-token edge: the only token rides the drain
        one = eng.generate(prompts, max_new_tokens=1)
        assert one[0]["output_ids"] == want[0][:1]
    assert len(eng._free_pages) == eng.num_pages - 1
