"""Crash-consistent checkpoints: a worker SIGKILLed mid-
``CheckpointManager.save`` must never cost more than the uncommitted
step — ``latest_step()`` still restores cleanly and the directory
still accepts new saves. The tmp-dir cleanup comment in
io/checkpoint.py documented this; nothing pinned it until now. The
worker body lives in tools/chaos_soak.py (``--ckpt-worker``) so the
chaos gate and this test exercise the same code."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "chaos_soak.py")


def _kill_mid_save(ckpt_dir, kill_at, sig, jitter_s=0.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, TOOL, "--ckpt-worker", ckpt_dir, "12"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
    killed_during = None
    for line in p.stdout:
        if line.startswith("SAVING "):
            k = int(line.split()[1])
            if k >= kill_at:
                if jitter_s:
                    time.sleep(jitter_s)
                p.send_signal(sig)
                killed_during = k
                break
    p.wait(timeout=120)
    assert killed_during is not None, "worker finished before the kill"
    return killed_during


@pytest.mark.parametrize("jitter_s", [0.0, 0.02, 0.05],
                         ids=["at-announce", "early-write", "mid-write"])
def test_sigkill_mid_save_latest_step_still_restores(tmp_path, jitter_s):
    from paddle_tpu.io.checkpoint import CheckpointManager
    ckpt_dir = str(tmp_path / "ckpt")
    killed_during = _kill_mid_save(ckpt_dir, kill_at=3,
                                   sig=signal.SIGKILL,
                                   jitter_s=jitter_s)
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    latest = mgr.latest_step()
    # the step being written may or may not have committed; anything
    # older must have survived
    assert latest is not None and latest >= killed_during - 1, (
        f"SIGKILL during save of step {killed_during} lost committed "
        f"steps (latest={latest})")
    tree = mgr.restore(latest)
    np.testing.assert_array_equal(
        tree["w"], np.arange(2048, dtype=np.int64) + latest)
    assert int(tree["step"]) == latest
    # tmp-dir debris from the kill must not wedge the next incarnation
    assert mgr.save(latest + 1,
                    {"w": np.arange(2048, dtype=np.int64) + latest + 1,
                     "step": np.asarray(latest + 1)})
    mgr.wait_until_finished()
    assert mgr.latest_step() == latest + 1
    mgr.close()
