"""Multi-worker DataLoader tests (ref: the reference's
_DataLoaderIterMultiProcess, fluid/dataloader/dataloader_iter.py:342,
and its test_dataloader_* unittests: same-results parity + worker
sharding of IterableDataset via get_worker_info)."""

import time

import numpy as np

from paddle_tpu.io import (DataLoader, Dataset, IterableDataset,
                           get_worker_info)

import pytest
pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


class _SlowDataset(Dataset):
    def __init__(self, n=32, delay=0.02):
        self.n = n
        self.delay = delay

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        time.sleep(self.delay)  # stand-in for CPU-bound augmentation
        return np.full((4,), i, np.float32), np.int64(i)


def _collect(loader):
    xs = []
    for x, y in loader:
        xs.append(np.asarray(x))
    return np.concatenate(xs)


def test_map_workers_match_serial():
    ds = _SlowDataset(n=16, delay=0.0)
    serial = _collect(DataLoader(ds, batch_size=4, num_workers=0,
                                 to_device=False))
    par = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                              to_device=False))
    np.testing.assert_array_equal(serial, par)


def test_map_workers_speedup_on_slow_transform():
    ds = _SlowDataset(n=32, delay=0.02)  # 0.64s of pure transform time

    t0 = time.perf_counter()
    _collect(DataLoader(ds, batch_size=4, num_workers=0, to_device=False))
    serial = time.perf_counter() - t0

    t0 = time.perf_counter()
    _collect(DataLoader(ds, batch_size=4, num_workers=4, to_device=False))
    par = time.perf_counter() - t0

    # 4 workers on a sleep-bound transform: expect ~4x; accept >1.8x to
    # stay robust on loaded CI machines
    assert par < serial / 1.8, (serial, par)


class _ShardedStream(IterableDataset):
    """Shards itself across workers via get_worker_info (ref contract)."""

    def __init__(self, n=24):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        wid = info.id if info else 0
        nw = info.num_workers if info else 1
        for i in range(wid, self.n, nw):
            yield np.float32(i)


def test_iterable_workers_shard_without_duplication():
    out = []
    for batch in DataLoader(_ShardedStream(24), batch_size=4,
                            num_workers=3, to_device=False):
        out.extend(np.asarray(batch).tolist())
    assert sorted(out) == [float(i) for i in range(24)]
    assert len(out) == 24  # no duplication across workers


def test_num_workers_zero_unchanged():
    out = []
    for batch in DataLoader(_ShardedStream(8), batch_size=4,
                            num_workers=0, to_device=False):
        out.extend(np.asarray(batch).tolist())
    assert out == [float(i) for i in range(8)]
