"""REAL multi-process distributed execution (VERDICT r2 item 3): spawn
2 OS processes, bring up jax.distributed via init_parallel_env, run a
cross-process all-reduce and a DP training run, and assert loss parity
with a single-process baseline — the reference's signature test trick
(fluid/tests/unittests/test_dist_base.py:786 spawning trainer
subprocesses and comparing losses; test_collective_api_base.py:19)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dist_worker  # noqa: E402

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_two_process_allreduce_and_dp_parity(tmp_path):
    from paddle_tpu import distributed

    ctx = distributed.spawn(dist_worker.allreduce_and_dp_train,
                            args=(str(tmp_path),), nprocs=2, join=False)
    ok = ctx.join(timeout=420)
    # on timeout, kill stragglers so the suite never wedges
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, "multi-process run failed or timed out"

    out = json.loads((tmp_path / "rank0.json").read_text())
    # all-reduce over 2 processes: 1 + 2
    assert out["allreduce"] == 3.0
    base = dist_worker.baseline_losses()
    np.testing.assert_allclose(out["losses"], base, rtol=2e-4, atol=2e-5,
                               err_msg="2-process DP losses diverge from "
                                       "single-process baseline")


def test_sharded_embedding_exceeds_single_host_budget(tmp_path):
    """Key-range-sharded host embedding across 2 OS processes (VERDICT
    r3 ask #2): the aggregate table exceeds any single per-host row
    budget, WideDeep trains with loss parity vs the unsharded
    single-process run, and a mid-run generation restart from sharded
    snapshots resumes losslessly."""
    from paddle_tpu import distributed

    budget = 2000
    ctx = distributed.spawn(dist_worker.sharded_embedding_train,
                            args=(str(tmp_path), 12, 8, budget),
                            nprocs=2, join=False)
    ok = ctx.join(timeout=420)
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, "sharded-embedding multi-process run failed or timed out"

    r0 = json.loads((tmp_path / "rank0.json").read_text())
    r1 = json.loads((tmp_path / "rank1.json").read_text())
    base, total_rows = dist_worker.sharded_embedding_baseline(12, 8)

    # capacity law: the whole table fits NO single host budget, but the
    # per-host shards each do — capacity scaled with the cluster.
    # (The worker itself asserts the sharded restore round-trips every
    # local row; the budget check raises in-step if a host overflows.)
    assert total_rows > budget, (total_rows, budget)
    assert r0["rows_final"] <= budget and r1["rows_final"] <= budget
    assert r0["rows_final"] + r1["rows_final"] == total_rows
    assert min(r0["rows_step8"], r1["rows_step8"]) > 0

    # loss parity with the unsharded reference, across the restart
    np.testing.assert_allclose(r0["losses"], base, rtol=2e-4, atol=2e-5,
                               err_msg="sharded-embedding losses diverge "
                                       "from unsharded baseline")
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=1e-6)


@pytest.mark.parametrize("axis", ["tp", "fsdp"])
def test_two_process_model_axis_parity(tmp_path, axis):
    """Cross-process MODEL parallelism (VERDICT r3 weak #6): tiny GPT
    on a 2-OS-process tp=2 / fsdp=2 mesh. Asserts from BOTH ranks: loss
    parity with the single-process dense baseline, identical losses
    across ranks, and that the MLP weight physically lived split
    across the two processes (tp shards the 'mlp' dim; fsdp shards dim
    0 of every 2D weight)."""
    from paddle_tpu import distributed

    ctx = distributed.spawn(dist_worker.model_axis_train,
                            args=(str(tmp_path), axis), nprocs=2,
                            join=False)
    ok = ctx.join(timeout=420)
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, f"{axis}=2 multi-process run failed or timed out"

    r0 = json.loads((tmp_path / "rank0.json").read_text())
    r1 = json.loads((tmp_path / "rank1.json").read_text())
    base = dist_worker.model_axis_baseline()

    for r in (r0, r1):  # the weight was actually split 2-ways
        full, shard = r["full_shape"], r["shard_shape"]
        assert full is not None and shard is not None
        assert shard != list(full), (axis, full, shard)
        assert 2 * int(np.prod(shard)) == int(np.prod(full))

    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=1e-6,
                               err_msg="ranks diverged")
    np.testing.assert_allclose(
        r0["losses"], base, rtol=5e-4, atol=5e-5,
        err_msg=f"{axis}=2 losses diverge from dense baseline")
