"""REAL multi-process distributed execution (VERDICT r2 item 3): spawn
2 OS processes, bring up jax.distributed via init_parallel_env, run a
cross-process all-reduce and a DP training run, and assert loss parity
with a single-process baseline — the reference's signature test trick
(fluid/tests/unittests/test_dist_base.py:786 spawning trainer
subprocesses and comparing losses; test_collective_api_base.py:19)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dist_worker  # noqa: E402

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_two_process_allreduce_and_dp_parity(tmp_path):
    from paddle_tpu import distributed

    ctx = distributed.spawn(dist_worker.allreduce_and_dp_train,
                            args=(str(tmp_path),), nprocs=2, join=False)
    ok = ctx.join(timeout=420)
    # on timeout, kill stragglers so the suite never wedges
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, "multi-process run failed or timed out"

    out = json.loads((tmp_path / "rank0.json").read_text())
    # all-reduce over 2 processes: 1 + 2
    assert out["allreduce"] == 3.0
    base = dist_worker.baseline_losses()
    np.testing.assert_allclose(out["losses"], base, rtol=2e-4, atol=2e-5,
                               err_msg="2-process DP losses diverge from "
                                       "single-process baseline")


def test_sharded_embedding_exceeds_single_host_budget(tmp_path):
    """Key-range-sharded host embedding across 2 OS processes (VERDICT
    r3 ask #2): the aggregate table exceeds any single per-host row
    budget, WideDeep trains with loss parity vs the unsharded
    single-process run, and a mid-run generation restart from sharded
    snapshots resumes losslessly."""
    from paddle_tpu import distributed

    budget = 2000
    ctx = distributed.spawn(dist_worker.sharded_embedding_train,
                            args=(str(tmp_path), 12, 8, budget),
                            nprocs=2, join=False)
    ok = ctx.join(timeout=420)
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, "sharded-embedding multi-process run failed or timed out"

    r0 = json.loads((tmp_path / "rank0.json").read_text())
    r1 = json.loads((tmp_path / "rank1.json").read_text())
    base, total_rows = dist_worker.sharded_embedding_baseline(12, 8)

    # capacity law: the whole table fits NO single host budget, but the
    # per-host shards each do — capacity scaled with the cluster.
    # (The worker itself asserts the sharded restore round-trips every
    # local row; the budget check raises in-step if a host overflows.)
    assert total_rows > budget, (total_rows, budget)
    assert r0["rows_final"] <= budget and r1["rows_final"] <= budget
    assert r0["rows_final"] + r1["rows_final"] == total_rows
    assert min(r0["rows_step8"], r1["rows_step8"]) > 0

    # loss parity with the unsharded reference, across the restart
    np.testing.assert_allclose(r0["losses"], base, rtol=2e-4, atol=2e-5,
                               err_msg="sharded-embedding losses diverge "
                                       "from unsharded baseline")
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=1e-6)
