"""REAL multi-process distributed execution (VERDICT r2 item 3): spawn
2 OS processes, bring up jax.distributed via init_parallel_env, run a
cross-process all-reduce and a DP training run, and assert loss parity
with a single-process baseline — the reference's signature test trick
(fluid/tests/unittests/test_dist_base.py:786 spawning trainer
subprocesses and comparing losses; test_collective_api_base.py:19)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dist_worker  # noqa: E402

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def test_two_process_allreduce_and_dp_parity(tmp_path):
    from paddle_tpu import distributed

    ctx = distributed.spawn(dist_worker.allreduce_and_dp_train,
                            args=(str(tmp_path),), nprocs=2, join=False)
    ok = ctx.join(timeout=420)
    # on timeout, kill stragglers so the suite never wedges
    for p in ctx.processes:
        if p.exitcode is None:
            p.terminate()
    assert ok, "multi-process run failed or timed out"

    out = json.loads((tmp_path / "rank0.json").read_text())
    # all-reduce over 2 processes: 1 + 2
    assert out["allreduce"] == 3.0
    base = dist_worker.baseline_losses()
    np.testing.assert_allclose(out["losses"], base, rtol=2e-4, atol=2e-5,
                               err_msg="2-process DP losses diverge from "
                                       "single-process baseline")
