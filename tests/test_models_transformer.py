"""GPT / BERT model family tests (tiny configs; CPU mesh).

Model-level analog of the reference's hapi/vision model tests
(python/paddle/tests/test_model.py, dist_hapi_* — SURVEY.md §4):
shape checks, finite grads, overfit-a-batch convergence, KV-cache
consistency, weight tying.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    BertForSequenceClassification,
                                    BertModel, BertPretrainingCriterion)
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion, gpt_config)
from paddle_tpu.nn.layer import functional_call, split_state

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

TINY_GPT = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                max_position_embeddings=64, hidden_dropout=0.0,
                attention_dropout=0.0)
TINY_BERT = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                 max_position_embeddings=64, hidden_dropout=0.0,
                 attention_dropout=0.0)


def _ids(shape, vocab=97, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, vocab, shape))


def test_gpt_forward_shapes():
    cfg = GPTConfig(**TINY_GPT)
    net = GPTForCausalLM(cfg)
    ids = _ids((2, 16))
    logits = net(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_gpt_presets():
    cfg = gpt_config("gpt3-1.3b")
    assert cfg.hidden_size == 2048 and cfg.num_layers == 24
    assert cfg.ffn_hidden_size == 4 * 2048


def test_gpt_weight_tying():
    cfg = GPTConfig(**TINY_GPT, tie_word_embeddings=True)
    net = GPTForCausalLM(cfg)
    names = [n for n, _ in net.named_parameters()]
    assert not any("lm_head" in n for n in names)
    # untied has its own head
    cfg2 = GPTConfig(**TINY_GPT, tie_word_embeddings=False)
    net2 = GPTForCausalLM(cfg2)
    assert any("lm_head" in n for n, _ in net2.named_parameters())


def test_gpt_causality():
    """Changing a future token must not change past logits."""
    cfg = GPTConfig(**TINY_GPT)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = _ids((1, 16))
    ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % cfg.vocab_size)
    l1 = net(ids)
    l2 = net(ids2)
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:])


def test_gpt_train_overfits_batch():
    cfg = GPTConfig(**TINY_GPT)
    net = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    ids = _ids((4, 32))
    params, buffers = split_state(net)
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    state = opt.init_state(params)

    @jax.jit
    def step(params, state, i):
        def loss_fn(p):
            logits, _ = functional_call(net, p, buffers, ids)
            return crit(logits, ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply_gradients(params, grads, state, i)
        return params, state, loss

    losses = []
    for i in range(30):
        params, state, loss = step(params, state, i)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert np.isfinite(losses[-1])


def test_gpt_kv_cache_matches_full_forward():
    cfg = GPTConfig(**TINY_GPT)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = _ids((2, 12))
    full = net(ids)
    caches = net.init_caches(2, 12)
    # prefill 8, then decode 4 one at a time
    logits, caches = net(ids[:, :8], caches=caches)
    outs = [logits]
    for t in range(8, 12):
        pos = jnp.full((2, 1), t)
        lg, caches = net(ids[:, t:t + 1], position_ids=pos, caches=caches)
        outs.append(lg)
    step_logits = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(step_logits, full, atol=1e-4, rtol=1e-4)


def test_gpt_generate_greedy_deterministic():
    cfg = GPTConfig(**TINY_GPT)
    net = GPTForCausalLM(cfg)
    ids = _ids((2, 5))
    out1 = net.generate(ids, max_new_tokens=6)
    out2 = net.generate(ids, max_new_tokens=6)
    assert out1.shape == (2, 11)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :5], ids)


def test_gpt_gqa_with_dropout_fallback():
    """GQA heads through the XLA fallback (dropout blocks flash)."""
    cfg = GPTConfig(**{**TINY_GPT, "hidden_dropout": 0.1,
                       "attention_dropout": 0.1}, num_kv_heads=2)
    net = GPTForCausalLM(cfg)
    ids = _ids((2, 16))
    logits = net(ids)  # training mode, dropout active → fallback path
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gpt_rejects_overlong_sequence():
    cfg = GPTConfig(**TINY_GPT)  # max_position_embeddings=64
    net = GPTForCausalLM(cfg)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        net(_ids((1, 65)))
    with pytest.raises(ValueError, match="max_position_embeddings"):
        net.generate(_ids((1, 60)), max_new_tokens=10)


def test_bert_forward_and_mask():
    cfg = BertConfig(**TINY_BERT)
    net = BertModel(cfg)
    net.eval()
    ids = _ids((2, 16))
    ids = ids.at[:, 12:].set(cfg.pad_token_id)
    mask = BertModel.attention_mask_from_ids(ids, cfg.pad_token_id)
    seq, pooled = net(ids, attn_mask=mask)
    assert seq.shape == (2, 16, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)
    # padding keys must not influence non-pad outputs
    ids2 = ids.at[:, 13].set(5)
    mask2 = BertModel.attention_mask_from_ids(
        ids.at[:, 13].set(cfg.pad_token_id), cfg.pad_token_id)
    seq2, _ = net(ids2, attn_mask=mask2)
    np.testing.assert_allclose(seq[:, :12], seq2[:, :12], atol=1e-5)


def test_bert_pretraining_loss_finite_and_grads():
    cfg = BertConfig(**TINY_BERT)
    net = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    ids = _ids((2, 16))
    mlm_labels = jnp.where(_ids((2, 16), 2, seed=3) > 0, ids, -100)
    nsp = jnp.asarray([0, 1])
    params, buffers = split_state(net)

    def loss_fn(p):
        (mlm_logits, nsp_logits), _ = functional_call(
            net, p, buffers, ids)
        return crit(mlm_logits, nsp_logits, mlm_labels, nsp)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # tied embedding grads flow from the MLM head
    g = grads["bert.embeddings.word_embeddings.weight"]
    assert float(jnp.abs(g).sum()) > 0


def test_bert_classifier_shapes():
    cfg = BertConfig(**TINY_BERT)
    net = BertForSequenceClassification(cfg, num_classes=3)
    out = net(_ids((4, 10)))
    assert out.shape == (4, 3)


def test_ernie_preset():
    from paddle_tpu.models.bert import ernie_config
    cfg = ernie_config("ernie-base")
    assert cfg.vocab_size == 18000 and cfg.num_layers == 12
