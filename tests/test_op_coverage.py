"""Mechanical op-coverage gate (VERDICT r2 item 9) + targeted checks for
the round-3 coverage fills (detection ops, Exponential, pad3d).

The coverage tool (tools/op_coverage.py) enumerates the reference's
public op surface from its api yaml registry (reference:
paddle/phi/api/yaml/api.yaml + legacy_api.yaml) and resolves every name
here; the gate asserts the missing list stays empty."""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.nn import functional as F  # noqa: E402
import paddle_tpu.vision.ops as vops  # noqa: E402


def test_reference_op_surface_fully_covered():
    from tools.op_coverage import classify
    r = classify()
    assert not r["missing"], r["missing"]
    covered = len(r["direct"]) + len(r["alias"])
    assert covered >= 250, covered  # VERDICT r2 target


def test_roi_pool_max_per_bin():
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
    boxes = np.array([[0.0, 0.0, 3.0, 3.0]])
    out = vops.roi_pool(x, boxes, [1], output_size=2)
    # quantized 2x2 bins over the full 4x4 map: max of each quadrant
    np.testing.assert_allclose(
        np.asarray(out)[0, 0], [[5.0, 7.0], [13.0, 15.0]])


def test_psroi_pool_position_sensitive_average():
    # 4 channels = 1 out-channel * 2x2 bins; each bin reads its own slice
    x = jnp.stack([jnp.full((4, 4), float(c)) for c in range(4)])[None]
    boxes = np.array([[0.0, 0.0, 4.0, 4.0]])
    out = vops.psroi_pool(x, boxes, [1], output_size=2)
    np.testing.assert_allclose(
        np.asarray(out)[0, 0], [[0.0, 1.0], [2.0, 3.0]])


def test_temporal_shift_moves_channel_folds():
    n, t, c, h, w = 1, 3, 4, 1, 1
    x = jnp.arange(n * t * c, dtype=jnp.float32).reshape(n * t, c, h, w)
    out = np.asarray(vops.temporal_shift(x, seg_num=t, shift_ratio=0.25))
    xr = np.asarray(x).reshape(n, t, c)
    outr = out.reshape(n, t, c)
    # channel 0: from t-1 (zero at t=0); channel 1: from t+1 (zero at
    # t=T-1); channels 2-3 unchanged
    np.testing.assert_allclose(outr[0, :, 0], [0.0, xr[0, 0, 0],
                                               xr[0, 1, 0]])
    np.testing.assert_allclose(outr[0, :, 1], [xr[0, 1, 1], xr[0, 2, 1],
                                               0.0])
    np.testing.assert_allclose(outr[0, :, 2:], xr[0, :, 2:])


def test_yolo_box_decode_shapes_and_center():
    n, an, cls, hw = 1, 2, 3, 2
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, an * (5 + cls), hw, hw), jnp.float32)
    boxes, scores = vops.yolo_box(x, np.array([[64, 64]]), [10, 13, 16, 30],
                                  class_num=cls, conf_thresh=0.0,
                                  downsample_ratio=32)
    assert boxes.shape == (n, hw * hw * an, 4)
    assert scores.shape == (n, hw * hw * an, cls)
    b = np.asarray(boxes)
    assert (b >= 0).all() and (b <= 63).all()  # clipped to img
    # zero logits decode to the cell center: cx=(0.5+gx)/W
    x0 = jnp.zeros_like(x)
    b0, s0 = vops.yolo_box(x0, np.array([[64, 64]]), [10, 13, 16, 30],
                           class_num=cls, conf_thresh=0.9,
                           downsample_ratio=32, clip_bbox=False)
    cx = (np.asarray(b0)[0, 0, 0] + np.asarray(b0)[0, 0, 2]) / 2
    np.testing.assert_allclose(cx, 0.5 / hw * 64, rtol=1e-5)
    # conf sigmoid(0)=0.5 < 0.9 threshold → all scores zeroed
    np.testing.assert_allclose(np.asarray(s0), 0.0)


def test_exponential_distribution():
    from paddle_tpu.distribution import Exponential
    pt.seed(0)
    d = Exponential(rate=jnp.asarray([2.0]))
    np.testing.assert_allclose(np.asarray(d.mean), [0.5])
    np.testing.assert_allclose(np.asarray(d.variance), [0.25])
    s = d.sample((20000,))
    assert abs(float(s.mean()) - 0.5) < 0.02
    np.testing.assert_allclose(
        float(d.log_prob(jnp.asarray(1.0))[0]),
        float(np.log(2.0) - 2.0), rtol=1e-6)
    np.testing.assert_allclose(float(d.cdf(jnp.asarray(0.5))[0]),
                               1 - np.exp(-1.0), rtol=1e-6)


def test_pad3d_pads_innermost_first():
    x = jnp.ones((1, 1, 2, 2, 2))
    out = F.pad3d(x, [1, 1, 0, 0, 0, 0])       # pad W only
    assert out.shape == (1, 1, 2, 2, 4)
    out = F.pad3d(x, [0, 0, 0, 0, 2, 0])       # pad D before
    assert out.shape == (1, 1, 4, 2, 2)
    with pytest.raises(ValueError, match="5-D"):
        F.pad3d(jnp.ones((2, 2)), [1, 1, 1, 1, 1, 1])
