"""MultiSlot data generator protocol round-trip (ref: unittests
test_data_generator.py) + the CSV bridge into the native feed."""

import numpy as np
import pytest

from paddle_tpu.incubate.data_generator import (MultiSlotDataGenerator,
                                                parse_multislot_line)


class CTRGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def gen():
            toks = line.split()
            yield [("click", [int(toks[0])]),
                   ("ids", [int(t) for t in toks[1:4]]),
                   ("dense", [float(t) for t in toks[4:]])]
        return gen


def test_protocol_roundtrip(tmp_path):
    src = tmp_path / "raw.txt"
    src.write_text("1 10 20 30 0.5 0.25\n0 7 8 9 1.5 2.5\n")
    out = tmp_path / "multislot.txt"
    CTRGen().run_from_files([str(src)], str(out))
    lines = out.read_text().splitlines()
    assert lines[0] == "1 1 3 10 20 30 2 0.5 0.25"
    parsed = parse_multislot_line(lines[1],
                                  ["click", "ids", "dense"])
    assert parsed == [("click", [0]), ("ids", [7, 8, 9]),
                      ("dense", [1.5, 2.5])]


def test_parse_validates():
    with pytest.raises(ValueError, match="declares"):
        parse_multislot_line("2 5", ["ids"])
    with pytest.raises(ValueError, match="trailing"):
        parse_multislot_line("1 5 99", ["ids"])


def test_csv_bridge_feeds_native_engine(tmp_path):
    from paddle_tpu.io.native_feed import FileDataFeed
    gen = CTRGen()
    p = tmp_path / "part-0.csv"
    with open(p, "w") as f:
        for i in range(10):
            sample = [("click", [i % 2]), ("ids", [i, i + 1, i + 2]),
                      ("dense", [i * 0.5, i * 0.25])]
            f.write(gen.to_csv(sample))
    feed = FileDataFeed([str(p)], schema="i64:1,i64:3,f32:2",
                        batch_size=5)
    rows = 0
    for batch in feed:
        clicks, ids, dense = batch
        assert ids.shape[1] == 3 and dense.shape[1] == 2
        rows += dense.shape[0]
    assert rows == 10
