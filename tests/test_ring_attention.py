"""Ring attention (context parallelism) on the 8-device CPU mesh —
exactness vs full attention, causal and bidirectional, plus grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import parallel
from paddle_tpu.ops.ring_attention import ring_attention

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _reference(q, k, v, causal=False):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(
        q.dtype)


def _qkv(b=2, s=64, h=2, d=16):
    rs = np.random.RandomState(0)
    return tuple(jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(sp, causal):
    q, k, v = _qkv()
    ref = np.asarray(_reference(q, k, v, causal))
    mesh = parallel.init_mesh(sp=sp, dp=8 // sp)
    try:
        out = np.asarray(jax.jit(
            lambda q, k, v: ring_attention(q, k, v, causal=causal,
                                           mesh=mesh))(q, k, v))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_grads_match_full():
    q, k, v = _qkv(s=32)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        def loss_ring(q, k, v):
            o = ring_attention(q, k, v, causal=True, mesh=mesh)
            return jnp.sum(o * jnp.cos(o))

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    finally:
        parallel.set_mesh(None)

    def loss_full(q, k, v):
        o = _reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{n}")


def test_ring_sp1_fallback():
    q, k, v = _qkv(s=16)
    mesh = parallel.init_mesh(dp=8)
    try:
        out = np.asarray(ring_attention(q, k, v, causal=True, mesh=mesh))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, np.asarray(_reference(q, k, v, True)),
                               atol=1e-5, rtol=1e-5)


def test_ring_rejects_indivisible():
    q, k, v = _qkv(s=30)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh)
    finally:
        parallel.set_mesh(None)
