"""Ring attention (context parallelism) on the 8-device CPU mesh —
exactness vs full attention, causal and bidirectional, plus grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import parallel
from paddle_tpu.ops.ring_attention import ring_attention

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _reference(q, k, v, causal=False):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(
        q.dtype)


def _qkv(b=2, s=64, h=2, d=16):
    rs = np.random.RandomState(0)
    return tuple(jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(sp, causal):
    q, k, v = _qkv()
    ref = np.asarray(_reference(q, k, v, causal))
    mesh = parallel.init_mesh(sp=sp, dp=8 // sp)
    try:
        out = np.asarray(jax.jit(
            lambda q, k, v: ring_attention(q, k, v, causal=causal,
                                           mesh=mesh))(q, k, v))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_grads_match_full():
    q, k, v = _qkv(s=32)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        def loss_ring(q, k, v):
            o = ring_attention(q, k, v, causal=True, mesh=mesh)
            return jnp.sum(o * jnp.cos(o))

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    finally:
        parallel.set_mesh(None)

    def loss_full(q, k, v):
        o = _reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{n}")


def test_ring_sp1_fallback():
    q, k, v = _qkv(s=16)
    mesh = parallel.init_mesh(dp=8)
    try:
        out = np.asarray(ring_attention(q, k, v, causal=True, mesh=mesh))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, np.asarray(_reference(q, k, v, True)),
                               atol=1e-5, rtol=1e-5)


def test_ring_rejects_indivisible():
    q, k, v = _qkv(s=30)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh)
    finally:
        parallel.set_mesh(None)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_ring_matches_unchunked(causal):
    """chunk_size streams each ring block's K/V tiles (flash-in-block);
    numerics must equal the unchunked ring and dense attention."""
    from paddle_tpu import parallel
    from paddle_tpu.ops.ring_attention import ring_attention

    b, s, h, d = 2, 64, 2, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    mesh = parallel.init_mesh(devices=jax.devices()[:4], sp=4)
    try:
        base = np.asarray(ring_attention(q, k, v, causal=causal,
                                         mesh=mesh))
        chunked = np.asarray(ring_attention(q, k, v, causal=causal,
                                            mesh=mesh, chunk_size=4))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(chunked, base, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_ring_gradients_match(causal):
    from paddle_tpu import parallel
    from paddle_tpu.ops.ring_attention import ring_attention

    b, s, h, d = 1, 32, 2, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    mesh = parallel.init_mesh(devices=jax.devices()[:4], sp=4)
    try:
        def loss(chunk):
            def f(q, k, v):
                return (ring_attention(q, k, v, causal=causal,
                                       mesh=mesh,
                                       chunk_size=chunk) ** 2).sum()
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g_base = loss(None)
        g_chunk = loss(4)
    finally:
        parallel.set_mesh(None)
    for a, bb in zip(g_base, g_chunk):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   atol=3e-5, rtol=3e-5)


def test_chunked_ring_memory_linear_in_seq():
    """With chunk_size fixed, doubling global seq at sp=8 grows
    per-device temps ~linearly (the [s/sp, s/sp] block logits no
    longer exist; tiles are [s/sp, chunk])."""
    from paddle_tpu import parallel
    from paddle_tpu.cost_model import memory_profile
    from paddle_tpu.ops.ring_attention import ring_attention

    def temps(s, chunk):
        mesh = parallel.init_mesh(devices=jax.devices()[:8], sp=8)
        try:
            b, h, d = 1, 2, 16
            q = jnp.asarray(np.random.RandomState(0).randn(b, s, h, d),
                            jnp.float32)

            def f(q, k, v):
                return ring_attention(q, k, v, causal=True, mesh=mesh,
                                      chunk_size=chunk).sum()

            return memory_profile(jax.grad(f, argnums=(0, 1, 2)),
                                  (q, q, q)).temp_bytes
        finally:
            parallel.set_mesh(None)

    t1 = temps(4096, 256)
    t2 = temps(8192, 256)
    assert t2 / t1 <= 2.6, (t1, t2)


@pytest.mark.parametrize("data_axis", ["dp", "fsdp"])
def test_gpt_sequence_parallel_training_matches_dense(data_axis):
    """GPTConfig.sequence_parallel: the flagship trains with ring
    attention over sp composed with dp AND with fsdp (ZeRO-3 param
    gathers crossing the partial-manual sp region), loss-parity with
    the dense single-mesh model — context parallelism as a model
    config, not just a standalone op."""
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_position_embeddings=32, hidden_dropout=0.0,
              attention_dropout=0.0, use_flash=False)
    ids = np.random.RandomState(0).randint(0, 64, (4, 32))

    def losses(sp):
        pt.seed(0)
        cfg = GPTConfig(**kw, sequence_parallel=bool(sp),
                        ring_chunk_size=4 if sp else None)
        net = GPTForCausalLM(cfg)
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        if sp:
            mesh = parallel.init_mesh(**{"sp": sp, data_axis: 8 // sp})
            parallel.distributed_model(m, mesh=mesh)
        try:
            return [float(m.train_batch([ids], [ids])["loss"])
                    for _ in range(3)]
        finally:
            if sp:
                parallel.set_mesh(None)

    dense = losses(0)
    ring = losses(4)
    np.testing.assert_allclose(ring, dense, rtol=5e-4, atol=5e-5)


def test_llama_style_scan_plus_sequence_parallel():
    """Feature interaction: LLaMA-style trunk (RoPE + RMSNorm + SwiGLU
    + GQA) with scan_layers AND sequence_parallel together — GQA head
    expansion inside ring blocks, rotary positions under the scanned
    trunk, loss parity with the same model dense."""
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTPretrainingCriterion,
                                       llama_config)

    ids = np.random.RandomState(0).randint(0, 64, (4, 32))

    def losses(sp):
        pt.seed(0)
        cfg = llama_config(hidden_size=32, num_layers=2, num_heads=4,
                           num_kv_heads=2, vocab_size=64,
                           max_position_embeddings=32, use_flash=False,
                           scan_layers=True, remat=True,
                           sequence_parallel=bool(sp),
                           ring_chunk_size=8 if sp else None)
        net = GPTForCausalLM(cfg)
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        if sp:
            mesh = parallel.init_mesh(sp=sp, dp=8 // sp)
            parallel.distributed_model(m, mesh=mesh)
        try:
            return [float(m.train_batch([ids], [ids])["loss"])
                    for _ in range(3)]
        finally:
            if sp:
                parallel.set_mesh(None)

    dense = losses(0)
    ring = losses(4)
    np.testing.assert_allclose(ring, dense, rtol=5e-4, atol=5e-5)
