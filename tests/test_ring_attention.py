"""Ring attention (context parallelism) on the 8-device CPU mesh —
exactness vs full attention, causal and bidirectional, plus grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import parallel
from paddle_tpu.ops.ring_attention import ring_attention

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _reference(q, k, v, causal=False):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(
        q.dtype)


def _qkv(b=2, s=64, h=2, d=16):
    rs = np.random.RandomState(0)
    return tuple(jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(sp, causal):
    q, k, v = _qkv()
    ref = np.asarray(_reference(q, k, v, causal))
    mesh = parallel.init_mesh(sp=sp, dp=8 // sp)
    try:
        out = np.asarray(jax.jit(
            lambda q, k, v: ring_attention(q, k, v, causal=causal,
                                           mesh=mesh))(q, k, v))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_grads_match_full():
    q, k, v = _qkv(s=32)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        def loss_ring(q, k, v):
            o = ring_attention(q, k, v, causal=True, mesh=mesh)
            return jnp.sum(o * jnp.cos(o))

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    finally:
        parallel.set_mesh(None)

    def loss_full(q, k, v):
        o = _reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{n}")


def test_ring_sp1_fallback():
    q, k, v = _qkv(s=16)
    mesh = parallel.init_mesh(dp=8)
    try:
        out = np.asarray(ring_attention(q, k, v, causal=True, mesh=mesh))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(out, np.asarray(_reference(q, k, v, True)),
                               atol=1e-5, rtol=1e-5)


def test_ring_rejects_indivisible():
    q, k, v = _qkv(s=30)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh)
    finally:
        parallel.set_mesh(None)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_ring_matches_unchunked(causal):
    """chunk_size streams each ring block's K/V tiles (flash-in-block);
    numerics must equal the unchunked ring and dense attention."""
    from paddle_tpu import parallel
    from paddle_tpu.ops.ring_attention import ring_attention

    b, s, h, d = 2, 64, 2, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    mesh = parallel.init_mesh(devices=jax.devices()[:4], sp=4)
    try:
        base = np.asarray(ring_attention(q, k, v, causal=causal,
                                         mesh=mesh))
        chunked = np.asarray(ring_attention(q, k, v, causal=causal,
                                            mesh=mesh, chunk_size=4))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(chunked, base, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_ring_gradients_match(causal):
    from paddle_tpu import parallel
    from paddle_tpu.ops.ring_attention import ring_attention

    b, s, h, d = 1, 32, 2, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    mesh = parallel.init_mesh(devices=jax.devices()[:4], sp=4)
    try:
        def loss(chunk):
            def f(q, k, v):
                return (ring_attention(q, k, v, causal=causal,
                                       mesh=mesh,
                                       chunk_size=chunk) ** 2).sum()
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g_base = loss(None)
        g_chunk = loss(4)
    finally:
        parallel.set_mesh(None)
    for a, bb in zip(g_base, g_chunk):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   atol=3e-5, rtol=3e-5)


def test_chunked_ring_memory_linear_in_seq():
    """With chunk_size fixed, doubling global seq at sp=8 grows
    per-device temps ~linearly (the [s/sp, s/sp] block logits no
    longer exist; tiles are [s/sp, chunk])."""
    from paddle_tpu import parallel
    from paddle_tpu.cost_model import memory_profile
    from paddle_tpu.ops.ring_attention import ring_attention

    def temps(s, chunk):
        mesh = parallel.init_mesh(devices=jax.devices()[:8], sp=8)
        try:
            b, h, d = 1, 2, 16
            q = jnp.asarray(np.random.RandomState(0).randn(b, s, h, d),
                            jnp.float32)

            def f(q, k, v):
                return ring_attention(q, k, v, causal=True, mesh=mesh,
                                      chunk_size=chunk).sum()

            return memory_profile(jax.grad(f, argnums=(0, 1, 2)),
                                  (q, q, q)).temp_bytes
        finally:
            parallel.set_mesh(None)

    t1 = temps(4096, 256)
    t2 = temps(8192, 256)
    assert t2 / t1 <= 2.6, (t1, t2)


@pytest.mark.parametrize("data_axis", ["dp", "fsdp"])
def test_gpt_sequence_parallel_training_matches_dense(data_axis):
    """GPTConfig.sequence_parallel: the flagship trains with ring
    attention over sp composed with dp AND with fsdp (ZeRO-3 param
    gathers crossing the partial-manual sp region), loss-parity with
    the dense single-mesh model — context parallelism as a model
    config, not just a standalone op."""
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_position_embeddings=32, hidden_dropout=0.0,
              attention_dropout=0.0, use_flash=False)
    ids = np.random.RandomState(0).randint(0, 64, (4, 32))

    def losses(sp):
        pt.seed(0)
        cfg = GPTConfig(**kw, sequence_parallel=bool(sp),
                        ring_chunk_size=4 if sp else None)
        net = GPTForCausalLM(cfg)
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        if sp:
            mesh = parallel.init_mesh(**{"sp": sp, data_axis: 8 // sp})
            parallel.distributed_model(m, mesh=mesh)
        try:
            return [float(m.train_batch([ids], [ids])["loss"])
                    for _ in range(3)]
        finally:
            if sp:
                parallel.set_mesh(None)

    dense = losses(0)
    ring = losses(4)
    np.testing.assert_allclose(ring, dense, rtol=5e-4, atol=5e-5)


def test_llama_style_scan_plus_sequence_parallel():
    """Feature interaction: LLaMA-style trunk (RoPE + RMSNorm + SwiGLU
    + GQA) with scan_layers AND sequence_parallel together — GQA head
    expansion inside ring blocks, rotary positions under the scanned
    trunk, loss parity with the same model dense."""
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTPretrainingCriterion,
                                       llama_config)

    ids = np.random.RandomState(0).randint(0, 64, (4, 32))

    def losses(sp):
        pt.seed(0)
        cfg = llama_config(hidden_size=32, num_layers=2, num_heads=4,
                           num_kv_heads=2, vocab_size=64,
                           max_position_embeddings=32, use_flash=False,
                           scan_layers=True, remat=True,
                           sequence_parallel=bool(sp),
                           ring_chunk_size=8 if sp else None)
        net = GPTForCausalLM(cfg)
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        if sp:
            mesh = parallel.init_mesh(sp=sp, dp=8 // sp)
            parallel.distributed_model(m, mesh=mesh)
        try:
            return [float(m.train_batch([ids], [ids])["loss"])
                    for _ in range(3)]
        finally:
            if sp:
                parallel.set_mesh(None)

    dense = losses(0)
    ring = losses(4)
    np.testing.assert_allclose(ring, dense, rtol=5e-4, atol=5e-5)


def _reference_masked(q, k, v, kpm, causal):
    """Dense reference with a key-padding mask (True = attend)."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    mask = kpm[:, None, None, :]
    if causal:
        s = q.shape[1]
        mask = mask & jnp.tril(jnp.ones((s, s), bool))[None, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    p = jnp.where(jnp.any(mask, -1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(
        q.dtype)


@pytest.mark.parametrize("chunk", [None, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_key_padding_mask_matches_full(causal, chunk):
    """r4 VERDICT item 7: padded batches under sp. The [b, s] key
    mask is sequence-sharded and rotated with the K/V ring; output
    matches the dense masked reference exactly (incl. the streamed
    chunk path)."""
    q, k, v = _qkv()
    rs = np.random.RandomState(1)
    kpm = jnp.asarray(rs.rand(q.shape[0], q.shape[1]) > 0.3)
    ref = np.asarray(_reference_masked(q, k, v, kpm, causal))
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        out = np.asarray(jax.jit(
            lambda q, k, v, m: ring_attention(
                q, k, v, causal=causal, mesh=mesh, chunk_size=chunk,
                key_padding_mask=m))(q, k, v, kpm))
    finally:
        parallel.set_mesh(None)
    # rows whose query is padded still produce values (queries are not
    # masked — matches dense semantics); fully-masked rows are zero
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_dropout_deterministic_and_exact_at_zero():
    """Dropout lane: p=0 is exactly the no-dropout path; p>0 is
    deterministic per key (checkpoint recompute safety), differs
    across keys, and preserves the undropped normalization (unbiased
    in expectation — checked loosely via the mean over heads)."""
    q, k, v = _qkv(b=2, s=64, h=4, d=8)
    key = jax.random.PRNGKey(7)
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        def make(p):  # dropout_p is a static (it selects code paths)
            return jax.jit(lambda q, k, v, key: ring_attention(
                q, k, v, causal=True, mesh=mesh, dropout_p=p,
                dropout_key=key))
        base = np.asarray(make(0.0)(q, k, v, key))
        f = make(0.5)
        d1 = np.asarray(f(q, k, v, key))
        d2 = np.asarray(f(q, k, v, key))
        d3 = np.asarray(f(q, k, v, jax.random.PRNGKey(8)))
        # and the chunked-stream path shares the determinism contract
        g = jax.jit(lambda q, k, v, key: ring_attention(
            q, k, v, causal=True, mesh=mesh, chunk_size=8,
            dropout_p=0.5, dropout_key=key))
        c1 = np.asarray(g(q, k, v, key))
        c2 = np.asarray(g(q, k, v, key))
    finally:
        parallel.set_mesh(None)
    ref = np.asarray(_reference(q, k, v, causal=True))
    np.testing.assert_allclose(base, ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(c1, c2)
    assert not np.allclose(d1, base)
    assert not np.allclose(d1, d3)
    # unbiasedness (loose): averaged over batch*heads*rows the dropped
    # output stays near the undropped one
    assert abs(d1.mean() - base.mean()) < 0.05


def test_gpt_sequence_parallel_trains_with_dropout_and_mask():
    """The r4 NotImplementedErrors are gone: the sp flagship trains
    with attention_dropout > 0 AND a padded-batch key mask; loss is
    finite and decreases, and dropout actually fires (train loss
    differs from the dropout-free run)."""
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    ids = np.random.RandomState(0).randint(0, 64, (4, 32))
    pos = np.broadcast_to(np.arange(32), (4, 32))
    kpm = np.ones((4, 32), bool)
    kpm[:, 28:] = False  # padded tail

    def run(drop, mask):
        pt.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=drop,
                        use_flash=False, sequence_parallel=True,
                        ring_chunk_size=4)
        net = GPTForCausalLM(cfg)
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        mesh = parallel.init_mesh(sp=4, dp=2)
        parallel.distributed_model(m, mesh=mesh)
        # positional feed: (input_ids, position_ids, attn_mask)
        feed = [ids, pos] + ([jnp.asarray(kpm)] if mask is not None
                             else [])
        try:
            return [float(m.train_batch(feed, [ids])["loss"])
                    for _ in range(4)]
        finally:
            parallel.set_mesh(None)

    plain = run(0.0, None)
    masked = run(0.0, kpm)
    dropped = run(0.3, kpm)
    assert np.isfinite(dropped).all()
    assert dropped[-1] < dropped[0]
    # the mask reaches attention (changes the loss) and dropout fires
    # on top of it
    assert not np.allclose(plain, masked)
    assert not np.allclose(masked, dropped)


def test_key_padding_mask_works_dense_single_device():
    """The [b, s] key-padding contract degrades to the dense path
    off-mesh: an sp-trained padded-batch config evaluates single-device
    unchanged (r5 review finding), and the mask changes the output."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False, sequence_parallel=True)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    kpm = np.ones((2, 16), bool)
    kpm[:, 12:] = False
    out_m = np.asarray(net(ids, attn_mask=jnp.asarray(kpm)))
    out_p = np.asarray(net(ids))
    assert np.isfinite(out_m).all()
    # masked keys change earlier queries' outputs only via later rows:
    # rows before the pad boundary never attend to padded keys... but
    # causal means rows < 12 can't see cols >= 12 anyway, so compare
    # the full tensors: padded rows DO differ
    assert not np.allclose(out_m, out_p)


def test_left_padded_rows_zero_not_nan_dense():
    """Left padding: queries whose whole causal window is padded come
    out ZERO on the dense path (finite sentinel + row zeroing), exactly
    like the ring path's fully-masked handling — train-under-sp then
    eval-dense stays NaN-free (r5 review finding)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False, sequence_parallel=True)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    kpm = np.ones((2, 16), bool)
    kpm[:, :4] = False                     # LEFT padding
    dense = np.asarray(net(ids, attn_mask=jnp.asarray(kpm)))
    assert np.isfinite(dense).all()
    mesh = parallel.init_mesh(sp=4, dp=2)
    try:
        from paddle_tpu.nn.layer import functional_call, split_state
        p_, b_ = split_state(net)
        ring = jax.jit(lambda p, i, m: functional_call(
            net, p, b_, i, None, m, training=False)[0])(
                p_, ids, jnp.asarray(kpm))
    finally:
        parallel.set_mesh(None)
    np.testing.assert_allclose(dense, np.asarray(ring), atol=2e-5,
                               rtol=2e-5)
