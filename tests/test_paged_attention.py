"""Paged attention vs dense reference (serving decode step).

Analog territory: the reference's fused_multi_transformer decode tests;
paged layout per PAPERS.md ragged-paged-attention."""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.paged_attention import PagedKVCache, paged_attention

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _dense_ref(q, k, v, lens):
    b, h, d = q.shape
    outs = []
    for i in range(b):
        ki, vi = k[i, :lens[i]], v[i, :lens[i]]          # [L, h, d]
        lg = np.einsum("hd,lhd->hl", q[i], ki) / math.sqrt(d)
        p = np.exp(lg - lg.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        outs.append(np.einsum("hl,lhd->hd", p, vi))
    return np.stack(outs)


def _build_cache(lens, page_size, kv_heads, d, seed=0):
    r = np.random.RandomState(seed)
    b = len(lens)
    pages_per_seq = -(-max(lens) // page_size)
    cache = PagedKVCache(num_pages=b * pages_per_seq + 2,
                         page_size=page_size, kv_heads=kv_heads,
                         head_dim=d, max_seqs=b,
                         pages_per_seq=pages_per_seq)
    dense_k = np.zeros((b, max(lens), kv_heads, d), np.float32)
    dense_v = np.zeros_like(dense_k)
    for i, L in enumerate(lens):
        kk = r.randn(L, kv_heads, d).astype(np.float32)
        vv = r.randn(L, kv_heads, d).astype(np.float32)
        cache.append(i, jnp.asarray(kk), jnp.asarray(vv))
        dense_k[i, :L], dense_v[i, :L] = kk, vv
    return cache, dense_k, dense_v


def test_matches_dense_ragged_lengths():
    lens = [7, 13, 3]
    kv_heads, d = 2, 8
    cache, dk, dv = _build_cache(lens, page_size=4, kv_heads=kv_heads,
                                 d=d)
    q = np.random.RandomState(1).randn(3, 2, 8).astype(np.float32)
    out = np.asarray(paged_attention(
        jnp.asarray(q), cache.k_pages, cache.v_pages,
        cache.block_tables, cache.context_lens))
    ref = _dense_ref(q, dk, dv, lens)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_gqa_heads():
    lens = [5, 9]
    cache, dk, dv = _build_cache(lens, page_size=4, kv_heads=2, d=8,
                                 seed=2)
    q = np.random.RandomState(3).randn(2, 4, 8).astype(np.float32)  # 4 q heads / 2 kv
    out = np.asarray(paged_attention(
        jnp.asarray(q), cache.k_pages, cache.v_pages,
        cache.block_tables, cache.context_lens))
    ref = _dense_ref(q, np.repeat(dk, 2, axis=2),
                     np.repeat(dv, 2, axis=2), lens)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_append_and_free_reuse_pages():
    cache, _, _ = _build_cache([4, 4], page_size=4, kv_heads=1, d=4)
    free_before = len(cache._free)
    cache.free(0)
    assert len(cache._free) == free_before + 1
    assert int(cache.context_lens[0]) == 0
    # page gets reused by a new sequence
    cache.append(0, jnp.ones((4, 1, 4)), jnp.ones((4, 1, 4)))
    assert len(cache._free) == free_before


def test_pool_exhaustion_raises():
    cache = PagedKVCache(num_pages=1, page_size=4, kv_heads=1,
                         head_dim=4, max_seqs=2, pages_per_seq=2)
    cache.append(0, jnp.ones((4, 1, 4)), jnp.ones((4, 1, 4)))
    with pytest.raises(RuntimeError, match="exhausted"):
        cache.append(1, jnp.ones((1, 1, 4)), jnp.ones((1, 1, 4)))


def test_jit_compatible_decode_step():
    lens = [6, 2]
    cache, dk, dv = _build_cache(lens, page_size=4, kv_heads=2, d=8,
                                 seed=4)
    q = jnp.asarray(np.random.RandomState(5).randn(2, 2, 8),
                    jnp.float32)
    fn = jax.jit(paged_attention)
    out = np.asarray(fn(q, cache.k_pages, cache.v_pages,
                        cache.block_tables, cache.context_lens))
    ref = _dense_ref(np.asarray(q), dk, dv, lens)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_empty_slot_returns_zeros_not_nan():
    cache, dk, dv = _build_cache([4], page_size=4, kv_heads=1, d=4,
                                 seed=6)
    # max_seqs=1 here; build a 2-slot case manually
    cache2 = PagedKVCache(num_pages=4, page_size=4, kv_heads=1,
                          head_dim=4, max_seqs=2, pages_per_seq=1)
    cache2.append(0, jnp.ones((4, 1, 4)), jnp.ones((4, 1, 4)))
    q = jnp.ones((2, 1, 4))
    out = np.asarray(paged_attention(
        q, cache2.k_pages, cache2.v_pages, cache2.block_tables,
        cache2.context_lens))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out[1], 0.0)


def test_capacity_validation():
    cache = PagedKVCache(num_pages=8, page_size=4, kv_heads=1,
                         head_dim=4, max_seqs=1, pages_per_seq=2)
    with pytest.raises(ValueError, match="pages_per_seq"):
        cache.append(0, jnp.ones((12, 1, 4)), jnp.ones((12, 1, 4)))


def test_append_spanning_pages_matches_dense():
    lens = [10]  # spans 3 pages of 4 with a partial page
    cache, dk, dv = _build_cache(lens, page_size=4, kv_heads=2, d=8,
                                 seed=7)
    q = np.random.RandomState(8).randn(1, 2, 8).astype(np.float32)
    out = np.asarray(paged_attention(
        jnp.asarray(q), cache.k_pages, cache.v_pages,
        cache.block_tables, cache.context_lens))
    ref = _dense_ref(q, dk, dv, lens)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_kernel_matches_xla_path():
    """The fused Pallas decode kernel (scalar-prefetched block tables,
    per-page streaming) equals the gather+dense XLA path across GQA/
    MHA, partial last pages, empty slots, and bf16 pages."""
    from paddle_tpu.ops.paged_attention import (paged_attention,
                                                paged_attention_kernel)
    rng = np.random.RandomState(1)
    for (H, KVH, PS, dtype) in [(4, 2, 8, jnp.float32),
                                (4, 4, 16, jnp.float32),
                                (8, 2, 8, jnp.bfloat16)]:
        B, D, NP, P = 3, 16, 20, 4
        q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
        kp = jnp.asarray(rng.randn(NP, PS, KVH, D), dtype)
        vp = jnp.asarray(rng.randn(NP, PS, KVH, D), dtype)
        tables = jnp.asarray(
            [[1, 2, 3, 0], [4, 5, 0, 0], [0, 0, 0, 0]], jnp.int32)
        lens = jnp.asarray([2 * PS + 3, PS + 1, 0], jnp.int32)
        ref = np.asarray(paged_attention(q, kp, vp, tables, lens))
        got = np.asarray(paged_attention_kernel(
            q, kp, vp, tables, lens, interpret=True))
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        np.testing.assert_allclose(got, ref, atol=tol, rtol=tol,
                                   err_msg=f"H{H} KVH{KVH} PS{PS}")
        np.testing.assert_allclose(got[2], 0.0)  # empty slot zeros


def test_paged_attention_ragged_matches_chunk_and_kernel():
    """The ragged prefill op: flattening the rectangular [B, K] chunk
    case into T=B*K tokens with per-token tables/limits must reproduce
    paged_attention_chunk exactly, on both the xla and pallas impls."""
    from paddle_tpu.ops.paged_attention import (paged_attention_chunk,
                                                paged_attention_ragged)

    rng = np.random.RandomState(0)
    B, K, H, KVH, PS, D, NP, P = 2, 3, 4, 2, 4, 16, 12, 3
    q = jnp.asarray(rng.randn(B, K, H, D), jnp.float32)
    kp = jnp.asarray(rng.randn(NP, PS, KVH, D), jnp.float32)
    vp = jnp.asarray(rng.randn(NP, PS, KVH, D), jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    base = jnp.asarray([5, 2], jnp.int32)

    ref = np.asarray(paged_attention_chunk(q, kp, vp, tables, base))
    qf = q.reshape(B * K, H, D)
    tf = jnp.repeat(tables, K, axis=0)
    lims = (base[:, None] + jnp.arange(K)[None, :] + 1).reshape(-1)
    got = np.asarray(paged_attention_ragged(qf, kp, vp, tf, lims))
    np.testing.assert_allclose(got, ref.reshape(B * K, H, D),
                               atol=1e-6, rtol=1e-6)
    got_k = np.asarray(paged_attention_ragged(qf, kp, vp, tf, lims,
                                              impl="pallas"))
    np.testing.assert_allclose(got_k, ref.reshape(B * K, H, D),
                               atol=2e-5, rtol=2e-5)
    # padding tokens (limit 0) produce zero rows
    zero = np.asarray(paged_attention_ragged(
        qf, kp, vp, tf, jnp.zeros((B * K,), jnp.int32)))
    np.testing.assert_allclose(zero, 0.0)


def test_engine_with_pallas_attention_matches_dense():
    """LLMEngine(attention_impl='pallas'): greedy decode through the
    fused kernel is token-identical to the dense generate."""
    import paddle_tpu as pt
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 9)]
    want = [np.asarray(net.generate(jnp.asarray([p]), max_new_tokens=6)
                       )[0, len(p):].tolist() for p in prompts]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,),
                   attention_impl="pallas") as eng:
        outs = eng.generate(prompts, max_new_tokens=6)
    for got, ref in zip(outs, want):
        assert got["output_ids"] == ref
