"""Regression pins for the round-4 code-review findings: fft Hermitian
composition direction, world-group identity, global bias initializer in
create_parameter, and the distributed resume-step agreement guard."""

import numpy as np
import pytest

import paddle_tpu as pt


def test_hfftn_ihfftn_match_torch_all_norms():
    """hfftn composes FORWARD fftn over leading axes (ihfftn the
    inverse); the frequency-reversed composition round-trips against
    itself, so pin against torch's reference implementation."""
    import torch
    from paddle_tpu import fft
    rng = np.random.RandomState(0)
    x = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
    xr = rng.randn(4, 6).astype(np.float32)
    for norm in ("backward", "forward", "ortho"):
        np.testing.assert_allclose(
            np.asarray(fft.hfftn(x, norm=norm)),
            torch.fft.hfftn(torch.from_numpy(x), norm=norm).numpy(),
            rtol=1e-4, atol=1e-4, err_msg=f"hfftn {norm}")
        np.testing.assert_allclose(
            np.asarray(fft.ihfftn(xr, norm=norm)),
            torch.fft.ihfftn(torch.from_numpy(xr), norm=norm).numpy(),
            rtol=1e-4, atol=1e-4, err_msg=f"ihfftn {norm}")
        np.testing.assert_allclose(
            np.asarray(fft.hfft2(x, norm=norm)),
            torch.fft.hfft2(torch.from_numpy(x), norm=norm).numpy(),
            rtol=1e-4, atol=1e-4, err_msg=f"hfft2 {norm}")


def test_new_group_before_world_access():
    """new_group() as the FIRST distributed call must not hijack the
    world group."""
    from paddle_tpu.distributed import comm
    saved_groups, saved_world = comm._groups, comm._world_group
    comm._groups, comm._world_group = [], None
    try:
        sub = comm.new_group([0])
        world = comm.get_group(0)
        assert world.gid == 0
        assert world.nranks >= 1
        assert sub.gid != 0
        assert comm.get_group(sub.gid) is sub
    finally:
        comm._groups, comm._world_group = saved_groups, saved_world


def test_create_parameter_global_bias_initializer():
    from paddle_tpu import nn
    from paddle_tpu.nn import initializer as I
    nn.initializer.set_global_initializer(I.Constant(2.0),
                                          I.Constant(0.5))
    try:
        w = pt.create_parameter([4], is_bias=False)
        b = pt.create_parameter([4], is_bias=True)
        np.testing.assert_allclose(np.asarray(w), 2.0)
        np.testing.assert_allclose(np.asarray(b), 0.5)
    finally:
        nn.initializer.set_global_initializer(None)


def test_agree_step_guard_fires_without_local_checkpoints(tmp_path):
    """A rank with NO local checkpoints receiving agreed >= 0 must get
    the diagnostic error (broken agree_fn), not an orbax missing-step
    failure."""
    from paddle_tpu import nn
    from paddle_tpu.io.checkpoint import AutoCheckpoint
    net = nn.Linear(2, 2)
    acp = AutoCheckpoint(str(tmp_path / "ckpt"), net)
    with pytest.raises(RuntimeError, match="global MIN"):
        list(acp.epochs(3, agree_step=lambda local: 1))
