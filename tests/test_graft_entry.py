"""Driver-artifact checks: entry() compiles, dryrun_multichip runs on the
8-device virtual mesh (what the driver does with
xla_force_host_platform_device_count=N)."""

import sys

import jax
import numpy as np

import pytest

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _load():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    return __graft_entry__


def test_entry_compiles():
    ge = _load()
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    # GPT-2-small flagship: [batch, seq, vocab] logits
    assert np.asarray(out).shape == (2, 256, 50304)
    assert np.all(np.isfinite(np.asarray(out)))


def test_dryrun_multichip_8():
    ge = _load()
    ge.dryrun_multichip(8)


def test_dryrun_multichip_4():
    ge = _load()
    ge.dryrun_multichip(4)
