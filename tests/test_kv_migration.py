"""KV-page migration for the disaggregated prefill/decode fleet
(ISSUE 18 tentpole).

Layers, inside out: the ``kv_pages/v1`` wire format rejects exactly
the corruptions it claims to (digest chain, checksum, geometry);
engine export → import roundtrips are byte- and token-exact at f32
AND int8 (deterministic quantization makes a migrated page identical
to the one the importer would have computed); accounting never leaks
a page (refcounts, the migrated memory-ledger row, free-pool
restoration at close); the router's disaggregated flow migrates only
past its threshold, and EVERY failure mode — injected transfer fault,
corrupt payload — degrades to nonce-pinned local recompute with an
identical token stream; per-role autoscalers size their own pools off
their own signals on an injectable clock."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference import kv_transfer as kvt
from paddle_tpu.inference.llm import LLMEngine
from paddle_tpu.inference.prefix_cache import (_SEED, chain_digest,
                                               page_digests)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.reliability import faults
from paddle_tpu.serving import Autoscaler, Router
from paddle_tpu.serving.replica import LocalReplica


def tiny_gpt(max_pos=96):
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=max_pos,
                     hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def mk_engine(kv_dtype="float32", num_pages=64, **kw):
    return LLMEngine(tiny_gpt(), max_seqs=4, page_size=4,
                     num_pages=num_pages, prefill_buckets=(32,),
                     seed=0, kv_dtype=kv_dtype, **kw)


def assert_no_leak(eng):
    # page 0 is the permanent scratch page; everything else must be
    # back in the free pool once the engine is closed
    assert len(eng._free_pages) == eng.num_pages - 1


PROMPT = list(range(1, 25))          # 24 tokens = 6 full pages
CHAIN = (len(PROMPT) - 1) // 4       # 5 exportable pages


# -- wire format (host only, no device) ---------------------------------


def _fake_chain(ps=4, n=3, kv_nbytes=32, scale_nbytes=0):
    """A synthetic, self-consistent page chain (not real KV — the
    verifier only checks identity/geometry, not contents)."""
    recs, parent = [], _SEED
    for i in range(n):
        toks = list(range(i * ps, (i + 1) * ps))
        d = chain_digest(parent, toks)
        k = bytes([i]) * kv_nbytes
        v = bytes([i + 100]) * kv_nbytes
        ks = vs = bytes(scale_nbytes)
        recs.append(kvt.encode_page(
            d, parent, toks, k, v,
            ks if scale_nbytes else b"", vs if scale_nbytes else b""))
        parent = d
    return kvt.make_payload(recs, kv_dtype="float32", page_size=ps,
                            kv_shape=[2, ps, 4, 1])


def _verify(payload, **over):
    kw = dict(kv_dtype="float32", page_size=4, kv_shape=[2, 4, 4, 1],
              kv_nbytes=32, scale_nbytes=0, resident=lambda d: False)
    kw.update(over)
    return kvt.verify_payload(payload, **kw)


def test_wire_roundtrip_accepts_honest_chain():
    acc, rej = _verify(_fake_chain())
    assert len(acc) == 3 and rej == []
    assert [r.tokens for r in acc] == [(0, 1, 2, 3), (4, 5, 6, 7),
                                       (8, 9, 10, 11)]


def test_wire_rejects_each_corruption_mode():
    # token tamper: the digest no longer commits to (parent, tokens)
    p = _fake_chain()
    p["pages"][1]["tokens"][0] = 77
    acc, rej = _verify(p)
    assert len(acc) == 1
    assert {r["reason"] for r in rej} == {"digest_mismatch",
                                          "orphan_parent"}
    # byte flip in flight: the transport checksum catches it, and the
    # chain BEHIND the rejected page orphans
    p = _fake_chain()
    k = bytearray(kvt._unb64(p["pages"][0]["k"]))
    k[5] ^= 0xFF
    p["pages"][0]["k"] = kvt._b64(bytes(k))
    acc, rej = _verify(p)
    assert acc == []
    assert rej[0]["reason"] == "checksum_mismatch"
    assert {r["reason"] for r in rej[1:]} == {"orphan_parent"}
    # wrong geometry bytes: the first page fails the length check and
    # the rest of the chain orphans behind it
    p = _fake_chain(kv_nbytes=16)
    acc, rej = _verify(p)
    assert acc == [] and rej[0]["reason"] == "bad_length"
    assert {r["reason"] for r in rej[1:]} == {"orphan_parent"}


def test_wire_geometry_mismatch_is_a_deployment_error():
    with pytest.raises(ValueError, match="kv_dtype"):
        _verify(_fake_chain(), kv_dtype="int8")
    with pytest.raises(ValueError, match="page_size"):
        _verify(_fake_chain(), page_size=8)
    with pytest.raises(ValueError, match="kv_shape"):
        _verify(_fake_chain(), kv_shape=[2, 4, 4, 2])
    with pytest.raises(ValueError, match="format"):
        kvt.verify_payload({"format": "bogus"}, kv_dtype="float32",
                           page_size=4, kv_shape=[1], kv_nbytes=1,
                           scale_nbytes=0, resident=lambda d: False)


def test_wire_resident_parent_anchors_a_suffix_run():
    p = _fake_chain()
    first = bytes.fromhex(p["pages"][0]["digest"])
    p["pages"] = p["pages"][1:]          # chain starts mid-history
    acc, rej = _verify(p, resident=lambda d: d == first)
    assert len(acc) == 2 and rej == []
    acc, rej = _verify(p, resident=lambda d: False)
    assert acc == [] and all(r["reason"] == "orphan_parent"
                             for r in rej)


# -- engine export / import roundtrip -----------------------------------


@pytest.mark.parametrize("kv_dtype", ["float32", "int8"])
def test_roundtrip_token_identical_and_leak_free(kv_dtype):
    src, dst, ref = (mk_engine(kv_dtype) for _ in range(3))
    try:
        want = ref.generate([PROMPT], max_new_tokens=8)[0]
        src.generate([PROMPT], max_new_tokens=1)
        digs = page_digests(PROMPT, 4)[:CHAIN]
        payload = src.export_pages(digs)
        assert payload["kv_dtype"] == ("int8" if kv_dtype == "int8"
                                       else "float32")
        assert len(payload["pages"]) == CHAIN
        if kv_dtype == "int8":
            assert "k_scales" in payload["pages"][0]
        res = dst.import_pages(payload)
        assert res == {"imported": CHAIN, "duplicates": 0,
                       "rejected": []}
        assert dst._cache.migrated_page_count == CHAIN
        # re-import is pure duplicates: nothing allocated twice
        res2 = dst.import_pages(payload)
        assert res2["imported"] == 0 and res2["duplicates"] == CHAIN
        # migrated pages serve the prompt's cached prefix and the
        # decode is token-identical to an engine that computed it all
        got = dst.generate([PROMPT], max_new_tokens=8)[0]
        assert got["output_ids"] == want["output_ids"]
        assert dst.n_cached_tokens == CHAIN * 4
    finally:
        for e in (src, dst, ref):
            e.close()
    for e in (src, dst, ref):
        assert_no_leak(e)


def test_roundtrip_seeded_sampling_identical():
    src, dst = mk_engine("int8"), mk_engine("int8")
    try:
        want = src.submit(PROMPT, max_new_tokens=8, temperature=0.8,
                          nonce=7).result(timeout=120)
        payload = src.export_pages(page_digests(PROMPT, 4)[:CHAIN])
        dst.import_pages(payload)
        got = dst.submit(PROMPT, max_new_tokens=8, temperature=0.8,
                         nonce=7).result(timeout=120)
        assert got["output_ids"] == want["output_ids"]
    finally:
        src.close()
        dst.close()


def test_import_rejects_corruption_then_recomputes_exactly():
    src, dst, ref = (mk_engine("int8") for _ in range(3))
    try:
        want = ref.generate([PROMPT], max_new_tokens=8)[0]
        src.generate([PROMPT], max_new_tokens=1)
        payload = src.export_pages(page_digests(PROMPT, 4)[:CHAIN])
        v = bytearray(kvt._unb64(payload["pages"][2]["v"]))
        v[0] ^= 0x01
        payload["pages"][2]["v"] = kvt._b64(bytes(v))
        res = dst.import_pages(payload)
        # the verified prefix installs; the corrupt page and its
        # descendants do not
        assert res["imported"] == 2
        reasons = {r["reason"] for r in res["rejected"]}
        assert "checksum_mismatch" in reasons
        assert len(res["rejected"]) == CHAIN - 2
        # decode recomputes the missing pages locally — exact anyway
        got = dst.generate([PROMPT], max_new_tokens=8)[0]
        assert got["output_ids"] == want["output_ids"]
    finally:
        for e in (src, dst, ref):
            e.close()
    for e in (src, dst, ref):
        assert_no_leak(e)


def test_export_stops_at_chain_break_and_nonresident():
    src = mk_engine("float32")
    try:
        src.generate([PROMPT], max_new_tokens=1)
        digs = page_digests(PROMPT, 4)[:CHAIN]
        # out-of-order request: digest 1 is not chained from the root
        assert src.export_pages([digs[1], digs[0]])["pages"] == []
        # a non-resident digest truncates the run
        fake = chain_digest(digs[-1], [1, 2, 3, 4])
        out = src.export_pages(digs[:2] + [fake] + digs[2:])
        assert len(out["pages"]) == 2
    finally:
        src.close()


def test_import_pool_exhaustion_rejects_tail_leaks_nothing():
    src = mk_engine("float32")
    # 4 pages: scratch + 3 usable — fewer free pages than the 5-page
    # chain wants, so the tail must reject without leaking
    dst = mk_engine("float32", num_pages=4)
    try:
        src.generate([PROMPT], max_new_tokens=1)
        payload = src.export_pages(page_digests(PROMPT, 4)[:CHAIN])
        res = dst.import_pages(payload)
        assert res["imported"] < CHAIN
        assert any(r["reason"] == "no_free_pages"
                   for r in res["rejected"])
        assert res["imported"] + len(res["rejected"]) == CHAIN
        assert dst._cache.migrated_page_count == res["imported"]
    finally:
        src.close()
        dst.close()
    assert_no_leak(src)
    assert_no_leak(dst)


def test_migration_accounting_metrics_and_ledger():
    from paddle_tpu.observability import memory as memobs
    src, dst = mk_engine("int8"), mk_engine("int8")
    try:
        src.generate([PROMPT], max_new_tokens=1)
        payload = src.export_pages(page_digests(PROMPT, 4)[:CHAIN])
        dst.import_pages(payload)
        exp = src._m["migrate_pages"].labels("export").value
        imp = dst._m["migrate_pages"].labels("import").value
        assert exp >= CHAIN and imp >= CHAIN
        assert src._m["migrate_bytes"].labels("export").value > 0
        # the memory ledger attributes migrated pages under their own
        # "migrated" detail row, carved out of prefix_shared
        rows = [r for r in memobs.instance().rows()
                if r.get("kind") == "migrated"]
        assert rows and rows[0]["bytes"] > 0
        assert dst._cache.n_imported == CHAIN
    finally:
        src.close()
        dst.close()


def test_engine_fault_sites_fire():
    src = mk_engine("float32")
    try:
        src.generate([PROMPT], max_new_tokens=1)
        digs = page_digests(PROMPT, 4)[:CHAIN]
        faults.enable(seed=3)
        faults.inject("kv.export", nth=(1,))
        with pytest.raises(faults.FaultInjected):
            src.export_pages(digs)
        payload = src.export_pages(digs)      # second call is clean
        faults.inject("kv.import", nth=(1,))
        with pytest.raises(faults.FaultInjected):
            src.import_pages(payload)
    finally:
        faults.reset()
        src.close()
    assert_no_leak(src)


# -- router: role-aware dispatch + migrate-or-recompute ------------------


@pytest.fixture
def disagg_fleet():
    pre, dec, ref = (mk_engine("int8") for _ in range(3))
    r = Router(page_size=4, disagg_threshold_tokens=8,
               health_poll_interval=5.0)
    r.attach("p0", LocalReplica(pre), role="prefill")
    r.attach("d0", LocalReplica(dec), role="decode")
    yield r, pre, dec, ref
    r.close()
    for e in (pre, dec, ref):
        e.close()
    for e in (pre, dec, ref):
        assert_no_leak(e)


def test_router_migrates_long_prompts_to_decode_pool(disagg_fleet):
    r, pre, dec, ref = disagg_fleet
    want = ref.generate([PROMPT], max_new_tokens=8)[0]
    out = r.submit(PROMPT, max_new_tokens=8).result(timeout=120)
    assert out["replica"] == "d0"              # decode pool serves
    assert out["prefill_replica"] == "p0"      # prefill pool filled
    assert out["migrated_pages"] == CHAIN
    assert out["migrate_s"] > 0
    assert out["output_ids"] == want["output_ids"]
    assert dec.n_cached_tokens == CHAIN * 4    # served off the pages
    assert r.n_migrations == 1 and r.n_migrate_failed == 0
    # the residency view skips migration for the now-warm prefix
    out2 = r.submit(PROMPT, max_new_tokens=8).result(timeout=120)
    assert out2["replica"] == "d0" and "migrate_s" not in out2
    assert out2["output_ids"] == want["output_ids"]
    assert r.n_migrations == 1
    fz = r._fleetz()
    assert fz["roles"]["prefill"]["attached"] == 1
    assert fz["roles"]["decode"]["attached"] == 1
    assert fz["migrations"]["completed"] == 1
    assert fz["migrations"]["pages"] == CHAIN


def test_router_threshold_edge_short_prompts_stay_local(disagg_fleet):
    r, pre, dec, ref = disagg_fleet
    short = PROMPT[:9]      # 9 tokens: 2 full pages = 8 uncached at
    want = ref.generate([short], max_new_tokens=4)[0]
    out = r.submit(short, max_new_tokens=4).result(timeout=120)
    # exactly AT the threshold (uncached == 9 > 8)… one page over:
    # the estimate is the whole prompt (9) vs threshold 8 → migrates
    # only if cap > 0 pages are transferable; with 2 full pages the
    # decision hinges on uncached > threshold. 9 > 8 → migrate.
    assert out["output_ids"] == want["output_ids"]
    # strictly below: 8 tokens (uncached 8 ≤ 8) must NOT migrate
    n0 = r.n_migrations
    tiny = list(range(50, 58))
    out = r.submit(tiny, max_new_tokens=4).result(timeout=120)
    assert out["replica"] == "d0" and "migrate_s" not in out
    assert r.n_migrations == n0
    # sub-page prompts trivially stay local
    out = r.submit([3, 1, 4], max_new_tokens=4).result(timeout=120)
    assert "migrate_s" not in out


def test_router_transfer_fault_falls_back_token_identical(
        disagg_fleet):
    r, pre, dec, ref = disagg_fleet
    want = ref.generate([PROMPT], max_new_tokens=8)[0]
    faults.enable(seed=5)
    faults.inject("router.migrate", nth=(1,))
    try:
        out = r.submit(PROMPT, max_new_tokens=8).result(timeout=120)
    finally:
        faults.reset()
    # the migration was abandoned; the decode replica recomputed
    # locally under the pinned nonce — same tokens, request not lost
    assert out["replica"] == "d0"
    assert "migrate_s" not in out
    assert out["output_ids"] == want["output_ids"]
    assert r.n_migrate_failed == 1 and r.n_migrations == 0


def test_router_prefill_pool_is_decode_fallback_of_last_resort():
    pre, ref = mk_engine("int8"), mk_engine("int8")
    r = Router(page_size=4, health_poll_interval=5.0)
    r.attach("p0", LocalReplica(pre), role="prefill")
    try:
        want = ref.generate([PROMPT], max_new_tokens=4)[0]
        out = r.submit(PROMPT, max_new_tokens=4).result(timeout=120)
        # no decode pool exists: the prefill replica serves rather
        # than shedding — never lose a request to pool purity
        assert out["replica"] == "p0"
        assert out["output_ids"] == want["output_ids"]
    finally:
        r.close()
        pre.close()
        ref.close()


# -- autoscaler: per-role pools on an injectable clock -------------------


class _RoleClient:
    def health(self):
        return "healthy"


class _RoleHandle:
    def alive(self):
        return True

    def terminate(self, grace_s=0.0):
        pass


class _RoleRouter:
    """Role-filtering slice of the Router surface the Autoscaler
    consumes: two pools with independently scripted load."""

    health_poll_interval = 0.0

    def __init__(self):
        self.replicas = {}          # name -> {"role", "warming"}
        self.inflight = {}
        self.expected = set()

    def expect_warming(self, name):
        self.expected.add(name)

    def attach(self, name, client, warming=False, role=None):
        self.replicas[name] = {
            "role": role or "unified",
            "warming": warming or name in self.expected}

    def mark_ready(self, name):
        self.expected.discard(name)
        self.replicas[name]["warming"] = False
        return True

    def drain(self, name):
        return name in self.replicas

    def inflight_of(self, name):
        return self.inflight.get(name, 0)

    def detach(self, name):
        self.replicas.pop(name, None)
        self.expected.discard(name)

    def fleet_load(self, slots=None, role=None):
        names = [n for n, r in self.replicas.items()
                 if role is None or r["role"] == role]
        ready = [n for n in names
                 if not self.replicas[n]["warming"]]
        infl = sum(self.inflight.get(n, 0) for n in ready)
        cap = (slots or 4) * len(ready)
        return {"attached": len(names), "ready": len(ready),
                "warming": len(names) - len(ready), "draining": 0,
                "inflight": infl, "capacity": cap,
                "occupancy": (infl / cap) if cap else None,
                "ready_names": sorted(ready)}

    def add_poll_hook(self, fn):
        pass

    def remove_poll_hook(self, fn):
        pass


def test_autoscaler_sizes_each_role_off_its_own_signal():
    router = _RoleRouter()
    router.attach("p0", _RoleClient(), role="prefill")
    router.attach("d0", _RoleClient(), role="decode")
    clock = [0.0]

    def mk_scaler(role):
        return Autoscaler(
            router, lambda name: (_RoleClient(), _RoleHandle()),
            min_replicas=1, max_replicas=3, replica_slots=4,
            high_water=0.8, low_water=0.1, role=role,
            synchronous=True, dwell_s=0.0, backoff_base_s=0.0,
            clock=lambda: clock[0],
            sleep=lambda s: clock.__setitem__(0, clock[0] + s),
            burn_fn=lambda: {})

    prefill_as, decode_as = mk_scaler("prefill"), mk_scaler("decode")
    # prefill pool saturated, decode idle: ONLY prefill scales out
    router.inflight["p0"] = 4
    router.inflight["d0"] = 0
    clock[0] += 1.0
    assert prefill_as.tick() == "scale_out"
    assert decode_as.tick() is None
    spawned = [n for n, r in router.replicas.items()
               if r["role"] == "prefill" and n != "p0"]
    assert len(spawned) == 1 and spawned[0].startswith("auto-prefill")
    assert router.fleet_load(4, role="prefill")["ready"] == 2
    assert router.fleet_load(4, role="decode")["ready"] == 1
    # decode pool saturated next: only decode scales, role-tagged
    router.inflight["p0"] = 0
    router.inflight[spawned[0]] = 0
    router.inflight["d0"] = 4
    clock[0] += 100.0
    assert decode_as.tick() == "scale_out"
    dec_new = [n for n, r in router.replicas.items()
               if r["role"] == "decode" and n != "d0"]
    assert len(dec_new) == 1 and dec_new[0].startswith("auto-decode")
    # /scalez reports the role
    assert prefill_as._scalez()["config"]["role"] == "prefill"
    assert decode_as._scalez()["config"]["role"] == "decode"
    prefill_as.close()
    decode_as.close()
