"""Profiler facade + StatRegistry (ref: unittests/test_profiler.py,
test_newprofiler.py — SURVEY.md §5)."""

import glob
import os

import jax.numpy as jnp
import numpy as np

from paddle_tpu import profiler
from paddle_tpu.core.monitor import StatRegistry, stat_add, stat_get


def test_scheduler_states():
    sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                    skip_first=1)
    states = [sched(i) for i in range(6)]
    S = profiler.ProfilerState
    assert states[0] == S.CLOSED          # skip_first
    assert states[1] == S.CLOSED
    assert states[2] == S.READY
    assert states[3] == S.RECORD
    assert states[4] == S.RECORD_AND_RETURN
    assert states[5] == S.CLOSED          # next cycle

def test_profiler_captures_trace_and_summary(tmp_path):
    log_dir = str(tmp_path / "prof")
    prof = profiler.Profiler(log_dir=log_dir)
    prof.start()
    for _ in range(3):
        with profiler.RecordEvent("train_step"):
            x = jnp.ones((128, 128))
            (x @ x).block_until_ready()
        prof.step()
    prof.stop()
    # XProf dump exists
    found = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                      recursive=True)
    assert found, os.listdir(log_dir)
    table = prof.summary()
    assert "train_step" in table and "Calls" in table
    assert "       3" in table  # 3 calls aggregated


def test_record_event_nesting_without_profiler():
    # RecordEvent outside an active profiler must be a cheap no-op
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass


def test_stat_registry():
    reg = StatRegistry.instance()
    reg.reset()
    stat_add("batches", 3)
    stat_add("batches")
    assert stat_get("batches") == 4
    reg.set("lr", 0.1)
    snap = reg.snapshot()
    assert snap["lr"] == 0.1 and snap["batches"] == 4


def test_summary_model_perspective_table(tmp_path):
    """Model.fit under an active Profiler auto-fills the
    Dataloader/TrainStep/Callbacks buckets and summary() renders the
    reference-style model-perspective table with ratios
    (ref: profiler_statistic.py SummaryView model table)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.profiler import Profiler, SortedKeys

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss())
    from paddle_tpu.io import TensorDataset
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))
    prof = Profiler(log_dir=str(tmp_path / "prof"))
    prof.start()
    m.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0)
    prof.stop()
    rep = prof.summary(sorted_by=SortedKeys.CPUTotal)
    assert "Model Perspective" in rep
    for bucket in ("Dataloader", "TrainStep", "Callbacks"):
        assert bucket in rep, rep
    assert "%" in rep and "Host Events" in rep
