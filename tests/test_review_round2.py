"""Round-2 weak-item coverage: AMP custom lists, the dygraph training
idiom, sparse value ops + grads, NaN/Inf attribution.

Analogs: reference amp white/black list tests (test_amp_base),
dygraph train loop tests (test_imperative_mnist), incubate sparse unary
tests, and test_nan_inf (FLAGS_check_nan_inf)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp, autograd, nn, sparse
from paddle_tpu.amp import debugging
from paddle_tpu.nn import functional as F


# -- AMP custom white/black lists ------------------------------------------

def test_amp_black_list_keeps_op_fp32():
    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    with amp.auto_cast(enable=True):
        assert F.linear(x, w).dtype == jnp.bfloat16
    with amp.auto_cast(enable=True, custom_black_list=["matmul"]):
        assert F.linear(x, w).dtype == jnp.float32
    # black-listing conv2d must not affect matmul
    with amp.auto_cast(enable=True, custom_black_list=["conv2d"]):
        assert F.linear(x, w).dtype == jnp.bfloat16


def test_amp_black_list_conv():
    x = jnp.ones((1, 3, 8, 8), jnp.float32)
    w = jnp.ones((4, 3, 3, 3), jnp.float32)
    with amp.auto_cast(enable=True):
        assert F.conv2d(x, w).dtype == jnp.bfloat16
    with amp.auto_cast(enable=True, custom_black_list=["conv2d"]):
        assert F.conv2d(x, w).dtype == jnp.float32


def test_amp_white_list_layer_norm_runs_low_precision():
    x = jnp.ones((4, 16), jnp.bfloat16)
    # default: fp32 statistics — check by tracing for convert ops is
    # overkill; observable contract: white-listed LN of bf16 stays bf16
    # end-to-end AND a large-dynamic-range input shows the numeric
    # difference between fp32 and bf16 statistics
    big = (jnp.arange(64, dtype=jnp.float32)
           .reshape(4, 16) * 100.0).astype(jnp.bfloat16)
    with amp.auto_cast(enable=True):
        default = np.asarray(F.layer_norm(big, 16), np.float32)
    with amp.auto_cast(enable=True, custom_white_list=["layer_norm"]):
        white = np.asarray(F.layer_norm(big, 16), np.float32)
    assert not np.allclose(default, white), \
        "white-listed layer_norm should use low-precision statistics"


def test_amp_white_list_softmax():
    x = jnp.linspace(-1, 1, 8, dtype=jnp.float32)[None]
    with amp.auto_cast(enable=True, custom_white_list=["softmax"]):
        assert F.softmax(x).dtype == jnp.bfloat16
    with amp.auto_cast(enable=True):
        assert F.softmax(x).dtype == jnp.float32


def test_amp_lists_restore_on_exit():
    with amp.auto_cast(enable=True, custom_black_list=["matmul"]):
        pass
    x = jnp.ones((2, 4))
    w = jnp.ones((4, 2))
    with amp.auto_cast(enable=True):
        assert F.linear(x, w).dtype == jnp.bfloat16


def test_model_prepare_passes_amp_lists():
    pt.seed(0)
    net = nn.Linear(8, 4)
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.1, parameters=net),
        loss=nn.MSELoss(),
        amp_configs={"level": "O1", "custom_black_list": ["matmul"]})
    ctx = model._amp_context()
    with ctx:
        assert F.linear(jnp.ones((2, 8)), jnp.ones((8, 4))).dtype == \
            jnp.float32


# -- dygraph idiom ----------------------------------------------------------

def test_dygraph_record_backward_step_trains():
    """The reference's loss.backward(); opt.step() loop, via the
    explicit-thunk tape (tapeless-autodiff design decision)."""
    pt.seed(0)
    net = nn.Sequential(("fc1", nn.Linear(8, 16)), ("act", nn.ReLU()),
                        ("fc2", nn.Linear(16, 2)))
    opt = pt.optimizer.Adam(learning_rate=0.05, parameters=net)
    crit = nn.MSELoss()
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(16, 8), jnp.float32)
    y = jnp.asarray(r.randn(16, 2), jnp.float32)

    losses = []
    for _ in range(20):
        tape = autograd.record(net)
        loss = tape.run(lambda: crit(net(x), y))
        grads = tape.backward()
        opt.step(grads)
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses[:2] + losses[-2:]
    # params actually moved inside the live layer objects
    assert float(jnp.abs(net.fc1.weight).sum()) > 0


def test_dygraph_minimize_equivalent():
    pt.seed(0)
    net = nn.Linear(4, 1)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net)
    x = jnp.ones((8, 4))
    y = jnp.zeros((8, 1))

    from paddle_tpu.nn.layer import functional_call

    def loss_fn(params):
        out, _ = functional_call(net, params, {}, x)
        return ((out - y) ** 2).mean()

    l0 = float(loss_fn(dict(net.named_parameters())))
    for _ in range(5):
        opt.minimize(loss_fn)
    l1 = float(loss_fn(dict(net.named_parameters())))
    assert l1 < l0


def test_record_updates_buffers():
    """BN running stats mutated inside the taped forward persist."""
    pt.seed(0)
    net = nn.Sequential(("fc", nn.Linear(4, 6)),
                        ("bn", nn.BatchNorm1D(6)))
    net.train()
    before = np.asarray(net.bn._mean).copy()
    tape = autograd.record(net)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 4), jnp.float32)
    tape.run(lambda: net(x).sum())
    after = np.asarray(net.bn._mean)
    assert not np.allclose(before, after)


# -- sparse value ops -------------------------------------------------------

def _sp(seed=0):
    d = np.zeros((4, 5), np.float32)
    r = np.random.RandomState(seed)
    idx = r.choice(20, 6, replace=False)
    d.flat[idx] = r.randn(6)
    return sparse.SparseCooTensor.from_dense(d), d


@pytest.mark.parametrize("op,ref", [
    ("relu", lambda d: np.maximum(d, 0)),
    ("tanh", np.tanh),
    ("square", np.square),
    ("neg", np.negative),
    ("expm1", np.expm1),
])
def test_sparse_unary_matches_dense(op, ref):
    sp, d = _sp()
    out = getattr(sparse, op)(sp)
    assert isinstance(out, sparse.SparseCooTensor)
    np.testing.assert_allclose(np.asarray(out.to_dense()), ref(d),
                               rtol=1e-6, atol=1e-6)


def test_sparse_transpose_and_mv():
    sp, d = _sp(1)
    t = sparse.transpose(sp, [1, 0])
    np.testing.assert_allclose(np.asarray(t.to_dense()), d.T)
    v = np.random.RandomState(2).randn(5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(sparse.mv(sp, v)), d @ v,
                               rtol=1e-5, atol=1e-5)


def test_sparse_matmul_grads_flow_through_values():
    """d(loss)/d(values) through the sparse matmul — no densify."""
    sp, d = _sp(3)
    dense = jnp.asarray(
        np.random.RandomState(4).randn(5, 3), jnp.float32)
    b = sp._bcoo

    def loss(values):
        import jax.experimental.sparse as js
        m = js.BCOO((values, b.indices), shape=b.shape)
        return ((m @ dense) ** 2).sum()

    g = jax.grad(loss)(b.data)
    assert g.shape == b.data.shape
    # numeric check on one value
    eps = 1e-3
    v0 = b.data
    lp = float(loss(v0.at[0].add(eps)))
    lm = float(loss(v0.at[0].add(-eps)))
    np.testing.assert_allclose(float(g[0]), (lp - lm) / (2 * eps),
                               rtol=5e-2, atol=1e-3)


# -- NaN/Inf attribution ----------------------------------------------------

def test_find_nonfinite_names_bad_tensors():
    tree = {"w": jnp.ones((3,)),
            "b": jnp.asarray([1.0, np.inf]),
            "nested": {"m": jnp.asarray([np.nan])}}
    bad = debugging.find_nonfinite(tree)
    assert any("b" in n for n in bad)
    assert any("m" in n for n in bad)
    assert not any(n == "w" for n in bad)


def test_check_numerics_eager_raises():
    debugging.check_numerics(jnp.ones((3,)), "ok")
    with pytest.raises(FloatingPointError, match="bad_tensor"):
        debugging.check_numerics(jnp.asarray([np.nan]), "bad_tensor")


def test_tensor_checker_toggles_debug_nans():
    assert not jax.config.jax_debug_nans
    debugging.enable_tensor_checker(debugging.TensorCheckerConfig())
    try:
        assert jax.config.jax_debug_nans
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)
                                          ).block_until_ready()
    finally:
        debugging.disable_tensor_checker()
    assert not jax.config.jax_debug_nans


def test_trainer_flag_reports_bad_tensor_names():
    from paddle_tpu.core import flags
    pt.seed(0)
    net = nn.Linear(4, 2)
    net.weight = jnp.full((4, 2), np.nan, jnp.float32)
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.1, parameters=net),
        loss=nn.MSELoss())
    flags.set_flags({"check_nan_inf": True})
    try:
        # the check is DEFERRED to the buffered drain (ISSUE 9: no
        # per-step host sync) — train_batch returns, the next drain
        # boundary raises with the per-tensor report
        with pytest.raises(FloatingPointError, match="weight"):
            model.train_batch([np.ones((2, 4), np.float32)],
                              [np.zeros((2, 2), np.float32)])
            model.drain_metrics()
    finally:
        flags.set_flags({"check_nan_inf": False})
