"""Serving concurrency tests (VERDICT r3 missing #6 / ask #8).

The reference serves AnalysisPredictor behind multi-threaded servers
with one predictor clone per thread (ref:
paddle/fluid/inference/api/analysis_predictor.h:95 + capi_exp thread
pools). Here ONE predictor serves all threads (PJRT execute is
re-entrant; per-request result handles remove the shared-output race),
and a DynamicBatcher coalesces queued rows into full-batch device
calls — the TPU-appropriate inversion of clone-per-thread.

Batcher mechanics run against a stub predictor (no hardware); the true
concurrent-run test follows test_inference_native's skip-on-busy
pattern against the real plugin.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.inference import DynamicBatcher


class StubPredictor:
    """Deterministic stand-in: y = x * 2 rowwise, records call shapes."""

    def __init__(self, delay=0.0):
        self.calls = []
        self.delay = delay
        self.lock = threading.Lock()

    def run(self, inputs):
        with self.lock:
            self.calls.append([a.shape for a in inputs])
        if self.delay:
            time.sleep(self.delay)
        return [inputs[0] * 2.0]


def test_batcher_coalesces_to_one_device_call():
    pred = StubPredictor()
    with DynamicBatcher(pred, max_batch=8, max_delay_ms=50) as b:
        futs = [b.submit([np.full((1, 4), float(i), np.float32)])
                for i in range(8)]
        outs = [f.result(timeout=10) for f in futs]
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o[0], np.full((1, 4), 2.0 * i))
        assert o[0].shape == (1, 4)
    # 8 single-row requests, batch capacity 8 -> ideally 1 call; the
    # worker may cut an early pack before all requests enqueue, but
    # coalescing must beat request-per-call
    assert pred.calls and all(s[0] == (8, 4) for s in pred.calls)
    assert b.n_device_calls < 8
    assert b.n_requests == 8


def test_batcher_pads_partial_batch():
    pred = StubPredictor()
    with DynamicBatcher(pred, max_batch=8, max_delay_ms=5) as b:
        out = b.run([np.ones((3, 2), np.float32)])
    assert out[0].shape == (3, 2)  # padding sliced back off
    assert pred.calls[0][0] == (8, 2)  # device saw the full batch


def test_batcher_multirow_and_overflow_holdover():
    """5+5 rows into batch 8: second request must be deferred to a
    second pack, order preserved, both correct."""
    pred = StubPredictor(delay=0.01)
    with DynamicBatcher(pred, max_batch=8, max_delay_ms=30) as b:
        f1 = b.submit([np.full((5, 2), 1.0, np.float32)])
        f2 = b.submit([np.full((5, 2), 3.0, np.float32)])
        o1 = f1.result(timeout=10)[0]
        o2 = f2.result(timeout=10)[0]
    np.testing.assert_allclose(o1, np.full((5, 2), 2.0))
    np.testing.assert_allclose(o2, np.full((5, 2), 6.0))
    assert b.n_device_calls == 2


def test_batcher_rejects_oversized_and_ragged():
    pred = StubPredictor()
    with DynamicBatcher(pred, max_batch=4, max_delay_ms=1) as b:
        with pytest.raises(ValueError):
            b.submit([np.ones((5, 2), np.float32)])
        with pytest.raises(ValueError):
            b.submit([np.ones((2, 2), np.float32),
                      np.ones((3, 2), np.float32)])


def test_batcher_propagates_run_errors():
    class Boom:
        def run(self, inputs):
            raise RuntimeError("device gone")

    with DynamicBatcher(Boom(), max_batch=4, max_delay_ms=1) as b:
        fut = b.submit([np.ones((1, 2), np.float32)])
        with pytest.raises(RuntimeError, match="device gone"):
            fut.result(timeout=10)


def test_batcher_survives_mismatched_trailing_shapes():
    """A pack whose rows can't concatenate must fail ITS futures and
    leave the worker alive for later requests."""
    pred = StubPredictor(delay=0.01)
    with DynamicBatcher(pred, max_batch=8, max_delay_ms=30) as b:
        f1 = b.submit([np.ones((1, 4), np.float32)])
        f2 = b.submit([np.ones((1, 6), np.float32)])  # ragged trailing
        excs = 0
        for f in (f1, f2):
            try:
                f.result(timeout=10)
            except ValueError:
                excs += 1
        assert excs >= 1  # at least the pack that mixed shapes failed
        out = b.run([np.ones((1, 4), np.float32)])  # worker still alive
        np.testing.assert_allclose(out[0], np.full((1, 4), 2.0))


def test_batcher_close_contract():
    """close() completes accepted work, then rejects new submits —
    FIFO ordering (submit's check+put and close's set+STOP share one
    lock) means every accepted request is ahead of STOP and served."""
    pred = StubPredictor(delay=0.01)
    b = DynamicBatcher(pred, max_batch=4, max_delay_ms=1)
    futs = [b.submit([np.full((1, 2), float(i), np.float32)])
            for i in range(6)]
    b.close()
    assert not b._worker.is_alive()
    for i, f in enumerate(futs):  # all accepted requests completed
        np.testing.assert_allclose(f.result(timeout=5)[0],
                                   np.full((1, 2), 2.0 * i))
    with pytest.raises(RuntimeError, match="batcher closed"):
        b.submit([np.ones((1, 2), np.float32)])


def test_batcher_drain_serves_accepted_work():
    """A graceful close must FLUSH work whose submit() already
    succeeded (r4 advisor finding), not fail it: queued and held items
    are packed like the live loop and every future resolves."""
    from concurrent.futures import Future
    pred = StubPredictor()
    b = DynamicBatcher(pred, max_batch=4, max_delay_ms=1)
    b.close()
    f1, f2 = Future(), Future()
    b._q.put(([np.ones((1, 2), np.float32)], 1, f1))
    b._held = ([np.full((1, 2), 3.0, np.float32)], 1, f2)
    b._drain()
    np.testing.assert_allclose(f1.result(timeout=5)[0],
                               np.full((1, 2), 2.0))
    np.testing.assert_allclose(f2.result(timeout=5)[0],
                               np.full((1, 2), 6.0))
    assert b._held is None
    # both fit one pack: the drain coalesces like the live loop
    assert pred.calls and pred.calls[-1][0][0] == 4  # padded to max


def test_batcher_close_resolves_inflight_submits():
    """End-to-end: submits accepted just before close() all resolve
    with results after close() returns."""
    pred = StubPredictor()
    b = DynamicBatcher(pred, max_batch=8, max_delay_ms=50)
    futs = [b.submit([np.full((1, 2), float(i), np.float32)])
            for i in range(5)]
    b.close()
    for i, f in enumerate(futs):
        np.testing.assert_allclose(f.result(timeout=5)[0],
                                   np.full((1, 2), 2.0 * i))
    with pytest.raises(RuntimeError, match="batcher closed"):
        b.submit([np.zeros((1, 2), np.float32)])


def test_batcher_threaded_clients_all_served():
    pred = StubPredictor(delay=0.002)
    results = {}
    with DynamicBatcher(pred, max_batch=4, max_delay_ms=10) as b:
        def client(i):
            out = b.run([np.full((1, 3), float(i), np.float32)])
            results[i] = out[0]

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    assert len(results) == 16
    for i, o in results.items():
        np.testing.assert_allclose(o, np.full((1, 3), 2.0 * i))
    assert b.n_device_calls < 16  # coalescing actually happened


# ---- serve_llm error-mapping contract over real HTTP (ISSUE 6)
#
# The fleet router routes on these exact status codes; pinning them
# here keeps the engine front and the HTTPReplica client in lockstep:
# shed/queue-full → 429, draining → 503, deadline → 504, cancel → 499.


import json as _json
from urllib.error import HTTPError
from urllib.request import Request, urlopen


@pytest.fixture(scope="module")
def llm_http():
    """One tiny engine behind serve_llm, shared by the mapping tests
    (each test restores any engine state it pokes)."""
    from paddle_tpu.inference.llm import serve_llm
    from paddle_tpu.serving.replica import make_engine_from_spec
    eng = make_engine_from_spec({"vocab": 97, "layers": 2,
                                 "hidden": 64})
    eng.submit([1, 2, 3], max_new_tokens=2).result(timeout=300)  # warm
    srv = serve_llm(eng)
    host, port = srv.server_address[:2]
    yield eng, f"http://{host}:{port}"
    srv.shutdown()
    eng.close()


def _post(base, path, body):
    req = Request(base + path, data=_json.dumps(body).encode(),
                  headers={"Content-Type": "application/json"})
    try:
        with urlopen(req, timeout=120) as r:
            return r.status, _json.loads(r.read())
    except HTTPError as e:
        return e.code, _json.loads(e.read())


def test_serve_llm_ok_carries_request_id(llm_http):
    _, base = llm_http
    code, out = _post(base, "/generate",
                      {"prompt_ids": [4, 5, 6], "max_new_tokens": 3})
    assert code == 200
    assert len(out["output_ids"]) == 3
    assert isinstance(out["request_id"], int)


def test_serve_llm_shed_maps_to_429(llm_http):
    eng, base = llm_http
    saved = eng.max_pending
    eng.max_pending = 0          # every submission is queue overflow
    try:
        code, out = _post(base, "/generate", {"prompt_ids": [1, 2]})
    finally:
        eng.max_pending = saved
    assert code == 429, (code, out)
    assert out["outcome"] == "shed" and out["reason"] == "queue_full"


def test_serve_llm_draining_maps_to_503(llm_http):
    eng, base = llm_http
    eng._health = "draining"     # the sticky latch, forced
    try:
        code, out = _post(base, "/generate", {"prompt_ids": [1, 2]})
    finally:
        eng.reset_health()
    assert code == 503, (code, out)
    assert out["outcome"] == "shed" and out["reason"] == "draining"
    assert eng.health == "healthy"


def test_serve_llm_deadline_maps_to_504(llm_http):
    _, base = llm_http
    code, out = _post(base, "/generate",
                      {"prompt_ids": [1, 2, 3], "deadline_s": -1.0})
    assert code == 504, (code, out)
    assert out["outcome"] == "deadline"


def test_serve_llm_cancel_maps_to_499(llm_http):
    eng, base = llm_http
    res = {}

    def client():
        res["resp"] = _post(base, "/generate",
                            {"prompt_ids": [7, 8, 9, 10],
                             "max_new_tokens": 80})

    t = threading.Thread(target=client)
    t.start()
    deadline = time.time() + 60
    rid = None
    while time.time() < deadline and rid is None:
        ids = list(eng._by_id)
        rid = ids[0] if ids else None
        time.sleep(0.005)
    assert rid is not None, "request never reached the engine"
    code, out = _post(base, "/cancel", {"request_id": rid})
    assert code == 200 and out["cancelled"] is True
    t.join(timeout=120)
    code, out = res["resp"]
    assert code == 499, (code, out)
    assert out["outcome"] == "cancelled"
    # cancelling a resolved request reports False, not an error
    code, out = _post(base, "/cancel", {"request_id": rid})
    assert code == 200 and out["cancelled"] is False


def test_serve_llm_nonce_passthrough_pins_stream(llm_http):
    _, base = llm_http
    body = {"prompt_ids": [11, 12, 13, 14], "max_new_tokens": 5,
            "temperature": 0.9, "nonce": 4242}
    _, out1 = _post(base, "/generate", body)
    _, out2 = _post(base, "/generate", body)
    assert out1["output_ids"] == out2["output_ids"]


def test_serve_llm_bad_request_maps_to_400(llm_http):
    _, base = llm_http
    code, out = _post(base, "/generate", {"prompt_ids": []})
    assert code == 400 and "error" in out


def test_serve_llm_response_carries_stream_integrity_headers(llm_http):
    """ISSUE 19 contract: a generate response carries its chain head
    (X-Stream-Digest) and the serving engine's knob fingerprint
    (X-Engine-Knobs) as headers, matching the body, so a caller can
    verify the stream without parsing JSON."""
    from paddle_tpu.observability import audit
    _, base = llm_http
    req = Request(base + "/generate",
                  data=_json.dumps({"prompt_ids": [7, 8, 9],
                                    "max_new_tokens": 4,
                                    "nonce": 99}).encode(),
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=120) as r:
        code, hdrs, out = r.status, dict(r.headers), \
            _json.loads(r.read())
    assert code == 200 and out["nonce"] == 99
    # header == body == the chain recomputed from the tokens
    assert hdrs["X-Stream-Digest"] == out["stream_digest"] == \
        audit.chain_of(99, out["output_ids"]).hex()
    knobs = _json.loads(hdrs["X-Engine-Knobs"])
    assert knobs == out["knobs"]
    assert set(knobs) == {"kv_dtype", "spec_k", "spec_slab", "draft"}


# ---- real-plugin concurrency (skip-on-busy, like test_inference_native)


def _plugin_available() -> bool:
    try:
        from paddle_tpu import inference
        inference.default_plugin()
        return True
    except Exception:
        return False


@pytest.mark.slow
@pytest.mark.skipif(not _plugin_available(),
                    reason="no PJRT plugin .so on this machine")
def test_concurrent_predictor_run_matches_serial(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu import jit

    class MLP(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = pt.nn.Linear(16, 64)
            self.l2 = pt.nn.Linear(64, 8)

        def forward(self, x):
            return self.l2(pt.nn.functional.relu(self.l1(x)))

    pt.seed(0)
    net = MLP()
    net.eval()
    rng = np.random.RandomState(0)
    xs = [rng.randn(4, 16).astype(np.float32) for _ in range(12)]
    refs = [np.asarray(net(x)) for x in xs]
    path = str(tmp_path / "artifact")
    jit.save(net, path, input_spec=[jit.InputSpec([4, 16], "float32")])

    from paddle_tpu import inference
    os.environ.setdefault("PT_PJRT_CREATE_TIMEOUT", "90")
    try:
        pred = inference.create_predictor(inference.Config(path))
    except TimeoutError as e:
        pytest.skip(f"device unavailable for native predictor: {e}")

    outs = [None] * len(xs)
    errs = []

    def worker(tid):
        try:
            for i in range(tid, len(xs), 4):
                outs[i] = pred.run([xs[i]])[0]
        except BaseException as e:  # surfaced below
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errs, errs
    for o, r in zip(outs, refs):
        assert o is not None
        np.testing.assert_allclose(o, r, atol=5e-2, rtol=2e-2)


@pytest.mark.slow
@pytest.mark.skipif(not _plugin_available(),
                    reason="no PJRT plugin .so on this machine")
def test_standalone_cpp_server_binary(tmp_path):
    """predictor_main.cc → ptserve: a pure-C++ process (zero Python)
    loads the artifact, serves concurrent requests through the
    thread-safe API, and its output-0 checksum matches the Python
    forward (the reference's demo_ci C++ consumer proof)."""
    import json
    import subprocess
    import sys

    import paddle_tpu as pt
    from paddle_tpu import inference, jit

    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "native")
    inference._load_lib()  # ensure libptpredictor.so is current
    exe = os.path.join(native, "ptserve")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "predictor_main.cc", "-o", exe,
         "-L.", "-lptpredictor", "-Wl,-rpath,$ORIGIN"],
        cwd=native, check=True, capture_output=True)

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(16, 32), pt.nn.Tanh(),
                           pt.nn.Linear(32, 4))
    net.eval()
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    ref_sum = float(np.asarray(net(x)).astype(np.float64).sum())
    art = str(tmp_path / "artifact")
    jit.save(net, art, input_spec=[jit.InputSpec([8, 16], "float32")])
    np.save(tmp_path / "x.npy", x)

    try:
        proc = subprocess.run(
            [exe, inference.default_plugin(),
             inference.default_plugin_options(), art,
             str(tmp_path / "x.npy"), "--threads", "3", "--iters", "4"],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "PT_PJRT_CREATE_TIMEOUT": "120"})
    except subprocess.TimeoutExpired:
        pytest.skip("device unavailable (serve binary timed out)")
    if proc.returncode == 3 or (proc.returncode != 0 and (
            "tunnel" in proc.stderr or "wedged" in proc.stderr
            or "Unavailable" in proc.stderr
            or "UNAVAILABLE" in proc.stderr)):
        pytest.skip(f"device unavailable: {proc.stderr[-200:]}")
    assert proc.returncode == 0, proc.stderr[-800:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["requests"] == 12
    np.testing.assert_allclose(out["out0_sum"], ref_sum,
                               rtol=2e-2, atol=1e-2)


def test_serve_binary_npy_parser():
    """Hardware-free: ptserve --parse-only must read multi-dim npy
    headers exactly (a comma-split once truncated (8,16) to (8,))."""
    import json
    import subprocess
    import tempfile

    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "native")
    exe = os.path.join(native, "ptserve")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "predictor_main.cc", "-o", exe,
         "-L.", "-lptpredictor", "-Wl,-rpath,$ORIGIN"],
        cwd=native, check=True, capture_output=True)
    with tempfile.TemporaryDirectory() as td:
        cases = {
            "a": np.ones((8, 16), np.float32),
            "b": np.arange(6, dtype=np.int64),
            "c": np.zeros((2, 3, 4), np.float64),
            "d": np.zeros((5,), np.int32),
        }
        paths = []
        for name, arr in cases.items():
            p = os.path.join(td, f"{name}.npy")
            np.save(p, arr)
            paths.append((p, arr))
        proc = subprocess.run(
            [exe, "x", "", "y"] + [p for p, _ in paths]
            + ["--parse-only"], capture_output=True, text=True,
            timeout=60)
        assert proc.returncode == 0, proc.stderr
        lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
        for (p, arr), rec in zip(paths, lines):
            assert rec["dims"] == list(arr.shape), (p, rec)
            assert rec["nbytes"] == arr.nbytes, (p, rec)
