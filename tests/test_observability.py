"""Unified metrics + trace-export layer (observability tentpole):
metrics core semantics, percentile math at bucket boundaries, the
make_scheduler edge cases, every exporter's output format, and the
end-to-end acceptance — a Model.fit + LLMEngine smoke run must leave
non-empty TTFT/tokens-per-sec histograms and step-time metrics in BOTH
the Prometheus text and JSONL exports."""

import json
import math
import threading

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.observability import (JSONLReporter, MetricRegistry,
                                      export_chrome_tracing,
                                      prometheus_text)


@pytest.fixture()
def registry():
    return MetricRegistry()


@pytest.fixture()
def clean_default_registry():
    reg = obs.default_registry()
    reg.reset()
    yield reg
    reg.reset()


# ---------------------------------------------------------------------------
# metrics core
# ---------------------------------------------------------------------------

def test_counter_monotonic(registry):
    c = registry.counter("reqs", "requests")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec(registry):
    g = registry.gauge("occupancy")
    g.set(0.5)
    g.inc(0.25)
    g.dec(0.5)
    assert g.value == pytest.approx(0.25)


def test_labels_vend_independent_series(registry):
    c = registry.counter("rpc", label_names=("method", "code"))
    c.labels(method="gen", code="200").inc(3)
    c.labels("gen", "500").inc()
    assert c.labels(method="gen", code="200").value == 3
    assert c.labels(method="gen", code="500").value == 1
    with pytest.raises(ValueError):
        c.inc()          # labeled family has no default child
    with pytest.raises(ValueError):
        c.labels("only-one")


def test_registry_rejects_kind_and_label_conflicts(registry):
    registry.counter("x")
    with pytest.raises(ValueError):
        registry.gauge("x")
    registry.histogram("h", label_names=("a",))
    with pytest.raises(ValueError):
        registry.histogram("h", label_names=("b",))


def test_registry_get_or_create_idempotent(registry):
    a = registry.counter("same")
    b = registry.counter("same")
    assert a is b


def test_snapshot_flattens_all_kinds(registry):
    registry.counter("c").inc(2)
    registry.gauge("g", label_names=("d",)).labels(d="tpu:0").set(7)
    h = registry.histogram("h", buckets=(1.0, 2.0))
    h.observe(1.5)
    snap = registry.snapshot()
    assert snap["c"] == 2
    assert snap['g{d="tpu:0"}'] == 7
    assert snap["h_count"] == 1 and snap["h_sum"] == 1.5
    assert "h_p50" in snap and "h_p99" in snap


# ---------------------------------------------------------------------------
# histogram bucket/percentile math (satellite: boundary cases)
# ---------------------------------------------------------------------------

def test_histogram_boundary_observation_is_inclusive(registry):
    """Prometheus semantics: le is an INCLUSIVE upper bound — a value
    exactly on a boundary lands in that boundary's bucket."""
    h = registry.histogram("lat", buckets=(1.0, 2.0, 4.0))
    for v in (1.0, 2.0, 4.0, 4.0001):
        h.observe(v)
    cum = dict(h.bucket_counts())
    assert cum[1.0] == 1
    assert cum[2.0] == 2
    assert cum[4.0] == 3
    assert cum[math.inf] == 4


def test_histogram_percentiles_exact_at_boundary(registry):
    # all mass at one boundary value → every quantile reports exactly it
    h = registry.histogram("t", buckets=(1.0, 2.0, 4.0))
    for _ in range(8):
        h.observe(2.0)
    for q in (0.0, 0.5, 0.9, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(2.0)


def test_histogram_percentile_interpolation_and_clamps(registry):
    h = registry.histogram("t", buckets=(1.0, 2.0))
    for v in (0.5, 1.0, 1.5, 2.0):
        h.observe(v)
    assert h.quantile(0.0) == pytest.approx(0.5)    # clamp to min
    assert h.quantile(1.0) == pytest.approx(2.0)    # clamp to max
    assert h.quantile(0.5) == pytest.approx(1.0)    # boundary rank
    p = h.percentiles((50, 90, 99))
    assert set(p) == {"p50", "p90", "p99"}
    assert p["p50"] <= p["p90"] <= p["p99"] <= 2.0


def test_histogram_overflow_bucket_reports_max(registry):
    # observations beyond the last finite bound live in +Inf: the
    # estimator must not fabricate values above the observed max
    h = registry.histogram("t", buckets=(1.0, 2.0))
    h.observe(100.0)
    h.observe(200.0)
    assert h.quantile(0.9) == pytest.approx(200.0)
    assert h.count == 2 and h.mean == pytest.approx(150.0)


def test_empty_histogram_is_safe(registry):
    h = registry.histogram("t")
    assert h.count == 0 and h.sum == 0.0 and h.mean == 0.0
    assert h.quantile(0.5) == 0.0


# ---------------------------------------------------------------------------
# make_scheduler edge cases (satellite)
# ---------------------------------------------------------------------------

def test_scheduler_skip_first_repeat_interaction():
    """skip_first shifts the whole cycle train; repeat counts cycles
    AFTER the skip — and the tail stays CLOSED forever."""
    S = profiler.ProfilerState
    sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                    repeat=2, skip_first=3)
    states = [sched(i) for i in range(12)]
    assert states[:3] == [S.CLOSED] * 3                     # skip_first
    cycle = [S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN]
    assert states[3:7] == cycle
    assert states[7:11] == cycle                            # 2nd repeat
    assert states[11] == S.CLOSED
    assert all(sched(i) == S.CLOSED for i in range(11, 40))


def test_scheduler_single_step_record_cycles():
    """record=1: the only recording step of each cycle IS the cycle
    boundary, so it must be RECORD_AND_RETURN (plain RECORD would never
    close the trace)."""
    S = profiler.ProfilerState
    sched = profiler.make_scheduler(closed=1, ready=0, record=1)
    assert [sched(i) for i in range(4)] == \
        [S.CLOSED, S.RECORD_AND_RETURN] * 2
    # degenerate but legal: record every step, one-step cycles
    sched = profiler.make_scheduler(closed=0, ready=0, record=1)
    assert all(sched(i) == S.RECORD_AND_RETURN for i in range(5))


def test_scheduler_record_and_return_drives_trace_cycles(tmp_path):
    """A RECORD_AND_RETURN → RECORD transition closes one trace and
    opens the next: on_trace_ready fires once per completed cycle."""
    fired = []
    prof = profiler.Profiler(
        scheduler=profiler.make_scheduler(closed=0, ready=0, record=1),
        log_dir=str(tmp_path / "prof"),
        on_trace_ready=lambda p: fired.append(p.step_num))
    prof.start()
    for _ in range(3):
        prof.step()
    prof.stop()
    # one close per step boundary + stop() closing the cycle in flight
    assert fired == [1, 2, 3, 3]


# ---------------------------------------------------------------------------
# profiler host events + race fix
# ---------------------------------------------------------------------------

def test_profiler_start_clear_races_worker_threads(tmp_path):
    """Satellite regression: start() clears the event table under the
    lock while worker threads are mid-RecordEvent — no lost-update
    crashes, and the table still aggregates afterwards."""
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            with profiler.RecordEvent("w"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    prof = profiler.Profiler(log_dir=str(tmp_path / "p"))
    prof.start()            # events flowing from line one
    for t in threads:
        t.start()
    try:
        for _ in range(20):
            prof.start()    # repeated clears against concurrent ends
    finally:
        stop.set()
        for t in threads:
            t.join()
        prof.stop()


def test_export_chrome_tracing_complete_events(tmp_path, monkeypatch):
    prof = profiler.Profiler(log_dir=str(tmp_path / "prof"))
    prof.start()
    for _ in range(3):
        with profiler.RecordEvent("step"):
            pass
    with profiler.RecordEvent("save"):
        pass
    prof.stop()
    path = export_chrome_tracing(prof, str(tmp_path / "t" / "trace.json"))
    with open(path) as f:
        trace = json.load(f)          # must json.load cleanly
    events = trace["traceEvents"]
    by_name = {}
    for ev in events:
        if ev["ph"] == "M":           # row-label metadata (Perfetto)
            assert ev["name"] in ("process_name", "thread_name")
            assert ev["args"]["name"]
            continue
        assert ev["ph"] == "X"
        assert ev["dur"] >= 0 and ev["ts"] > 0
        assert isinstance(ev["tid"], int) and isinstance(ev["pid"], int)
        by_name[ev["name"]] = by_name.get(ev["name"], 0) + 1
    assert by_name["step"] == 3       # one X event per annotation
    assert by_name["save"] == 1
    # recording threads get labeled rows, not bare tids
    assert any(ev["ph"] == "M" and ev["name"] == "thread_name"
               for ev in events)
    # profiler module re-exports it (the old `= None` parity marker)
    assert profiler.export_chrome_tracing is export_chrome_tracing


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_text_format(registry):
    registry.counter("llm.tokens", "tokens out").inc(5)
    registry.gauge("util", label_names=("device",)).labels(
        device="tpu:0").set(0.5)
    h = registry.histogram("lat", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(3.0)
    text = prometheus_text(registry)
    assert "# TYPE llm_tokens counter" in text       # dots sanitized
    assert "llm_tokens 5.0" in text
    assert 'util{device="tpu:0"} 0.5' in text
    assert '# TYPE lat histogram' in text
    assert 'lat_bucket{le="1.0"} 1' in text
    assert 'lat_bucket{le="2.0"} 1' in text
    assert 'lat_bucket{le="+Inf"} 2' in text         # cumulative total
    assert "lat_sum 3.5" in text and "lat_count 2" in text
    # 0.0.4 exposition: every sample line is `name[{labels}] value`
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        _, value = line.rsplit(" ", 1)
        float(value if value != "+Inf" else "inf")


def test_jsonl_reporter_writes_and_shuts_down(tmp_path, registry):
    registry.counter("c").inc(3)
    path = str(tmp_path / "m.jsonl")
    with JSONLReporter(path, interval=0.05, registry=registry):
        import time
        time.sleep(0.2)
        registry.counter("c").inc()
    with open(path) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    assert len(rows) >= 2                 # periodic ticks happened
    assert rows[-1]["metrics"]["c"] == 4  # stop() wrote a final snapshot
    assert all("ts" in r for r in rows)
    rep = JSONLReporter(path, interval=60, registry=registry)
    rep.stop()
    rep.stop()                            # idempotent


def test_sample_device_memory_no_crash_on_cpu(registry):
    # CPU memory_stats() is None — the sampler must cope and not create
    # bogus series
    out = obs.sample_device_memory(registry)
    assert isinstance(out, dict)
    gauge = registry.get("device_memory_bytes")
    assert gauge is not None            # family registered either way


# ---------------------------------------------------------------------------
# StatRegistry is backed by the MetricRegistry
# ---------------------------------------------------------------------------

def test_stat_registry_flows_into_exports(clean_default_registry):
    from paddle_tpu.core.monitor import StatRegistry, stat_add, stat_get
    sreg = StatRegistry.instance()
    sreg.reset()
    stat_add("elastic.restarts")
    stat_add("elastic.restarts", 2)
    sreg.set("lr", 0.1)
    assert stat_get("elastic.restarts") == 3
    snap = sreg.snapshot()
    assert snap["elastic.restarts"] == 3 and snap["lr"] == 0.1
    # the same stats surface through the observability exporters
    text = prometheus_text()
    assert "elastic_restarts 3.0" in text
    assert clean_default_registry.snapshot()["elastic.restarts"] == 3
    sreg.reset()
    assert sreg.snapshot() == {}
    assert stat_get("elastic.restarts") == 0


def test_stat_registry_never_raises_on_typed_name_collisions(
        clean_default_registry):
    """The reference's StatRegistry contract: add/get never raise. A
    stat whose name is already a histogram or labeled family parks
    under a suffixed gauge instead of exploding the call site."""
    from paddle_tpu.core.monitor import StatRegistry, stat_add, stat_get
    sreg = StatRegistry.instance()
    sreg.reset()
    reg = clean_default_registry
    reg.histogram("train_step_seconds").observe(0.5)
    reg.gauge("device_memory_bytes", label_names=("device",))
    stat_add("train_step_seconds", 2)          # collides with histogram
    stat_add("device_memory_bytes")            # collides with labels
    assert stat_get("train_step_seconds") == 2
    assert stat_get("device_memory_bytes") == 1
    assert sreg.snapshot()["train_step_seconds"] == 2
    # reading a typed metric name with no stat behind it returns 0
    sreg.reset()
    assert stat_get("train_step_seconds") == 0
    assert stat_get("device_memory_bytes") == 0
    # ...and the exposition renders both without duplicate names
    text = prometheus_text()
    assert text.count("# TYPE train_step_seconds ") == 1


def test_prometheus_sanitized_name_collision_disambiguated(registry):
    registry.histogram("a.b", buckets=(1.0,)).observe(0.5)
    registry.gauge("a_b").set(3)
    text = prometheus_text(registry)
    type_names = [ln.split()[2] for ln in text.splitlines()
                  if ln.startswith("# TYPE")]
    assert len(set(type_names)) == len(type_names), text


def test_checkpoint_metrics_recorded(tmp_path, clean_default_registry):
    pytest.importorskip("orbax.checkpoint")
    from paddle_tpu.io.checkpoint import CheckpointManager
    with CheckpointManager(str(tmp_path / "ck"), async_save=False) as mgr:
        mgr.save(0, {"w": np.arange(8, dtype=np.float32)})
        mgr.wait_until_finished()
        got = mgr.restore(0)
    assert np.allclose(got["w"], np.arange(8))
    snap = clean_default_registry.snapshot()
    assert snap["checkpoint_save_seconds_count"] == 1
    assert snap["checkpoint_restore_seconds_count"] == 1
    assert snap["checkpoint_bytes_written"] >= 32
    # satellite: the STAT_ADD wiring fires too
    from paddle_tpu.core.monitor import stat_get
    assert stat_get("checkpoint.saves") == 1
    assert stat_get("checkpoint.restores") == 1
    assert stat_get("checkpoint.saved_bytes") >= 32


# ---------------------------------------------------------------------------
# acceptance: instrumented hot paths → non-empty exports
# ---------------------------------------------------------------------------

def test_model_fit_populates_metrics(tmp_path, clean_default_registry):
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.io import TensorDataset

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss())
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))
    jsonl = str(tmp_path / "m.jsonl")
    with JSONLReporter(jsonl, interval=60):   # final snapshot on stop
        m.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0)

    snap = clean_default_registry.snapshot()
    assert snap["train_step_seconds_count"] == 8      # 4 batches × 2
    assert snap["train_step_seconds_p50"] > 0
    assert snap["train_examples_per_second_count"] == 8
    assert snap["train_compile_count"] == 1           # one shape → one
    assert snap["dataloader_batches"] == 8
    assert m.compiled_shape_count == 1

    text = prometheus_text()
    assert "train_step_seconds_count 8" in text
    assert "train_compile_seconds_count 1" in text
    with open(jsonl) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    assert rows[-1]["metrics"]["train_step_seconds_count"] == 8


def test_llm_engine_populates_metrics(clean_default_registry, tmp_path):
    import paddle_tpu as pt
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 9, 3)]
    jsonl = str(tmp_path / "llm.jsonl")
    with JSONLReporter(jsonl, interval=60):
        with LLMEngine(net, max_seqs=4, page_size=4, num_pages=64,
                       prefill_buckets=(16,)) as eng:
            outs = eng.generate(prompts, max_new_tokens=6)
    assert all(len(o["output_ids"]) == 6 for o in outs)

    snap = clean_default_registry.snapshot()
    assert snap["llm_ttft_seconds_count"] == 3        # one per request
    assert snap["llm_ttft_seconds_p90"] > 0
    assert snap["llm_queue_wait_seconds_count"] == 3
    assert snap["llm_decode_tokens_per_second_count"] > 0
    assert snap["llm_decode_tokens_per_second_p50"] > 0
    assert snap["llm_tokens_generated"] == 18         # 3 reqs × 6
    assert snap["llm_requests_completed"] == 3
    assert snap["llm_batch_occupancy_count"] > 0
    assert 'llm_kv_page_utilization' in snap

    text = prometheus_text()
    assert "llm_ttft_seconds_count 3" in text
    assert "llm_decode_tokens_per_second_bucket" in text
    with open(jsonl) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    last = rows[-1]["metrics"]
    assert last["llm_ttft_seconds_count"] == 3
    assert last["llm_decode_tokens_per_second_count"] > 0
