"""Elastic manager: rank death and stall trigger restart + resume.

Analog of the reference's elastic tests (unittests/test_fleet_elastic_
manager.py — status decisions) combined with its subprocess-based dist
test pattern (test_dist_base.py): a real training script is killed /
wedged mid-run, the manager restarts it, and training resumes from the
latest checkpoint with state continuity."""

import json
import os
import textwrap

import numpy as np

from paddle_tpu.distributed.elastic import (ElasticManager, ElasticStatus,
                                            Heartbeat)

import pytest
pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

# A tiny "training" script that needs no jax in the subprocess: a
# counter parameter trained for 6 epochs with an epoch-granular
# checkpoint (the AutoCheckpoint pattern), appending one JSON line per
# epoch to a shared log. On the first incarnation it kills itself after
# committing epoch 2.
_TRAIN = textwrap.dedent("""
    import json, os, sys
    work = sys.argv[1]
    kill_mode = sys.argv[2]   # "exit" | "stall" | "none"
    rank = os.environ["PADDLE_TRAINER_ID"]
    incarnation = int(os.environ.get("PADDLE_ELASTIC_RESTART_COUNT", 0))
    hb = None
    if os.environ.get("PADDLE_ELASTIC_HB_DIR"):
        sys.path.insert(0, {repo!r})
        from paddle_tpu.distributed.elastic import Heartbeat
        Heartbeat(mode="thread", interval=0.2)  # liveness (auto path)
        hb = Heartbeat(mode="manual")   # progress beats from the loop
    ckpt = os.path.join(work, f"state.{{rank}}.json")
    state = {{"epoch": -1, "weight": 0.0}}
    if os.path.exists(ckpt):
        state = json.load(open(ckpt))
    start = state["epoch"] + 1
    for epoch in range(start, 6):
        state = {{"epoch": epoch, "weight": state["weight"] + 1.0}}
        with open(os.path.join(work, f"log.{{rank}}.txt"), "a") as f:
            f.write(json.dumps({{"epoch": epoch, "inc": incarnation,
                                 "weight": state["weight"]}}) + "\\n")
        tmp = ckpt + ".tmp"
        json.dump(state, open(tmp, "w"))
        os.replace(tmp, ckpt)
        if hb is not None:
            hb.beat()
        if incarnation == 0 and epoch == 2 and rank == "0":
            if kill_mode == "exit":
                os._exit(17)
            if kill_mode == "stall":
                import time
                time.sleep(3600)   # wedged rank: alive but no progress
""").format(repo=os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _write_script(tmp_path, name="train.py"):
    p = tmp_path / name
    p.write_text(_TRAIN)
    return str(p)


def _read_log(tmp_path, rank):
    path = tmp_path / f"log.{rank}.txt"
    return [json.loads(l) for l in path.read_text().splitlines()]


def test_dead_rank_restarts_and_resumes(tmp_path):
    script = _write_script(tmp_path)
    mgr = ElasticManager(2, script, [str(tmp_path), "exit"],
                         max_restarts=1, poll_interval=0.05)
    rc = mgr.run()
    assert rc == 0
    assert mgr.restarts == 1
    log = _read_log(tmp_path, 0)
    # loss/state continuity: epochs 0..2 trained in incarnation 0,
    # 3..5 in incarnation 1, weight strictly continuous (no reset)
    assert [e["epoch"] for e in log] == [0, 1, 2, 3, 4, 5]
    assert [e["weight"] for e in log] == [1, 2, 3, 4, 5, 6]
    assert [e["inc"] for e in log] == [0, 0, 0, 1, 1, 1]


def test_restart_budget_exhausted_reports_failure(tmp_path):
    script = _write_script(tmp_path)
    mgr = ElasticManager(1, script, [str(tmp_path), "exit"],
                         max_restarts=0, poll_interval=0.05)
    rc = mgr.run()
    assert rc == 17
    # only the first incarnation ran
    assert [e["inc"] for e in _read_log(tmp_path, 0)] == [0, 0, 0]


def test_stalled_rank_detected_by_heartbeat_and_restarted(tmp_path):
    """A rank that wedges (alive, no progress) is only catchable via
    progress heartbeats — the manager must kill + restart it even
    though the auto liveness THREAD keeps beating (progress files
    outrank hb files in the staleness decision)."""
    script = _write_script(tmp_path)
    mgr = ElasticManager(2, script, [str(tmp_path), "stall"],
                         log_dir=str(tmp_path / "logs"),
                         max_restarts=1, heartbeat_timeout=1.5,
                         poll_interval=0.05)
    rc = mgr.run()
    assert rc == 0
    assert mgr.restarts == 1
    log = _read_log(tmp_path, 0)
    assert [e["epoch"] for e in log] == [0, 1, 2, 3, 4, 5]
    assert [e["weight"] for e in log] == [1, 2, 3, 4, 5, 6]


def test_clean_run_no_restarts(tmp_path):
    script = _write_script(tmp_path)
    mgr = ElasticManager(2, script, [str(tmp_path), "none"],
                         max_restarts=3, poll_interval=0.05)
    assert mgr.run() == 0
    assert mgr.restarts == 0
    for rank in (0, 1):
        assert [e["epoch"] for e in _read_log(tmp_path, rank)] == \
            [0, 1, 2, 3, 4, 5]


def test_heartbeat_thread_mode(tmp_path):
    hb = Heartbeat(directory=str(tmp_path), rank=3, interval=0.05)
    import time
    t0 = os.path.getmtime(tmp_path / "hb.3")
    time.sleep(0.3)
    assert os.path.getmtime(tmp_path / "hb.3") > t0
    hb.stop()


def test_elastic_status_enum_parity():
    # ref: elastic/manager.py ElasticStatus members
    assert {s.name for s in ElasticStatus} == \
        {"HOLD", "COMPLETED", "RESTART", "ERROR"}
