"""fft/signal/sparse modules (ref: unittests fft/, test_signal.py,
sparse test suite)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import fft, signal, sparse


def test_fft_roundtrip():
    x = np.random.RandomState(0).randn(64).astype(np.float32)
    X = fft.fft(x)
    np.testing.assert_allclose(np.asarray(fft.ifft(X)).real, x,
                               atol=1e-5)
    Xr = fft.rfft(x)
    assert Xr.shape == (33,)
    np.testing.assert_allclose(np.asarray(fft.irfft(Xr, 64)), x,
                               atol=1e-5)


def test_stft_istft_roundtrip():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 400).astype(np.float32)
    win = np.hanning(128).astype(np.float32)
    spec = signal.stft(x, n_fft=128, hop_length=32, window=win)
    assert spec.shape[-2] == 65  # onesided bins
    y = signal.istft(spec, n_fft=128, hop_length=32, window=win,
                     length=400)
    np.testing.assert_allclose(np.asarray(y), x, atol=1e-3)


def test_frame_shapes():
    x = jnp.arange(10.0)
    f = signal.frame(x, frame_length=4, hop_length=2)
    assert f.shape == (4, 4)
    np.testing.assert_allclose(f[:, 0], [0, 1, 2, 3])
    np.testing.assert_allclose(f[:, 1], [2, 3, 4, 5])


def test_sparse_coo_roundtrip_and_matmul():
    dense = np.zeros((4, 5), np.float32)
    dense[0, 1] = 2.0
    dense[3, 4] = -1.0
    sp = sparse.SparseCooTensor.from_dense(dense)
    assert sp.nnz() == 2
    np.testing.assert_allclose(np.asarray(sp.to_dense()), dense)
    rhs = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(sp @ rhs), dense @ rhs,
                               atol=1e-5)


def test_sparse_constructors():
    sp = sparse.sparse_coo_tensor([[0, 1], [2, 0]], [1.5, 2.5], (2, 3))
    dense = np.asarray(sp.to_dense())
    assert dense[0, 2] == 1.5 and dense[1, 0] == 2.5
    csr = sparse.sparse_csr_tensor([0, 1, 2], [2, 0], [1.5, 2.5], (2, 3))
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)


def test_frame_axis0_layout():
    x = np.arange(20.0).reshape(10, 2)  # [time, batch]
    f = signal.frame(x, frame_length=4, hop_length=2, axis=0)
    assert f.shape == (4, 4, 2)  # [num, frame, batch]
    np.testing.assert_allclose(np.asarray(f[1, :, 0]), [4, 6, 8, 10])
    with pytest.raises(ValueError, match="frame_length"):
        signal.frame(np.arange(3.0), 8, 4)


def test_sparse_add_stays_sparse():
    a = sparse.sparse_coo_tensor([[0, 1], [1, 1]], [1.0, 2.0], (3, 3))
    b = sparse.sparse_coo_tensor([[0, 2], [1, 0]], [5.0, 7.0], (3, 3))
    c = a + b
    dense = np.asarray(c.to_dense())
    assert dense[0, 1] == 6.0 and dense[1, 1] == 2.0 and dense[2, 0] == 7.0


def test_masked_matmul_sddmm():
    rs = np.random.RandomState(0)
    a = rs.randn(4, 6).astype(np.float32)
    b = rs.randn(6, 5).astype(np.float32)
    mask_dense = np.zeros((4, 5), np.float32)
    mask_dense[1, 2] = 1.0
    mask_dense[3, 0] = 1.0
    mask = sparse.SparseCooTensor.from_dense(mask_dense)
    out = sparse.masked_matmul(a, b, mask)
    full = a @ b
    out_d = np.asarray(out.to_dense())
    np.testing.assert_allclose(out_d[1, 2], full[1, 2], atol=1e-5)
    np.testing.assert_allclose(out_d[3, 0], full[3, 0], atol=1e-5)
    assert out_d[0, 0] == 0.0


def test_sparse_round3_surface():
    """sparse_api.yaml fills: softmax over nonzeros, addmm, elementwise
    binary ops, CSR interchange, full_like/values/to_dense forms."""
    import numpy as np
    from paddle_tpu import sparse

    d = np.array([[0.0, 2.0, 0.0], [3.0, 0.0, 4.0]], np.float32)
    sp = sparse.SparseCooTensor.from_dense(d)

    sm = np.asarray(sparse.softmax(sp).to_dense())
    np.testing.assert_allclose(sm[0, 1], 1.0)      # lone nonzero row
    np.testing.assert_allclose(sm[1, 0] + sm[1, 2], 1.0)
    assert sm[0, 0] == 0.0                          # pattern preserved

    out = np.asarray(sparse.addmm(np.ones((2, 2), np.float32), sp,
                                  np.ones((3, 2), np.float32),
                                  beta=2.0, alpha=1.0))
    np.testing.assert_allclose(out, [[4.0, 4.0], [9.0, 9.0]])

    np.testing.assert_allclose(
        np.asarray(sparse.multiply(sp, 2.0).to_dense()), d * 2)
    dense_b = np.arange(6, dtype=np.float32).reshape(2, 3) + 1
    np.testing.assert_allclose(
        np.asarray(sparse.multiply(sp, dense_b).to_dense()), d * dense_b)
    np.testing.assert_allclose(
        np.asarray(sparse.divide(sp, 2.0).to_dense()), d / 2)
    np.testing.assert_allclose(
        np.asarray(sparse.subtract(sp, sp).to_dense()), 0.0)

    crows, cols, vals = sparse.to_sparse_csr(d)
    np.testing.assert_array_equal(np.asarray(crows), [0, 1, 3])
    np.testing.assert_array_equal(np.asarray(cols), [1, 0, 2])
    np.testing.assert_allclose(np.asarray(vals), [2.0, 3.0, 4.0])

    fl = sparse.full_like(sp, 7.0)
    np.testing.assert_allclose(np.asarray(sparse.values(fl)), 7.0)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(sp)), d)
    # unary fills keep the pattern
    np.testing.assert_allclose(
        np.asarray(sparse.leaky_relu(
            sparse.SparseCooTensor.from_dense(-d)).to_dense()),
        np.where(-d >= 0, -d, -0.01 * d), atol=1e-7)


def test_reference_sparse_surface_covered():
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.op_coverage import classify
    missing = [n for n, _ in classify()["missing"]
               if n.startswith("sparse.")]
    assert not missing, missing
