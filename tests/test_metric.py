"""Metric regression tests (ref: python/paddle/metric/metrics.py;
test harness analog: fluid/tests/unittests/test_metrics.py)."""

import jax.numpy as jnp
import numpy as np

from paddle_tpu.metric import Accuracy, Precision, Recall


def test_accuracy_label_column():
    """[N,1] index labels must NOT be treated as one-hot (bug caught on
    TPU verification: argmax over a width-1 axis zeroed every label)."""
    m = Accuracy()
    pred = jnp.asarray(np.eye(10, dtype=np.float32)[[3, 1, 4]])
    label = jnp.asarray(np.array([[3], [1], [0]]))
    correct = m.compute(pred, label)
    m.update(correct)
    assert abs(m.accumulate() - 2 / 3) < 1e-6


def test_accuracy_label_flat_and_onehot():
    m = Accuracy()
    pred = jnp.asarray(np.eye(4, dtype=np.float32)[[0, 1, 2, 3]])
    m.update(m.compute(pred, jnp.asarray(np.array([0, 1, 2, 0]))))
    assert abs(m.accumulate() - 0.75) < 1e-6
    m2 = Accuracy()
    onehot = jnp.asarray(np.eye(4, dtype=np.float32)[[0, 1, 2, 0]])
    m2.update(m2.compute(pred, onehot))
    assert abs(m2.accumulate() - 0.75) < 1e-6


def test_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    pred = jnp.asarray(np.array([[0.1, 0.9, 0.5, 0.0]], np.float32))
    m.update(m.compute(pred, jnp.asarray(np.array([[2]]))))
    top1, top2 = m.accumulate()
    assert top1 == 0.0 and top2 == 1.0


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.8, 0.1, 0.7])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    assert abs(r.accumulate() - 2 / 3) < 1e-6
