"""Key-range-sharded beyond-HBM embedding (VERDICT r3 ask #2) on the
single-process 8-device mesh: routed pull/push parity with the
unsharded table, exactly-once updates, sharded snapshot re-keying.
The REAL 2-OS-process run (aggregate capacity > any one host budget +
generation restart) lives in tests/test_dist_multiprocess.py.

Reference analog: paddle/fluid/distributed/ps/table/memory_sparse_table.h
(key-sharded tables), service/brpc_ps_client.cc (id → shard routing)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn, parallel
from paddle_tpu.nn.layers.host_embedding import HostOffloadedEmbedding
from paddle_tpu.nn.layers.sharded_embedding import (
    ShardedHostEmbedding, _owned_device_indices)

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


@pytest.fixture
def dp8_mesh():
    mesh = parallel.init_mesh(dp=8)
    yield mesh
    parallel.set_mesh(None)


def test_forward_and_push_parity_with_unsharded(dp8_mesh):
    """psum-routed gather == dense host-table lookup, and the backward
    routes each row's grad to exactly one owner (updates match the
    unsharded accessor step exactly)."""
    from paddle_tpu.nn.layer import functional_call, split_state

    pt.seed(0)
    sh = ShardedHostEmbedding(100_000, 8, seed=5, optimizer="sgd",
                              learning_rate=1.0, padding_idx=None)
    un = HostOffloadedEmbedding(100_000, 8, seed=5, optimizer="sgd",
                                learning_rate=1.0, padding_idx=None)
    ids = np.random.RandomState(0).randint(1, 100_000, (16, 4))

    np.testing.assert_allclose(np.asarray(sh(ids)), np.asarray(un(ids)),
                               rtol=1e-6)

    params, _ = split_state(sh)

    def loss(p, i):
        out, _ = functional_call(sh, p, {}, i)
        return out.sum()

    g = jax.grad(loss)(params, jnp.asarray(ids))
    jax.effects_barrier()
    np.testing.assert_allclose(np.asarray(g["push_anchor"]), 0.0)
    # d(sum)/d(row) = 1 per occurrence; lr=1 sgd → row -= #occurrences,
    # applied ONCE by the owning device (not once per device)
    flat = np.unique(ids.reshape(-1))
    before = un._pull(flat)
    un._push(ids.reshape(-1),
             np.ones((ids.size, 8), np.float32))
    np.testing.assert_allclose(sh._local._pull(flat), un._pull(flat),
                               rtol=1e-6)
    assert not np.allclose(un._pull(flat), before)


def test_padding_and_combiners_match_unsharded(dp8_mesh):
    pt.seed(0)
    for combiner in ("sum", "mean", "sqrtn"):
        sh = ShardedHostEmbedding(1000, 4, seed=2, combiner=combiner)
        un = HostOffloadedEmbedding(1000, 4, seed=2, combiner=combiner)
        ids = np.array([[5, 0, 9, 0], [3, 3, 0, 0],
                        [0, 0, 0, 0], [7, 1, 2, 4]] * 2)  # 8 rows
        np.testing.assert_allclose(np.asarray(sh(ids)),
                                   np.asarray(un(ids)), rtol=1e-6,
                                   err_msg=combiner)


def test_ownership_and_restore_rekey(dp8_mesh, tmp_path):
    """Every device index is owned by this (single) process; restoring
    shard files re-filters rows by the CURRENT world size."""
    mine = _owned_device_indices(dp8_mesh.mesh, "dp")
    np.testing.assert_array_equal(mine, np.arange(8))

    sh = ShardedHostEmbedding(10_000, 4, seed=1)
    ids = np.arange(1, 65).reshape(8, 8)
    sh(ids)
    assert sh.touched_rows_local == 64
    path = sh.snapshot_shard(str(tmp_path / "t"))
    assert path.endswith(".shard0of1.npz")

    fresh = ShardedHostEmbedding(10_000, 4, seed=1)
    fresh.restore_shards([path])
    assert fresh.touched_rows_local == 64
    np.testing.assert_allclose(fresh._local._pull(np.arange(1, 65)),
                               sh._local._pull(np.arange(1, 65)))
    bad = ShardedHostEmbedding(99, 4)
    with pytest.raises(ValueError, match="shape mismatch"):
        bad.restore_shards([path])
    # fold-scheme mismatch refused (same guard as the unsharded table)
    folded = ShardedHostEmbedding(10_000, 4, hash_ids=True)
    with pytest.raises(ValueError, match="fold scheme"):
        folded.restore_shards([path])


def test_degenerate_mesh_falls_back_to_local_table():
    """No dp axis installed → the plain host-table path (same rows)."""
    parallel.set_mesh(None)
    sh = ShardedHostEmbedding(1000, 4, seed=3)
    un = HostOffloadedEmbedding(1000, 4, seed=3)
    ids = np.array([[1, 2, 3, 0]])
    np.testing.assert_allclose(np.asarray(sh(ids)), np.asarray(un(ids)),
                               rtol=1e-6)


def test_sharded_with_spill_dir_parity_and_snapshot(dp8_mesh, tmp_path):
    """Feature interaction: key-range sharding OVER the disk-spill tier
    (ssd_sparse_table analog under the routed pull/push) — numerics
    identical to the RAM-pooled sharded table, snapshot round-trips,
    and the pool files actually live on disk."""
    import os

    pt.seed(0)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        1, 100_000, (16, 4)))

    def run(spill):
        pt.seed(0)
        sh = ShardedHostEmbedding(
            100_000, 8, seed=5, optimizer="adagrad", learning_rate=0.5,
            spill_dir=str(tmp_path / "spill") if spill else None)
        out1 = np.asarray(sh(ids))
        # one push through the custom-vjp path
        from paddle_tpu.nn.layer import functional_call, split_state
        params, buffers = split_state(sh)

        def loss(p):
            out, _ = functional_call(sh, p, buffers, ids)
            return (out ** 2).sum()

        jax.grad(loss)(params)
        jax.effects_barrier()
        out2 = np.asarray(sh(ids))
        return out1, out2, sh

    r1, r2, _ = run(False)
    s1, s2, sh = run(True)
    np.testing.assert_allclose(s1, r1, atol=0, rtol=0)
    np.testing.assert_allclose(s2, r2, atol=0, rtol=0)
    assert not np.allclose(s1, s2)  # the push actually updated rows
    files = os.listdir(tmp_path / "spill")
    assert any("pool_vals" in f for f in files), files

    # sharded snapshot round-trip on the spilled table
    sh.snapshot_shard(str(tmp_path / "snap"))
    pt.seed(0)
    sh2 = ShardedHostEmbedding(
        100_000, 8, seed=5, optimizer="adagrad", learning_rate=0.5,
        spill_dir=str(tmp_path / "spill2"))
    import glob
    shards = sorted(glob.glob(str(tmp_path / "snap.shard*")))
    sh2.restore_shards(shards)
    np.testing.assert_allclose(np.asarray(sh2(ids)), s2, atol=0, rtol=0)
