"""Regression tests for review findings (round-1 code review)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_pad_innermost_first():
    x = jnp.zeros((1, 1, 3, 3))
    y = F.pad(x, [1, 0, 0, 0])  # pad left of W only
    assert y.shape == (1, 1, 3, 4)
    y2 = F.pad(x, [0, 0, 2, 0])  # pad top of H only
    assert y2.shape == (1, 1, 5, 3)


def test_pad_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 3, 4, 5).astype(np.float32)
    pad = [1, 2, 3, 4]
    got = np.asarray(F.pad(jnp.asarray(x), pad, value=7.0))
    ref = torch.nn.functional.pad(torch.tensor(x), pad, value=7.0).numpy()
    np.testing.assert_array_equal(got, ref)


def test_frozen_param_not_updated():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.frozen = nn.Parameter(jnp.ones((4,)), trainable=False)
            self.lin = nn.Linear(4, 1)

        def forward(self, x):
            return self.lin(x * self.frozen)

    from paddle_tpu.io import TensorDataset
    from paddle_tpu.optimizer import SGD
    net = Net()
    model = pt.Model(net)
    model.prepare(optimizer=SGD(learning_rate=0.1, parameters=net),
                  loss=nn.MSELoss())
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 1).astype(np.float32)
    model.fit(TensorDataset([x, y]), batch_size=8, epochs=2, verbose=0)
    np.testing.assert_array_equal(np.asarray(net.frozen), 1.0)
    # but the trainable linear moved
    assert model._step_count == 2


def test_adamw_decay_exclusion():
    from paddle_tpu.optimizer import AdamW
    params = {"w": jnp.ones((4,)), "norm.bias": jnp.ones((4,))}
    opt = AdamW(learning_rate=0.0, weight_decay=0.5,
                apply_decay_param_fun=lambda n: "norm" not in n)
    # lr=0 isolates... decay is multiplied by lr, so use lr>0 and zero grads
    opt = AdamW(learning_rate=0.1, weight_decay=0.5,
                apply_decay_param_fun=lambda n: "norm" not in n)
    state = opt.init_state(params)
    zero_g = {k: jnp.zeros_like(v) for k, v in params.items()}
    p1, _ = opt.apply_gradients(params, zero_g, state, 0)
    assert float(p1["w"][0]) < 1.0            # decayed
    np.testing.assert_allclose(np.asarray(p1["norm.bias"]), 1.0)  # excluded


def test_transformer_clone_keeps_activation():
    proto = nn.TransformerEncoderLayer(16, 2, 32, 0.1, activation="gelu",
                                       normalize_before=True)
    enc = nn.TransformerEncoder(proto, 3)
    for layer in enc.layers:
        assert layer.activation is F.gelu
        assert layer.normalize_before


def test_interpolate_align_corners_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(1, 2, 5, 7).astype(np.float32)
    got = np.asarray(F.interpolate(jnp.asarray(x), size=(10, 3),
                                   mode="bilinear", align_corners=True))
    ref = torch.nn.functional.interpolate(
        torch.tensor(x), size=(10, 3), mode="bilinear",
        align_corners=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_nonpersistable_buffer_roundtrip():
    class L(nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("tmp", jnp.zeros((2,)), persistable=False)
            self.register_buffer("keep", jnp.ones((2,)))

        def forward(self, x):
            return x

    l1 = L()
    sd = l1.state_dict()
    assert "tmp" not in sd and "keep" in sd
    L().set_state_dict(sd)  # must not raise


def test_fan_in_out_conv_layout():
    from paddle_tpu.nn.initializer import _fan_in_out
    fi, fo = _fan_in_out([64, 32, 3, 3])  # [out, in, kh, kw]
    assert fi == 32 * 9
    assert fo == 64 * 9


def test_named_rng_streams_stable():
    import subprocess, sys
    # pin the fresh interpreters to CPU: this tests RNG determinism,
    # and key creation on the tunneled TPU would hang the suite if the
    # device is busy/wedged (env vars are too late — sitecustomize has
    # already imported jax — so the child flips the config itself)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import paddle_tpu as pt; import numpy as np; pt.seed(3); "
            "from paddle_tpu.core import rng; "
            "print(np.asarray(jax.random.key_data("
            "rng.next_key('init'))).tolist())")
    outs = set()
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120)
        assert proc.returncode == 0, proc.stderr[-1000:]
        outs.add(proc.stdout.strip())
    assert len(outs) == 1  # identical across fresh interpreters


# -- round-2 advisor fixes -------------------------------------------------

def test_pylayer_nested_attrs_not_swapped():
    """Two applies of the same PyLayer with different ctx.attrs inside one
    differentiated function must keep their own attrs in backward
    (round-1 advisor: FIFO side-stack swapped them under custom_vjp's
    LIFO backward order; attrs now ride the residuals)."""
    from paddle_tpu import autograd

    class Scale(autograd.PyLayer):
        @staticmethod
        def forward(ctx, x, s):
            ctx.attrs["s"] = s
            return x * s

        @staticmethod
        def backward(ctx, g):
            return g * ctx.attrs["s"], jnp.zeros(())

    def f(x):
        y = Scale.apply(x, 3.0)   # dy/dx = 3
        z = Scale.apply(y, 4.0)   # dz/dy = 4
        return z

    g = jax.grad(f)(jnp.asarray(2.0))
    assert float(g) == 12.0  # was 11 with swapped attrs

    # also correct under jit (retracing-safe: no side stack)
    gj = jax.jit(jax.grad(f))(jnp.asarray(2.0))
    assert float(gj) == 12.0


def test_vjp_multi_output_default_cotangent():
    from paddle_tpu import autograd

    def f(x):
        return (x * 2.0, x * 3.0)

    out, g = autograd.vjp(f, jnp.asarray(1.0))
    assert float(g) == 5.0


def test_totensor_scales_by_dtype_not_data():
    from paddle_tpu.vision.transforms import ToTensor
    dark = np.zeros((4, 4, 3), np.uint8)
    dark[0, 0, 0] = 1  # max == 1: the old data-based check skipped /255
    out = ToTensor()(dark)
    assert abs(float(out.max()) - 1.0 / 255.0) < 1e-7
    # float input in [0,1] is untouched
    f = np.full((4, 4, 3), 0.5, np.float32)
    assert float(ToTensor()(f).max()) == 0.5


def test_viterbi_include_bos_eos_tag():
    """Against a brute force with the reference convention: start tag =
    last transitions row, stop tag = second-to-last row
    (viterbi_decode_kernel.cc:222-252)."""
    from paddle_tpu.text import viterbi_decode
    import itertools
    rs = np.random.RandomState(3)
    b, s, n = 2, 4, 4
    pot = rs.randn(b, s, n).astype(np.float32)
    trans = rs.randn(n, n).astype(np.float32)
    lengths = np.array([4, 2], np.int32)
    scores, paths = viterbi_decode(pot, trans, lengths,
                                   include_bos_eos_tag=True)
    for bi in range(b):
        L = int(lengths[bi])
        best, bestp = -1e30, None
        for tags in itertools.product(range(n), repeat=L):
            sc = trans[n - 1, tags[0]] + pot[bi, 0, tags[0]]
            for t in range(1, L):
                sc += trans[tags[t - 1], tags[t]] + pot[bi, t, tags[t]]
            sc += trans[n - 2, tags[L - 1]]
            if sc > best:
                best, bestp = sc, tags
        assert abs(float(scores[bi]) - best) < 1e-4
        assert list(np.asarray(paths[bi])[:L]) == list(bestp)
