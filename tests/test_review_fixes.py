"""Regression tests for review findings (round-1 code review)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_pad_innermost_first():
    x = jnp.zeros((1, 1, 3, 3))
    y = F.pad(x, [1, 0, 0, 0])  # pad left of W only
    assert y.shape == (1, 1, 3, 4)
    y2 = F.pad(x, [0, 0, 2, 0])  # pad top of H only
    assert y2.shape == (1, 1, 5, 3)


def test_pad_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 3, 4, 5).astype(np.float32)
    pad = [1, 2, 3, 4]
    got = np.asarray(F.pad(jnp.asarray(x), pad, value=7.0))
    ref = torch.nn.functional.pad(torch.tensor(x), pad, value=7.0).numpy()
    np.testing.assert_array_equal(got, ref)


def test_frozen_param_not_updated():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.frozen = nn.Parameter(jnp.ones((4,)), trainable=False)
            self.lin = nn.Linear(4, 1)

        def forward(self, x):
            return self.lin(x * self.frozen)

    from paddle_tpu.io import TensorDataset
    from paddle_tpu.optimizer import SGD
    net = Net()
    model = pt.Model(net)
    model.prepare(optimizer=SGD(learning_rate=0.1, parameters=net),
                  loss=nn.MSELoss())
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 1).astype(np.float32)
    model.fit(TensorDataset([x, y]), batch_size=8, epochs=2, verbose=0)
    np.testing.assert_array_equal(np.asarray(net.frozen), 1.0)
    # but the trainable linear moved
    assert model._step_count == 2


def test_adamw_decay_exclusion():
    from paddle_tpu.optimizer import AdamW
    params = {"w": jnp.ones((4,)), "norm.bias": jnp.ones((4,))}
    opt = AdamW(learning_rate=0.0, weight_decay=0.5,
                apply_decay_param_fun=lambda n: "norm" not in n)
    # lr=0 isolates... decay is multiplied by lr, so use lr>0 and zero grads
    opt = AdamW(learning_rate=0.1, weight_decay=0.5,
                apply_decay_param_fun=lambda n: "norm" not in n)
    state = opt.init_state(params)
    zero_g = {k: jnp.zeros_like(v) for k, v in params.items()}
    p1, _ = opt.apply_gradients(params, zero_g, state, 0)
    assert float(p1["w"][0]) < 1.0            # decayed
    np.testing.assert_allclose(np.asarray(p1["norm.bias"]), 1.0)  # excluded


def test_transformer_clone_keeps_activation():
    proto = nn.TransformerEncoderLayer(16, 2, 32, 0.1, activation="gelu",
                                       normalize_before=True)
    enc = nn.TransformerEncoder(proto, 3)
    for layer in enc.layers:
        assert layer.activation is F.gelu
        assert layer.normalize_before


def test_interpolate_align_corners_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(1, 2, 5, 7).astype(np.float32)
    got = np.asarray(F.interpolate(jnp.asarray(x), size=(10, 3),
                                   mode="bilinear", align_corners=True))
    ref = torch.nn.functional.interpolate(
        torch.tensor(x), size=(10, 3), mode="bilinear",
        align_corners=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_nonpersistable_buffer_roundtrip():
    class L(nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("tmp", jnp.zeros((2,)), persistable=False)
            self.register_buffer("keep", jnp.ones((2,)))

        def forward(self, x):
            return x

    l1 = L()
    sd = l1.state_dict()
    assert "tmp" not in sd and "keep" in sd
    L().set_state_dict(sd)  # must not raise


def test_fan_in_out_conv_layout():
    from paddle_tpu.nn.initializer import _fan_in_out
    fi, fo = _fan_in_out([64, 32, 3, 3])  # [out, in, kh, kw]
    assert fi == 32 * 9
    assert fo == 64 * 9


def test_named_rng_streams_stable():
    import subprocess, sys
    code = ("import paddle_tpu as pt; import numpy as np; pt.seed(3); "
            "from paddle_tpu.core import rng; "
            "print(np.asarray(__import__('jax').random.key_data("
            "rng.next_key('init'))).tolist())")
    outs = {subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True).stdout.strip()
            for _ in range(2)}
    assert len(outs) == 1  # identical across fresh interpreters
