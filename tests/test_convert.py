"""HF checkpoint interop (models/convert.py): converted models must
reproduce the source model's outputs — the PaddleNLP-converter analog
(ref: the reference ecosystem's per-family convert.py scripts mapping
HF torch checkpoints onto paddle Layers). HF models are constructed
offline with random weights; parity is numerical, not just structural.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # torch import + compile; smoke skips

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_gpt2_roundtrip_logits_match():
    from transformers import GPT2Config, GPT2LMHeadModel

    from paddle_tpu.models.convert import gpt2_from_huggingface

    hf_cfg = GPT2Config(vocab_size=160, n_positions=32, n_embd=64,
                        n_layer=2, n_head=2,
                        resid_pdrop=0.0, embd_pdrop=0.0,
                        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg)
    hf.eval()

    ids = np.random.RandomState(0).randint(0, 160, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()

    net = gpt2_from_huggingface(
        hf, config={"num_heads": 2, "hidden_dropout": 0.0,
                    "attention_dropout": 0.0, "use_flash": False})
    net.eval()
    out = np.asarray(net(ids))
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)

    # and generation agrees greedily (the strongest end-to-end check)
    ours = np.asarray(net.generate(ids[:1, :8], max_new_tokens=4))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(ids[:1, :8]),
                             max_new_tokens=4, do_sample=False).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_gpt2_convert_composes_with_tpu_features():
    """The converted model is a first-class zoo member: scan_layers +
    fused_loss train on it directly."""
    import paddle_tpu as pt
    from transformers import GPT2Config, GPT2LMHeadModel

    from paddle_tpu.models.convert import gpt2_from_huggingface
    from paddle_tpu.models.gpt import GPTFusedPretrainingCriterion

    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=160, n_positions=32, n_embd=64, n_layer=2, n_head=2))
    net = gpt2_from_huggingface(
        hf, config={"num_heads": 2, "hidden_dropout": 0.0,
                    "attention_dropout": 0.0, "use_flash": False,
                    "scan_layers": True, "remat": True,
                    "fused_loss": True})
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-4,
                                           parameters=net),
              loss=GPTFusedPretrainingCriterion())
    ids = np.random.RandomState(0).randint(0, 160, (2, 16))
    losses = [float(m.train_batch([ids], [ids])["loss"])
              for _ in range(2)]
    assert all(np.isfinite(losses)) and losses[1] < losses[0]


def test_bert_roundtrip_hidden_states_match():
    from transformers import BertConfig as HFBertConfig
    from transformers import BertModel as HFBertModel

    from paddle_tpu.models.convert import bert_from_huggingface

    hf_cfg = HFBertConfig(vocab_size=160, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=128,
                          max_position_embeddings=32,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = HFBertModel(hf_cfg)
    hf.eval()

    ids = np.random.RandomState(0).randint(3, 160, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()

    net = bert_from_huggingface(
        hf, config={"num_heads": 2, "hidden_dropout": 0.0,
                    "attention_dropout": 0.0, "use_flash": False})
    net.eval()
    seq_out, _pooled = net(ids)
    np.testing.assert_allclose(np.asarray(seq_out), ref,
                               atol=3e-4, rtol=3e-4)


def test_llama_roundtrip_logits_match():
    from transformers import LlamaConfig, LlamaForCausalLM

    from paddle_tpu.models.convert import llama_from_huggingface

    hf_cfg = LlamaConfig(vocab_size=160, hidden_size=64,
                         intermediate_size=96, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=32, rope_theta=10000.0,
                         attention_dropout=0.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg)
    hf.eval()

    ids = np.random.RandomState(0).randint(0, 160, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()

    net = llama_from_huggingface(hf, config={"use_flash": False})
    net.eval()
    out = np.asarray(net(ids))
    np.testing.assert_allclose(out, ref, atol=3e-4, rtol=3e-4)

    ours = np.asarray(net.generate(ids[:1, :8], max_new_tokens=4))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(ids[:1, :8]),
                             max_new_tokens=4, do_sample=False).numpy()
    np.testing.assert_array_equal(ours, theirs)
