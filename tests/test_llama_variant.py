"""LLaMA-style GPT variant: RoPE + RMSNorm + SwiGLU + GQA, with
cache-correct rotary decode."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion, llama_config)

import pytest
pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)


def _tiny_llama(**kw):
    pt.seed(0)
    return GPTForCausalLM(llama_config(
        hidden_size=32, num_layers=2, num_heads=4, num_kv_heads=2,
        vocab_size=64, max_position_embeddings=32, use_flash=False,
        **kw))


def test_structure():
    net = _tiny_llama()
    names = dict(net.named_parameters())
    # no learned position table under rope
    assert not any("position_embeddings" in n for n in names)
    # untied head exists; swiglu doubles fc_in width
    assert "lm_head.weight" in names
    assert names["gpt.layers.0.mlp.fc_in.weight"].shape[1] == \
        2 * names["gpt.layers.0.mlp.fc_out.weight"].shape[0]
    # rms norms have no bias
    assert "gpt.ln_f.bias" not in names and "gpt.ln_f.weight" in names


def test_cache_decode_matches_full_forward():
    """Incremental RoPE decode == full forward (the decode-offset
    contract through the KV cache)."""
    net = _tiny_llama()
    net.eval()
    ids = np.random.RandomState(0).randint(0, 64, (2, 10))
    full = np.asarray(net(ids))

    caches = net.init_caches(2, 10)
    lg, caches = net(jnp.asarray(ids[:, :6]), caches=caches)
    np.testing.assert_allclose(np.asarray(lg), full[:, :6], rtol=2e-4,
                               atol=2e-5)
    for t in range(6, 10):
        lg, caches = net(jnp.asarray(ids[:, t:t + 1]),
                         caches=caches)
        np.testing.assert_allclose(np.asarray(lg)[:, 0], full[:, t],
                                   rtol=2e-4, atol=2e-5)


def test_generate_and_train():
    net = _tiny_llama()
    net.eval()
    ids = np.random.RandomState(1).randint(0, 64, (1, 6))
    out = net.generate(jnp.asarray(ids), max_new_tokens=4)
    assert out.shape == (1, 10)

    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=net),
        loss=GPTPretrainingCriterion())
    batch = np.random.RandomState(2).randint(0, 64, (4, 16))
    losses = [float(model.train_batch([batch], [batch])["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_default_gpt_unchanged():
    """The flags default off: classic GPT still has learned positions,
    LayerNorm with bias, and 4h gelu MLP."""
    pt.seed(0)
    net = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_position_embeddings=16, use_flash=False))
    names = dict(net.named_parameters())
    assert any("position_embeddings" in n for n in names)
    assert "gpt.ln_f.bias" in names
    assert names["gpt.layers.0.mlp.fc_in.weight"].shape[1] == 4 * 32


def test_rope_honors_explicit_position_ids():
    """Left-padded batches pass custom position_ids; rope must use
    them, not arange."""
    net = _tiny_llama()
    net.eval()
    ids = np.random.RandomState(3).randint(0, 64, (1, 6))
    full = np.asarray(net(ids))
    # a UNIFORM shift leaves outputs unchanged (rope is relative) —
    # this also proves the explicit ids actually reach the rotation
    shifted = np.asarray(net(ids, position_ids=jnp.arange(2, 8)[None]))
    np.testing.assert_allclose(shifted, full, rtol=1e-4, atol=1e-5)
    # a NON-uniform layout (gap => different relative distances) must
    # change the result
    gapped = np.asarray(net(
        ids, position_ids=jnp.asarray([[0, 1, 2, 10, 11, 12]])))
    assert not np.allclose(gapped, full, atol=1e-4)


def test_pipe_ln_f_honors_norm_type():
    from paddle_tpu.models.gpt import GPTForCausalLMPipe
    pt.seed(0)
    cfg = llama_config(hidden_size=16, num_layers=2, num_heads=2,
                       num_kv_heads=2, vocab_size=32,
                       max_position_embeddings=16, use_flash=False)
    net = GPTForCausalLMPipe(cfg, num_microbatches=1)
    assert isinstance(net.ln_f, nn.RMSNorm)
