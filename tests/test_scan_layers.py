"""scan_layers: lax.scan over the decoder stack (GPTConfig.scan_layers).

The TPU-native depth loop — the block lowers once (compile O(1) in
depth) and, with remat, the scan carries are the ONLY saved
activations: recompute happens inside the backward scan body, where no
backend pass can CSE it against the forward (XLA:CPU strips
jax.checkpoint's optimization barriers from the unrolled trunk and
merges the recompute away — discovered measuring the r4 1.3B
feasibility study; the scan form is what makes remat memory provable
on every backend). ref: the reference's trunk is an eager Python loop
(incubate fused blocks are its depth lever instead).
"""

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   GPTFusedPretrainingCriterion,
                                   GPTPretrainingCriterion, gpt_config)

pytestmark = pytest.mark.slow  # compile-bound; smoke runs the pick below

_TINY = dict(vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
             max_position_embeddings=16, hidden_dropout=0.0,
             attention_dropout=0.0, use_flash=False)


def _ids(b=2, s=16):
    return np.random.RandomState(0).randint(0, 128, (b, s))


@pytest.mark.smoke
def test_scan_forward_matches_loop():
    pt.seed(0)
    loop = GPTForCausalLM(GPTConfig(**_TINY))
    pt.seed(0)
    scan = GPTForCausalLM(GPTConfig(**_TINY, scan_layers=True))
    ids = _ids()
    np.testing.assert_allclose(np.asarray(loop(ids)),
                               np.asarray(scan(ids)),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("remat", [False, True])
def test_scan_training_matches_loop(remat):
    ids = _ids()
    losses = {}
    for scan in (False, True):
        pt.seed(0)
        net = GPTForCausalLM(GPTConfig(**_TINY, scan_layers=scan,
                                       remat=remat))
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=GPTPretrainingCriterion())
        losses[scan] = [float(m.train_batch([ids], [ids])["loss"])
                        for _ in range(3)]
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=1e-5, atol=1e-6)


def test_scan_with_dropout_trains_and_varies():
    """Dropout inside the scan body folds the layer index into the key:
    training must be finite and actually stochastic across steps."""
    cfg = dict(_TINY)
    cfg["hidden_dropout"] = 0.3
    pt.seed(0)
    net = GPTForCausalLM(GPTConfig(**cfg, scan_layers=True))
    m = pt.Model(net)
    m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=0.0,
                                           parameters=net),
              loss=GPTPretrainingCriterion())
    ids = _ids()
    # lr=0: same params every step, so loss variation isolates dropout
    ls = [float(m.train_batch([ids], [ids])["loss"]) for _ in range(4)]
    assert all(np.isfinite(ls))
    assert len({round(v, 8) for v in ls}) > 1, ls


def test_scan_decode_cache_falls_back_to_loop():
    """caches present -> the loop path serves (scan has no cache lane):
    greedy generation from a scan model matches the loop model's."""
    ids = _ids(1, 8)
    outs = []
    for scan in (False, True):
        pt.seed(0)
        net = GPTForCausalLM(GPTConfig(**_TINY, scan_layers=scan))
        net.eval()
        outs.append(np.asarray(net.generate(ids, max_new_tokens=5)))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_scan_remat_memory_is_structural():
    """The load-bearing property: on the 8-device fsdp mesh the
    scan+remat train step's compiled temps undercut the unrolled
    remat trunk by >=3x (the unrolled form's checkpoint barriers are
    stripped by the CPU pipeline; the scan form survives it)."""
    from paddle_tpu import parallel
    from paddle_tpu.core import rng as rng_mod

    def temps(scan):
        # deep enough that per-layer activations dominate the fixed
        # embedding/loss/optimizer buffers (at 4 layers the ratio
        # dilutes to ~2.7x; the effect scales with depth)
        cfg = gpt_config("gpt2-small", hidden_size=256, num_heads=4,
                         hidden_dropout=0.0, attention_dropout=0.0,
                         use_flash=False, remat=True, fused_loss=True,
                         num_layers=12, scan_layers=scan)
        mesh = parallel.init_mesh(fsdp=8)
        try:
            pt.seed(0)
            net = GPTForCausalLM(cfg)
            m = pt.Model(net)
            m.prepare(optimizer=pt.optimizer.AdamW(
                learning_rate=1e-4, parameters=net),
                loss=GPTFusedPretrainingCriterion())
            parallel.distributed_model(m, mesh=mesh)
            m._sync_state_in()
            m._train_step_fn = m._build_train_step()
            ids = np.zeros((32, 512), np.int32)
            inputs = m._shard_batch((ids,))
            labels = m._shard_batch((ids,))
            key = rng_mod.split_for_step(0)
            mem = m._train_step_fn.lower(
                m._params, m._frozen, m._opt_state, m._buffers, 0,
                key, inputs, labels).compile().memory_analysis()
            return float(mem.temp_size_in_bytes)
        finally:
            parallel.set_mesh(None)

    unrolled = temps(False)
    scanned = temps(True)
    assert scanned * 3 <= unrolled, (scanned, unrolled)


def test_bert_scan_matches_loop():
    """BERT/ERNIE trunks share the scan depth loop (nn.utils.
    scan_layer_stack): forward + training parity with the eager loop."""
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        BertPretrainingCriterion)

    kw = dict(vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
              max_position_embeddings=32, hidden_dropout=0.0,
              attention_dropout=0.0, use_flash=False)
    ids = np.random.RandomState(0).randint(3, 128, (2, 16))
    labels = np.random.RandomState(1).randint(0, 128, (2, 16))
    nsp = np.asarray([0, 1])
    losses = {}
    for scan in (False, True):
        pt.seed(0)
        net = BertForPretraining(BertConfig(**kw, scan_layers=scan,
                                            remat=scan))
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=BertPretrainingCriterion())
        losses[scan] = [
            float(m.train_batch([ids], [labels, nsp])["loss"])
            for _ in range(3)]
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=1e-5, atol=1e-6)
