"""Stream-integrity auditor (ISSUE 19): every token stream carries a
verifiable blake2b chain, and the fleet proves its own determinism.

Contract under test: the chain folds (nonce, position, token) into
every link, so two chains agree iff the streams are identical and the
first divergent link IS the first wrong token; the drift table counts
verdicts per scope/kind, mints its counters at FIRST record
(hole-not-zero federation), serves /driftz, and fires ONE flight dump
per process on divergence; the engine returns stream_digest/knobs in
result dicts with the audit flag ON and adds NOTHING — zero result
keys, zero compiled ops — with it OFF; router-side verification files
failover / migration / shadow verdicts; fleet federation reads a
never-armed replica as a HOLE, never a clean zero."""

import glob
import json
import threading
import types
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
from paddle_tpu.observability import audit


@pytest.fixture(autouse=True)
def _fresh_audit():
    """Every test starts hole-not-zero (no table, no counters, no
    /driftz provider) with the auditor enabled, and leaves the
    module in the same state for its neighbors."""
    audit.reset()
    audit.enable()
    yield
    audit.reset()
    audit.enable()


# ---------------------------------------------------------------------------
# chain math
# ---------------------------------------------------------------------------


def test_extend_is_deterministic_and_input_sensitive():
    base = audit.extend(b"", 7, 0, 42)
    assert base == audit.extend(b"", 7, 0, 42)
    assert len(base) == audit.DIGEST_SIZE
    # every folded field matters — nonce, position, token, prior chain
    assert base != audit.extend(b"", 8, 0, 42)
    assert base != audit.extend(b"", 7, 1, 42)
    assert base != audit.extend(b"", 7, 0, 43)
    assert base != audit.extend(b"x", 7, 0, 42)


def test_chain_of_matches_incremental_extends_and_heads():
    nonce, toks = 1234, [5, 9, 2, 2, 7]
    chain = b""
    for i, t in enumerate(toks):
        chain = audit.extend(chain, nonce, i, t)
    assert audit.chain_of(nonce, toks) == chain
    heads = audit.heads_of(nonce, toks)
    assert len(heads) == len(toks)
    for i in range(len(toks)):
        assert heads[i] == audit.chain_of(nonce, toks[:i + 1])
    # suffix folding on top of an existing head (the engine's
    # incremental path) reaches the same final chain
    assert audit.chain_of(nonce, toks[2:], chain=heads[1],
                          start=2) == chain
    # empty stream's head is the genesis
    assert audit.chain_of(nonce, []) == b""


def test_verify_prefix_accepts_exact_prefix_only():
    nonce, toks = 55, [3, 1, 4, 1, 5]
    for p in range(len(toks) + 1):
        head = audit.chain_of(nonce, toks[:p])
        assert audit.verify_prefix(nonce, toks, head, p)
    # one flipped token in the claimed prefix breaks it
    bad = audit.chain_of(nonce, [3, 1, 9])
    assert not audit.verify_prefix(nonce, toks, bad, 3)
    # prefix longer than the stream can never verify
    assert not audit.verify_prefix(nonce, toks,
                                   audit.chain_of(nonce, toks), 6)
    assert not audit.verify_prefix(nonce, toks, b"", -1)


def test_first_divergence_names_the_first_wrong_token():
    assert audit.first_divergence([1, 2, 3], [1, 2, 3]) is None
    assert audit.first_divergence([1, 2, 3], [1, 9, 3]) == 1
    assert audit.first_divergence([9, 2], [1, 2]) == 0
    # a pure length difference diverges at the shorter stream's end
    assert audit.first_divergence([1, 2, 3], [1, 2]) == 2
    assert audit.first_divergence([], [4]) == 0


def test_sampled_is_deterministic_and_tracks_the_rate():
    assert not audit.sampled(1, 0.0)
    assert audit.sampled(1, 1.0)
    # pure hash of the nonce: a replayed fleet shadows the SAME set
    picks = [audit.sampled(n, 0.25) for n in range(2000)]
    assert picks == [audit.sampled(n, 0.25) for n in range(2000)]
    frac = sum(picks) / len(picks)
    assert 0.15 < frac < 0.35, frac


# ---------------------------------------------------------------------------
# the drift table: verdicts, lazy mint, /driftz, one-shot dump
# ---------------------------------------------------------------------------


def test_drift_table_counts_verdicts_per_scope_and_kind():
    assert audit.record("a", "failover", True) is None
    assert audit.record("a", "shadow", True) is None
    div = audit.record("b", "migration", False, position=0,
                       chain_ours=b"\x01" * 16, chain_theirs=b"\x02" * 16,
                       nonce=9, knobs_ours={"kv_dtype": "bf16"},
                       knobs_theirs={"kv_dtype": "int8"},
                       detail="mismatched sibling")
    assert div is not None and div["position"] == 0
    pz = audit.driftz_payload()
    assert pz["totals"] == {"verified": 2, "diverged": 1}
    assert pz["scopes"]["a"]["verified"] == 2
    assert pz["scopes"]["b"]["by_kind"]["migration"] == 1
    last = pz["scopes"]["b"]["last_divergence"]
    assert last["chain_ours"] == "01" * 16
    assert last["chain_theirs"] == "02" * 16
    assert last["knobs_theirs"] == {"kv_dtype": "int8"}
    assert audit.instance().counts() == {"verified": 2, "diverged": 1}
    with pytest.raises(ValueError, match="unknown drift kind"):
        audit.record("a", "gossip", True)


def test_metrics_and_driftz_mint_at_first_record_hole_not_zero():
    from paddle_tpu.observability import server as dbg
    from paddle_tpu.observability.metrics import default_registry
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # BEFORE the first record: no drift_* families (line-anchored
        # — fleet_drift_* minted by other tests contains the name as
        # a substring) and /driftz 404s — the federation hole
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        for line in text.splitlines():
            assert not line.startswith(("drift_verified_total",
                                        "drift_divergence_total"))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/driftz", timeout=30)
        assert ei.value.code == 404
        # first record arms everything
        audit.record("engine", "shadow", True)
        audit.record("engine", "shadow", False, position=2)
        with urllib.request.urlopen(base + "/driftz", timeout=30) as r:
            dz = json.loads(r.read())
        pz = dz["drift"]["audit"]
        assert pz["enabled"] is True
        assert pz["kinds"] == list(audit.KINDS)
        assert pz["totals"] == {"verified": 1, "diverged": 1}
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        assert "drift_verified_total 1" in text
        assert 'drift_divergence_total{kind="shadow"} 1' in text
        # reset restores the hole (the fixture relies on this too)
        audit.reset()
        fams = {f.name for f in default_registry().families()}
        assert "drift_verified_total" not in fams
        assert "drift_divergence_total" not in fams
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/driftz", timeout=30)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_divergence_fires_one_flight_dump_per_process(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder
    rec = FlightRecorder(str(tmp_path)).install()
    try:
        audit.record("r", "shadow", False, position=3,
                     chain_ours=b"\xaa" * 16, chain_theirs=b"\xbb" * 16,
                     nonce=77, knobs_ours={"kv_dtype": "bf16"})
        audit.record("r", "failover", False, position=0)  # the storm
        dumps = glob.glob(str(tmp_path / "*stream_divergence*"))
        assert len(dumps) == 1, dumps
        rows = [json.loads(x) for x in
                open(dumps[0]).read().splitlines()]
        extra = next(r for r in rows if r.get("kind") == "extra")
        # nested under "divergence" so the record's own claim kind
        # cannot shadow the dump row's kind="extra" tag
        div = extra["divergence"]
        assert div["position"] == 3 and div["kind"] == "shadow"
        assert div["chain_ours"] == "aa" * 16
        assert div["chain_theirs"] == "bb" * 16
        assert div["knobs_ours"] == {"kv_dtype": "bf16"}
    finally:
        rec.uninstall()


# ---------------------------------------------------------------------------
# engine integration: digest in results, disabled adds NOTHING
# ---------------------------------------------------------------------------


def _tiny_engine():
    from paddle_tpu.inference.llm import LLMEngine
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return LLMEngine(GPTForCausalLM(cfg), max_seqs=2, page_size=4,
                     num_pages=32, prefill_buckets=(16,), seed=0)


def test_engine_result_digest_is_the_chain_of_its_stream():
    eng = _tiny_engine()
    with eng:
        out = eng.submit([4, 5, 6], max_new_tokens=4,
                         temperature=0.8).result(timeout=300)
    assert out["stream_digest"] == \
        audit.chain_of(out["nonce"], out["output_ids"]).hex()
    assert set(out["knobs"]) == {"kv_dtype", "spec_k", "spec_slab",
                                 "draft"}


def test_disabled_audit_adds_no_result_keys_and_no_ops():
    """Disabled cost is one module-flag check: the result dict gains
    no audit keys, and the compiled decode program is byte-identical
    to an audit-enabled engine's (the chain is pure host arithmetic
    — the HLO pin that keeps it off the device forever)."""
    def tick_hlo(eng):
        b = eng.max_seqs
        zeros = jnp.zeros((b,), jnp.int32)
        return eng._decode_fn.lower(
            eng._params, eng._buffers, zeros, zeros,
            jnp.zeros((b, eng.pages_per_seq), jnp.int32), zeros,
            eng.k_pages, eng.v_pages, jnp.zeros((b,), jnp.float32),
            zeros, eng._key).as_text()

    eng_on = _tiny_engine()
    with eng_on:
        on = eng_on.submit([1, 2, 3],
                           max_new_tokens=3).result(timeout=300)
        hlo_on = tick_hlo(eng_on)
    assert "stream_digest" in on
    audit.disable()
    try:
        eng_off = _tiny_engine()
        with eng_off:
            off = eng_off.submit([1, 2, 3],
                                 max_new_tokens=3).result(timeout=300)
            hlo_off = tick_hlo(eng_off)
        assert "stream_digest" not in off
        assert "knobs" not in off
        assert off["output_ids"] == on["output_ids"]
    finally:
        audit.enable()
    assert hlo_on == hlo_off, \
        "the audit flag changed a compiled program"
    # nothing was recorded either way: no claim, no verdict
    assert audit.instance().counts() == {"verified": 0, "diverged": 0}


# ---------------------------------------------------------------------------
# router verdicts: failover / migration / shadow
# ---------------------------------------------------------------------------


def _stub_router():
    """The slice of Router state _verify_stream/_shadow touch —
    verdict logic under test without spinning replicas (chaos_soak's
    drift storm exercises the full stack)."""
    from paddle_tpu.serving.router import Router
    stub = types.SimpleNamespace(
        name="router", _mu=threading.Lock(), _knobs={}, n_shadows=0,
        _pool=None)
    stub.verify = lambda req, st, out: Router._verify_stream(
        stub, req, st, out)
    stub.shadow = lambda req, st, out: Router._shadow(
        stub, req, st, out)
    return stub


def _req(nonce, *, failovers=0, migrate=None, prior_knobs=None):
    return types.SimpleNamespace(
        nonce=nonce, failovers=failovers, migrate=migrate,
        prior_knobs=prior_knobs, prompt=[1, 2], max_new_tokens=4,
        temperature=0.0)


def _out(nonce, tokens, knobs=None):
    return {"output_ids": list(tokens),
            "stream_digest": audit.chain_of(nonce, tokens).hex(),
            "knobs": knobs or {"kv_dtype": "bf16"}}


def test_router_failover_verdicts():
    r = _stub_router()
    st = types.SimpleNamespace(name="b")
    knobs = {"kv_dtype": "bf16", "spec_k": 0}
    # intact chain + matching sibling knobs -> verified
    r.verify(_req(1, failovers=1, prior_knobs=knobs), st,
             _out(1, [7, 8, 9], knobs))
    assert audit.instance().counts() == {"verified": 1, "diverged": 0}
    # a sibling serving under DIFFERENT knobs is a detected drift
    r.verify(_req(2, failovers=1,
                  prior_knobs={"kv_dtype": "int8", "spec_k": 0}),
             st, _out(2, [7, 8], knobs))
    # a digest that does not match the returned tokens is corruption
    bad = _out(3, [4, 5, 6], knobs)
    bad["stream_digest"] = audit.chain_of(3, [4, 5, 9]).hex()
    r.verify(_req(3, failovers=1, prior_knobs=knobs), st, bad)
    pz = audit.driftz_payload()
    assert pz["scopes"]["router"]["by_kind"]["failover"] == 2
    assert pz["scopes"]["router"]["last_divergence"]["position"] == 3
    # no failover claimed, no verdict filed (shadows own that case)
    r.verify(_req(4), st, _out(4, [1, 1]))
    assert audit.instance().counts()["verified"] == 1


def test_router_migration_fill_witness_verdicts():
    r = _stub_router()
    st = types.SimpleNamespace(name="decode0")
    toks = [11, 12, 13]
    fill_ok = audit.chain_of(5, toks[:1]).hex()
    r.verify(_req(5, migrate={"fill_digest": fill_ok,
                              "prefill": "p0"}), st, _out(5, toks))
    assert audit.instance().counts() == {"verified": 1, "diverged": 0}
    # a fill emitted under drifted pages names position 0
    fill_bad = audit.chain_of(6, [99]).hex()
    r.verify(_req(6, migrate={"fill_digest": fill_bad,
                              "prefill": "p0"}), st, _out(6, toks))
    last = audit.driftz_payload()["scopes"]["router"]["last_divergence"]
    assert last["kind"] == "migration" and last["position"] == 0


def test_router_shadow_reexecution_verdicts():
    r = _stub_router()
    served = _out(9, [3, 4, 5, 6])
    agree = types.SimpleNamespace(
        name="a", client=types.SimpleNamespace(
            submit=lambda *a, **k: _out(9, [3, 4, 5, 6])))
    r.shadow(_req(9), agree, dict(served))
    assert audit.instance().counts() == {"verified": 1, "diverged": 0}
    differ = types.SimpleNamespace(
        name="a", client=types.SimpleNamespace(
            submit=lambda *a, **k: _out(9, [3, 4, 1, 6])))
    r.shadow(_req(9), differ, dict(served))
    last = audit.driftz_payload()["scopes"]["router"]["last_divergence"]
    assert last["kind"] == "shadow" and last["position"] == 2
    assert last["chain_ours"] == served["stream_digest"]


# ---------------------------------------------------------------------------
# fleet federation: hole-not-zero
# ---------------------------------------------------------------------------


def test_fleet_drift_federation_reads_never_armed_as_a_hole():
    from paddle_tpu.observability.metrics import MetricRegistry
    from paddle_tpu.serving.fleet import FleetScraper
    fs = FleetScraper(registry=MetricRegistry())
    # nobody armed: sums are None (unverified != verified-clean)
    fs.record("hole", "llm_requests_completed 3\n")
    agg = fs.aggregates()
    assert agg["drift_verified"] is None
    assert agg["drift_divergences"] is None
    assert agg["drift_replicas"] == 0
    # one armed replica enters; the hole stays out of the denominator
    fs.record("armed", "drift_verified_total 5\n"
                       'drift_divergence_total{kind="shadow"} 1\n'
                       'drift_divergence_total{kind="failover"} 2\n')
    agg = fs.aggregates()
    assert agg["drift_verified"] == 5
    assert agg["drift_divergences"] == 3   # every {kind} sample summed
    assert agg["drift_replicas"] == 1
    # the armed replica's series federate; the hole exports none
    text = fs.render_prometheus()
    assert 'fleet_drift_verified_total{replica="armed"} 5.0' in text
    assert ('fleet_drift_divergence_total'
            '{replica="armed",kind="shadow"} 1.0') in text
    assert not any("drift_" in ln for ln in text.splitlines()
                   if 'replica="hole"' in ln)
