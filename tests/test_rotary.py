"""RoPE + SwiGLU (parity vs the standard formulas / torch reference)."""

import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.ops.rotary import apply_rotary_pos_emb, rope_tables


def test_rope_relative_position_property():
    """<RoPE(q,m), RoPE(k,n)> depends only on m-n."""
    d, L = 8, 32
    cos, sin = rope_tables(d, L)
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(1, L, 1, d), jnp.float32)
    k = jnp.asarray(r.randn(1, L, 1, d), jnp.float32)
    # use the same q/k vector at every position
    q = jnp.broadcast_to(q[:, :1], q.shape)
    k = jnp.broadcast_to(k[:, :1], k.shape)
    qr, kr = apply_rotary_pos_emb(q, k, cos, sin)
    dots = np.asarray(jnp.einsum("bshd,bthd->st", qr, kr))
    # all pairs with the same offset m-n share the same score
    for off in (1, 3, 7):
        diag = np.diagonal(dots, offset=off)
        np.testing.assert_allclose(diag, diag[0], rtol=1e-4, atol=1e-5)


def test_rope_norm_preserved():
    d, L = 16, 8
    cos, sin = rope_tables(d, L)
    q = jnp.asarray(np.random.RandomState(1).randn(2, L, 3, d),
                    jnp.float32)
    qr, _ = apply_rotary_pos_emb(q, q, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)


def test_rope_position_ids_decode_offset():
    """Decoding one token at absolute position p equals slicing the
    full-sequence application — the KV-cache contract."""
    d, L = 8, 16
    cos, sin = rope_tables(d, L)
    r = np.random.RandomState(2)
    q = jnp.asarray(r.randn(1, L, 2, d), jnp.float32)
    full, _ = apply_rotary_pos_emb(q, q, cos, sin)
    p = 5
    one, _ = apply_rotary_pos_emb(
        q[:, p:p + 1], q[:, p:p + 1], cos, sin,
        position_ids=jnp.asarray([[p]]))
    np.testing.assert_allclose(np.asarray(one), np.asarray(full[:, p:p + 1]),
                               rtol=1e-5, atol=1e-6)


def test_rope_matches_torch_convention():
    torch = pytest.importorskip("torch")
    d, L = 8, 6
    cos, sin = rope_tables(d, L)
    r = np.random.RandomState(3)
    x = r.randn(1, L, 1, d).astype(np.float32)

    # the LLaMA rotate_half reference implementation
    tc = np.asarray(cos)[None, :, None, :]
    ts = np.asarray(sin)[None, :, None, :]
    def rot(v):
        return np.concatenate([-v[..., d // 2:], v[..., :d // 2]], -1)
    ref = x * tc + rot(x) * ts
    got, _ = apply_rotary_pos_emb(jnp.asarray(x), jnp.asarray(x), cos,
                                  sin)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5,
                               atol=1e-6)


def test_swiglu():
    r = np.random.RandomState(4)
    x = jnp.asarray(r.randn(3, 8), jnp.float32)
    out = F.swiglu(x)
    a, g = np.split(np.asarray(x), 2, axis=-1)
    ref = a / (1 + np.exp(-a)) * g
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-6)
    out2 = F.swiglu(x[:, :4], x[:, 4:])
    np.testing.assert_allclose(np.asarray(out2), ref, rtol=1e-5,
                               atol=1e-6)
