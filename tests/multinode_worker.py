"""Rank body for tests/test_multinode_elastic.py: a 2-process DP
training job under the multi-node NodeAgent launcher, with
step-granular AutoCheckpoint and cross-rank resume-step agreement.

Run (by the NodeAgent): python multinode_worker.py <workdir> <steps>

Env knobs (set by the test):
  MN_PREEMPT  "s@g[,s@g...]" — after committing step s while in
              generation g, exit RESTART_EXIT_CODE (graceful
              preemption; the agent restarts budget-free).
  MN_CRASH    "s@g" — crash hard (exit 3) BEFORE committing step s in
              generation g (burns the failure budget).

Rank 0 appends "step loss generation" per completed step to
<workdir>/losses.txt; the last line per step is the authoritative one
(steps re-run after a mid-epoch kill legitimately appear twice).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_points(spec):
    out = set()
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            s, g = part.split("@")
            out.add((int(s), int(g)))
    return out


def main(workdir: str, total_steps: int):
    import jax
    # sitecustomize pre-imports jax with the TPU plugin: pin CPU in-code
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, parallel
    from paddle_tpu.distributed import elastic
    from paddle_tpu.io.checkpoint import AutoCheckpoint

    parallel.init_parallel_env()
    rank = jax.process_index()
    gen = elastic.restart_count()
    preempt_at = _parse_points(os.environ.get("MN_PREEMPT"))
    crash_at = _parse_points(os.environ.get("MN_CRASH"))

    mesh = parallel.init_mesh(dp=2)
    pt.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())
    parallel.distributed_model(model, mesh=mesh)

    # ONE shared checkpoint directory for all ranks — orbax's native
    # multi-process mode: replicated trees are written once by the
    # primary process, finalization is atomic, and latest_step() is
    # therefore consistent on every rank after any kill. (Per-rank
    # directories are wrong here: each rank's manager would run its own
    # global sync with the primary writing nothing into the others'
    # dirs.)
    acp = AutoCheckpoint.for_model(os.path.join(workdir, "ckpt"), model)

    def agree(local_latest: int) -> int:
        # with a shared manager every rank already sees the same latest
        # step; the allgather-min remains as a guard (and covers
        # non-shared layouts), logging each rank's resume decision
        from jax.experimental import multihost_utils
        steps = multihost_utils.process_allgather(
            np.asarray([local_latest], np.int32))
        agreed = int(np.min(steps))
        with open(os.path.join(workdir, f"agree_rank{rank}.log"),
                  "a") as f:
            f.write(f"gen={gen} local={local_latest} all={steps.tolist()}"
                    f" agreed={agreed}\n")
        return agreed

    loss_path = os.path.join(workdir, "losses.txt")
    for step in acp.epochs(total_steps, agree_step=agree):
        rng = np.random.RandomState(1000 + step)  # data keyed by step
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randint(0, 4, (8, 1))
        if (step, gen) in crash_at:
            os._exit(3)  # hard failure before the commit: step is lost
        logs = model.train_batch([x], [y])
        if rank == 0:
            with open(loss_path, "a") as f:
                f.write(f"{step} {float(logs['loss']):.8f} {gen}\n")
        acp.commit(step)
        if (step, gen) in preempt_at:
            sys.exit(elastic.RESTART_EXIT_CODE)
    print("done", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]))
