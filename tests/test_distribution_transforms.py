"""Distribution transforms (ref: unittests/distribution/test_transform*.py
— forward/inverse roundtrips + log-det checked against autodiff)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu import distribution as D


def _x(*s, seed=0, lo=-2.0, hi=2.0):
    return jnp.asarray(np.random.RandomState(seed).uniform(lo, hi, s),
                       jnp.float32)


@pytest.mark.parametrize("t,dom", [
    (D.AffineTransform(1.5, 2.0), (-2, 2)),
    (D.ExpTransform(), (-2, 2)),
    (D.PowerTransform(3.0), (0.1, 2)),
    (D.SigmoidTransform(), (-3, 3)),
    (D.TanhTransform(), (-2, 2)),
])
def test_roundtrip_and_logdet_vs_autodiff(t, dom):
    x = _x(7, seed=1, lo=dom[0], hi=dom[1])
    y = t.forward(x)
    np.testing.assert_allclose(np.asarray(t.inverse(y)), np.asarray(x),
                               rtol=1e-4, atol=1e-5)
    # analytic log|J| == log|d forward/dx| from autodiff, elementwise
    grads = jax.vmap(jax.grad(lambda v: t.forward(v).sum()))(x)
    np.testing.assert_allclose(np.asarray(t.forward_log_det_jacobian(x)),
                               np.log(np.abs(np.asarray(grads))),
                               rtol=1e-4, atol=1e-5)


def test_chain_compose():
    chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                              D.ExpTransform()])
    x = _x(5, seed=2)
    y = chain.forward(x)
    np.testing.assert_allclose(np.asarray(y), np.exp(2 * np.asarray(x)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(chain.inverse(y)),
                               np.asarray(x), rtol=1e-4, atol=1e-5)
    ldj = chain.forward_log_det_jacobian(x)
    grads = jax.vmap(jax.grad(lambda v: chain.forward(v).sum()))(x)
    np.testing.assert_allclose(np.asarray(ldj),
                               np.log(np.abs(np.asarray(grads))),
                               rtol=1e-4, atol=1e-5)


def test_stick_breaking_simplex():
    t = D.StickBreakingTransform()
    x = _x(4, 3, seed=3)
    y = t.forward(x)
    assert y.shape == (4, 4)
    np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-5)
    assert (np.asarray(y) > 0).all()
    np.testing.assert_allclose(np.asarray(t.inverse(y)), np.asarray(x),
                               rtol=1e-3, atol=1e-4)


def test_reshape_and_stack():
    t = D.ReshapeTransform((4,), (2, 2))
    x = _x(3, 4, seed=4)
    assert t.forward(x).shape == (3, 2, 2)
    np.testing.assert_allclose(np.asarray(t.inverse(t.forward(x))),
                               np.asarray(x))
    st = D.StackTransform([D.ExpTransform(),
                           D.AffineTransform(0.0, 2.0)], axis=1)
    x2 = _x(3, 2, seed=5)
    y2 = st.forward(x2)
    np.testing.assert_allclose(np.asarray(y2[:, 0]),
                               np.exp(np.asarray(x2[:, 0])), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y2[:, 1]),
                               2 * np.asarray(x2[:, 1]), rtol=1e-5)


def test_transformed_distribution_lognormal():
    """Normal pushed through Exp == LogNormal: log_prob matches the
    closed form."""
    base = D.Normal(loc=0.0, scale=1.0)
    ln = D.TransformedDistribution(base, [D.ExpTransform()])
    v = jnp.asarray([0.5, 1.0, 2.0])
    got = np.asarray(ln.log_prob(v))
    ref = -np.log(np.asarray(v)) - 0.5 * np.log(2 * np.pi) - \
        0.5 * np.log(np.asarray(v)) ** 2
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    s = ln.sample((1000,))
    assert (np.asarray(s) > 0).all()


def test_transform_call_on_distribution():
    out = D.ExpTransform()(D.Normal(loc=0.0, scale=1.0))
    assert isinstance(out, D.TransformedDistribution)


def test_independent_sums_event_dims():
    base = D.Normal(loc=jnp.zeros((3, 4)), scale=jnp.ones((3, 4)))
    ind = D.Independent(base, 1)
    v = _x(3, 4, seed=6)
    lp = ind.log_prob(v)
    assert lp.shape == (3,)
    np.testing.assert_allclose(np.asarray(lp),
                               np.asarray(base.log_prob(v).sum(-1)),
                               rtol=1e-6)


def test_chain_with_shape_changing_member():
    """Reshape then Exp: jacobians reduce to the chain's batch dims."""
    chain = D.ChainTransform([D.ReshapeTransform((4,), (2, 2)),
                              D.ExpTransform()])
    x = _x(3, 4, seed=7)
    ldj = chain.forward_log_det_jacobian(x)
    assert ldj.shape == (3,)
    # exp's elementwise jacobian summed over the event: sum(x)
    np.testing.assert_allclose(np.asarray(ldj),
                               np.asarray(x).sum(-1), rtol=1e-5)
    assert chain.forward_shape((3, 4)) == (3, 2, 2)
    assert chain.inverse_shape((3, 2, 2)) == (3, 4)


def test_transformed_distribution_event_base():
    """Elementwise transform over an event-shaped base (Dirichlet):
    ldj must reduce over the event dim."""
    base = D.Dirichlet(jnp.ones(3))
    td = D.TransformedDistribution(base, [D.AffineTransform(0.0, 2.0)])
    v = jnp.asarray([0.4, 0.6, 1.0])  # = 2 * simplex point
    lp = td.log_prob(v)
    assert np.ndim(lp) == 0
    ref = float(base.log_prob(v / 2)) - 3 * np.log(2.0)
    np.testing.assert_allclose(float(lp), ref, rtol=1e-5)


def test_transformed_distribution_shapes_stick_breaking():
    base = D.Normal(loc=jnp.zeros(3), scale=jnp.ones(3))
    td = D.TransformedDistribution(base, [D.StickBreakingTransform()])
    assert td.event_shape == (4,) and td.batch_shape == ()
    s = td.sample((5,))
    assert s.shape == (5, 4)
    np.testing.assert_allclose(np.asarray(s.sum(-1)), 1.0, rtol=1e-5)


def test_independent_rank_validated():
    base = D.Normal(loc=jnp.zeros(3), scale=jnp.ones(3))
    with pytest.raises(ValueError, match="out of range"):
        D.Independent(base, 2)


def test_transform_shape_queries():
    assert D.StickBreakingTransform().forward_shape((5, 3)) == (5, 4)
    assert D.StickBreakingTransform().inverse_shape((5, 4)) == (5, 3)
    assert D.ReshapeTransform((4,), (2, 2)).forward_shape((3, 4)) == \
        (3, 2, 2)
