"""Optimizer + LR schedule tests (ref test strategy: unittests/
test_adam_op.py etc. compare against NumPy reference updates)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import (SGD, Adam, AdamW, Lamb, LarsMomentum,
                                  Momentum, RMSProp, lr)


def quad_loss(params):
    return sum(jnp.sum(jnp.square(p)) for p in params.values())


def run_steps(opt_cls, n=50, **kw):
    params = {"w": jnp.asarray(np.random.randn(4, 4).astype(np.float32)),
              "b": jnp.asarray(np.random.randn(4).astype(np.float32))}
    opt = opt_cls(**kw)
    state = opt.init_state(params)
    for i in range(n):
        grads = jax.grad(quad_loss)(params)
        params, state = opt.apply_gradients(params, grads, state, i)
    return params


@pytest.mark.parametrize("opt_cls,kw", [
    (SGD, {"learning_rate": 0.1}),
    (Momentum, {"learning_rate": 0.05, "momentum": 0.9}),
    (Adam, {"learning_rate": 0.1}),
    (AdamW, {"learning_rate": 0.1, "weight_decay": 0.01}),
    (RMSProp, {"learning_rate": 0.05}),
    (Lamb, {"learning_rate": 0.1}),
])
def test_optimizers_converge_on_quadratic(opt_cls, kw):
    params = run_steps(opt_cls, n=100, **kw)
    final = float(quad_loss(params))
    assert final < 0.05, f"{opt_cls.__name__} did not converge: {final}"


def test_lars_decreases_loss():
    """LARS's layer-wise trust ratio gives tiny effective LRs on toy
    problems; assert monotone improvement rather than full convergence."""
    np.random.seed(0)
    params = {"w": jnp.asarray(np.random.randn(4, 4).astype(np.float32))}
    opt = LarsMomentum(learning_rate=1.0, lars_coeff=0.1)
    state = opt.init_state(params)
    start = float(quad_loss(params))
    for i in range(50):
        grads = jax.grad(quad_loss)(params)
        params, state = opt.apply_gradients(params, grads, state, i)
    assert float(quad_loss(params)) < 0.5 * start


def test_adam_matches_reference_formula():
    """One Adam step vs hand-computed update (matching the reference's phi
    adam kernel semantics: bias-corrected, eps outside sqrt)."""
    w0 = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, 0.3], np.float32)
    params = {"w": jnp.asarray(w0)}
    opt = Adam(learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8,
               multi_precision=False)
    state = opt.init_state(params)
    new_params, _ = opt.apply_gradients(params, {"w": jnp.asarray(g)},
                                        state, 0)
    m = 0.1 * g
    v = 0.001 * g * g
    m_hat = m / (1 - 0.9)
    v_hat = v / (1 - 0.999)
    ref = w0 - 0.01 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(np.asarray(new_params["w"]), ref, rtol=1e-5)


def test_master_weights_bf16():
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = SGD(learning_rate=1e-3, multi_precision=True)
    state = opt.init_state(params)
    assert state["master"]["w"].dtype == jnp.float32
    g = {"w": jnp.full((4,), 1e-2, jnp.bfloat16)}
    p1, s1 = opt.apply_gradients(params, g, state, 0)
    assert p1["w"].dtype == jnp.bfloat16
    # master accumulates small updates (1e-5) that a bf16 weight at 1.0
    # would lose entirely (bf16 eps at 1.0 is ~7.8e-3)
    master = np.asarray(s1["master"]["w"], np.float32)
    assert np.all(master < 1.0)
    np.testing.assert_allclose(master, 1.0 - 1e-5, rtol=1e-3)
    # the bf16 copy rounds back to 1.0 — master carried the difference
    np.testing.assert_array_equal(np.asarray(p1["w"], np.float32), 1.0)


def test_eager_step_updates_layer():
    net = nn.Linear(3, 3, bias_attr=False)
    w_before = np.asarray(net.weight).copy()
    opt = SGD(learning_rate=0.5, parameters=net)
    x = jnp.ones((2, 3))

    def loss_fn(p):
        out, _ = nn.functional_call(net, p, {}, x)
        return jnp.sum(out ** 2)

    params = dict(net.named_parameters())
    grads = jax.grad(loss_fn)(params)
    opt.step(grads)
    assert not np.allclose(np.asarray(net.weight), w_before)


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm
    params = {"w": jnp.zeros((10,))}
    opt = SGD(learning_rate=1.0, grad_clip=ClipGradByGlobalNorm(1.0))
    state = opt.init_state(params)
    g = {"w": jnp.full((10,), 100.0)}
    p1, _ = opt.apply_gradients(params, g, state, 0)
    np.testing.assert_allclose(
        np.sqrt(np.sum(np.square(np.asarray(p1["w"])))), 1.0, rtol=1e-5)


# -- LR schedules -----------------------------------------------------------

def test_noam():
    s = lr.NoamDecay(d_model=64, warmup_steps=100, learning_rate=1.0)
    lrs = [float(s.lr_at(jnp.asarray(i))) for i in [1, 50, 100, 1000]]
    assert lrs[1] > lrs[0]
    assert lrs[3] < lrs[2]


def test_piecewise():
    s = lr.PiecewiseDecay(boundaries=[3, 6], values=[0.1, 0.01, 0.001])
    got = [float(s.lr_at(jnp.asarray(i))) for i in [0, 3, 4, 7]]
    np.testing.assert_allclose(got, [0.1, 0.01, 0.01, 0.001], rtol=1e-6)


def test_cosine():
    s = lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    assert abs(float(s.lr_at(jnp.asarray(0))) - 1.0) < 1e-6
    assert float(s.lr_at(jnp.asarray(10))) < 1e-6


def test_warmup_wraps_scheduler():
    inner = lr.CosineAnnealingDecay(learning_rate=1.0, T_max=100)
    s = lr.LinearWarmup(inner, warmup_steps=10, start_lr=0.0, end_lr=1.0)
    assert float(s.lr_at(jnp.asarray(0))) < 0.01
    np.testing.assert_allclose(float(s.lr_at(jnp.asarray(10))), 1.0,
                               rtol=1e-5)


def test_stateful_scheduler_step():
    s = lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.1)
    lrs = []
    for _ in range(4):
        lrs.append(s.get_lr())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.01, 0.01], rtol=1e-5)


def test_reduce_on_plateau():
    s = lr.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.5)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    assert s.get_lr() == 0.5


def test_lr_schedule_in_jit():
    """Schedules must be traceable — LR changes can't trigger recompiles."""
    s = lr.CosineAnnealingDecay(learning_rate=0.1, T_max=100)
    traces = []

    @jax.jit
    def step(i):
        traces.append(1)
        return s.lr_at(i)

    vals = [float(step(jnp.asarray(i))) for i in range(5)]
    assert len(set(vals)) == 5  # different lr values...
    assert sum(traces) == 1     # ...single compile


def test_adafactor_converges_and_state_is_sublinear():
    """Adafactor (no reference analog — the single-chip big-model
    optimizer): converges on the quadratic, and its second-moment state
    for a [R, C] weight is R+C floats, not R*C (the property that fits
    1.5B params on one 16 GB chip)."""
    from paddle_tpu.optimizer import Adafactor
    np.random.seed(0)
    # scale_parameter: alpha = rms(p)·lr, so steps shrink geometrically
    # near the optimum (unit-RMS updates alone would oscillate at lr)
    params = run_steps(Adafactor, n=200, learning_rate=0.1)
    assert float(quad_loss(params)) < 0.05

    p = {"w": jnp.zeros((128, 64)), "b": jnp.zeros((64,))}
    opt = Adafactor()
    s = opt.init_state(p)
    assert s["vr"]["w"].shape == (128,)
    assert s["vc"]["w"].shape == (64,)
    assert s["v"]["w"].size == 0          # factored: no full moment
    assert s["v"]["b"].shape == (64,)     # 1-D: full moment
    assert "m" not in s                   # beta1=None: no first moment
    state_floats = sum(x.size for x in jax.tree_util.tree_leaves(s))
    assert state_floats < 0.05 * (128 * 64)


def test_adafactor_relative_step_and_momentum():
    """Default (no lr): T5 relative step min(1e-2, 1/sqrt(t)) with
    parameter scaling; beta1 adds a first moment that changes the
    trajectory but still converges."""
    from paddle_tpu.optimizer import Adafactor
    np.random.seed(1)
    params = {"w": jnp.asarray(np.random.randn(8, 8).astype(np.float32))}
    opt = Adafactor(beta1=0.9)
    state = opt.init_state(params)
    assert state["m"]["w"].shape == (8, 8)
    start = float(quad_loss(params))
    for i in range(300):
        grads = jax.grad(quad_loss)(params)
        params, state = opt.apply_gradients(params, grads, state, i)
    assert float(quad_loss(params)) < 0.5 * start


def test_adafactor_stacked_leaves_sequential_parity():
    """[L, r, c] scan-stacked leaves (big slices) update via a
    sequential lax.map; the result equals running Adafactor on each
    slice as its own parameter (per-slice clip/scale semantics), and
    the factored state stays per-slice shaped."""
    from paddle_tpu.optimizer import Adafactor
    np.random.seed(3)
    L, r, c = 3, 1024, 1024           # slice >= 1Mi elements
    stacked = {"w": jnp.asarray(np.random.randn(L, r, c)
                                .astype(np.float32))}
    g = {"w": jnp.asarray(np.random.randn(L, r, c)
                          .astype(np.float32) * 0.1)}
    opt = Adafactor(learning_rate=0.01)
    s = opt.init_state(stacked)
    assert s["vr"]["w"].shape == (L, r) and s["vc"]["w"].shape == (L, c)
    new_stacked, _ = opt.apply_gradients(stacked, g, s, 0)

    for i in range(L):
        per = {"w": stacked["w"][i]}
        opt_i = Adafactor(learning_rate=0.01)
        s_i = opt_i.init_state(per)
        new_i, _ = opt_i.apply_gradients(per, {"w": g["w"][i]}, s_i, 0)
        np.testing.assert_allclose(np.asarray(new_stacked["w"][i]),
                                   np.asarray(new_i["w"]),
                                   rtol=1e-5, atol=1e-6)
