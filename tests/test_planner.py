"""Auto-parallel planner: cost model + layout search.

Analog of the reference's planner tests
(unittests/auto_parallel/test_cost_model.py, test_planner.py): the cost
model must predict the OOM the runtime would hit and pick a layout that
avoids it."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import parallel
from paddle_tpu.parallel import planner
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion, gpt_config)

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

_GiB = float(1 << 30)


@pytest.fixture(scope="module")
def gpt_1p3b():
    # shape-only construction: 1.3B params never materialize
    return planner.abstract_model(
        lambda: GPTForCausalLM(gpt_config("gpt3-1.3b")))


def test_naive_dp_ooms_on_1p3b(gpt_1p3b):
    """GPT-1.3B with Adam on 8 v5e chips under pure DP: ~21 GiB/chip of
    params+grads+moments alone — the cost model must flag it."""
    p = planner.evaluate(gpt_1p3b, {"dp": 8}, global_batch=64,
                         seq_len=2048)
    assert not p.fits, p.describe()
    # params+grads (f32) + 2 adam moments = 4x param bytes, unsharded
    assert p.breakdown["params"] > 4.5 * _GiB
    assert p.hbm_bytes > p.hbm_limit


def test_planner_picks_nontrivial_layout_for_1p3b(gpt_1p3b):
    """VERDICT r1 item 5 'done' bar: the planner must find a layout that
    fits where naive DP OOMs, and it must be non-trivial."""
    best, cands = planner.plan(gpt_1p3b, 8, global_batch=64,
                               seq_len=2048, return_all=True)
    assert best.fits, best.describe()
    assert best.axes.get("fsdp", 1) * best.axes.get("tp", 1) > 1, \
        best.describe()
    assert best.hbm_bytes < best.hbm_limit
    # and it should be the fastest feasible candidate
    for c in cands:
        if c.fits:
            assert best.step_time_s <= c.step_time_s + 1e-12


def test_planner_prefers_pure_dp_when_everything_fits():
    """Small model: dp has the least comm (no param all-gather, no
    activation all-reduce), so the planner must not over-shard."""
    pt.seed(0)
    net = GPTForCausalLM(GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        max_position_embeddings=32, use_flash=False))
    best = planner.plan(net, 8, global_batch=32, seq_len=32)
    assert best.fits
    assert best.axes["dp"] == 8, best.describe()


def test_evaluate_breakdown_sums_to_total(gpt_1p3b):
    p = planner.evaluate(gpt_1p3b, {"fsdp": 4, "tp": 2},
                         global_batch=64, seq_len=2048)
    parts = (p.breakdown["params"] + p.breakdown["grads"] +
             p.breakdown["opt_state"] + p.breakdown["activations"])
    np.testing.assert_allclose(p.hbm_bytes, parts, rtol=1e-9)
    # fsdp shards the bulk of the params
    assert p.breakdown["params"] < 2.0 * _GiB


def test_batch_divisibility_filters_layouts(gpt_1p3b):
    # global_batch=4 rules out dp*fsdp=8 factorizations
    best = planner.plan(gpt_1p3b, 8, global_batch=4, seq_len=2048)
    assert best.axes.get("dp", 1) * best.axes.get("fsdp", 1) <= 4


def test_seq_len_inferred_from_model_hints(gpt_1p3b):
    """seq_len=None must read max_position_embeddings (2048 for 1.3B) —
    a silent default of 1 would understate activations 2048x."""
    inferred = planner.plan(gpt_1p3b, 8, global_batch=64)
    explicit = planner.plan(gpt_1p3b, 8, global_batch=64, seq_len=2048)
    assert inferred.axes == explicit.axes
    np.testing.assert_allclose(inferred.hbm_bytes, explicit.hbm_bytes)


def test_strategy_and_global_batch_conflict():
    pt.seed(0)
    net = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
        max_position_embeddings=32, use_flash=False))
    model = pt.Model(net)
    with pytest.raises(ValueError, match="not both"):
        parallel.distributed_model(
            model, strategy=parallel.DistributedStrategy(),
            global_batch=16)


def test_distributed_model_auto_plans_mesh():
    """distributed_model(global_batch=...) runs the planner and attaches
    the chosen mesh + plan (Engine auto-mode analog)."""
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_heads=2, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLM(cfg)
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.AdamW(learning_rate=1e-3, parameters=net),
        loss=GPTPretrainingCriterion())
    try:
        parallel.distributed_model(model, global_batch=16, seq_len=32)
        assert model._plan.fits
        assert model._mesh is not None
        ids = np.random.RandomState(0).randint(0, 64, (16, 32))
        logs = model.train_batch([ids], [ids])
        assert np.isfinite(logs["loss"])
    finally:
        parallel.set_mesh(None)


def test_verify_plan_corrects_bad_estimate():
    """VERDICT r2 item 7: close the planner loop. A model whose
    activations the fallback estimator badly understates gets planned
    dp-only; verify_plan measures the compiled step via XLA's memory
    analysis, detects the mis-estimate against a tight chip budget, and
    re-plans with the measured calibration — landing on a sharded layout
    that actually fits."""
    from paddle_tpu import nn
    from paddle_tpu.parallel import planner

    pt.seed(0)

    class WideMLP(nn.Layer):
        """Params tiny, activations huge: the non-transformer fallback
        (act ~ 2x params) underestimates by >2x."""

        def __init__(self):
            super().__init__()
            self.up = nn.Linear(8, 4096, axes=(None, "embed"))
            self.down = nn.Linear(4096, 8, axes=("embed", None))

        def forward(self, x):
            return self.down(pt.nn.functional.gelu(self.up(x)))

    def fresh_model():
        net = WideMLP()
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-3,
                                               parameters=net),
                  loss=nn.MSELoss())
        return m

    rng = np.random.RandomState(0)
    x = rng.randn(512, 8).astype(np.float32)
    y = rng.randn(512, 8).astype(np.float32)

    try:
        # pass 1: learn this model's true compiled footprint
        probe = fresh_model()
        parallel.distributed_model(probe, global_batch=512)
        measured = planner.measured_step_bytes(probe, (x,), (y,))
        predicted = probe._plan.hbm_bytes
        assert measured > 2.0 * predicted, (measured, predicted)
        parallel.set_mesh(None)

        # pass 2: a chip whose budget the dp-only layout exceeds
        chip = planner.ChipSpec(hbm_bytes=measured * 0.7)
        model = fresh_model()
        parallel.distributed_model(model, global_batch=512)
        old_axes = dict(model._plan.axes)
        with pytest.warns(UserWarning, match="mis-estimate"):
            report, new_plan = planner.verify_plan(
                model, (x,), (y,), tolerance=2.0, chip=chip)
        assert report["replanned"]
        assert new_plan.axes != old_axes, new_plan.axes
        # the corrected layout shards the model/data axes
        assert max(new_plan.axes.get("fsdp", 1),
                   new_plan.axes.get("tp", 1)) > 1
        # and the model still trains under the re-installed mesh
        logs = model.train_batch([x], [y])
        assert np.isfinite(float(logs["loss"]))
    finally:
        parallel.set_mesh(None)


def test_planner_agrees_with_compiled_feasibility_study():
    """Reconcile the analytic planner against the committed compiled
    1.3B study (FEASIBILITY_1P3B.json, VERDICT r3 ask #7): for every
    non-pp row the planner must (a) never OVER-estimate the compiled
    f32 proxy, (b) stay within the 4x calibration band verify_plan
    corrects from one compile, and (c) agree on the clear-cut
    infeasibility verdicts (dp=8) and feasibility (tp=8)."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FEASIBILITY_1P3B.json")
    if not os.path.exists(path):
        pytest.skip("feasibility study artifact not present")
    rows = [r for r in json.load(open(path))["rows"]
            if "error" not in r and r.get("planner_ratio")
            and not r.get("use_flash") and not r.get("amp")]
    # ^ band calibrated for the f32 dense-attention proxy rows; the
    #   flash/amp probe variants are deliberately non-representative
    #   (see the artifact's "note")
    assert len(rows) >= 5, "study artifact lost its planner rows"
    for r in rows:
        assert 1.0 <= r["planner_ratio"] <= 4.0, (r["axes"],
                                                  r["planner_ratio"])
    budget = 16 * (1 << 30) * 0.85
    by_axes = {tuple(sorted(r["axes"].items())): r for r in rows}
    dp8 = by_axes.get((("dp", 8),))
    if dp8 is not None:  # planner and compiler agree: hopeless
        assert not dp8["fits_v5e"]
        assert dp8["planner_predicted_bytes"] > budget
    tp8 = by_axes.get((("tp", 8),))
    if tp8 is not None:  # and: comfortable
        assert tp8["fits_v5e"]
        assert tp8["planner_predicted_bytes"] <= budget
