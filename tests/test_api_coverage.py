"""Public-API parity gate (VERDICT r3 ask #4): the reference's
``paddle.*`` python surface — top-level __all__, 28 submodule __all__
lists, and the Tensor-method table — must stay fully adjudicated
(direct / alias / declined-with-record). A new reference export or a
regression dropping one of ours fails here.

Ref: python/paddle/__init__.py (269 names),
python/paddle/tensor/__init__.py:281 tensor_method_func,
python/paddle/static/nn/__init__.py, operators/sequence_ops/, ...
(enumerated by tools/api_coverage.py)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

import api_coverage  # noqa: E402

pytestmark = pytest.mark.slow  # imports the whole package tree


@pytest.fixture(scope="module")
def report():
    if not os.path.isdir(api_coverage.REF):
        pytest.skip("reference checkout not present")
    return api_coverage.collect()


def test_no_missing_names(report):
    assert report["missing_keys"] == [], report["missing_keys"]


def test_fully_adjudicated(report):
    t = report["totals"]
    assert t["covered_pct"] >= 99.5, t
    assert t["total"] > 1100, t  # the enumeration itself still works


def test_declines_carry_reasons():
    for key, reason in api_coverage.DECLINED.items():
        assert len(reason) > 30, f"{key}: decision record too thin"


def test_surface_counts_sane(report):
    # spot-pin the big surfaces so a silent enumeration regression
    # (e.g. an __all__ regex miss) cannot fake a green gate
    s = report["surfaces"]
    assert s["paddle"]["direct"] >= 260
    assert s["paddle.Tensor"]["direct"] >= 210
    assert s["paddle.nn"]["direct"] >= 120
    assert s["paddle.nn.functional"]["direct"] >= 100
    assert s["paddle.static.nn"]["direct"] >= 41
