"""Cross-process trace propagation (ISSUE 7 tentpole 1).

Pins: the W3C traceparent wire format round-trips; malformed/absent
headers degrade to a root span and NEVER reject a request; tracing
disabled on either side produces no orphan parents; and — the
acceptance criterion — a request through the Router to a replica over
REAL HTTP yields ONE trace: ``router.request`` → ``router.dispatch``
→ the replica's ``llm.request`` tree share a trace_id, with failover
re-dispatches recorded as span links.
"""

import json
import threading
import urllib.request

import pytest

from paddle_tpu.observability import propagation, tracing
from paddle_tpu.observability.propagation import (format_traceparent,
                                                  parse_traceparent)
from paddle_tpu.observability.tracing import SpanContext


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.clear()
    tracing.enable()
    yield
    tracing.disable()
    tracing.clear()


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_native_ids_are_w3c_sized_and_round_trip():
    root = tracing.start_span("req", parent=None)
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    header = format_traceparent(root.context)
    assert header == f"00-{root.trace_id}-{root.span_id}-01"
    ctx = parse_traceparent(header)
    assert ctx.trace_id == root.trace_id
    assert ctx.span_id == root.span_id
    root.end()


def test_foreign_short_ids_pad_on_inject():
    header = format_traceparent(SpanContext("abc123", "9f"))
    assert header == f"00-{'abc123'.zfill(32)}-{'9f'.zfill(16)}-01"


@pytest.mark.parametrize("bad", [
    None, 42, "", "junk", "00", "00-xyz-abc-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",       # all-zero trace
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",       # all-zero span
    "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",       # forbidden version
    "00-" + "1" * 31 + "-" + "2" * 16 + "-01",       # short trace
    "00-" + "1" * 32 + "-" + "2" * 15 + "-01",       # short span
    "00-" + "g" * 32 + "-" + "2" * 16 + "-01",       # non-hex
])
def test_malformed_traceparent_parses_to_none(bad):
    assert parse_traceparent(bad) is None


def test_future_version_with_extra_fields_accepted():
    v = "cc-" + "a" * 32 + "-" + "b" * 16 + "-01-what-ever"
    ctx = parse_traceparent(v)
    assert ctx is not None and ctx.trace_id == "a" * 32


def test_disabled_tracing_injects_nothing():
    tracing.disable()
    sp = tracing.start_span("ghost")
    assert format_traceparent(sp.context) is None
    carrier = propagation.inject({}, context=sp)
    assert carrier == {}


def test_extract_is_header_case_insensitive():
    root = tracing.start_span("req", parent=None)
    hdr = format_traceparent(root)
    for key in ("traceparent", "Traceparent", "TRACEPARENT"):
        ctx = propagation.extract({key: hdr})
        assert ctx is not None and ctx.trace_id == root.trace_id
    root.end()


def test_context_from_coercions():
    root = tracing.start_span("req", parent=None)
    hdr = format_traceparent(root)
    for obj in (root, root.context, hdr, {"traceparent": hdr}):
        ctx = propagation.context_from(obj)
        assert ctx.trace_id == root.trace_id, obj
    assert propagation.context_from(None) is None
    assert propagation.context_from("garbage") is None
    assert propagation.context_from(tracing.NOOP_SPAN) is None
    root.end()


def test_remote_parent_links_child_into_remote_trace():
    remote = SpanContext("a" * 32, "b" * 16)
    child = tracing.start_span("phase", parent=remote)
    assert child.trace_id == "a" * 32
    assert child.parent_id == "b" * 16
    child.end()


def test_span_links_survive_to_dict():
    a = tracing.start_span("attempt0", parent=None)
    b = tracing.start_span("attempt1", parent=None)
    b.add_link(a.context, {"relation": "retry_of"})
    b.add_link(tracing.NOOP_SPAN, {"relation": "nope"})   # no-op
    a.end()
    b.end()
    d = [s for s in tracing.finished_spans()
         if s["name"] == "attempt1"][0]
    assert d["links"] == [{"trace_id": a.trace_id,
                           "span_id": a.span_id,
                           "attrs": {"relation": "retry_of"}}]


# ---------------------------------------------------------------------------
# serve_llm header handling (fake engine: no compiles)
# ---------------------------------------------------------------------------

class _FakeEngine:
    """Records submit kwargs; resolves immediately."""

    def __init__(self):
        self.calls = []
        self.cancels = []

    def submit(self, prompt_ids, **kw):
        from concurrent.futures import Future
        self.calls.append(dict(kw, prompt_ids=list(prompt_ids)))
        f = Future()
        f.request_id = 7
        f.set_result({"output_ids": [1, 2], "prompt_ids": prompt_ids})
        return f

    def cancel(self, request_id):
        self.cancels.append(request_id)
        return True


@pytest.fixture()
def fake_http():
    from paddle_tpu.inference.llm import serve_llm
    eng = _FakeEngine()
    srv = serve_llm(eng)
    host, port = srv.server_address[:2]
    yield eng, f"http://{host}:{port}"
    srv.shutdown()


def _post(url, body, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_serve_llm_forwards_traceparent(fake_http):
    eng, base = fake_http
    root = tracing.start_span("client", parent=None)
    hdr = format_traceparent(root)
    code, _out = _post(base + "/generate", {"prompt_ids": [1, 2]},
                       {"traceparent": hdr})
    assert code == 200
    assert eng.calls[-1]["trace_context"] == hdr
    root.end()


def test_serve_llm_absent_header_passes_no_context(fake_http):
    eng, base = fake_http
    code, _out = _post(base + "/generate", {"prompt_ids": [1, 2]})
    assert code == 200
    assert "trace_context" not in eng.calls[-1]


def test_serve_llm_cancel_span_joins_remote_trace(fake_http):
    eng, base = fake_http
    remote = SpanContext("c" * 32, "d" * 16)
    code, out = _post(base + "/cancel", {"request_id": 7},
                      {"traceparent": format_traceparent(remote)})
    assert code == 200 and out["cancelled"] is True
    assert eng.cancels == [7]
    cancels = [s for s in tracing.finished_spans()
               if s["name"] == "llm.cancel"]
    assert cancels and cancels[-1]["trace_id"] == "c" * 32
    assert cancels[-1]["parent_id"] == "d" * 16
    assert cancels[-1]["attrs"]["cancelled"] is True


# ---------------------------------------------------------------------------
# the real thing: engine behind serve_llm, router in front, real HTTP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def llm_http_fleet():
    """One tiny real engine behind serve_llm; an HTTPReplica-backed
    Router in front. The traceparent genuinely crosses an HTTP
    boundary (same process, so both tables are inspectable)."""
    from paddle_tpu.inference.llm import serve_llm
    from paddle_tpu.serving import HTTPReplica, Router
    from paddle_tpu.serving.replica import make_engine_from_spec
    eng = make_engine_from_spec({"vocab": 97, "layers": 2,
                                 "hidden": 64})
    srv = serve_llm(eng)
    host, port = srv.server_address[:2]
    replica = HTTPReplica(f"http://{host}:{port}",
                          "http://127.0.0.1:1/healthz")
    router = Router({"r0": replica}, health_poll_interval=5.0,
                    page_size=4)
    yield eng, router, f"http://{host}:{port}"
    router.close()
    eng.close()
    srv.shutdown()


def test_one_trace_across_router_http_replica(llm_http_fleet):
    """THE acceptance pin: one trace_id end to end over real HTTP."""
    eng, router, _base = llm_http_fleet
    out = router.submit([5, 6, 7, 8, 9], max_new_tokens=3) \
        .result(timeout=120)
    tid = out["trace_id"]
    assert tid and len(tid) == 32
    spans = [s for s in tracing.finished_spans()
             if s["trace_id"] == tid]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    for want in ("router.request", "router.dispatch", "llm.request",
                 "llm.queue", "llm.decode"):
        assert want in by_name, (want, sorted(by_name))
    root = by_name["router.request"][0]
    dispatch = by_name["router.dispatch"][0]
    llm_req = by_name["llm.request"][0]
    assert root["parent_id"] is None
    assert dispatch["parent_id"] == root["span_id"]
    # the HTTP hop preserved the parent link exactly
    assert llm_req["parent_id"] == dispatch["span_id"]
    assert llm_req["attrs"].get("remote_parent") is True
    # the replica-side phases stay inside the same trace
    for s in by_name["llm.queue"] + by_name["llm.decode"]:
        assert s["trace_id"] == tid


def test_malformed_traceparent_never_rejects_over_real_http(
        llm_http_fleet):
    _eng, _router, base = llm_http_fleet
    code, out = _post(base + "/generate",
                      {"prompt_ids": [1, 2, 3], "max_new_tokens": 2},
                      {"traceparent": "00-born-bad-ff"})
    assert code == 200 and out["output_ids"]
    roots = [s for s in tracing.finished_spans()
             if s["name"] == "llm.request"
             and s["attrs"].get("prompt_tokens") == 3]
    assert roots and roots[-1]["parent_id"] is None


def test_absent_traceparent_roots_locally_over_real_http(
        llm_http_fleet):
    _eng, _router, base = llm_http_fleet
    code, out = _post(base + "/generate",
                      {"prompt_ids": [9, 9, 9, 9], "max_new_tokens": 2})
    assert code == 200 and out["output_ids"]
    roots = [s for s in tracing.finished_spans()
             if s["name"] == "llm.request"
             and s["attrs"].get("prompt_tokens") == 4]
    assert roots and roots[-1]["parent_id"] is None
    assert "remote_parent" not in roots[-1]["attrs"]


def test_tracing_disabled_side_produces_no_orphans(llm_http_fleet):
    """Receiver disabled: a context arrives, nothing records, nothing
    breaks; re-enabled, a disabled SENDER (no header) roots locally —
    no span anywhere claims a parent that does not exist."""
    eng, _router, base = llm_http_fleet
    tracing.disable()
    tracing.clear()
    remote = SpanContext("e" * 32, "f" * 16)
    hdr = format_traceparent(remote)
    code, out = _post(base + "/generate",
                      {"prompt_ids": [1, 2], "max_new_tokens": 2},
                      {"traceparent": hdr})
    assert code == 200 and out["output_ids"]
    assert tracing.finished_spans() == []
    assert tracing.live_spans() == []
    # direct engine submit with a context while disabled: same story
    eng.submit([3, 4], max_new_tokens=2,
               trace_context=remote).result(timeout=120)
    assert tracing.finished_spans() == []
    tracing.enable()
    code, out = _post(base + "/generate",
                      {"prompt_ids": [1, 2, 3, 4, 5, 6],
                       "max_new_tokens": 2})
    assert code == 200
    spans = tracing.finished_spans()
    ids = {s["span_id"] for s in spans}
    for s in spans:
        assert s["parent_id"] is None or s["parent_id"] in ids, s


def test_failover_redispatch_records_span_link():
    """A failover re-dispatch links back to the attempt it replaces."""
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.replica import ReplicaUnavailable

    class Flaky:
        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.lock = threading.Lock()

        def submit(self, prompt_ids, **kw):
            with self.lock:
                if self.fail_n > 0:
                    self.fail_n -= 1
                    raise ReplicaUnavailable("boom")
            return {"output_ids": [1], "prompt_ids": list(prompt_ids)}

        def health(self):
            return "healthy"

        def cancel(self, request_id):
            return False

        def close(self):
            pass

    from paddle_tpu.serving.router import affinity_key, rendezvous_pick
    prompt, n = None, 0
    while prompt is None:     # a prompt whose affinity prefers "a"
        cand = [n, n + 1, n + 2]
        if rendezvous_pick(affinity_key(cand, 16, 2),
                           ("a", "b")) == "a":
            prompt = cand
        n += 1
    with Router({"a": Flaky(fail_n=1), "b": Flaky(fail_n=0)},
                failover_budget=2, health_poll_interval=5.0,
                scrape_metrics=False) as r:
        out = r.submit(prompt, max_new_tokens=1).result(timeout=60)
    assert out["failovers"] == 1
    tid = out["trace_id"]
    dispatches = sorted(
        (s for s in tracing.finished_spans()
         if s["trace_id"] == tid and s["name"] == "router.dispatch"),
        key=lambda s: s["ts"])
    assert len(dispatches) == 2
    first, second = dispatches
    assert first["status"] == "error"
    assert "links" not in first
    assert second["links"] == [{
        "trace_id": tid, "span_id": first["span_id"],
        "attrs": {"relation": "retry_of",
                  "replica": first["attrs"]["replica"]}}]
