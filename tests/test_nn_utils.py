"""nn.utils reparameterizations + incubate.nn fused functionals
(ref: test_weight_norm.py, test_spectral_norm.py,
test_fused_attention_op.py families)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import utils as U
from paddle_tpu.nn.layer import functional_call, split_state


def test_weight_norm_preserves_function_and_reparams():
    pt.seed(0)
    lin = nn.Linear(6, 4)
    w0 = np.asarray(lin.weight).copy()
    x = jnp.asarray(np.random.RandomState(0).randn(3, 6), jnp.float32)
    y0 = np.asarray(lin(x))
    U.weight_norm(lin, "weight", dim=0)
    names = dict(lin.named_parameters())
    assert "weight_v" in names and "weight_g" in names
    assert "weight" not in names
    np.testing.assert_allclose(np.asarray(lin(x)), y0, rtol=1e-5,
                               atol=1e-6)
    # g scales the output norm directionally
    lin.weight_g = names["weight_g"] * 2.0
    np.testing.assert_allclose(np.asarray(lin(x)), 2 * y0, rtol=1e-5,
                               atol=1e-5)
    U.remove_weight_norm(lin)
    names = dict(lin.named_parameters())
    assert "weight" in names and "weight_v" not in names
    np.testing.assert_allclose(np.asarray(names["weight"]), 2 * w0,
                               rtol=1e-5, atol=1e-6)


def test_weight_norm_trains_under_jit():
    pt.seed(0)
    lin = nn.Linear(4, 2)
    U.weight_norm(lin)
    params, buffers = split_state(lin)
    x = jnp.ones((8, 4))
    y = jnp.zeros((8, 2))

    @jax.jit
    def step(p):
        def loss(p):
            out, _ = functional_call(lin, p, buffers, x)
            return ((out - y) ** 2).mean()
        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * b, p, g)

    l0, params = step(params)
    for _ in range(10):
        l, params = step(params)
    assert float(l) < float(l0)
    assert set(params) == {"weight_v", "weight_g", "bias"}


def test_spectral_norm_bounds_singular_value():
    pt.seed(0)
    lin = nn.Linear(8, 8)
    lin.weight = jnp.asarray(
        np.random.RandomState(0).randn(8, 8) * 3.0, jnp.float32)
    U.spectral_norm(lin, "weight", n_power_iterations=3)
    x = jnp.eye(8)
    for _ in range(5):  # warm up the power iteration buffer
        lin(x)
    w_eff = np.asarray(lin.weight)  # derived attr after last forward
    s = np.linalg.svd(w_eff, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=2e-2)


def test_parameters_vector_roundtrip():
    pt.seed(0)
    net = nn.Linear(3, 5)
    params = [net.weight, net.bias]
    vec = U.parameters_to_vector(params)
    assert vec.shape == (3 * 5 + 5,)
    back = U.vector_to_parameters(vec, params)
    for a, b in zip(back, params):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_fused_feedforward_matches_unfused():
    from paddle_tpu.incubate.nn import functional as IF
    from paddle_tpu.nn import functional as F
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(2, 4, 8), jnp.float32)
    w1 = jnp.asarray(r.randn(8, 16), jnp.float32)
    w2 = jnp.asarray(r.randn(16, 8), jnp.float32)
    out = IF.fused_feedforward(x, w1, w2, dropout1_rate=0.0,
                               dropout2_rate=0.0, training=False)
    ref = F.layer_norm(x + F.relu(x @ w1) @ w2, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_mha_runs_both_layouts():
    from paddle_tpu.incubate.nn import functional as IF
    r = np.random.RandomState(2)
    b, s, d, h = 2, 6, 8, 2
    x = jnp.asarray(r.randn(b, s, d), jnp.float32)
    wo = jnp.asarray(r.randn(d, d) * 0.1, jnp.float32)
    # 2D layout
    qkv2 = jnp.asarray(r.randn(d, 3 * d) * 0.1, jnp.float32)
    out2 = IF.fused_multi_head_attention(
        x, qkv2, wo, num_heads=h, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    assert out2.shape == (b, s, d)
    # reference 4D layout [3, heads, head_dim, D]
    qkv4 = jnp.asarray(r.randn(3, h, d // h, d) * 0.1, jnp.float32)
    out4 = IF.fused_multi_head_attention(
        x, qkv4, wo, dropout_rate=0.0, attn_dropout_rate=0.0,
        training=False)
    assert out4.shape == (b, s, d)
    assert np.all(np.isfinite(np.asarray(out4)))


def test_fused_linear():
    from paddle_tpu.incubate.nn import functional as IF
    x = jnp.ones((2, 4))
    w = jnp.ones((4, 3))
    np.testing.assert_allclose(np.asarray(IF.fused_linear(x, w)),
                               4 * np.ones((2, 3)))
    np.testing.assert_allclose(
        np.asarray(IF.fused_linear(x, w.T, transpose_weight=True)),
        4 * np.ones((2, 3)))


def test_weight_norm_negative_dim_and_frozen():
    from paddle_tpu.nn.layer import Parameter
    pt.seed(0)
    lin = nn.Linear(6, 4)
    U.weight_norm(lin, "weight", dim=-1)
    g = dict(lin.named_parameters())["weight_g"]
    assert g.shape == (1, 4)  # per-output-column magnitude

    lin2 = nn.Linear(4, 3)
    lin2._param_meta["weight"].trainable = False
    U.weight_norm(lin2)
    meta = lin2.param_meta()
    assert not meta["weight_v"].trainable
    assert not meta["weight_g"].trainable


def test_weight_norm_no_tracer_leak_after_jit():
    pt.seed(0)
    lin = nn.Linear(4, 2)
    U.weight_norm(lin)
    params, buffers = split_state(lin)
    x = jnp.ones((2, 4))

    @jax.jit
    def fwd(p):
        out, _ = functional_call(lin, p, buffers, x)
        return out

    fwd(params)
    # derived attr resolves from live (concrete) params — no stale
    # tracer from the trace above
    w = np.asarray(lin.weight)
    assert np.all(np.isfinite(w))


def test_spectral_norm_validates_iterations():
    lin = nn.Linear(4, 4)
    with pytest.raises(ValueError, match=">= 1"):
        U.spectral_norm(lin, n_power_iterations=0)


def test_fused_mha_4d_bias():
    from paddle_tpu.incubate.nn import functional as IF
    r = np.random.RandomState(3)
    b, s, d, h = 2, 4, 8, 2
    x = jnp.asarray(r.randn(b, s, d), jnp.float32)
    wo = jnp.asarray(r.randn(d, d) * 0.1, jnp.float32)
    qkv4 = jnp.asarray(r.randn(3, h, d // h, d) * 0.1, jnp.float32)
    bias4 = jnp.asarray(r.randn(3, h, d // h) * 0.1, jnp.float32)
    out = IF.fused_multi_head_attention(
        x, qkv4, wo, qkv_bias=bias4, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    assert out.shape == (b, s, d)
    assert np.all(np.isfinite(np.asarray(out)))
