"""Continuous-batching LLM decode engine (VERDICT r4 item 4: the
serving-era analog of the reference's AnalysisPredictor,
reference: paddle/fluid/inference/api/analysis_predictor.h:95).

Strategy: exact greedy parity against GPTForCausalLM.generate (the
paged path recomputes the same math over a different memory layout),
then serving behaviors the dense predictor can't express: token-level
admission, page-pool exhaustion, concurrent HTTP clients."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import LLMEngine, serve_llm
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config, llama_config


def tiny_gpt(**kw):
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0, **kw)
    return GPTForCausalLM(cfg)


def tiny_llama():
    pt.seed(0)
    cfg = llama_config(hidden_size=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, vocab_size=97,
                       max_position_embeddings=96, ffn_hidden_size=128)
    return GPTForCausalLM(cfg)


@pytest.mark.parametrize("lookahead", [0, 3], ids=["sync", "lookahead3"])
@pytest.mark.parametrize("build", [tiny_gpt, tiny_llama],
                         ids=["gpt2", "llama-gqa"])
def test_engine_greedy_matches_dense_generate(build, lookahead):
    net = build()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 11, 3)]
    want = [np.asarray(net.generate(jnp.asarray([p]), max_new_tokens=8)
                       )[0, len(p):].tolist() for p in prompts]
    with LLMEngine(net, max_seqs=4, page_size=4, num_pages=128,
                   prefill_buckets=(16,), lookahead=lookahead) as eng:
        outs = eng.generate(prompts, max_new_tokens=8)
    for got, ref, p in zip(outs, want, prompts):
        assert got["output_ids"] == ref, (p, got["output_ids"], ref)
        assert not got["truncated"]
        assert got["ttft_s"] is not None and got["latency_s"] > 0


def test_engine_continuous_admission_and_page_reuse():
    """Requests joining mid-flight don't perturb running sequences,
    and every page returns to the pool."""
    net = tiny_gpt()
    rng = np.random.RandomState(1)
    p0 = rng.randint(0, 97, 6).tolist()
    p1 = rng.randint(0, 97, 4).tolist()
    ref0 = np.asarray(net.generate(jnp.asarray([p0]),
                                   max_new_tokens=12))[0, len(p0):]
    ref1 = np.asarray(net.generate(jnp.asarray([p1]),
                                   max_new_tokens=6))[0, len(p1):]
    eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(8,))
    free0 = len(eng._free_pages)
    f0 = eng.submit(p0, max_new_tokens=12)
    # second request lands while the first decodes (token-level join)
    f1 = eng.submit(p1, max_new_tokens=6)
    assert f0.result(timeout=300)["output_ids"] == ref0.tolist()
    assert f1.result(timeout=300)["output_ids"] == ref1.tolist()
    eng.close()
    assert len(eng._free_pages) == free0  # no page leaked
    assert eng.n_steps > 0 and eng.n_tokens >= 18


def test_engine_more_requests_than_slots():
    """8 requests through 2 slots: admission queues and drains."""
    net = tiny_gpt()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 97, 1 + (i % 5)).tolist()
               for i in range(8)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,)) as eng:
        outs = eng.generate(prompts, max_new_tokens=4)
    assert all(len(o["output_ids"]) == 4 for o in outs)


def test_engine_pool_exhaustion_truncates_gracefully():
    """A pool too small for the request's full length finishes the
    request early with truncated=True instead of crashing the engine
    (the reference predictor's analog failure is a hard OOM)."""
    net = tiny_gpt()
    # 3 usable pages of 4 tokens = 12 cached tokens max
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=4,
                   prefill_buckets=(8,)) as eng:
        out = eng.generate([[1, 2, 3, 4, 5]], max_new_tokens=40)[0]
    assert out["truncated"]
    assert 0 < len(out["output_ids"]) < 40
    # pool drained and engine still serviceable was exercised by close()


def test_engine_sampling_temperature_and_eos():
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), eos_token_id=7) as eng:
        out = eng.generate([[3, 1, 4]], max_new_tokens=64,
                           temperature=1.0)[0]
        assert len(out["output_ids"]) >= 1
        # eos stops early when sampled; otherwise runs to length
        if 7 in out["output_ids"]:
            assert out["output_ids"][-1] == 7


def test_http_serving_concurrent_clients():
    """N concurrent clients against one engine through the HTTP front
    (VERDICT done-criterion: N clients decoding from one predictor)."""
    import json
    from urllib.request import Request, urlopen

    net = tiny_gpt()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 97, 2 + i).tolist() for i in range(6)]
    refs = [np.asarray(net.generate(jnp.asarray([p]), max_new_tokens=5)
                       )[0, len(p):].tolist() for p in prompts]
    with LLMEngine(net, max_seqs=4, page_size=4, num_pages=128,
                   prefill_buckets=(16,)) as eng:
        srv = serve_llm(eng)
        host, port = srv.server_address
        results = {}

        def client(i):
            body = json.dumps({"prompt_ids": prompts[i],
                               "max_new_tokens": 5}).encode()
            req = Request(f"http://{host}:{port}/generate", data=body,
                          headers={"Content-Type": "application/json"})
            with urlopen(req, timeout=300) as r:
                results[i] = json.loads(r.read())

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        srv.shutdown()
    assert len(results) == len(prompts)
    for i, ref in enumerate(refs):
        assert results[i]["output_ids"] == ref


def test_engine_rejects_impossible_requests_cleanly():
    """Failure paths resolve, never hang: a prompt that can NEVER fit
    the page pool fails its future (the chunked path accepts ANY
    prompt length up to max_len — prefill buckets only bound the
    speculative inline path); a device-side error mid-serving fails
    in-flight requests but leaves the engine serving."""
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=4,
                   prefill_buckets=(16,)) as eng:
        # 20 tokens clear the (spec-only) bucket bound on the chunked
        # path, but need 5 pages where only 3 exist -> future fails
        fut = eng.submit(list(range(20)), max_new_tokens=2)
        with pytest.raises(ValueError, match="cannot fit"):
            fut.result(timeout=60)
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit([], max_new_tokens=2)
        # 13 tokens need 4 pages; only 3 usable exist -> future fails
        fut = eng.submit([1] * 13, max_new_tokens=2)
        with pytest.raises(ValueError, match="cannot fit"):
            fut.result(timeout=60)

    net2 = tiny_gpt()
    eng = LLMEngine(net2, max_seqs=2, page_size=4, num_pages=64,
                    prefill_buckets=(8,))
    real_decode = eng._decode_fn
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient PJRT failure")
        return real_decode(*a, **kw)

    eng._decode_fn = flaky
    bad = eng.submit([1, 2, 3], max_new_tokens=4)
    with pytest.raises(RuntimeError, match="transient"):
        bad.result(timeout=60)
    # engine survived: the next request completes
    ok = eng.submit([4, 5], max_new_tokens=3).result(timeout=60)
    assert len(ok["output_ids"]) == 3
    eng.close()



def test_engine_lookahead_chains_and_discards_overrun():
    """lookahead > 0: token streams are IDENTICAL to sync mode (the
    chain computes the same values on device), finished requests never
    exceed max_new_tokens despite overrun steps, pages all return, and
    the host fetch count drops to ~1 per lookahead+1 steps."""
    net = tiny_gpt()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 97, n).tolist() for n in (4, 7, 3, 9)]

    def run(k):
        pt.seed(0)
        eng = LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                        prefill_buckets=(16,), lookahead=k)
        free0 = len(eng._free_pages)
        outs = eng.generate(prompts, max_new_tokens=11)
        eng.close()
        assert len(eng._free_pages) == free0
        return outs

    sync = run(0)
    la = run(4)
    for a, b in zip(sync, la):
        assert a["output_ids"] == b["output_ids"]
        assert len(b["output_ids"]) == 11


def test_engine_lookahead_eos_and_truncation():
    net = tiny_gpt()
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), eos_token_id=7,
                   lookahead=3) as eng:
        out = eng.generate([[3, 1, 4]], max_new_tokens=40,
                           temperature=1.0)[0]
        if 7 in out["output_ids"]:
            assert out["output_ids"][-1] == 7    # nothing after EOS
    # pool exhaustion under lookahead still truncates gracefully
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=4,
                   prefill_buckets=(8,), lookahead=3) as eng:
        out = eng.generate([[1, 2, 3, 4, 5]], max_new_tokens=40)[0]
    assert out["truncated"]
    assert 0 < len(out["output_ids"]) < 40
