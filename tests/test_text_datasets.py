"""Text datasets against synthetic standard-format files
(ref: unittests test_datasets.py imdb/imikolov/movielens cases)."""

import os

import numpy as np
import pytest

from paddle_tpu import text


def test_imikolov_ngram(tmp_path):
    (tmp_path / "ptb.train.txt").write_text(
        "the cat sat on the mat\nthe dog sat on the rug\n" * 30)
    (tmp_path / "ptb.valid.txt").write_text("the cat sat on the mat\n")
    ds = text.Imikolov(str(tmp_path), window_size=3, mode="train",
                       min_word_freq=10)
    assert len(ds) > 0
    gram = ds[0]
    assert gram.shape == (3,) and gram.dtype == np.int64
    assert "the" in ds.word_idx and "<unk>" in ds.word_idx
    # valid split shares the train vocab
    dv = text.Imikolov(str(tmp_path), window_size=3, mode="valid",
                       min_word_freq=10)
    assert dv.word_idx == ds.word_idx


def test_imdb_reader(tmp_path):
    for split in ("train", "test"):
        for label in ("pos", "neg"):
            d = tmp_path / "aclImdb" / split / label
            os.makedirs(d)
            for i in range(3):
                (d / f"{i}.txt").write_text(
                    ("great movie loved it " if label == "pos" else
                     "terrible movie hated it ") * 5)
    ds = text.Imdb(str(tmp_path), mode="train", cutoff=1)
    assert len(ds) == 6
    ids, label = ds[0]
    assert ids.dtype == np.int64 and label in (0, 1)
    # pos docs come first with label 0 (reference convention)
    assert ds.labels[:3].tolist() == [0, 0, 0]
    assert "movie" in ds.word_idx


def test_movielens_reader(tmp_path):
    d = tmp_path / "ml-1m"
    d.mkdir()
    (d / "users.dat").write_text("1::F::1::10::48067\n2::M::56::16::70072\n")
    (d / "movies.dat").write_text("1::Toy Story (1995)::Animation\n"
                                  "2::Jumanji (1995)::Adventure\n")
    (d / "ratings.dat").write_text(
        "\n".join(f"{u}::{m}::{3 + (u + m) % 3}::97830{u}{m}"
                  for u in (1, 2) for m in (1, 2)) + "\n")
    tr = text.Movielens(str(tmp_path), mode="train", test_ratio=0.5,
                        rand_seed=0)
    te = text.Movielens(str(tmp_path), mode="test", test_ratio=0.5,
                        rand_seed=0)
    assert len(tr) + len(te) == 4
    u, m, s = tr[0]
    assert u.dtype == np.int64 and s.dtype == np.float32
    assert 1.0 <= float(s) <= 5.0


def test_ucihousing(tmp_path):
    rows = np.random.RandomState(0).rand(20, 14)
    np.savetxt(tmp_path / "housing.data", rows)
    tr = text.UCIHousing(str(tmp_path), mode="train")
    te = text.UCIHousing(str(tmp_path), mode="test")
    assert len(tr) == 16 and len(te) == 4
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_missing_files_raise(tmp_path):
    with pytest.raises(FileNotFoundError, match="zero-egress"):
        text.Imdb(str(tmp_path))
    with pytest.raises(FileNotFoundError, match="zero-egress"):
        text.Movielens(str(tmp_path))


def test_imikolov_sentinels_and_unk_in_range(tmp_path):
    # literal <unk> in the corpus must not push ids out of range
    (tmp_path / "ptb.train.txt").write_text(
        "the cat <unk> on the mat\n" * 40)
    (tmp_path / "ptb.valid.txt").write_text("the cat sat\n")
    ds = text.Imikolov(str(tmp_path), window_size=3, mode="train",
                       min_word_freq=10)
    V = len(ds.word_idx)
    for g in ds.data:
        assert (g < V).all() and (g >= 0).all()
    # sentinels are real vocab entries and appear in the grams
    s, e = ds.word_idx["<s>"], ds.word_idx["<e>"]
    flat = np.concatenate(ds.data)
    assert s in flat and e in flat


def test_user_role_maker_indices_consulted():
    from paddle_tpu.distributed import fleet
    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        current_id=1, worker_num=4), is_collective=True)
    try:
        assert fleet.worker_index() == 1
        assert fleet.worker_num() == 4
        assert not fleet.is_first_worker()
    finally:
        fleet.init(is_collective=True)  # restore default role maker
