"""Native C++ data-feed engine (paddle_tpu/native/datafeed.cc) —
completeness, multi-thread correctness, shuffle, partial batches
(ref: data_feed tests in the reference's framework unittests)."""

import os

import numpy as np
import pytest

from paddle_tpu.io.native_feed import FileDataFeed


def _write_files(tmp_path, n_files=3, rows_per_file=50, width=4):
    files = []
    counter = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i}.csv"
        with open(p, "w") as f:
            for _ in range(rows_per_file):
                feats = [counter + 0.25 * k for k in range(width)]
                label = counter % 7
                f.write(",".join(str(x) for x in feats) +
                        f",{label}\n")
                counter += 1
        files.append(str(p))
    return files, counter


def test_reads_all_rows_single_thread(tmp_path):
    files, total = _write_files(tmp_path)
    feed = FileDataFeed(files, "f32:4,i64:1", batch_size=16,
                        num_threads=1)
    rows = 0
    seen = set()
    for x, y in feed:
        assert x.dtype == np.float32 and y.dtype == np.int64
        assert x.shape[1] == 4 and x.shape[0] == y.shape[0]
        rows += x.shape[0]
        seen.update(int(v) for v in x[:, 0])
    assert rows == total
    assert seen == set(range(total))


def test_reads_all_rows_multi_thread(tmp_path):
    files, total = _write_files(tmp_path, n_files=6, rows_per_file=37)
    feed = FileDataFeed(files, "f32:4,i64:1", batch_size=32,
                        num_threads=4)
    seen = []
    for x, y in feed:
        seen.extend(int(v) for v in x[:, 0])
        # row integrity: col k == col0 + 0.25*k, label == col0 % 7
        np.testing.assert_allclose(x[:, 1], x[:, 0] + 0.25, atol=1e-5)
        np.testing.assert_array_equal(y, (x[:, 0].astype(np.int64)) % 7)
    assert sorted(seen) == list(range(total))


def test_shuffle_window_changes_order_keeps_set(tmp_path):
    files, total = _write_files(tmp_path, n_files=1, rows_per_file=200)
    plain = [int(v) for x, _ in
             FileDataFeed(files, "f32:4,i64:1", batch_size=50,
                          num_threads=1) for v in x[:, 0]]
    shuf = [int(v) for x, _ in
            FileDataFeed(files, "f32:4,i64:1", batch_size=50,
                         num_threads=1, shuffle_window=64,
                         seed=3) for v in x[:, 0]]
    assert sorted(shuf) == sorted(plain) == list(range(total))
    assert shuf != plain  # windowed shuffle really permutes


def test_partial_final_batch(tmp_path):
    files, total = _write_files(tmp_path, n_files=1, rows_per_file=10)
    feed = FileDataFeed(files, "f32:4,i64:1", batch_size=8,
                        num_threads=1)
    sizes = [x.shape[0] for x, _ in feed]
    assert sum(sizes) == 10 and sizes[-1] == 2


def test_missing_file_skipped(tmp_path):
    files, total = _write_files(tmp_path, n_files=1, rows_per_file=5)
    feed = FileDataFeed(files + [str(tmp_path / "nope.csv")],
                        "f32:4,i64:1", batch_size=4, num_threads=2)
    rows = sum(x.shape[0] for x, _ in feed)
    assert rows == 5


def test_feeds_training(tmp_path):
    """End-to-end: native feed → Model.train_batch."""
    import paddle_tpu as pt
    from paddle_tpu import nn

    files, _ = _write_files(tmp_path, n_files=2, rows_per_file=32)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 7))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3,
                                              parameters=net),
                  loss=nn.CrossEntropyLoss())
    n = 0
    for x, y in FileDataFeed(files, "f32:4,i64:1", batch_size=16):
        logs = model.train_batch([x], [y.reshape(-1, 1)])
        assert np.isfinite(logs["loss"])
        n += 1
    assert n >= 4
