"""Layer-system + functional-op tests (modelled on the reference's OpTest
NumPy-reference pattern, ref: python/paddle/fluid/tests/unittests/
op_test.py:309 check_output_with_place)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_matches_numpy():
    layer = nn.Linear(8, 4)
    x = np.random.randn(3, 8).astype(np.float32)
    y = layer(jnp.asarray(x))
    ref = x @ np.asarray(layer.weight) + np.asarray(layer.bias)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)


def test_linear_no_bias():
    layer = nn.Linear(8, 4, bias_attr=False)
    assert layer.bias is None
    assert len(layer.parameters()) == 1


def test_conv2d_matches_torch():
    torch = pytest.importorskip("torch")
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = np.random.randn(2, 3, 16, 16).astype(np.float32)
    y = conv(jnp.asarray(x))
    ty = torch.nn.functional.conv2d(
        torch.tensor(x), torch.tensor(np.asarray(conv.weight)),
        torch.tensor(np.asarray(conv.bias)), stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_conv2d_transpose_shape():
    deconv = nn.Conv2DTranspose(4, 6, 3, stride=2, padding=1,
                                output_padding=1)
    x = jnp.ones((2, 4, 8, 8))
    y = deconv(x)
    assert y.shape == (2, 6, 16, 16)


def test_pooling():
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    y = F.max_pool2d(jnp.asarray(x), 2)
    assert y.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(
        np.asarray(y)[0, 0, 0, 0], x[0, 0, :2, :2].max(), rtol=1e-6)
    ya = F.avg_pool2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(
        np.asarray(ya)[0, 0, 0, 0], x[0, 0, :2, :2].mean(), rtol=1e-5)


def test_adaptive_avg_pool():
    x = jnp.ones((2, 3, 7, 7))
    y = F.adaptive_avg_pool2d(x, 1)
    assert y.shape == (2, 3, 1, 1)
    np.testing.assert_allclose(np.asarray(y), 1.0, rtol=1e-6)


def test_batchnorm_train_and_eval():
    bn = nn.BatchNorm2D(4)
    x = np.random.randn(8, 4, 5, 5).astype(np.float32) * 3 + 1
    y = bn(jnp.asarray(x))
    # normalized output: per-channel mean ~0, var ~1
    m = np.asarray(y).mean(axis=(0, 2, 3))
    v = np.asarray(y).var(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0.0, atol=1e-4)
    np.testing.assert_allclose(v, 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(bn._mean), 0.0)
    bn.eval()
    y2 = bn(jnp.asarray(x))
    assert y2.shape == x.shape


def test_layernorm():
    ln = nn.LayerNorm(16)
    x = np.random.randn(4, 10, 16).astype(np.float32)
    y = np.asarray(ln(jnp.asarray(x)))
    np.testing.assert_allclose(y.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1.0, atol=1e-2)


def test_dropout_modes():
    x = jnp.ones((1000,))
    d = nn.Dropout(0.5)
    y = d(x)
    # upscale_in_train: surviving elements are 2.0
    vals = np.unique(np.asarray(y))
    assert set(np.round(vals, 5)).issubset({0.0, 2.0})
    d.eval()
    np.testing.assert_array_equal(np.asarray(d(x)), np.asarray(x))


def test_cross_entropy_matches_numpy():
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (6,))
    got = float(F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(6), labels]).mean()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([0, -100, 2, -100])
    got = float(F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels),
                                ignore_index=-100))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 2], [0, 2]]).mean()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_state_dict_roundtrip():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = net.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net2.set_state_dict(sd)
    x = jnp.ones((1, 4))
    np.testing.assert_allclose(np.asarray(net(x)), np.asarray(net2(x)),
                               rtol=1e-6)


def test_functional_call_pure():
    bn = nn.BatchNorm1D(3)
    params, buffers = nn.split_state(bn)
    x = jnp.asarray(np.random.randn(10, 3).astype(np.float32))
    out, new_buffers = nn.functional_call(bn, params, buffers, x,
                                          training=True)
    # original layer state untouched
    np.testing.assert_allclose(np.asarray(bn._mean), 0.0)
    # returned buffers updated
    assert not np.allclose(np.asarray(new_buffers["_mean"]), 0.0)


def test_functional_call_under_jit_and_grad():
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    params, buffers = nn.split_state(net)
    x = jnp.ones((2, 4))

    @jax.jit
    def loss_fn(p):
        out, _ = nn.functional_call(net, p, buffers, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss_fn)(dict(params))
    assert set(g) == set(params)
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())


def test_hooks():
    layer = nn.Linear(2, 2)
    calls = []
    h = layer.register_forward_post_hook(
        lambda l, inp, out: calls.append(out.shape))
    layer(jnp.ones((1, 2)))
    assert calls == [(1, 2)]
    h.remove()
    layer(jnp.ones((1, 2)))
    assert len(calls) == 1


def test_transformer_encoder_forward():
    enc = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                     dim_feedforward=64)
    x = jnp.asarray(np.random.randn(2, 10, 32).astype(np.float32))
    y = enc(x)
    assert y.shape == (2, 10, 32)


def test_multihead_attention_causal():
    mha = nn.MultiHeadAttention(16, 2)
    mha.eval()
    x = jnp.asarray(np.random.randn(1, 6, 16).astype(np.float32))
    y = mha(x, is_causal=True)
    assert y.shape == (1, 6, 16)
    # causality: output at position 0 must not depend on later tokens
    x2 = x.at[:, 3:].set(0.0)
    y2 = mha(x2, is_causal=True)
    np.testing.assert_allclose(np.asarray(y[:, :3]), np.asarray(y2[:, :3]),
                               rtol=1e-4, atol=1e-5)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = jnp.asarray([[0, 1, 2]])
    out = emb(ids)
    np.testing.assert_allclose(np.asarray(out[0, 0]), 0.0)


def test_seed_reproducible():
    pt.seed(7)
    a = nn.Linear(4, 4).weight
    pt.seed(7)
    b = nn.Linear(4, 4).weight
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_clip_global_norm():
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm, global_norm
    grads = {"a": jnp.ones((10,)) * 3, "b": jnp.ones((5,)) * 4}
    clip = ClipGradByGlobalNorm(1.0)
    clipped = clip(grads)
    np.testing.assert_allclose(float(global_norm(clipped)), 1.0, rtol=1e-5)
