"""Worker for tests/test_preemption.py: trains with step-granular
AutoCheckpoint + PreemptionGuard; on SIGTERM it checkpoints and exits
RESTART_EXIT_CODE; on relaunch it resumes losslessly.

Run: python preemption_worker.py <workdir> <total_steps>
Appends one line per completed step to <workdir>/losses.txt.
"""

import sys

sys.path.insert(0, "/root/repo")


def main(workdir: str, total_steps: int):
    import jax
    # sitecustomize pre-imports jax with the TPU plugin: pin CPU in-code
    jax.config.update("jax_platforms", "cpu")
    import os

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed import elastic
    from paddle_tpu.io.checkpoint import AutoCheckpoint

    pt.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.AdamW(learning_rate=1e-2,
                                               parameters=net),
                  loss=nn.CrossEntropyLoss())

    guard = elastic.PreemptionGuard()
    # sentinel for the race-the-compile test: from here on a SIGTERM is
    # flag-only; the first train_batch (trace+compile) happens after
    open(os.path.join(workdir, "guard_installed"), "w").write("1")
    acp = AutoCheckpoint.for_model(os.path.join(workdir, "ckpt"), model)
    loss_path = os.path.join(workdir, "losses.txt")
    for step in acp.epochs(total_steps):   # step-granular range
        rng = np.random.RandomState(1000 + step)   # data keyed by step
        x = rng.randn(16, 16).astype(np.float32)
        y = rng.randint(0, 4, (16, 1))
        logs = model.train_batch([x], [y])
        with open(loss_path, "a") as f:
            f.write(f"{step} {float(logs['loss']):.8f}\n")
        acp.commit(step)
        guard.check()   # preempted? checkpoint is committed → exit 67
    print("done")


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]))
