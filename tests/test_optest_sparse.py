"""OpTest coverage for the sparse kernel set (VERDICT r4 item 9; ref:
paddle/phi/kernels/sparse/{matmul,sddmm,softmax,fused_attention}
kernels and their unittests) — each op vs a NumPy dense reference plus
the directional finite-difference gradient identity, at a FIXED
sparsity pattern so every input the harness perturbs is a plain dense
array (values / operands), exactly how the phi kernels see them."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import sparse
from paddle_tpu.sparse.nn import functional as SF
from paddle_tpu.testing import OpSpec, arr, run_spec

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

# fixed pattern for a [6, 5] matrix, nnz=9, incl. an empty row (4)
ROWS = np.array([0, 0, 1, 2, 2, 2, 3, 5, 5])
COLS = np.array([1, 4, 0, 0, 2, 3, 3, 1, 2])
IDX = np.stack([ROWS, COLS], 1).astype(np.int32)
SHAPE = (6, 5)
NNZ = len(ROWS)


def _coo(vals):
    from jax.experimental import sparse as jsparse
    return sparse.SparseCooTensor(
        jsparse.BCOO((jnp.asarray(vals), jnp.asarray(IDX)),
                     shape=SHAPE))


def _dense(vals):
    d = np.zeros(SHAPE, np.float32)
    d[ROWS, COLS] = np.asarray(vals)
    return d


def _spmm(vals, rhs):
    return sparse.matmul(_coo(vals), rhs)


def _mv(vals, vec):
    return sparse.mv(_coo(vals), vec)


def _addmm(inp, vals, rhs):
    return sparse.addmm(inp, _coo(vals), rhs, beta=0.5, alpha=2.0)


def _sddmm_values(a, b):
    return sparse.masked_matmul(a, b, _coo(np.ones(NNZ))).values()


def _softmax_values(vals):
    return sparse.softmax(_coo(vals)).values()


def _np_softmax_values(vals):
    d = _dense(vals)
    mask = np.zeros(SHAPE, bool)
    mask[ROWS, COLS] = True
    lo = np.where(mask, d, -np.inf)
    with np.errstate(invalid="ignore"):
        e = np.exp(lo - lo.max(-1, keepdims=True))
        p = e / np.nansum(e, -1, keepdims=True)
    return np.nan_to_num(p)[ROWS, COLS]


def _attention(q, k, v):
    # pattern built per call from numpy constants: static nnz (no
    # fromdense/concrete-nse issue), nothing device-side at pytest
    # collection, and no tracer-backed arrays cached across jits
    r, c = np.tril_indices(8)
    sp = sparse.sparse_coo_tensor(np.stack([r, c]),
                                  np.ones(len(r), np.float32), (8, 8))
    return SF.attention(q, k, v, sp)


def _np_attention(q, k, v):
    d = q.shape[-1]
    lo = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    causal = np.tril(np.ones((8, 8), bool))
    lo = np.where(causal, lo, -np.inf)
    e = np.exp(lo - lo.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


SPECS = [
    OpSpec("sparse_spmm", _spmm,
           lambda v, r: _dense(v) @ r,
           (arr((NNZ,), seed=1), arr((5, 4), seed=2)),
           grad_wrt=(0, 1)),
    OpSpec("sparse_mv", _mv,
           lambda v, x: _dense(v) @ x,
           (arr((NNZ,), seed=3), arr((5,), seed=4)),
           grad_wrt=(0, 1)),
    OpSpec("sparse_addmm", _addmm,
           lambda i, v, r: 0.5 * i + 2.0 * (_dense(v) @ r),
           (arr((6, 4), seed=5), arr((NNZ,), seed=6),
            arr((5, 4), seed=7)),
           grad_wrt=(0, 1, 2)),
    OpSpec("sparse_sddmm", _sddmm_values,
           lambda a, b: (a @ b)[ROWS, COLS],
           (arr((6, 3), seed=8), arr((3, 5), seed=9)),
           grad_wrt=(0, 1)),
    OpSpec("sparse_softmax", _softmax_values, _np_softmax_values,
           (arr((NNZ,), seed=10),)),
    OpSpec("sparse_attention", _attention, _np_attention,
           (arr((2, 2, 8, 4), seed=11), arr((2, 2, 8, 4), seed=12),
            arr((2, 2, 8, 4), seed=13)),
           grad_wrt=(0, 1, 2), atol=1e-4, rtol=1e-4),
    # value-wise unaries keep the pattern; forward-only vs numpy
    OpSpec("sparse_relu",
           lambda v: sparse.relu(_coo(v)).values(),
           lambda v: np.maximum(v, 0), (arr((NNZ,), seed=14),),
           grad=False),
    OpSpec("sparse_scale",
           lambda v: sparse.scale(_coo(v), 2.0, 1.0).values(),
           lambda v: v * 2.0 + 1.0, (arr((NNZ,), seed=15),)),
]


@pytest.mark.parametrize("spec", SPECS, ids=repr)
def test_sparse_ops(spec):
    run_spec(spec)


def test_sparse_attention_empty_row_zeros():
    """Pattern rows with no admitted key produce zeros, not NaN (same
    contract as the ring/dense fully-masked rows)."""
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 1, 4, 2), jnp.float32)
               for _ in range(3))
    mask = np.zeros((4, 4), np.float32)
    mask[0, 0] = mask[2, 1] = 1.0      # rows 1 and 3 empty
    out = np.asarray(SF.attention(
        q, k, v, sparse.SparseCooTensor.from_dense(jnp.asarray(mask))))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[0, 0, 1], 0.0)
    np.testing.assert_allclose(out[0, 0, 3], 0.0)
