"""The perf ledger (tools/bench_ledger.py): one canonical row schema
for every bench tool, and a regression gate that fails loudly on an
empty or regressed trajectory (ISSUE 11 acceptance: an injected slow
row fails --ci, an honest row passes)."""

import json
import os

import pytest

from tools import bench_ledger as bl


@pytest.fixture()
def ledger(tmp_path, monkeypatch):
    path = str(tmp_path / "LEDGER.jsonl")
    monkeypatch.setenv("PT_BENCH_LEDGER", path)
    return path


def _row(value, workload="w", backend="cpu", **kw):
    return bl.make_row("test_tool", workload, value, "tokens/sec",
                       backend=backend, metrics={}, **kw)


def test_schema_roundtrip(ledger):
    p = bl.append_row(_row(100.0), path=ledger)
    assert p == ledger
    rows = bl.read_ledger(ledger)
    assert len(rows) == 1
    r = rows[0]
    for k in bl.REQUIRED:
        assert r.get(k) is not None, k
    assert r["schema"] == "bench_ledger/v1"
    assert r["tool"] == "test_tool" and r["value"] == 100.0
    assert len(r["run_id"]) == 12


def test_env_override_and_disable(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("PT_BENCH_LEDGER", path)
    assert bl.append("t", "w", 1.0, "u") == path
    assert os.path.exists(path)
    monkeypatch.setenv("PT_BENCH_LEDGER", "0")
    assert bl.append("t", "w", 1.0, "u") is None


def test_malformed_row_rejected(ledger):
    row = _row(1.0)
    del row["git_rev"]
    with pytest.raises(ValueError, match="git_rev"):
        bl.append_row(row, path=ledger)
    row = _row(1.0)
    row["schema"] = "bench_ledger/v0"
    with pytest.raises(ValueError, match="schema"):
        bl.append_row(row, path=ledger)


def test_reader_skips_garbage_lines(ledger):
    bl.append_row(_row(1.0), path=ledger)
    with open(ledger, "a") as f:
        f.write("not json\n")
        f.write(json.dumps({"schema": "other"}) + "\n")
    bl.append_row(_row(2.0), path=ledger)
    assert [r["value"] for r in bl.read_ledger(ledger)] == [1.0, 2.0]


def test_ci_empty_trajectory_fails_loudly(ledger):
    assert bl.ci_gate(path=ledger) == 2          # no file at all
    open(ledger, "w").close()
    assert bl.ci_gate(path=ledger) == 2          # empty file
    with open(ledger, "w") as f:
        f.write("garbage\n")
    assert bl.ci_gate(path=ledger) == 2          # unreadable rows only


def test_ci_honest_row_passes_injected_slow_row_fails(ledger):
    # an honest trajectory: stable values within noise
    for v in (100.0, 104.0, 98.0, 101.0):
        bl.append_row(_row(v), path=ledger)
    assert bl.ci_gate(path=ledger) == 0

    # injected regression: the newest row fell off a cliff
    bl.append_row(_row(30.0), path=ledger)
    assert bl.ci_gate(path=ledger) == 3

    # an honest recovery row passes again (baseline = median of prior)
    bl.append_row(_row(99.0), path=ledger)
    assert bl.ci_gate(path=ledger) == 0


def test_ci_single_row_series_is_new_not_fail(ledger):
    bl.append_row(_row(42.0), path=ledger)
    assert bl.ci_gate(path=ledger) == 0
    v = bl.compare(bl.read_ledger(ledger))
    assert v[0]["status"] == "new"


def test_tolerance_tight_on_hardware_wide_on_cpu(ledger):
    # 20% drop: inside the CPU tolerance, outside the TPU one
    for v in (100.0, 100.0, 80.0):
        bl.append_row(_row(v, workload="cpu_w", backend="cpu"),
                      path=ledger)
    for v in (100.0, 100.0, 80.0):
        bl.append_row(_row(v, workload="hw_w", backend="TPU v5 lite"),
                      path=ledger)
    verdicts = {v["workload"]: v["status"]
                for v in bl.compare(bl.read_ledger(ledger))}
    assert verdicts["cpu_w"] == "ok"
    assert verdicts["hw_w"] == "regressed"
    assert bl.ci_gate(path=ledger) == 3


def test_direction_lower_is_better(ledger):
    for v in (10.0, 10.0):
        bl.append_row(_row(v, workload="lat", direction="lower"),
                      path=ledger)
    # latency doubled: with direction=lower that IS the regression
    bl.append_row(_row(25.0, workload="lat", direction="lower"),
                  path=ledger)
    assert bl.ci_gate(path=ledger) == 3


def test_series_keyed_by_host(ledger, monkeypatch):
    # a slower machine's rows start their OWN trajectory: committed
    # fast-host baselines must not fail a contributor's CI run
    monkeypatch.setenv("PT_BENCH_HOST", "fast-host")
    for v in (1000.0, 1000.0):
        bl.append_row(_row(v), path=ledger)
    monkeypatch.setenv("PT_BENCH_HOST", "slow-host")
    bl.append_row(_row(300.0), path=ledger)   # 3.3x slower machine
    assert bl.ci_gate(path=ledger) == 0
    verdicts = {(v["host"]): v["status"]
                for v in bl.compare(bl.read_ledger(ledger))}
    assert verdicts["fast-host"] == "ok"
    assert verdicts["slow-host"] == "new"
    # same slow host regressing against ITS OWN baseline still fails
    bl.append_row(_row(300.0), path=ledger)
    bl.append_row(_row(50.0), path=ledger)
    assert bl.ci_gate(path=ledger) == 3


def test_series_keyed_by_workload_and_backend(ledger):
    # the same workload on another backend is its own series: a CPU
    # smoke number must never read as a TPU regression
    bl.append_row(_row(100000.0, backend="TPU v5 lite"), path=ledger)
    bl.append_row(_row(400.0, backend="cpu"), path=ledger)
    assert bl.ci_gate(path=ledger) == 0


def test_emitters_share_the_schema():
    """The repo trajectory (BENCH_LEDGER.jsonl) carries rows from all
    three bench tools in the one schema — the acceptance pin. Skipped
    only if a fresh checkout hasn't run the bench steps yet."""
    rows = bl.read_ledger(bl.DEFAULT_PATH)
    if not rows:
        pytest.skip("no repo ledger yet (bench tools not run)")
    tools = {r["tool"] for r in rows}
    assert {"llm_bench", "bench", "tpu_sweep"} <= tools, tools
    for r in rows:
        assert r["schema"] == "bench_ledger/v1"
