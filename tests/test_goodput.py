"""Goodput ledger (observability/goodput.py, ISSUE 16): every
wall-clock second since arming has exactly one owner. Injected clocks
pin the reconciliation identity (Σ buckets + unattributed == elapsed),
the documented precedence chain resolves overlaps without
double-counting, a disabled ledger records nothing, /goodputz and
/metrics serve the table over real HTTP, an SLO burn-rate trip
snapshots which bucket grew, fleet federation reads a never-armed
replica as a hole, and the bench ledger row carries the optional
goodput fields round-trip.
"""

import json
import os
import sys
import time
import urllib.request

import pytest

from paddle_tpu.observability import goodput
from paddle_tpu.observability.metrics import (MetricRegistry,
                                              default_registry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_ledger():
    """Process-global singleton isolation: every test gets a fresh
    ledger, an enabled flag, and a clean goodput metric namespace."""
    goodput.reset()
    was = goodput.enabled()
    goodput.enable()
    reg = default_registry()
    for fam in ("goodput_fraction", "badput_seconds_total"):
        reg.unregister(fam)
    yield
    goodput.reset()
    (goodput.enable if was else goodput.disable)()


def ticking(start=100.0):
    """Injected monotonic clock: a one-cell list the test advances."""
    t = [start]
    return t, (lambda: t[0])


# ---------------------------------------------------------------------------
# reconciliation: Σ buckets + unattributed == elapsed, always
# ---------------------------------------------------------------------------


def test_injected_clock_reconciliation_pin():
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 101.0
    led.note("compile", 1.0)        # arms at 100.0, [100, 101]
    t[0] = 103.0
    led.note("productive", 2.0)     # [101, 103]
    t[0] = 104.0
    led.note("input_wait", 0.5)     # [103.5, 104]; gap [103, 103.5]
    totals = led.totals()
    assert totals["compile"] == pytest.approx(1.0)
    assert totals["productive"] == pytest.approx(2.0)
    assert totals["input_wait"] == pytest.approx(0.5)
    # the 0.5s uncovered gap is ≤ gap_max_s → host_gap, not a leak
    assert totals["host_gap"] == pytest.approx(0.5)
    assert totals["unattributed"] == 0.0
    assert led.elapsed() == pytest.approx(4.0)
    assert sum(totals.values()) == pytest.approx(led.elapsed(),
                                                 abs=1e-9)
    assert led.goodput_fraction() == pytest.approx(2.0 / 4.0)


def test_long_gap_classifies_unattributed_short_gap_host():
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 101.0
    led.note("productive", 1.0)     # [100, 101]
    t[0] = 109.5
    led.note("productive", 0.5)     # [109, 109.5]; gap [101,109] = 8s
    totals = led.totals()
    assert totals["productive"] == pytest.approx(1.5)
    assert totals["unattributed"] == pytest.approx(8.0)
    assert totals["host_gap"] == 0.0
    assert sum(totals.values()) == pytest.approx(9.5, abs=1e-9)


def test_lazy_arm_keeps_the_arming_notes_own_interval():
    # arming at note time would clamp the first interval to zero
    # length — the first observed compile must keep its seconds
    t, clk = ticking(200.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 205.0
    led.note("compile", 5.0)
    assert led.armed
    assert led.elapsed() == pytest.approx(5.0)
    assert led.totals()["compile"] == pytest.approx(5.0)


def test_goodput_fraction_none_before_arming():
    led = goodput.TimeLedger(clock=lambda: 1.0,
                             registry=MetricRegistry())
    assert not led.armed
    assert led.goodput_fraction() is None          # a hole, not a 0
    assert led.elapsed() == 0.0
    assert all(v == 0.0 for v in led.totals().values())


# ---------------------------------------------------------------------------
# precedence: overlaps resolve by the documented chain, once each
# ---------------------------------------------------------------------------


def test_overlap_precedence_no_double_count():
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 110.0
    led.note("queue_wait", 10.0)    # [100, 110]
    led.note("productive", 4.0)     # [106, 110] — overlaps queue_wait
    totals = led.totals()
    # productive owns its 4s; queue_wait keeps only the uncontested 6
    assert totals["productive"] == pytest.approx(4.0)
    assert totals["queue_wait"] == pytest.approx(6.0)
    assert sum(totals.values()) == pytest.approx(10.0, abs=1e-9)


def test_same_bucket_overlap_unions_not_sums():
    # ten queued requests over one second are one second of
    # queue_wait, not ten
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 101.0
    for _ in range(10):
        led.note("queue_wait", 1.0)         # all stamp [100, 101]
    assert led.totals()["queue_wait"] == pytest.approx(1.0)
    assert led.elapsed() == pytest.approx(1.0)


def test_three_way_overlap_follows_precedence_order():
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 106.0
    led.note("queue_wait", 6.0)     # [100, 106]
    led.note("recovery", 4.0)       # [102, 106]
    led.note("productive", 2.0)     # [104, 106]
    totals = led.totals()
    assert totals["productive"] == pytest.approx(2.0)
    assert totals["recovery"] == pytest.approx(2.0)   # [102, 104]
    assert totals["queue_wait"] == pytest.approx(2.0)  # [100, 102]
    assert sum(totals.values()) == pytest.approx(6.0, abs=1e-9)


def test_precedence_is_the_documented_chain():
    assert goodput.BUCKETS == ("productive", "compile", "ckpt_stall",
                               "input_wait", "recovery", "migration",
                               "audit", "shed", "queue_wait",
                               "host_gap")
    assert goodput.DERIVED == ("unattributed",)


# ---------------------------------------------------------------------------
# memory bound: settling keeps the identity exact
# ---------------------------------------------------------------------------


def test_settle_bounds_pending_and_keeps_reconciliation():
    t, clk = ticking(0.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    n = goodput.PENDING_SOFT_CAP + 512
    for i in range(n):
        t[0] = (i + 1) * 0.1
        led.note("productive", 0.05)
    assert len(led._pending) <= goodput.PENDING_SOFT_CAP
    totals = led.totals()
    assert sum(totals.values()) == pytest.approx(led.elapsed(),
                                                 abs=1e-6)
    # every note was 0.05 covered + 0.05 gap (gaps ≤ gap_max_s)
    assert totals["productive"] == pytest.approx(n * 0.05, rel=1e-3)
    assert totals["unattributed"] == 0.0


def test_note_into_settled_region_clips_never_double_books():
    t, clk = ticking(0.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    n = goodput.PENDING_SOFT_CAP + 512
    for i in range(n):
        t[0] = (i + 1) * 0.1
        led.note("productive", 0.05)
    assert led._settled_until > 0.0
    # a late arrival spanning the whole settled region: its settled
    # part was already closed out — clamp and count, never re-own
    led.note("compile", t[0])
    totals = led.totals()
    assert sum(totals.values()) == pytest.approx(led.elapsed(),
                                                 abs=1e-6)
    assert led._clipped_s > 0.0


# ---------------------------------------------------------------------------
# disabled: records nothing, costs one module-flag check
# ---------------------------------------------------------------------------


def test_disabled_records_nothing():
    goodput.disable()
    try:
        goodput.note("productive", 1.0)
        goodput.note("compile", 1.0)
        inst = goodput.instance()
        assert not inst.armed                   # never armed
        assert all(v == 0.0 for v in inst.totals().values())
        pz = goodput.goodputz_payload()
        assert pz["enabled"] is False
        assert pz["armed"] is False
        assert goodput.note_trip("x") is None
    finally:
        goodput.enable()
    # re-enabled: the same entry point records again
    goodput.note("productive", 0.01)
    assert goodput.instance().armed


# ---------------------------------------------------------------------------
# export: hole until armed, monotone counters after
# ---------------------------------------------------------------------------


def test_update_gauges_mints_nothing_until_armed():
    reg = MetricRegistry()
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=reg)
    assert led.update_gauges() is None
    assert reg.get("goodput_fraction") is None      # the hole
    assert reg.get("badput_seconds_total") is None
    t[0] = 104.0
    led.note("productive", 3.0)     # arms at 101.0
    led.note("compile", 1.0)        # [103, 104]
    led.update_gauges()
    frac = reg.get("goodput_fraction")
    assert frac is not None
    # compile [103,104] overlaps productive's tail? no: productive is
    # [101,104], compile yields to it entirely → fraction = 3/3 = 1.0
    assert frac.value == pytest.approx(1.0)
    t[0] = 106.0
    led.note("input_wait", 1.5)     # [104.5, 106]
    led.update_gauges()
    bad = reg.get("badput_seconds_total")
    by_cause = {c.label_values[0]: c.value for c in bad.children()}
    assert by_cause["input_wait"] == pytest.approx(1.5)
    # counters are monotone projections: more badput only increases
    t[0] = 108.0
    led.note("input_wait", 2.0)
    led.update_gauges()
    by_cause2 = {c.label_values[0]: c.value for c in bad.children()}
    assert by_cause2["input_wait"] == pytest.approx(3.5)
    for cause, v in by_cause.items():
        assert by_cause2.get(cause, 0.0) >= v


def test_top_badput_picks_the_biggest_cause():
    totals = {b: 0.0 for b in goodput.BUCKETS + goodput.DERIVED}
    assert goodput.TimeLedger.top_badput(totals) is None
    totals["productive"] = 100.0    # productive never counts as badput
    totals["compile"] = 2.0
    totals["input_wait"] = 5.0
    top = goodput.TimeLedger.top_badput(totals)
    assert top == {"cause": "input_wait", "seconds": 5.0}


# ---------------------------------------------------------------------------
# /goodputz + /metrics over real HTTP
# ---------------------------------------------------------------------------


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def test_goodputz_and_metrics_over_http():
    from paddle_tpu.observability import server as dbg
    goodput.note("input_wait", 0.01)
    time.sleep(0.02)
    goodput.note("productive", 0.01)
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        gz = _get_json(base, "/goodputz")
        assert gz["enabled"] is True and gz["armed"] is True
        assert gz["buckets"]["productive"] > 0
        assert gz["buckets"]["input_wait"] > 0
        rec = gz["reconciliation"]
        assert rec["attributed_s"] + rec["unattributed_s"] == \
            pytest.approx(rec["elapsed_s"], abs=1e-5)
        assert rec["residual_s"] == pytest.approx(0.0, abs=1e-6)
        assert gz["precedence"] == list(goodput.BUCKETS)
        st = _get_json(base, "/statusz")
        assert st["goodput"]["enabled"] is True
        assert st["goodput"]["armed"] is True
        assert st["goodput"]["goodput_fraction"] is not None
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        assert "goodput_fraction" in text
        assert 'badput_seconds_total{cause="input_wait"}' in text
    finally:
        srv.stop()


def test_goodputz_unarmed_payload_is_explicit():
    from paddle_tpu.observability import server as dbg
    srv = dbg.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        gz = _get_json(base, "/goodputz")
        assert gz["enabled"] is True and gz["armed"] is False
        assert gz["goodput_fraction"] is None
        # never-armed process exports NEITHER goodput family: the
        # hole fleet federation is specified to read
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        # line-anchored: fleet_* aggregates minted by OTHER tests'
        # scrapers legitimately contain these names as substrings
        for line in text.splitlines():
            assert not line.startswith(("goodput_fraction",
                                        "badput_seconds_total",
                                        "# TYPE goodput_fraction",
                                        "# TYPE badput_seconds_total"))
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# SLO trip forensics: the breach latch snapshots which bucket grew
# ---------------------------------------------------------------------------


def test_slo_breach_trip_blames_the_grown_bucket():
    from paddle_tpu.observability.slo import SLOTracker
    goodput.note("productive", 0.01)
    inst = goodput.instance()
    inst.snapshot_watermark("baseline")
    # the window must really exist post-arming: note() clamps an
    # interval reaching before the arm point
    time.sleep(0.25)
    goodput.note("recovery", 0.2)   # the badput that grew since
    tracker = SLOTracker(targets={"gold": 0.99}, min_samples=1,
                         breach_threshold=1.0,
                         registry=MetricRegistry())
    tracker.record("gold", None, 0.1, "error")   # burn 100 ≫ 1
    pz = goodput.goodputz_payload()
    trips = pz["trips"]
    assert trips, "breach latch did not note a trip"
    trip = trips[-1]
    assert trip["tag"] == "slo_breach:gold"
    assert trip["delta"]["recovery"] == pytest.approx(0.2, abs=0.05)
    assert trip["top_grown"] == "recovery"
    # the trip advanced the watermark so consecutive trips don't
    # re-blame the same seconds
    assert pz["watermark"]["span"] == "slo_breach:gold"
    d = pz["delta_since_watermark"]
    assert d["recovery"] == pytest.approx(0.0, abs=1e-6)


def test_watermark_delta_reads_against_previous_watermark():
    t, clk = ticking(100.0)
    led = goodput.TimeLedger(clock=clk, registry=MetricRegistry())
    t[0] = 101.0
    led.note("productive", 1.0)
    first = led.snapshot_watermark("w0")
    assert first["productive"] == pytest.approx(1.0)
    t[0] = 103.0
    led.note("ckpt_stall", 2.0)
    second = led.snapshot_watermark("w1")
    assert second["ckpt_stall"] == pytest.approx(2.0)
    assert second["productive"] == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# fleet federation: never-armed replica is a hole, not a zero
# ---------------------------------------------------------------------------

ARMED_TEXT = ('# TYPE goodput_fraction gauge\n'
              'goodput_fraction 0.8\n'
              '# TYPE badput_seconds_total counter\n'
              'badput_seconds_total{cause="compile"} 1.5\n')
WARMING_TEXT = ('# TYPE llm_tokens_generated counter\n'
                'llm_tokens_generated 5\n')


def test_fleet_goodput_federation_hole_semantics():
    from paddle_tpu.serving.fleet import FleetScraper
    reg = MetricRegistry()
    s = FleetScraper(registry=reg)
    s.record("armed", ARMED_TEXT)
    s.record("warming", WARMING_TEXT)       # serving, never armed
    s.record("down", None)                  # dead
    agg = s.aggregates()
    assert agg["goodput_fraction"] == pytest.approx(0.8)
    assert agg["goodput_replicas"] == 1     # holes stay OUT of both
    assert reg.get("fleet_goodput_fraction").value == \
        pytest.approx(0.8)
    assert reg.get("fleet_goodput_replicas").value == 1
    # a second armed replica enters the mean
    s.record("armed2", '# TYPE goodput_fraction gauge\n'
                       'goodput_fraction 0.4\n')
    agg = s.aggregates()
    assert agg["goodput_fraction"] == pytest.approx(0.6)
    assert agg["goodput_replicas"] == 2
    # nobody armed: mean is None (not 0-with-denominator)
    s.forget("armed")
    s.forget("armed2")
    agg = s.aggregates()
    assert agg["goodput_fraction"] is None
    assert agg["goodput_replicas"] == 0


def test_fleet_federates_badput_causes_not_the_fraction():
    from paddle_tpu.serving.fleet import FleetScraper
    s = FleetScraper(registry=MetricRegistry())
    s.record("r0", ARMED_TEXT)
    text = s.render_prometheus()
    # per-replica badput causes federate by prefix...
    assert 'fleet_badput_seconds_total{replica="r0",cause="compile"}'\
        in text
    # ...but the replica's goodput_fraction gauge must NOT: its
    # federated name would collide with the unlabeled
    # fleet_goodput_fraction aggregate in the same exposition
    assert "fleet_goodput_fraction{" not in text
    # per-replica fractions surface on /fleetz instead
    rep = s.replica_report()
    assert rep["r0"]["goodput_fraction"] == pytest.approx(0.8)


def test_fleet_replica_report_unarmed_fraction_is_none():
    from paddle_tpu.serving.fleet import FleetScraper
    s = FleetScraper(registry=MetricRegistry())
    s.record("warming", WARMING_TEXT)
    rep = s.replica_report()
    assert rep["warming"]["goodput_fraction"] is None


# ---------------------------------------------------------------------------
# bench ledger: optional goodput fields round-trip
# ---------------------------------------------------------------------------


def test_bench_ledger_goodput_fields_roundtrip(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_ledger as bl
    path = str(tmp_path / "ledger.jsonl")
    # old-schema row (no goodput keys at all) + new row
    old = bl.make_row("llm_bench", "wl", 10.0, "tok/s", backend="cpu")
    old.pop("goodput_fraction")
    old.pop("badput_top")
    bl.append_row(old, path=path)
    new = bl.make_row("llm_bench", "wl", 11.0, "tok/s", backend="cpu",
                      goodput_fraction=0.83, badput_top="input_wait")
    assert new["goodput_fraction"] == 0.83
    assert new["badput_top"] == "input_wait"
    bl.append_row(new, path=path)
    rows = bl.read_ledger(path)
    assert len(rows) == 2
    assert "goodput_fraction" not in rows[0]
    assert rows[1]["goodput_fraction"] == 0.83
    # --compare tolerates the absent field on the old row
    verdicts = bl.compare(rows)
    assert len(verdicts) == 1
    assert verdicts[0]["newest_goodput_fraction"] == 0.83
    assert verdicts[0]["newest_badput_top"] == "input_wait"
    assert verdicts[0]["status"] in ("ok", "regressed")
    assert bl.ci_gate(path=path) in (0, 3)


def test_bench_ledger_goodput_row_fields_hole_semantics():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_ledger as bl
    # never-armed process: no fields at all (absent beats null — the
    # same hole discipline the fleet reads)
    assert bl.goodput_row_fields() == {}
    goodput.note("productive", 0.05)
    time.sleep(0.01)
    goodput.note("input_wait", 0.005)
    fields = bl.goodput_row_fields()
    assert 0.0 < fields["goodput_fraction"] <= 1.0
    assert fields["badput_top"] in goodput.BADPUT_CAUSES
    # disabled: no fields, regardless of the armed singleton
    goodput.disable()
    try:
        assert bl.goodput_row_fields() == {}
    finally:
        goodput.enable()
