"""Speculative decoding over the paged engine (no reference analog —
the 2026 serving lever; greedy acceptance is EXACT by construction).

Layer-level: one _PagedVerify pass must equal K sequential
_PagedDecode steps — same greedy tokens AND same page contents.
Engine-level (added with the engine wiring): speculative greedy ==
dense generate, with fewer target passes than tokens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import (_PagedDecode, _PagedPrefill,
                                      _PagedVerify)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config, llama_config
from paddle_tpu.nn.layer import functional_call, split_state

pytestmark = pytest.mark.slow  # smoke tier skips (tools/ci.sh --smoke)

PS, NP, P = 4, 32, 8  # page size, pool pages, pages/seq


def _build(gqa: bool):
    pt.seed(0)
    if gqa:
        cfg = llama_config(hidden_size=64, num_layers=2, num_heads=4,
                           num_kv_heads=2, vocab_size=97,
                           max_position_embeddings=64,
                           ffn_hidden_size=128)
    else:
        cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                         num_heads=4, vocab_size=97,
                         max_position_embeddings=64,
                         hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def _seed_pages(net, prompt):
    """Prefill one slot's pages; returns (pages, tables, ctx, t0)."""
    cfg = net.cfg
    L = cfg.num_layers
    kp = jnp.zeros((L, NP, PS, cfg.num_kv_heads, cfg.head_dim))
    vp = jnp.zeros_like(kp)
    tables = np.zeros((1, P), np.int32)
    for i in range(P):
        tables[0, i] = i + 1
    prefill = _PagedPrefill(net)
    params, buffers = split_state(prefill)
    ids = np.zeros((1, 16), np.int32)
    ids[0, :len(prompt)] = prompt
    (t0, kp, vp), _ = functional_call(
        prefill, params, buffers, jnp.asarray(ids),
        jnp.int32(len(prompt)), jnp.asarray(tables[0]), kp, vp,
        jnp.float32(0.0), jnp.int32(0), jax.random.PRNGKey(0),
        training=False)
    return kp, vp, jnp.asarray(tables), len(prompt), int(t0)


@pytest.mark.parametrize("gqa", [False, True], ids=["mha", "gqa"])
def test_verify_pass_equals_sequential_decode(gqa):
    net = _build(gqa)
    prompt = [3, 1, 4, 1, 5]
    K = 4

    # (a) K sequential greedy decode steps
    kp, vp, tables, ctx, t0 = _seed_pages(net, prompt)
    decode = _PagedDecode(net)
    params, buffers = split_state(decode)
    toks = [t0]
    for j in range(K):
        (nxt, kp, vp), _ = functional_call(
            decode, params, buffers,
            jnp.asarray([toks[-1]], jnp.int32),
            jnp.asarray([ctx + j], jnp.int32), tables,
            jnp.asarray([ctx + j + 1], jnp.int32), kp, vp,
            jnp.asarray([0.0], jnp.float32),
            jnp.asarray([0], jnp.int32), jax.random.PRNGKey(9),
            training=False)
        toks.append(int(nxt[0]))
    seq_pages = (np.asarray(kp), np.asarray(vp))

    # (b) one verify pass over [t0, d1..d_{K-1}]
    kp2, vp2, tables2, ctx2, t02 = _seed_pages(net, prompt)
    assert t02 == t0
    verify = _PagedVerify(net)
    vparams, vbuffers = split_state(verify)
    (logits, kp2, vp2), _ = functional_call(
        verify, vparams, vbuffers,
        jnp.asarray([toks[:K]], jnp.int32),
        jnp.asarray([ctx2], jnp.int32), tables2, kp2, vp2,
        training=False)
    greedy = jnp.argmax(logits, axis=-1)
    # target greedy after each prefix == the sequential outputs
    assert np.asarray(greedy)[0].tolist() == toks[1:K + 1]
    # page contents identical everywhere the sequential run wrote
    np.testing.assert_allclose(np.asarray(kp2), seq_pages[0],
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vp2), seq_pages[1],
                               atol=1e-6, rtol=1e-6)


def test_verify_rejection_prefix_semantics():
    """With WRONG draft tokens, the verify outputs are still the true
    target choices for every prefix up to and including the first
    mismatch — all the acceptance rule reads."""
    net = _build(False)
    prompt = [7, 2, 9]
    kp, vp, tables, ctx, t0 = _seed_pages(net, prompt)
    decode = _PagedDecode(net)
    params, buffers = split_state(decode)
    # true continuation
    (g1, kp_t, vp_t), _ = functional_call(
        decode, params, buffers, jnp.asarray([t0], jnp.int32),
        jnp.asarray([ctx], jnp.int32), tables,
        jnp.asarray([ctx + 1], jnp.int32), kp, vp,
        jnp.asarray([0.0], jnp.float32),
        jnp.asarray([0], jnp.int32), jax.random.PRNGKey(0),
        training=False)
    wrong = (int(g1[0]) + 1) % 97
    kp2, vp2, tables2, ctx2, _ = _seed_pages(net, prompt)
    verify = _PagedVerify(net)
    vparams, vbuffers = split_state(verify)
    (logits, _, _), _ = functional_call(
        verify, vparams, vbuffers,
        jnp.asarray([[t0, wrong, wrong]], jnp.int32),
        jnp.asarray([ctx2], jnp.int32), tables2, kp2, vp2,
        training=False)
    greedy = jnp.argmax(logits, axis=-1)
    # g_0 (after t0) must equal the true next token even though the
    # LATER positions in the chunk carried garbage drafts
    assert int(np.asarray(greedy)[0, 0]) == int(g1[0])


def test_speculative_engine_exact_with_perfect_draft():
    """draft == target: every proposal accepted — outputs EXACTLY match
    dense generate while target passes collapse to ~tokens/K."""
    from paddle_tpu.inference.llm import LLMEngine
    net = _build(False)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 9)]
    want = [np.asarray(net.generate(jnp.asarray([p]),
                                    max_new_tokens=12))[0, len(p):]
            .tolist() for p in prompts]
    with pytest.raises(ValueError, match="spec_tokens"):
        LLMEngine(net, draft_net=net, spec_tokens=1)
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(16,), draft_net=net,
                   spec_tokens=4) as eng:
        outs = eng.generate(prompts, max_new_tokens=12)
        rounds, toks = eng.n_spec_rounds, eng.n_tokens
    for got, ref in zip(outs, want):
        assert got["output_ids"] == ref
    # every round commits K tokens when the draft is perfect
    assert rounds <= -(-12 // 4) * 2 + 2, (rounds, toks)


def test_speculative_engine_exact_with_imperfect_draft():
    """A DIFFERENT (smaller, differently-initialized) draft: outputs
    still exactly match dense generate — acceptance only changes how
    many target passes it took."""
    from paddle_tpu.inference.llm import LLMEngine
    net = _build(False)
    pt.seed(123)
    dcfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                      num_heads=2, vocab_size=97,
                      max_position_embeddings=64, hidden_dropout=0.0,
                      attention_dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, n).tolist() for n in (4, 7, 3)]
    want = [np.asarray(net.generate(jnp.asarray([p]),
                                    max_new_tokens=10))[0, len(p):]
            .tolist() for p in prompts]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=draft,
                   spec_tokens=3) as eng:
        free0 = len(eng._free_pages)
        outs = eng.generate(prompts, max_new_tokens=10)
    assert len(eng._free_pages) == free0          # no page leaked
    for got, ref in zip(outs, want):
        assert got["output_ids"] == ref
        assert len(got["output_ids"]) == 10


def test_speculative_engine_eos_and_guards():
    from paddle_tpu.inference.llm import LLMEngine
    net = _build(False)
    # the LEGACY inline path (spec_slab=False) keeps its guards:
    # greedy-only sampling and the bucketized prefill bound
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=net,
                   spec_tokens=3, eos_token_id=7,
                   spec_slab=False) as eng:
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], max_new_tokens=4, temperature=0.9)
        with pytest.raises(ValueError, match="prefill bucket"):
            eng.submit(list(range(20)), max_new_tokens=2)
        out = eng.generate([[3, 1, 4]], max_new_tokens=40)[0]
        if 7 in out["output_ids"]:
            assert out["output_ids"][-1] == 7
        assert len(out["output_ids"]) <= 40
    # the slab path (the default) lifts BOTH guards: chunked ragged
    # prefill takes any length, rejection sampling serves temp>0
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=net,
                   spec_tokens=3, eos_token_id=7) as eng:
        out = eng.generate([list(range(20))], max_new_tokens=4,
                           temperature=0.9)[0]
        assert len(out["output_ids"]) <= 4
        out = eng.generate([[3, 1, 4]], max_new_tokens=40)[0]
        if 7 in out["output_ids"]:
            assert out["output_ids"][-1] == 7
        assert len(out["output_ids"]) <= 40
    with pytest.raises(ValueError, match="lookahead"):
        LLMEngine(net, draft_net=net, lookahead=2)


def test_speculative_tight_max_len_parity():
    """A request whose tail round cannot fit K positions (engine
    max_len reached) still completes EXACTLY like plain decode —
    acceptance clamps to the cache capacity instead of truncating
    (r5 review finding)."""
    from paddle_tpu.inference.llm import LLMEngine
    net = _build(False)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 97, 13).tolist()
    want = np.asarray(net.generate(jnp.asarray([prompt]),
                                   max_new_tokens=3))[0, 13:].tolist()
    with LLMEngine(net, max_seqs=1, page_size=4, num_pages=64,
                   prefill_buckets=(16,), max_len=16, draft_net=net,
                   spec_tokens=4) as eng:
        out = eng.generate([prompt], max_new_tokens=3)[0]
    assert out["output_ids"] == want
    assert not out["truncated"]
