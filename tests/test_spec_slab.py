"""On-device speculative slab (draft-K/verify-1 rounds inside the
DecodeCarry scan) — sampling semantics.

Layer-level (fast tier): the rejection-sampling acceptance rule
``_spec_accept`` reduces EXACTLY to greedy prefix acceptance at T=0,
and at T>0 the first committed token's marginal distribution equals
the target model's one-token-at-a-time sampler ``softmax(logits/T)``
REGARDLESS of draft quality (the speculative-sampling theorem, checked
by Monte-Carlo over the nonce lane — the same lane that varies across
real requests).

Engine-level (slow tier): greedy slab output is token-identical to a
target-only engine across prefix cache on/off × fused-slab width
N∈{1,8} × kv_dtype, with all four previously-excluded knobs (cache,
N>1 slabs, mixed_tick, int8) enabled SIMULTANEOUSLY on one spec
engine; temperature>0 realized streams are nonce-pinned deterministic
across cache/slab/batch-shape configurations (the failover
token-identity contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.llm import (LLMEngine, _SPEC_DRAFT_SALT,
                                      _spec_accept)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config


def _target():
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def _draft():
    pt.seed(123)
    cfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                     num_heads=2, vocab_size=97,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


# ---------------------------------------------------------------- #
# layer level: _spec_accept                                        #
# ---------------------------------------------------------------- #

def test_spec_accept_greedy_reduction():
    """T=0: acceptance is EXACT prefix matching of the proposals
    against the verifier's argmax chain, and the committed run is the
    argmax chain itself — so greedy slab decoding cannot depend on
    the draft distribution (only on how LONG its guesses match)."""
    V, K, B = 11, 4, 3
    rng = np.random.RandomState(0)
    vlg = jnp.asarray(rng.randn(B, K, V), jnp.float32)
    dlg = jnp.asarray(rng.randn(B, K - 1, V), jnp.float32)
    greedy = np.asarray(jnp.argmax(vlg, axis=-1))      # [B, K]
    toks = np.zeros((B, K), np.int32)
    toks[:, 0] = 5
    # slot 0: all proposals right; slot 1: first wrong; slot 2:
    # right, wrong, (ignored)
    toks[0, 1:] = greedy[0, :K - 1]
    toks[1, 1] = (greedy[1, 0] + 1) % V
    toks[1, 2:] = greedy[1, 1:K - 1]
    toks[2, 1] = greedy[2, 0]
    toks[2, 2] = (greedy[2, 1] + 3) % V
    toks[2, 3] = greedy[2, 2]
    out, n_acc = _spec_accept(
        jnp.asarray(toks), dlg, vlg,
        jnp.zeros((B,), jnp.float32),                  # T = 0
        jnp.arange(B, dtype=jnp.int32),
        jnp.full((B,), 9, jnp.int32), jax.random.PRNGKey(3))
    out, n_acc = np.asarray(out), np.asarray(n_acc)
    assert n_acc.tolist() == [K - 1, 0, 1]
    for b in range(B):
        # committed tokens (first n_acc+1) ARE the greedy chain
        assert out[b, :n_acc[b] + 1].tolist() == \
            greedy[b, :n_acc[b] + 1].tolist()


def test_spec_accept_first_token_marginal():
    """T>0 Monte-Carlo over the nonce lane: the first committed
    token's empirical marginal matches the target's sequential
    sampler softmax(vlg/T) even though proposals come from a very
    DIFFERENT draft distribution — accept + residual must conspire
    to exactness (speculative sampling theorem)."""
    V, K, T = 7, 3, 0.7
    rng = np.random.RandomState(0)
    vlg = jnp.asarray(rng.randn(1, K, V) * 2.0, jnp.float32)
    dlg = jnp.asarray(rng.randn(1, K - 1, V) * 2.0, jnp.float32)
    temps = jnp.asarray([T], jnp.float32)
    positions = jnp.asarray([5], jnp.int32)
    key = jax.random.PRNGKey(7)

    @jax.jit
    def one(nonce):
        n = jnp.asarray([nonce], jnp.int32)
        # proposal ~ q via the DRAFT-salted chain, exactly the key
        # the slab's draft probe folds for this (nonce, position)
        dk = jax.random.fold_in(key, _SPEC_DRAFT_SALT)
        kk = jax.random.fold_in(jax.random.fold_in(dk, n[0]),
                                positions[0])
        prop = jax.random.categorical(kk, dlg[0, 0] / T)
        toks = jnp.concatenate(
            [jnp.zeros((1, 1), jnp.int32), prop[None, None],
             jnp.zeros((1, K - 2), jnp.int32)], axis=1)
        out, _ = _spec_accept(toks, dlg, vlg, temps, n, positions,
                              key)
        return out[0, 0]

    trials = 3000
    counts = np.zeros(V)
    for t in range(trials):
        counts[int(one(t))] += 1
    emp = counts / trials
    ref = np.asarray(jax.nn.softmax(vlg[0, 0] / T))
    assert float(np.max(np.abs(emp - ref))) < 0.03, (emp, ref)


# ---------------------------------------------------------------- #
# engine level                                                     #
# ---------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.parametrize("cache", [True, False], ids=["cache", "nocache"])
@pytest.mark.parametrize("n_ticks", [1, 8], ids=["n1", "n8"])
def test_greedy_slab_identity_vs_target_only(cache, n_ticks):
    """Greedy spec slab == target-only engine, with the prefix cache
    and fused slabs ON for the spec engine — the lifted exclusions
    must not move a single token."""
    net, draft = _target(), _draft()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, n).tolist() for n in (4, 9, 3)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,)) as ref:
        want = [o["output_ids"]
                for o in ref.generate(prompts, max_new_tokens=10)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=draft,
                   spec_tokens=3, prefix_cache=cache,
                   decode_ticks_per_dispatch=n_ticks) as eng:
        assert eng.spec_slab and eng.mixed_tick
        free0 = len(eng._free_pages)
        outs = eng.generate(prompts, max_new_tokens=10)
    assert len(eng._free_pages) == eng.num_pages - 1  # close() flushed
    assert free0 <= eng.num_pages - 1
    assert [o["output_ids"] for o in outs] == want


@pytest.mark.slow
def test_greedy_slab_identity_int8_all_knobs():
    """int8 spec engine (quantized draft pool) + prefix cache + N=8
    fused slabs + mixed_tick, all simultaneously: token-identical to
    the target-only int8 engine (quantization moves logits, so the
    reference is int8 too)."""
    net, draft = _target(), _draft()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 11, 3)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), kv_dtype="int8") as ref:
        want = [o["output_ids"]
                for o in ref.generate(prompts, max_new_tokens=10)]
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=draft,
                   spec_tokens=3, kv_dtype="int8",
                   decode_ticks_per_dispatch=8) as eng:
        assert eng.spec_slab and eng.mixed_tick \
            and eng._cache is not None
        outs = eng.generate(prompts, max_new_tokens=10)
        assert eng.n_spec_rounds > 0
    assert [o["output_ids"] for o in outs] == want


@pytest.mark.slow
def test_temp_rejection_nonce_pinned_determinism():
    """temperature>0 slab decoding: realized streams depend ONLY on
    (nonce, position) — identical across prefix cache on/off, slab
    width, and batch shape (the cross-replica failover contract)."""
    net, draft = _target(), _draft()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 97, n).tolist() for n in (4, 7, 3)]

    def run(**kw):
        ms = kw.pop("max_seqs", 2)
        with LLMEngine(net, max_seqs=ms, page_size=4, num_pages=64,
                       prefill_buckets=(8,), draft_net=draft,
                       spec_tokens=3, **kw) as eng:
            futs = [eng.submit(p, max_new_tokens=10, temperature=0.8,
                               nonce=100 + i)
                    for i, p in enumerate(prompts)]
            return [f.result(timeout=300)["output_ids"] for f in futs]

    base = run()
    assert all(len(o) == 10 for o in base)
    assert run(prefix_cache=False) == base
    assert run(decode_ticks_per_dispatch=8) == base
    assert run(max_seqs=1) == base
    # a different nonce moves the stream (the lane is real)
    with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                   prefill_buckets=(8,), draft_net=draft,
                   spec_tokens=3) as eng:
        other = eng.submit(prompts[0], max_new_tokens=10,
                           temperature=0.8,
                           nonce=999).result(timeout=300)
    assert other["output_ids"] != base[0]


@pytest.mark.slow
def test_slab_dispatch_reduction_vs_legacy():
    """The tentpole's arithmetic, engine-level: host dispatches per
    emitted token must drop >=2x vs the legacy inline path at K=4
    (the legacy round pays K draft + 1 verify dispatches per round;
    the slab pays 1 per N rounds)."""
    net, draft = _target(), _draft()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 7)]

    def per_token(spec_slab, n_ticks):
        with LLMEngine(net, max_seqs=2, page_size=4, num_pages=64,
                       prefill_buckets=(8,), draft_net=draft,
                       spec_tokens=4, spec_slab=spec_slab,
                       decode_ticks_per_dispatch=n_ticks) as eng:
            outs = eng.generate(prompts, max_new_tokens=16)
            toks = sum(len(o["output_ids"]) for o in outs)
            return eng.n_host_dispatches / max(1, toks)

    legacy = per_token(False, 1)
    slab = per_token(True, 8)
    assert slab * 2.0 <= legacy, (slab, legacy)
