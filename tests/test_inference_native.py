"""Native (C++ PJRT) serving tests — the AnalysisPredictor analog
(ref: paddle/fluid/inference/api/analysis_predictor.h:95; tests model
the reference's inference api_impl_tester pattern: save from Python,
load+run natively, compare outputs).

The predictor is exercised both in-process (ctypes) and in a FRESH
subprocess with no prior jax state — the serving deployment shape.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit


def _plugin_available() -> bool:
    try:
        from paddle_tpu import inference
        inference.default_plugin()
        return True
    except Exception:
        return False


pytestmark = [
    pytest.mark.skipif(not _plugin_available(),
                       reason="no PJRT plugin .so on this machine"),
    pytest.mark.slow,  # smoke tier skips (tools/ci.sh --smoke)
]


def _save_and_serve(net, x, tmp_path, atol):
    net.eval()
    ref = np.asarray(net(x))
    path = str(tmp_path / "artifact")
    jit.save(net, path,
             input_spec=[jit.InputSpec(list(x.shape), str(x.dtype))])
    from paddle_tpu import inference
    os.environ.setdefault("PT_PJRT_CREATE_TIMEOUT", "90")
    try:
        pred = inference.create_predictor(inference.Config(path))
    except TimeoutError as e:
        pytest.skip(f"device unavailable for native predictor: {e}")
    out = pred.run([x])[0]
    assert out.shape == ref.shape
    # CPU-exported f32 convs run through the MXU's bf16 passes on TPU:
    # ~1% relative deviation is expected, not a serving bug
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=atol,
                               rtol=2e-2)
    return path, ref


def test_native_predictor_lenet(tmp_path):
    from paddle_tpu.models.lenet import LeNet
    pt.seed(0)
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    _save_and_serve(LeNet(), x, tmp_path, atol=5e-2)


def test_native_predictor_resnet(tmp_path):
    from paddle_tpu.models.resnet import resnet18
    pt.seed(0)
    x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
    _save_and_serve(resnet18(num_classes=10), x, tmp_path, atol=1e-1)


def test_native_predictor_gpt(tmp_path):
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    pt.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=16,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_flash=False)
    net = GPTForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(
        np.int64)
    _save_and_serve(net, ids, tmp_path, atol=5e-2)


def test_native_predictor_fresh_process(tmp_path):
    """Serving shape: artifact produced here, consumed by a brand-new
    process that never touches this process's jax state."""
    from paddle_tpu.models.lenet import LeNet
    pt.seed(0)
    net = LeNet()
    net.eval()
    x = np.random.RandomState(1).randn(2, 1, 28, 28).astype(np.float32)
    ref = np.asarray(net(x))
    path = str(tmp_path / "artifact")
    jit.save(net, path, input_spec=[jit.InputSpec([2, 1, 28, 28],
                                                  "float32")])
    np.save(tmp_path / "x.npy", x)

    script = textwrap.dedent(f"""
        import numpy as np
        from paddle_tpu import inference
        x = np.load({str(tmp_path / 'x.npy')!r})
        pred = inference.create_predictor(
            inference.Config({path!r}))
        out = pred.run([x])[0]
        np.save({str(tmp_path / 'out.npy')!r}, out)
        print("SERVED_OK")
    """)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the server pick its backend
    env.setdefault("PT_PJRT_CREATE_TIMEOUT", "90")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=300,
                          env=env, cwd=os.path.dirname(
                              os.path.dirname(os.path.abspath(__file__))))
    if "TimeoutError" in proc.stderr and "tunnel" in proc.stderr:
        pytest.skip("device unavailable for native predictor")
    assert "SERVED_OK" in proc.stdout, proc.stderr[-2000:]
    out = np.load(tmp_path / "out.npy")
    np.testing.assert_allclose(out, ref, atol=5e-2)


def test_artifact_has_native_files(tmp_path):
    from paddle_tpu.models.lenet import LeNet
    pt.seed(0)
    path = str(tmp_path / "a")
    jit.save(LeNet(), path,
             input_spec=[jit.InputSpec([1, 1, 28, 28], "float32")])
    for f in ("program.stablehlo", "program.mlir.bc", "params.pbin",
              "meta.json"):
        assert os.path.exists(os.path.join(path, f)), f
    meta = json.load(open(os.path.join(path, "meta.json")))
    assert meta["n_state_args"] > 0
    assert meta["outputs"][0]["shape"] == [1, 10]
